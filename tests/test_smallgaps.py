"""Small-gap sweep: gRPC TLS, persistent needle map, query engine,
Query RPC, delta heartbeats, 5-byte offsets.

Reference roles: security/tls.go, needle_map_leveldb.go:24,
query/json/query_json.go:18 + volume_grpc_query.go:12,
master.proto:43-44 delta beats, types/offset_5bytes.go."""

import os
import socket
import subprocess
import sys
import time

import pytest


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


# ---------------------------------------------------------------------------
# TLS


def _make_certs(tmp_path):
    """Self-signed CA + a server/client cert signed by it. Skips (not
    errors) on images without the cryptography package — the mTLS code
    under test only ever runs where certs exist."""
    import datetime

    pytest.importorskip("cryptography", reason="no cryptography package")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    def key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    now = datetime.datetime.now(datetime.timezone.utc)

    ca_key = key()
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(name("weed-ca"))
        .issuer_name(name("weed-ca"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), True)
        .sign(ca_key, hashes.SHA256())
    )

    leaf_key = key()
    leaf_cert = (
        x509.CertificateBuilder()
        .subject_name(name("seaweedfs"))
        .issuer_name(name("weed-ca"))
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("seaweedfs")]), False
        )
        .sign(ca_key, hashes.SHA256())
    )

    paths = {}
    for nm, data in [
        ("ca.crt", ca_cert.public_bytes(serialization.Encoding.PEM)),
        ("node.crt", leaf_cert.public_bytes(serialization.Encoding.PEM)),
        (
            "node.key",
            leaf_key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ),
        ),
    ]:
        p = tmp_path / nm
        p.write_bytes(data)
        paths[nm] = str(p)
    return paths


class TestGrpcTls:
    def test_mtls_handshake_and_plaintext_rejection(self, tmp_path):
        import grpc

        from seaweedfs_tpu.pb import master_pb2, rpc
        from seaweedfs_tpu.security.tls import (
            TlsConfig,
            client_credentials,
            server_credentials,
        )

        certs = _make_certs(tmp_path)
        tls = TlsConfig(
            ca_pem=open(certs["ca.crt"], "rb").read(),
            cert_pem=open(certs["node.crt"], "rb").read(),
            key_pem=open(certs["node.key"], "rb").read(),
        )

        # a bare gRPC server with the master service behind mTLS
        from concurrent import futures

        class Impl:
            def __getattr__(self, name):
                def h(req, ctx):
                    return master_pb2.StatisticsResponse(total_size=42)

                return h

        server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        server.add_generic_rpc_handlers(
            (rpc.servicer_handler(rpc.MASTER_SERVICE, rpc.MASTER_METHODS, Impl()),)
        )
        port = free_port()
        server.add_secure_port(
            f"127.0.0.1:{port}", server_credentials(tls)
        )
        server.start()
        try:
            # mTLS client succeeds (cert CN "seaweedfs" needs override)
            ch = grpc.secure_channel(
                f"127.0.0.1:{port}",
                client_credentials(tls),
                (("grpc.ssl_target_name_override", "seaweedfs"),),
            )
            resp = rpc.master_stub(ch).Statistics(
                master_pb2.StatisticsRequest(), timeout=5
            )
            assert resp.total_size == 42
            ch.close()

            # plaintext client is refused
            ch = grpc.insecure_channel(f"127.0.0.1:{port}")
            with pytest.raises(grpc.RpcError):
                rpc.master_stub(ch).Statistics(
                    master_pb2.StatisticsRequest(), timeout=5
                )
            ch.close()
        finally:
            server.stop(grace=0)

    def test_dial_seam_honors_set_tls(self, tmp_path):
        from seaweedfs_tpu.pb import rpc
        from seaweedfs_tpu.security.tls import TlsConfig

        certs = _make_certs(tmp_path)
        tls = TlsConfig(
            ca_pem=open(certs["ca.crt"], "rb").read(),
            cert_pem=open(certs["node.crt"], "rb").read(),
            key_pem=open(certs["node.key"], "rb").read(),
        )
        try:
            rpc.set_tls(tls, "seaweedfs")
            ch = rpc.dial("127.0.0.1:1")  # no connect yet; type check only
            assert ch is not None
            ch.close()
        finally:
            rpc.set_tls(None)


# ---------------------------------------------------------------------------
# persistent needle map


class TestDbNeedleMap:
    def test_roundtrip_and_resume(self, tmp_path):
        from seaweedfs_tpu.storage.needle_map import CompactNeedleMap, DbNeedleMap

        idx = str(tmp_path / "1.idx")
        nm = DbNeedleMap.load(idx)
        nm.put(5, 10, 100)
        nm.put(9, 30, 200)
        nm.put(5, 50, 120)  # overwrite
        nm.delete(9, 70)
        assert nm.get(5).offset == 50 and nm.get(5).size == 120
        assert nm.get(9).size == 0xFFFFFFFF
        assert nm.file_count == 3 and nm.deletion_count == 2
        assert nm.max_file_key == 9
        nm.close()

        # resume: no .idx replay needed (watermark), state intact
        nm2 = DbNeedleMap.load(idx)
        assert nm2.get(5).offset == 50
        assert nm2.max_file_key == 9
        assert sorted(v.key for v in nm2.items()) == [5, 9]
        nm2.close()

        # the .idx bytes are identical to what the in-memory map writes
        cm = CompactNeedleMap.load(str(tmp_path / "2.idx"))
        cm.put(5, 10, 100)
        cm.put(9, 30, 200)
        cm.put(5, 50, 120)
        cm.delete(9, 70)
        cm.close()
        assert (
            open(idx, "rb").read() == open(str(tmp_path / "2.idx"), "rb").read()
        )

    def test_tail_replay_after_external_append(self, tmp_path):
        from seaweedfs_tpu.storage import idx as idx_codec
        from seaweedfs_tpu.storage.needle_map import DbNeedleMap

        idx = str(tmp_path / "3.idx")
        nm = DbNeedleMap.load(idx)
        nm.put(1, 8, 64)
        nm.close()
        # an external writer (e.g. replication) appends to the .idx
        with open(idx, "ab") as f:
            f.write(idx_codec.pack_entry(2, 16, 128))
        nm2 = DbNeedleMap.load(idx)
        assert nm2.get(2).offset == 16
        nm2.close()

    def test_volume_with_db_map(self, tmp_path):
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), 7, needle_map_kind="db")
        n = Needle(cookie=0x1234, id=42, data=b"persistent map payload")
        v.write_needle(n)
        got = v.read_needle(42, cookie=0x1234)
        assert bytes(got.data) == b"persistent map payload"
        v.close()
        v2 = Volume(str(tmp_path), 7, create=False, needle_map_kind="db")
        got = v2.read_needle(42, cookie=0x1234)
        assert bytes(got.data) == b"persistent map payload"
        v2.close()


# ---------------------------------------------------------------------------
# query engine


class TestJsonQuery:
    def test_ops(self):
        from seaweedfs_tpu.query import Query, query_json

        line = '{"name": "alice", "age": 30, "vip": true, "addr": {"city": "sf"}}'
        cases = [
            (Query("name", "=", "alice"), True),
            (Query("name", "!=", "alice"), False),
            (Query("name", "%", "al*"), True),
            (Query("name", "!%", "al*"), False),
            (Query("age", ">", "29"), True),
            (Query("age", "<=", "29"), False),
            (Query("vip", "=", "true"), True),
            (Query("addr.city", "=", "sf"), True),
            (Query("missing", "=", "x"), False),
            (Query("addr.city", "", ""), True),  # existence
        ]
        for q, expect in cases:
            passed, _ = query_json(line, [], q)
            assert passed is expect, q

    def test_projections(self):
        from seaweedfs_tpu.query import Query, query_json

        line = '{"a": 1, "b": {"c": [10, 20]}}'
        passed, values = query_json(line, ["a", "b.c.1", "nope"], Query("a", "=", "1"))
        assert passed
        assert values == [1, 20, None]

    def test_gjson_path_table(self):
        """gjson.Get path semantics table (query_json.go:18 →
        tidwall/gjson): wildcards match keys first-wins, `#` is array
        length / per-element collection, no negative indices."""
        from seaweedfs_tpu.query.json_query import _MISSING, get_path

        doc = {
            "name": {"first": "Tom", "last": "Anderson"},
            "age": 37,
            "children": ["Sara", "Alex", "Jack"],
            "friends": [
                {"first": "Dale", "last": "Murphy", "age": 44},
                {"first": "Roger", "last": "Craig", "age": 68},
                {"first": "Jane", "last": "Murphy"},
            ],
            "fav.movie": "Deer Hunter",
        }
        cases = [
            # (path, expected) — mirrors the gjson README examples
            ("name.last", "Anderson"),
            ("age", 37),
            ("children", ["Sara", "Alex", "Jack"]),
            ("children.#", 3),
            ("children.1", "Alex"),
            ("child*.2", "Jack"),
            ("c?ildren.0", "Sara"),
            ("friends.#.first", ["Dale", "Roger", "Jane"]),
            ("friends.#.age", [44, 68]),  # missing elements skipped
            ("friends.1.last", "Craig"),
            ("friends.#", 3),
            ("name.*", "Tom"),  # wildcard: first matching key wins
            ("x*", _MISSING),
            ("children.-1", _MISSING),  # gjson has no negative indexing
            ("children.9", _MISSING),
            ("friends.#.nope", []),
            ("age.#", _MISSING),  # `#` only applies to arrays
        ]
        for path, expect in cases:
            got = get_path(doc, path)
            assert got == expect or (got is expect), (path, got, expect)


# ---------------------------------------------------------------------------
# cluster-level: Query RPC + delta heartbeats


@pytest.fixture(scope="module")
def mini_cluster(tmp_path_factory):
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    master = MasterServer(port=free_port(), volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("sgvs"))],
        port=free_port(),
        master=f"127.0.0.1:{master.port}",
        heartbeat_interval=0.1,
        max_volume_counts=[100],
    )
    vs.start()
    deadline = time.time() + 45
    while time.time() < deadline and len(master.topology.data_nodes()) < 1:
        time.sleep(0.05)
    yield master, vs
    vs.stop()
    master.stop()


class TestQueryRpc:
    def test_select_from_json_lines(self, mini_cluster):
        import grpc

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.pb import rpc, volume_pb2

        master, vs = mini_cluster
        rows = b"\n".join(
            [
                b'{"name": "a", "n": 1}',
                b'{"name": "b", "n": 5}',
                b'{"name": "c", "n": 9}',
            ]
        )
        ar = op.assign(f"127.0.0.1:{master.port}")
        assert not op.upload(f"{ar.url}/{ar.fid}", rows, jwt=ar.auth).error

        with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
            stripes = list(
                rpc.volume_stub(ch).Query(
                    volume_pb2.QueryRequest(
                        selections=["name", "n"],
                        from_file_ids=[ar.fid],
                        filter=volume_pb2.QueryRequest.Filter(
                            field="n", operand=">", value="2"
                        ),
                    )
                )
            )
        records = b"".join(s.records for s in stripes).decode().strip().splitlines()
        assert records == ['["b", 5]', '["c", 9]']


class TestDeltaHeartbeats:
    def test_new_volume_registers_via_delta(self, mini_cluster):
        """After the first full beat, a freshly grown volume reaches the
        master through a delta beat (O(changes) chatter)."""
        from seaweedfs_tpu.client import operation as op

        master, vs = mini_cluster
        # force growth in a new collection -> new volumes appear between
        # full beats; the master must learn them from the delta path
        ar = op.assign(f"127.0.0.1:{master.port}", collection="deltac")
        vid = int(ar.fid.split(",")[0])
        deadline = time.time() + 5
        while time.time() < deadline:
            if master.topology.lookup("deltac", vid):
                break
            time.sleep(0.05)
        assert master.topology.lookup("deltac", vid)
        assert not op.upload(f"{ar.url}/{ar.fid}", b"delta beat", jwt=ar.auth).error


# ---------------------------------------------------------------------------
# 5-byte offsets (subprocess: the switch is process-wide)


class TestFiveByteOffsets:
    def test_idx_layout_and_volume_roundtrip(self, tmp_path):
        code = f"""
import os
os.environ["WEED_VOLUME_OFFSET_SIZE"] = "5"
import jax
jax.config.update("jax_platforms", "cpu")
from seaweedfs_tpu.storage import types as t, idx
assert t.OFFSET_SIZE == 5 and idx.ENTRY_SIZE == 17
e = idx.pack_entry(7, 0xFFFFFFFFF, 123)
assert len(e) == 17
assert idx.unpack_entry(e) == (7, 0xFFFFFFFFF, 123)

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
v = Volume({str(tmp_path)!r}, 3)
v.write_needle(Needle(cookie=1, id=11, data=b"five byte offsets"))
assert bytes(v.read_needle(11, cookie=1).data) == b"five byte offsets"
v.close()
v2 = Volume({str(tmp_path)!r}, 3, create=False)
assert bytes(v2.read_needle(11, cookie=1).data) == b"five byte offsets"
print("OK")
"""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            env=env,
            cwd="/root/repo",
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "OK" in proc.stdout


# ---------------------------------------------------------------------------
# master vacuum loop + tail RPCs + durable sequencer


class TestMasterVacuumLoop:
    def test_vacuum_once_compacts_garbage(self, tmp_path_factory):
        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        master = MasterServer(
            port=free_port(),
            volume_size_limit_mb=64,
            garbage_threshold=0.3,
            vacuum_interval=0,  # loop off; drive _vacuum_once directly
        )
        master.start()
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp("vacvs"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.1,
            max_volume_counts=[100],
        )
        vs.start()
        try:
            deadline = time.time() + 45
            while time.time() < deadline and len(master.topology.data_nodes()) < 1:
                time.sleep(0.05)
            ar = op.assign(f"127.0.0.1:{master.port}", collection="vacloop")
            vid = int(ar.fid.split(",")[0])
            # create garbage: write then delete a fat needle
            assert not op.upload(
                f"{ar.url}/{ar.fid}", b"x" * 20000, jwt=ar.auth
            ).error
            op.delete(f"{ar.url}/{ar.fid}")
            keeper = op.assign(f"127.0.0.1:{master.port}", collection="vacloop")
            assert not op.upload(
                f"{keeper.url}/{keeper.fid}", b"keep me", jwt=keeper.auth
            ).error

            vol = vs.store.find_volume(vid)
            assert vol.garbage_level() > 0.3
            compacted = master._vacuum_once()
            assert compacted >= 1
            assert vol.garbage_level() < 0.1
            # live needle survives compaction
            if int(keeper.fid.split(",")[0]) == vid:
                data, _ = op.download(f"{keeper.url}/{keeper.fid}")
                assert data == b"keep me"
        finally:
            vs.stop()
            master.stop()


class TestTailRpcs:
    def test_sender_streams_and_receiver_applies(self, mini_cluster, tmp_path_factory):
        import grpc

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.pb import rpc, volume_pb2
        from seaweedfs_tpu.server.volume_server import VolumeServer

        master, vs = mini_cluster
        ar = op.assign(f"127.0.0.1:{master.port}", collection="tail")
        vid = int(ar.fid.split(",")[0])
        # incompressible payload: a text one would be stored gzipped
        # (the write path's transparent compression), and this test
        # asserts on the RAW tailed record bytes
        payload = bytes(range(256)) * 4
        assert not op.upload(f"{ar.url}/{ar.fid}", payload, jwt=ar.auth).error

        # sender drains after the idle timeout and delivers the needle
        with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
            frames = list(
                rpc.volume_stub(ch).VolumeTailSender(
                    volume_pb2.VolumeTailSenderRequest(
                        volume_id=vid, since_ns=0, idle_timeout_seconds=1
                    ),
                    timeout=30,
                )
            )
        assert frames, "expected at least one tailed needle"
        assert payload in b"".join(f.needle_body for f in frames)

        # a second server replicates the volume through TailReceiver
        vs2 = VolumeServer(
            [str(tmp_path_factory.mktemp("tailvs2"))],
            port=free_port(),
            master="",  # standalone; no heartbeats needed
        )
        vs2.start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{vs2.grpc_port}") as ch:
                rpc.volume_stub(ch).AllocateVolume(
                    volume_pb2.AllocateVolumeRequest(
                        volume_id=vid, collection="", replication="000"
                    )
                )
                rpc.volume_stub(ch).VolumeTailReceiver(
                    volume_pb2.VolumeTailReceiverRequest(
                        volume_id=vid,
                        since_ns=0,
                        idle_timeout_seconds=1,
                        source_volume_server=f"{vs.host}:{vs.port}",
                    ),
                    timeout=60,
                )
            data, _ = op.download(f"127.0.0.1:{vs2.port}/{ar.fid}")
            assert data == payload
        finally:
            vs2.stop()


class TestFileSequencer:
    def test_no_reuse_across_restart(self, tmp_path):
        from seaweedfs_tpu.sequence import FileSequencer

        path = str(tmp_path / "seq.txt")
        s = FileSequencer(path, batch=10)
        first = s.next_file_id(5)
        assert first == 1
        second = s.next_file_id(1)
        assert second == 6

        # crash (no clean shutdown): a new instance must never re-issue
        s2 = FileSequencer(path, batch=10)
        third = s2.next_file_id(1)
        assert third > second

    def test_set_max_advances(self, tmp_path):
        from seaweedfs_tpu.sequence import FileSequencer

        s = FileSequencer(str(tmp_path / "seq2.txt"), batch=10)
        s.set_max(500)
        assert s.next_file_id(1) == 501


class TestDbNeedleMapCluster:
    """-index db under a live cluster: writes, reads, restart resume,
    and vacuum (whose commit must invalidate the sqlite table)."""

    def test_write_read_vacuum_restart(self, tmp_path_factory):
        import grpc

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.pb import rpc, volume_pb2
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        data_dir = str(tmp_path_factory.mktemp("dbmapvs"))
        master = MasterServer(port=free_port(), volume_size_limit_mb=64)
        master.start()
        vs = VolumeServer(
            [data_dir],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            needle_map_kind="db",
        )
        vs.start()
        vs2 = None
        try:
            deadline = time.time() + 45
            while time.time() < deadline and len(master.topology.data_nodes()) < 1:
                time.sleep(0.05)

            keep = op.assign(f"127.0.0.1:{master.port}", collection="dbm")
            assert not op.upload(
                f"{keep.url}/{keep.fid}", b"keeper " * 300, jwt=keep.auth
            ).error
            doomed = op.assign(f"127.0.0.1:{master.port}", collection="dbm")
            assert not op.upload(
                f"{doomed.url}/{doomed.fid}", b"x" * 30000, jwt=doomed.auth
            ).error
            op.delete(f"{doomed.url}/{doomed.fid}")

            # vacuum through the gRPC 4-phase (db map rebuilds on commit)
            with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
                stub = rpc.volume_stub(ch)
                for v in {int(keep.fid.split(",")[0]), int(doomed.fid.split(",")[0])}:
                    stub.VacuumVolumeCompact(
                        volume_pb2.VacuumVolumeCompactRequest(volume_id=v)
                    )
                    stub.VacuumVolumeCommit(
                        volume_pb2.VacuumVolumeCommitRequest(volume_id=v)
                    )
                    stub.VacuumVolumeCleanup(
                        volume_pb2.VacuumVolumeCleanupRequest(volume_id=v)
                    )
            data, _ = op.download(f"{vs.host}:{vs.port}/{keep.fid}")
            assert data == b"keeper " * 300

            # restart the volume server on the same directory: the db
            # map resumes (or rebuilds) and serves the same bytes
            vs.stop()
            vs2 = VolumeServer(
                [data_dir],
                port=free_port(),
                master=f"127.0.0.1:{master.port}",
                heartbeat_interval=0.2,
                max_volume_counts=[100],
                needle_map_kind="db",
            )
            vs2.start()
            data, _ = op.download(f"{vs2.host}:{vs2.port}/{keep.fid}")
            assert data == b"keeper " * 300
            import urllib.error

            with pytest.raises(urllib.error.HTTPError):
                op.download(f"{vs2.host}:{vs2.port}/{doomed.fid}")
        finally:
            (vs2 or vs).stop()
            master.stop()


class TestEtcdSequencer:
    """External-KV sequencer over the etcd v3 gateway REST protocol
    (sequence/etcd_sequencer.go role) against tests/cloud_fakes.FakeEtcd."""

    @pytest.fixture()
    def etcd(self):
        from tests.cloud_fakes import FakeEtcd

        f = FakeEtcd()
        f.start()
        yield f
        f.stop()

    def test_allocates_monotonic_ranges(self, etcd):
        from seaweedfs_tpu.sequence import EtcdSequencer

        s = EtcdSequencer(etcd.endpoint, step=50)
        a = s.next_file_id(1)
        b = s.next_file_id(10)
        c = s.next_file_id(1)
        assert a >= 1 and b == a + 1 and c == b + 10

    def test_two_sequencers_never_overlap(self, etcd):
        """Two masters against one etcd: CAS range reservation keeps
        their id ranges disjoint (the multi-master coordination the
        external KV exists for)."""
        from seaweedfs_tpu.sequence import EtcdSequencer

        s1 = EtcdSequencer(etcd.endpoint, step=20)
        s2 = EtcdSequencer(etcd.endpoint, step=20)
        got1 = {s1.next_file_id(1) for _ in range(60)}
        got2 = {s2.next_file_id(1) for _ in range(60)}
        assert not got1 & got2

    def test_survives_restart_without_reuse(self, etcd):
        from seaweedfs_tpu.sequence import EtcdSequencer

        s = EtcdSequencer(etcd.endpoint, step=10)
        issued = [s.next_file_id(1) for _ in range(15)]
        s2 = EtcdSequencer(etcd.endpoint, step=10)
        fresh = [s2.next_file_id(1) for _ in range(15)]
        assert not set(issued) & set(fresh)

    def test_key_deleted_externally_does_not_spin(self, etcd):
        """If the sequence key is deleted behind the sequencer's back, a
        VALUE compare can never match the absent key — the reserve loop
        must fall back to create-if-absent instead of spinning."""
        from seaweedfs_tpu.sequence import EtcdSequencer

        s = EtcdSequencer(etcd.endpoint, step=5)
        first = s.next_file_id(1)
        s._kv.call("deleterange", {"key": s._key_b64})
        # exhaust the local reservation to force a fresh CAS round
        ids = [s.next_file_id(1) for _ in range(20)]
        assert len(set(ids)) == 20 and min(ids) > first

    def test_set_max_lifts_stored_value(self, etcd):
        from seaweedfs_tpu.sequence import EtcdSequencer

        s = EtcdSequencer(etcd.endpoint, step=10)
        s.set_max(10_000)
        assert s.next_file_id(1) == 10_001
        # a fresh sequencer sees the lifted max, never reissues below it
        s2 = EtcdSequencer(etcd.endpoint, step=10)
        assert s2.next_file_id(1) > 10_000

    def test_gates_on_connectivity(self):
        from seaweedfs_tpu.sequence import EtcdSequencer

        with pytest.raises(RuntimeError, match="cannot reach"):
            EtcdSequencer("127.0.0.1:1")

    def test_master_assigns_through_etcd_sequencer(self, etcd):
        """A MasterServer wired to the etcd sequencer serves
        /dir/assign with etcd-reserved ids."""
        from seaweedfs_tpu.sequence import EtcdSequencer
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer
        import tempfile

        master = MasterServer(
            port=free_port(),
            volume_size_limit_mb=64,
            sequencer=EtcdSequencer(etcd.endpoint),
        )
        master.start()
        vs = VolumeServer(
            [tempfile.mkdtemp()],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.1,
            max_volume_counts=[100],
        )
        vs.start()
        try:
            deadline = time.time() + 30
            while time.time() < deadline and not master.topology.data_nodes():
                time.sleep(0.05)
            import json as _json
            import urllib.request

            with urllib.request.urlopen(
                f"http://127.0.0.1:{master.port}/dir/assign", timeout=10
            ) as r:
                assert r.status == 200
                fid = _json.loads(r.read())["fid"]
            assert "," in fid
            # etcd now holds a reserved max covering the issued id
            assert master.sequencer._get() >= 1
        finally:
            vs.stop()
            master.stop()
