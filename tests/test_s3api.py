"""S3 gateway tests: sigv4 + chunked-payload units, then a live
master → volume → filer → s3 stack driven with real HTTP requests
(the reference's s3api has only XML/list unit tests; this adds the
end-to-end path its docker-compose setup covers manually)."""

import hashlib
import io
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.s3api import auth as s3auth
from seaweedfs_tpu.s3api import chunked_reader
from seaweedfs_tpu.s3api.errors import S3Error
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.s3api.s3api_server import S3ApiServer


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


# ----------------------------------------------------------------------
# units


class TestSigV4:
    IAM = s3auth.IdentityAccessManagement(
        [s3auth.Identity("admin", "AKIDEXAMPLE", "secret123")]
    )

    def _signed(self, method="GET", path="/bucket/key", body=b""):
        headers = {"Host": "s3.local:8333"}
        headers.update(
            s3auth.sign_request_v4(
                method, path, {}, headers, body, "AKIDEXAMPLE", "secret123"
            )
        )
        return headers

    def test_round_trip(self):
        headers = self._signed()
        ident = self.IAM.authenticate("GET", "/bucket/key", {}, headers, b"")
        assert ident.name == "admin"

    def test_wrong_secret_rejected(self):
        headers = {"Host": "s3.local:8333"}
        headers.update(
            s3auth.sign_request_v4(
                "GET", "/bucket/key", {}, headers, b"", "AKIDEXAMPLE", "wrong"
            )
        )
        with pytest.raises(S3Error) as e:
            self.IAM.authenticate("GET", "/bucket/key", {}, headers, b"")
        assert e.value.code == "SignatureDoesNotMatch"

    def test_unknown_access_key(self):
        headers = {"Host": "s3.local:8333"}
        headers.update(
            s3auth.sign_request_v4(
                "GET", "/k", {}, headers, b"", "NOPE", "secret123"
            )
        )
        with pytest.raises(S3Error) as e:
            self.IAM.authenticate("GET", "/k", {}, headers, b"")
        assert e.value.code == "InvalidAccessKeyId"

    def test_body_hash_checked(self):
        headers = self._signed(method="PUT", body=b"hello")
        with pytest.raises(S3Error):
            self.IAM.authenticate("PUT", "/bucket/key", {}, headers, b"tampered")

    def test_anonymous_rejected_when_enabled(self):
        with pytest.raises(S3Error) as e:
            self.IAM.authenticate("GET", "/bucket/key", {}, {}, b"")
        assert e.value.code == "AccessDenied"

    def test_open_gateway_allows_all(self):
        open_iam = s3auth.IdentityAccessManagement()
        assert open_iam.authenticate("GET", "/x", {}, {}, b"") is None

    def test_skewed_date_rejected(self):
        headers = {"Host": "s3.local"}
        import datetime

        old = datetime.datetime.now(datetime.timezone.utc) - datetime.timedelta(
            hours=2
        )
        headers.update(
            s3auth.sign_request_v4(
                "GET", "/k", {}, headers, b"", "AKIDEXAMPLE", "secret123", now=old
            )
        )
        with pytest.raises(S3Error) as e:
            self.IAM.authenticate("GET", "/k", {}, headers, b"")
        assert e.value.code == "RequestTimeTooSkewed"


class TestChunkedReader:
    def test_unsigned_round_trip(self):
        data = b"x" * 100000
        framed = chunked_reader.encode_chunked_payload(data, 8192)
        got = chunked_reader.decode_chunked_payload(io.BytesIO(framed))
        assert got == data

    def test_signed_round_trip(self):
        key = b"signing-key-material"
        data = b"abc" * 50000
        framed = chunked_reader.encode_chunked_payload(
            data, 16384, signing_key=key, seed_signature="seed",
            amz_date="20260729T000000Z", scope="20260729/us-east-1/s3/aws4_request",
        )
        got = chunked_reader.decode_chunked_payload(
            io.BytesIO(framed), signing_key=key, seed_signature="seed",
            amz_date="20260729T000000Z", scope="20260729/us-east-1/s3/aws4_request",
        )
        assert got == data

    def test_tampered_chunk_rejected(self):
        key = b"signing-key-material"
        data = b"payload-bytes" * 1000
        framed = bytearray(
            chunked_reader.encode_chunked_payload(
                data, 4096, signing_key=key, seed_signature="seed",
                amz_date="d", scope="s",
            )
        )
        idx = framed.find(b"payload")
        framed[idx] ^= 0xFF
        with pytest.raises(chunked_reader.ChunkSignatureMismatch):
            chunked_reader.decode_chunked_payload(
                io.BytesIO(bytes(framed)), signing_key=key,
                seed_signature="seed", amz_date="d", scope="s",
            )

    def test_empty_payload(self):
        framed = chunked_reader.encode_chunked_payload(b"", 8192)
        assert chunked_reader.decode_chunked_payload(io.BytesIO(framed)) == b""


# ----------------------------------------------------------------------
# live stack


@pytest.fixture(scope="module")
def s3stack(tmp_path_factory):
    mport = free_port()
    master = MasterServer(port=mport, volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("s3vol"))],
        port=free_port(),
        master=f"127.0.0.1:{mport}",
        heartbeat_interval=0.2,
        max_volume_counts=[50],
    )
    vs.start()
    fport = free_port()
    # lsm store: the S3 suite doubles as an integration soak of the
    # embedded LSM engine under multipart/list/delete churn (the other
    # stack fixture below keeps the memory store covered)
    filer = FilerServer(
        [f"127.0.0.1:{mport}"],
        port=fport,
        store="lsm",
        store_path=str(tmp_path_factory.mktemp("s3lsm")),
        max_mb=1,
    )
    filer.start()
    s3port = free_port()
    s3 = S3ApiServer(filer=f"127.0.0.1:{fport}", port=s3port)
    s3.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topology.data_nodes():
        time.sleep(0.05)
    yield s3, f"http://127.0.0.1:{s3port}"
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


def req(url, method="GET", data=None, headers=None):
    r = urllib.request.Request(url, data=data, method=method)
    for k, v in (headers or {}).items():
        r.add_header(k, v)
    # one retry on a transport-level drop (full-suite thread/fd pressure
    # can surface as RemoteDisconnected on this 1-vCPU rig) — real S3
    # clients retry these; HTTP-status errors still raise immediately
    import http.client

    try:
        return urllib.request.urlopen(r, timeout=15)
    except (http.client.RemoteDisconnected, ConnectionResetError):
        return urllib.request.urlopen(r, timeout=15)
    except urllib.error.URLError as e:
        if isinstance(
            e.reason, (http.client.RemoteDisconnected, ConnectionResetError)
        ):
            return urllib.request.urlopen(r, timeout=15)
        raise


def xml_of(body: bytes) -> ET.Element:
    root = ET.fromstring(body)
    # strip namespaces for easy assertions
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


class TestS3EndToEnd:
    def test_bucket_lifecycle(self, s3stack):
        _, base = s3stack
        with req(f"{base}/bucket1", "PUT") as r:
            assert r.status == 200
        with req(f"{base}/bucket1", "HEAD") as r:
            assert r.status == 200
        # duplicate create → 409
        with pytest.raises(urllib.error.HTTPError) as e:
            req(f"{base}/bucket1", "PUT")
        assert e.value.code == 409
        root = xml_of(req(f"{base}/").read())
        names = [b.findtext("Name") for b in root.iter("Bucket")]
        assert "bucket1" in names
        # missing bucket head → 404
        with pytest.raises(urllib.error.HTTPError) as e:
            req(f"{base}/nosuch", "HEAD")
        assert e.value.code == 404

    def test_object_put_get_head_delete(self, s3stack):
        _, base = s3stack
        req(f"{base}/objb", "PUT").close()
        body = b"hello s3 world" * 1000
        with req(f"{base}/objb/dir/hello.txt", "PUT", data=body,
                 headers={"Content-Type": "text/plain"}) as r:
            etag = r.headers["ETag"]
            assert etag == f'"{hashlib.md5(body).hexdigest()}"'
        with req(f"{base}/objb/dir/hello.txt") as r:
            assert r.read() == body
            assert r.headers["Content-Type"] == "text/plain"
        with req(f"{base}/objb/dir/hello.txt", "HEAD") as r:
            assert r.status == 200
        with req(f"{base}/objb/dir/hello.txt", "DELETE") as r:
            assert r.status == 204
        with pytest.raises(urllib.error.HTTPError) as e:
            req(f"{base}/objb/dir/hello.txt")
        assert e.value.code == 404

    def test_copy_object(self, s3stack):
        _, base = s3stack
        req(f"{base}/copyb", "PUT").close()
        req(f"{base}/copyb/src.bin", "PUT", data=b"copy-me").close()
        with req(
            f"{base}/copyb/dst.bin",
            "PUT",
            data=b"",
            headers={"X-Amz-Copy-Source": "/copyb/src.bin"},
        ) as r:
            root = xml_of(r.read())
            assert root.tag == "CopyObjectResult"
        assert req(f"{base}/copyb/dst.bin").read() == b"copy-me"

    def test_list_objects_v1_v2(self, s3stack):
        _, base = s3stack
        req(f"{base}/listb", "PUT").close()
        for name in ("a.txt", "b.txt", "c.txt"):
            req(f"{base}/listb/{name}", "PUT", data=b"x").close()
        req(f"{base}/listb/sub/nested.txt", "PUT", data=b"y").close()
        # v1 without a delimiter: flat recursive listing, no CommonPrefixes
        root = xml_of(req(f"{base}/listb").read())
        keys = [c.findtext("Key") for c in root.iter("Contents")]
        assert keys == ["a.txt", "b.txt", "c.txt", "sub/nested.txt"]
        assert list(root.iter("CommonPrefixes")) == []
        # v1 with delimiter=/: immediate keys + rolled-up prefixes
        root = xml_of(req(f"{base}/listb?delimiter=/").read())
        keys = [c.findtext("Key") for c in root.iter("Contents")]
        assert keys == ["a.txt", "b.txt", "c.txt"]
        prefixes = [p.findtext("Prefix") for p in root.iter("CommonPrefixes")]
        assert prefixes == ["sub/"]
        # v2 with prefix into the subdirectory
        root = xml_of(req(f"{base}/listb?list-type=2&prefix=sub/").read())
        keys = [c.findtext("Key") for c in root.iter("Contents")]
        assert keys == ["sub/nested.txt"]
        assert root.findtext("KeyCount") == "1"
        # truncation
        root = xml_of(req(f"{base}/listb?max-keys=2").read())
        assert root.findtext("IsTruncated") == "true"
        keys = [c.findtext("Key") for c in root.iter("Contents")]
        assert len(keys) == 2
        # marker continues
        root = xml_of(req(f"{base}/listb?max-keys=2&marker={keys[-1]}").read())
        more = [c.findtext("Key") for c in root.iter("Contents")]
        assert "c.txt" in more

    def test_delete_multiple(self, s3stack):
        _, base = s3stack
        req(f"{base}/delb", "PUT").close()
        for name in ("x1", "x2"):
            req(f"{base}/delb/{name}", "PUT", data=b"d").close()
        body = (
            b'<Delete><Object><Key>x1</Key></Object>'
            b'<Object><Key>x2</Key></Object></Delete>'
        )
        root = xml_of(req(f"{base}/delb?delete=", "POST", data=body).read())
        deleted = [d.findtext("Key") for d in root.iter("Deleted")]
        assert sorted(deleted) == ["x1", "x2"]
        root = xml_of(req(f"{base}/delb").read())
        assert list(root.iter("Contents")) == []

    def test_multipart_upload(self, s3stack):
        _, base = s3stack
        req(f"{base}/mpb", "PUT").close()
        root = xml_of(req(f"{base}/mpb/big.bin?uploads=", "POST", data=b"").read())
        upload_id = root.findtext("UploadId")
        assert upload_id
        part1 = b"A" * (2 * 1024 * 1024)  # 2 MB > filer max_mb=1 → multi-chunk
        part2 = b"B" * (1024 * 1024)
        req(
            f"{base}/mpb/big.bin?partNumber=1&uploadId={upload_id}",
            "PUT",
            data=part1,
        ).close()
        req(
            f"{base}/mpb/big.bin?partNumber=2&uploadId={upload_id}",
            "PUT",
            data=part2,
        ).close()
        # list parts
        root = xml_of(req(f"{base}/mpb/big.bin?uploadId={upload_id}").read())
        nums = [int(p.findtext("PartNumber")) for p in root.iter("Part")]
        assert nums == [1, 2]
        # complete
        root = xml_of(
            req(
                f"{base}/mpb/big.bin?uploadId={upload_id}", "POST", data=b"<x/>"
            ).read()
        )
        assert root.tag == "CompleteMultipartUploadResult"
        with req(f"{base}/mpb/big.bin") as r:
            got = r.read()
        assert got == part1 + part2
        # upload staging dir is gone
        root = xml_of(req(f"{base}/mpb?uploads=").read())
        assert list(root.iter("Upload")) == []

    def test_multipart_abort(self, s3stack):
        _, base = s3stack
        req(f"{base}/abortb", "PUT").close()
        root = xml_of(req(f"{base}/abortb/f?uploads=", "POST", data=b"").read())
        upload_id = root.findtext("UploadId")
        req(f"{base}/abortb/f?partNumber=1&uploadId={upload_id}", "PUT", data=b"z").close()
        with req(f"{base}/abortb/f?uploadId={upload_id}", "DELETE") as r:
            assert r.status == 204
        with pytest.raises(urllib.error.HTTPError) as e:
            req(f"{base}/abortb/f?uploadId={upload_id}")
        assert e.value.code == 404

    def test_streaming_chunked_put(self, s3stack):
        _, base = s3stack
        req(f"{base}/chunkb", "PUT").close()
        data = b"streamed-bytes" * 5000
        framed = chunked_reader.encode_chunked_payload(data, 65536)
        with req(
            f"{base}/chunkb/streamed.bin",
            "PUT",
            data=framed,
            headers={"x-amz-content-sha256": s3auth.STREAMING_PAYLOAD},
        ) as r:
            assert r.status == 200
        assert req(f"{base}/chunkb/streamed.bin").read() == data

    def test_delete_bucket(self, s3stack):
        _, base = s3stack
        req(f"{base}/gone", "PUT").close()
        req(f"{base}/gone/f.txt", "PUT", data=b"1").close()
        with req(f"{base}/gone", "DELETE") as r:
            assert r.status == 204
        with pytest.raises(urllib.error.HTTPError) as e:
            req(f"{base}/gone", "HEAD")
        assert e.value.code == 404


def _error_code(exc: urllib.error.HTTPError) -> str:
    return xml_of(exc.read()).findtext("Code")


class TestMultipartHardening:
    """Typed multipart errors (filer_multipart.go semantics): abort is
    NoSuchUpload for unknown ids and reclaims staged chunks; complete
    validates the client manifest — ascending order, staged parts,
    matching ETags — instead of silently splicing whatever exists."""

    def _initiate(self, base, bucket, key) -> str:
        req(f"{base}/{bucket}", "PUT").close()
        root = xml_of(
            req(f"{base}/{bucket}/{key}?uploads=", "POST", data=b"").read()
        )
        return root.findtext("UploadId")

    def _put_part(self, base, bucket, key, upload_id, num, data) -> str:
        with req(
            f"{base}/{bucket}/{key}?partNumber={num}&uploadId={upload_id}",
            "PUT",
            data=data,
        ) as r:
            return r.headers["ETag"]

    @staticmethod
    def _manifest(parts) -> bytes:
        root = ET.Element("CompleteMultipartUpload")
        for num, etag in parts:
            p = ET.SubElement(root, "Part")
            ET.SubElement(p, "PartNumber").text = str(num)
            ET.SubElement(p, "ETag").text = etag
        return ET.tostring(root)

    def test_abort_unknown_upload_is_nosuchupload(self, s3stack):
        _, base = s3stack
        req(f"{base}/mph0", "PUT").close()
        with pytest.raises(urllib.error.HTTPError) as e:
            req(f"{base}/mph0/f?uploadId=deadbeef", "DELETE")
        assert e.value.code == 404
        assert _error_code(e.value) == "NoSuchUpload"

    def test_abort_cleans_staged_parts(self, s3stack):
        _, base = s3stack
        uid = self._initiate(base, "mph1", "f.bin")
        self._put_part(base, "mph1", "f.bin", uid, 1, b"staged" * 1000)
        with req(f"{base}/mph1/f.bin?uploadId={uid}", "DELETE") as r:
            assert r.status == 204
        # staging dir is gone: the uploads listing is empty and the
        # same id can be neither listed nor completed nor re-aborted
        root = xml_of(req(f"{base}/mph1?uploads=").read())
        assert list(root.iter("Upload")) == []
        for method, path in (
            ("GET", f"{base}/mph1/f.bin?uploadId={uid}"),
            ("DELETE", f"{base}/mph1/f.bin?uploadId={uid}"),
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                req(path, method)
            assert _error_code(e.value) == "NoSuchUpload"

    def test_complete_manifest_happy_path(self, s3stack):
        _, base = s3stack
        uid = self._initiate(base, "mph2", "ok.bin")
        p1, p2 = b"1" * 2048, b"2" * 1024
        e1 = self._put_part(base, "mph2", "ok.bin", uid, 1, p1)
        e2 = self._put_part(base, "mph2", "ok.bin", uid, 2, p2)
        # the part PUT response carries the md5 ETag real clients echo
        assert e1 == f'"{hashlib.md5(p1).hexdigest()}"'
        root = xml_of(
            req(
                f"{base}/mph2/ok.bin?uploadId={uid}",
                "POST",
                data=self._manifest([(1, e1), (2, e2)]),
            ).read()
        )
        assert root.tag == "CompleteMultipartUploadResult"
        assert req(f"{base}/mph2/ok.bin").read() == p1 + p2

    def test_complete_out_of_order_manifest_rejected(self, s3stack):
        _, base = s3stack
        uid = self._initiate(base, "mph3", "ooo.bin")
        e1 = self._put_part(base, "mph3", "ooo.bin", uid, 1, b"a" * 100)
        e2 = self._put_part(base, "mph3", "ooo.bin", uid, 2, b"b" * 100)
        with pytest.raises(urllib.error.HTTPError) as e:
            req(
                f"{base}/mph3/ooo.bin?uploadId={uid}",
                "POST",
                data=self._manifest([(2, e2), (1, e1)]),
            )
        assert e.value.code == 400
        assert _error_code(e.value) == "InvalidPartOrder"

    def test_complete_missing_part_rejected(self, s3stack):
        _, base = s3stack
        uid = self._initiate(base, "mph4", "gap.bin")
        e1 = self._put_part(base, "mph4", "gap.bin", uid, 1, b"x" * 100)
        with pytest.raises(urllib.error.HTTPError) as e:
            req(
                f"{base}/mph4/gap.bin?uploadId={uid}",
                "POST",
                data=self._manifest([(1, e1), (7, '"feedface"')]),
            )
        assert _error_code(e.value) == "InvalidPart"

    def test_complete_wrong_etag_rejected(self, s3stack):
        _, base = s3stack
        uid = self._initiate(base, "mph5", "etag.bin")
        self._put_part(base, "mph5", "etag.bin", uid, 1, b"y" * 100)
        wrong = f'"{hashlib.md5(b"other bytes").hexdigest()}"'
        with pytest.raises(urllib.error.HTTPError) as e:
            req(
                f"{base}/mph5/etag.bin?uploadId={uid}",
                "POST",
                data=self._manifest([(1, wrong)]),
            )
        assert _error_code(e.value) == "InvalidPart"

    def test_complete_malformed_xml_rejected(self, s3stack):
        _, base = s3stack
        uid = self._initiate(base, "mph6", "bad.bin")
        self._put_part(base, "mph6", "bad.bin", uid, 1, b"z" * 100)
        for body in (b"<CompleteMultipartUpload><Part>", b"\x00\x01notxml"):
            with pytest.raises(urllib.error.HTTPError) as e:
                req(
                    f"{base}/mph6/bad.bin?uploadId={uid}", "POST", data=body
                )
            assert e.value.code == 400
            assert _error_code(e.value) == "MalformedXML"
        # a non-integer PartNumber is malformed too
        with pytest.raises(urllib.error.HTTPError) as e:
            req(
                f"{base}/mph6/bad.bin?uploadId={uid}",
                "POST",
                data=b"<CompleteMultipartUpload><Part>"
                b"<PartNumber>one</PartNumber></Part>"
                b"</CompleteMultipartUpload>",
            )
        assert _error_code(e.value) == "MalformedXML"


@pytest.fixture(scope="module")
def secured_s3(tmp_path_factory):
    mport = free_port()
    master = MasterServer(port=mport, volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("s3sec"))],
        port=free_port(),
        master=f"127.0.0.1:{mport}",
        heartbeat_interval=0.2,
        max_volume_counts=[20],
    )
    vs.start()
    fport = free_port()
    filer = FilerServer([f"127.0.0.1:{mport}"], port=fport, store="memory")
    filer.start()
    s3port = free_port()
    iam = s3auth.IdentityAccessManagement(
        [s3auth.Identity("admin", "AKID1", "topsecret")]
    )
    s3 = S3ApiServer(filer=f"127.0.0.1:{fport}", port=s3port, iam=iam)
    s3.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topology.data_nodes():
        time.sleep(0.05)
    yield f"http://127.0.0.1:{s3port}", s3port
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


class TestS3Auth:
    def _signed_req(self, base, port, method, path, body=b""):
        headers = {"Host": f"127.0.0.1:{port}"}
        url = urllib.parse.urlparse(path)
        query = urllib.parse.parse_qs(url.query, keep_blank_values=True)
        headers.update(
            s3auth.sign_request_v4(
                method, url.path, query, headers, body, "AKID1", "topsecret"
            )
        )
        return req(f"{base}{path}", method, data=body or None, headers=headers)

    def test_unsigned_rejected(self, secured_s3):
        base, _ = secured_s3
        with pytest.raises(urllib.error.HTTPError) as e:
            req(f"{base}/private", "PUT")
        assert e.value.code == 403

    def test_signed_accepted(self, secured_s3):
        base, port = secured_s3
        with self._signed_req(base, port, "PUT", "/private") as r:
            assert r.status == 200
        body = b"secret-object"
        with self._signed_req(base, port, "PUT", "/private/obj", body) as r:
            assert r.status == 200
        with self._signed_req(base, port, "GET", "/private/obj") as r:
            assert r.read() == body


class TestSigV4KnownAnswer:
    """AWS's published SigV4 example (AWS General Reference,
    'Signature Version 4 signing process'): known-answer coverage that
    a shared bug in our sign AND verify paths cannot fake — round-trip
    tests alone would pass with a mutually-wrong canonicalization."""

    def test_aws_documented_vector(self):
        import hashlib
        import hmac

        from seaweedfs_tpu.s3api.auth import canonical_request, derive_signing_key

        class H(dict):
            def get(self, k, d=None):
                return super().get(k.lower(), d)

        headers = H(
            {
                "content-type": "application/x-www-form-urlencoded; charset=utf-8",
                "host": "iam.amazonaws.com",
                "x-amz-date": "20150830T123600Z",
            }
        )
        canon = canonical_request(
            "GET",
            "/",
            {"Action": ["ListUsers"], "Version": ["2010-05-08"]},
            headers,
            ["content-type", "host", "x-amz-date"],
            hashlib.sha256(b"").hexdigest(),
        )
        assert (
            hashlib.sha256(canon.encode()).hexdigest()
            == "f536975d06c0309214f805bb90ccff089219ecd68b2577efef23edd43b7e1a59"
        )
        string_to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                "20150830T123600Z",
                "20150830/us-east-1/iam/aws4_request",
                hashlib.sha256(canon.encode()).hexdigest(),
            ]
        )
        key = derive_signing_key(
            "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
            "20150830",
            "us-east-1",
            "iam",
        )
        sig = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
        assert (
            sig
            == "5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7"
        )
