"""Byte-identity of the C one-pass POST (native/post.c) vs the pure
Python write path (write_path.build_upload_needle + Volume.write_needle).

The C hot loop must either DECLINE (and the Python fallback serves the
request) or produce the exact .dat bytes, .idx bytes, and HTTP reply
body the Python path produces — swept here over the upload matrix the
reference's handlers support: raw bodies, multipart with/without
filename, pre-gzipped payloads, ?ts=/?ttl= params, Seaweed-* pairs,
cm=true, and the decline triggers (gzippable text, .jpg orientation,
existing ids, non-ASCII names).
"""

from __future__ import annotations

import json
import os

import pytest

from seaweedfs_tpu.server import write_path
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.util.httpd import FastHeaders

pytestmark = pytest.mark.usefixtures("native_post_toolchain")

TS = "1700000000"  # pin ?ts= so last_modified is deterministic


def _pin_clock(monkeypatch):
    """Deterministic stamps: each Volume instance gets its own tick
    sequence starting from the same base (so the C-path volume and the
    Python-path volume write identical append_at_ns trailers), and
    time.time is frozen (so a no-?ts= case derives the same
    last_modified on both sides)."""
    import time as _time

    def now_ns(self):
        # pure function of volume state, like the real _now_ns (which
        # never mutates): a declined C attempt must not advance time
        return self.last_append_at_ns + 1

    monkeypatch.setattr(Volume, "_now_ns", now_ns)
    monkeypatch.setattr(_time, "time", lambda: 1_700_000_123.0)


def _headers(d: dict) -> FastHeaders:
    h = FastHeaders()
    for k, v in d.items():
        h[k.lower()] = v
    return h


def _python_write(v: Volume, fid: FileId, q: dict, body: bytes, headers,
                  url_filename: str) -> tuple[int, bytes]:
    n, fname, err = write_path.build_upload_needle(
        fid, q, body, headers, url_filename, fix_jpg_orientation=True
    )
    assert err is None, err
    size, _unchanged = (lambda r: (r[1], r[2]))(v.write_needle(n))
    reply = b'{"name": %s, "size": %d, "eTag": "%s"}' % (
        json.dumps(fname).encode(),
        size,
        n.etag().encode(),
    )
    return size, reply


def _fast_write(v: Volume, fid: FileId, q: dict, body: bytes, headers,
                url_filename: str) -> bytes | None:
    return write_path.try_native_post(
        v, fid, q, body, headers, url_filename, fix_jpg_orientation=True
    )


def _files(v: Volume) -> tuple[bytes, bytes]:
    with open(v.base_name + ".dat", "rb") as f:
        dat = f.read()
    with open(v.base_name + ".idx", "rb") as f:
        idx = f.read()
    return dat, idx


MP = (
    b"--BouNDary123\r\n"
    b'Content-Disposition: form-data; name="file"; filename="blob.bin"\r\n'
    b"Content-Type: application/x-custom\r\n"
    b"\r\n"
    b"\x00\x01\x02\xff\xfe binary payload \x80\x81" + bytes(range(256)) +
    b"\r\n--BouNDary123--\r\n"
)
MP_CT = "multipart/form-data; boundary=BouNDary123"

MP_NO_FILENAME = (
    b"--bnd\r\n"
    b'Content-Disposition: form-data; name="field"\r\n'
    b"\r\n"
    b"\x07\x08\x00raw field bytes\xff" + os.urandom(64).replace(b"\x00", b"x") +
    b"\r\n--bnd--\r\n"
)

MP_GZ = (
    b"--bnd\r\n"
    b'Content-Disposition: form-data; name="f"; filename="log.txt"\r\n'
    b"Content-Type: text/plain\r\n"
    b"Content-Encoding: gzip\r\n"
    b"\r\n"
    b"\x1f\x8b\x08\x00fake-gzip-bytes-do-not-matter" + bytes(200) +
    b"\r\n--bnd--\r\n"
)

BIN = b"\x03\x80\xff" + bytes(range(255, 0, -1)) * 3  # never gzippable


CASES = [
    # (name, q, body, headers, url_filename, expect_fast)
    ("raw-bin", {"ts": TS}, BIN, {"content-type": "application/octet-stream"}, "", True),
    ("raw-no-ct", {"ts": TS}, BIN, {}, "", True),
    ("raw-url-name", {"ts": TS}, BIN, {}, "pic.bin", True),
    ("raw-query-name", {"ts": TS, "filename": "q.bin"}, BIN, {}, "u.bin", True),
    ("raw-gzipped", {"ts": TS}, b"\x1f\x8b\x08\x00" + bytes(500),
     {"content-encoding": "gzip", "content-type": "text/plain"}, "", True),
    ("raw-pairs", {"ts": TS}, BIN,
     {"seaweed-color": "blue", "seaweed-k2": "v2"}, "", True),
    ("raw-cm", {"ts": TS, "cm": "true"}, BIN, {}, "", True),
    ("mp-filename", {"ts": TS}, MP, {"content-type": MP_CT}, "", True),
    ("mp-no-filename", {"ts": TS}, MP_NO_FILENAME,
     {"content-type": "multipart/form-data; boundary=bnd"}, "", True),
    ("mp-part-gzipped", {"ts": TS}, MP_GZ,
     {"content-type": "multipart/form-data; boundary=bnd"}, "", True),
    ("mp-quoted-boundary", {"ts": TS},
     MP_NO_FILENAME,
     {"content-type": 'multipart/form-data; boundary="bnd"'}, "", True),
    # decline rows: the C path must hand these to Python untouched
    ("decline-gzippable-text", {"ts": TS}, b"compressible text " * 40,
     {"content-type": "text/plain"}, "", False),
    # mime-prefix rules are case-SENSITIVE like Python's startswith:
    # 'Image/svg' does NOT hit the image/ early-out, so a mostly-text
    # body falls to the sniff and Python compresses -> C must decline
    # (review finding: ci_prefix here silently stored raw bytes)
    ("decline-capital-image-mime", {"ts": TS},
     b"looks like text to the sniff " * 20,
     {"content-type": "Image/svg"}, "", False),
    # ...while the same capital trick on a BINARY body changes nothing
    # for either side: sniff says no, C handles it
    ("capital-text-mime-binary", {"ts": TS}, BIN,
     {"content-type": "Text/plain"}, "", True),
    # unterminated quoted filename: Python's regex falls back to the
    # token branch and keeps the opening quote in the stored name —
    # C must decline rather than invent a closing quote
    ("decline-unterminated-quote", {"ts": TS},
     b"--bnd\r\n"
     b'Content-Disposition: form-data; name="f"; filename="abc.bin\r\n'
     b"\r\n" + BIN + b"\r\n--bnd--\r\n",
     {"content-type": "multipart/form-data; boundary=bnd"}, "", False),
    ("decline-jpg", {"ts": TS}, BIN, {}, "photo.jpg", False),
    ("decline-ttl", {"ts": TS, "ttl": "5m"}, BIN, {}, "", False),
    ("decline-nonascii-name", {"ts": TS, "filename": "résumé"},
     BIN, {}, "", False),
    ("no-ts", {}, BIN, {}, "", True),  # wall-clock seconds: same second
]


class TestNativePostByteIdentity:
    @pytest.mark.parametrize(
        "name,q,body,hdrs,url_filename,expect_fast",
        CASES,
        ids=[c[0] for c in CASES],
    )
    def test_dat_idx_reply_identical(
        self, tmp_path, monkeypatch, name, q, body, hdrs, url_filename,
        expect_fast
    ):
        _pin_clock(monkeypatch)
        headers = _headers(hdrs)
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        va = Volume(str(tmp_path / "a"), 1)
        vb = Volume(str(tmp_path / "b"), 1)
        fid = FileId(1, 0x1234, 0xCAFE)
        try:
            fast = _fast_write(va, fid, q, body, headers, url_filename)
            if fast is None:
                assert not expect_fast, f"{name}: C path unexpectedly declined"
                # declined: the fallback serves the request on volume A
                _size, fast = _python_write(va, fid, q, body, headers, url_filename)
            else:
                assert expect_fast, f"{name}: expected decline, C handled it"
            _size, py_reply = _python_write(vb, fid, q, body, headers, url_filename)
            dat_a, idx_a = _files(va)
            dat_b, idx_b = _files(vb)
            assert idx_a == idx_b, f"{name}: .idx diverged"
            assert dat_a == dat_b, f"{name}: .dat diverged"
            assert fast == py_reply, f"{name}: reply diverged"
        finally:
            va.close()
            vb.close()

    def test_fast_path_actually_engaged(self, tmp_path, monkeypatch):
        """A control: the hot case must NOT silently decline (a decline
        bug would turn this suite into Python-vs-Python tautology)."""
        _pin_clock(monkeypatch)
        v = Volume(str(tmp_path), 7)
        try:
            fid = FileId(7, 1, 2)
            reply = _fast_write(v, fid, {"ts": TS}, BIN, _headers({}), "")
            assert reply is not None
            assert json.loads(reply)["size"] > 0
            # and the stored needle reads back with a passing CRC
            n = v.read_needle(1, cookie=2)
            assert bytes(n.data) == BIN
        finally:
            v.close()

    def test_existing_id_declines_to_python(self, tmp_path, monkeypatch):
        """Overwrite semantics (cookie check, dedup) belong to Python."""
        _pin_clock(monkeypatch)
        v = Volume(str(tmp_path), 7)
        try:
            fid = FileId(7, 1, 2)
            h = _headers({})
            assert _fast_write(v, fid, {"ts": TS}, BIN, h, "") is not None
            assert _fast_write(v, fid, {"ts": TS}, BIN, h, "") is None
        finally:
            v.close()

    def test_kill_switch(self, tmp_path, monkeypatch):
        _pin_clock(monkeypatch)
        monkeypatch.setattr(write_path, "NATIVE_POST_ENABLED", False)
        v = Volume(str(tmp_path), 7)
        try:
            assert _fast_write(v, FileId(7, 1, 2), {}, BIN, _headers({}), "") is None
        finally:
            v.close()


class TestStageNameIdentity:
    """Tracing plane: the C hot loop and the Python fallback must emit
    the SAME write-path stage names (write_path.WRITE_STAGES), so a
    bench `--trace` breakdown or a /debug/traces span reads identically
    whichever path served the write (docs/TRACING.md)."""

    def test_c_and_python_stage_names_identical(self, tmp_path, monkeypatch):
        _pin_clock(monkeypatch)
        (tmp_path / "c").mkdir()
        (tmp_path / "py").mkdir()
        fid = FileId(1, 0x42, 0xCAFE)
        h = _headers({})

        vc = Volume(str(tmp_path / "c"), 1)
        c_stages: dict = {}
        try:
            reply = write_path.try_native_post(
                vc, fid, {"ts": TS}, BIN, h, "", stages=c_stages
            )
            assert reply is not None  # the C path must have served this
        finally:
            vc.close()

        vp = Volume(str(tmp_path / "py"), 1)
        py_stages: dict = {}
        try:
            n, _fname, err = write_path.build_upload_needle(
                fid, {"ts": TS}, BIN, h, "", stages=py_stages
            )
            assert err is None
            vp.write_needle(n, stages=py_stages)
            t0 = 0.0  # reply formatting is the handler's stage; stamp it
            py_stages["reply"] = t0
        finally:
            vp.close()

        assert set(c_stages) == set(write_path.WRITE_STAGES)
        assert set(py_stages) == set(write_path.WRITE_STAGES)
        assert set(c_stages) == set(py_stages)
        # C stage values are real (non-negative, pwrite non-zero)
        assert all(v >= 0 for v in c_stages.values())
        assert c_stages["pwrite"] > 0

    def test_stage_order_matches_declaration(self):
        assert write_path.WRITE_STAGES == (
            "parse", "assemble", "crc", "pwrite", "reply"
        )


class TestBenchCheckSmoke:
    def test_bench_check(self):
        """`bench.py --check` — the CI smoke that builds the ext and
        pushes one write through both paths — must pass in-tree."""
        import os
        import subprocess
        import sys

        # inner marker: from inside tier-1 the smoke only needs the
        # one-write C/Python identity leg — the weedlint and sanitizer
        # legs of --check run their own tests (test_weedlint.py,
        # test_fuzz_corpus.py) and would recurse/slow the suite here
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", WEED_BENCH_CHECK_INNER="1"
        )
        proc = subprocess.run(
            [sys.executable, "bench.py", "--check"],
            capture_output=True,
            text=True,
            timeout=180,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert '"ok": true' in proc.stdout, proc.stdout
