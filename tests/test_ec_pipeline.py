"""Device-resident EC streaming pipeline (ISSUE 15, docs/CODEC.md):
staging ring, fused CRC32-C, mesh batch arm, kill switch, stage
accounting, and tile-cache scan resistance.

Everything runs on the CPU backend (tier-1 is JAX_PLATFORMS=cpu), so
byte- and CRC-identity assertions here are exactly what the bench
--check pipeline_identity smoke enforces in production."""

import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import ec_files, ec_stream
from seaweedfs_tpu.ec.codec import new_encoder
from seaweedfs_tpu.ec.tile_cache import TileCache
from seaweedfs_tpu.util.crc import crc32c, crc32c_combine

# small two-tier geometry: fast, still exercises large-tier striding,
# super-tile coalescing, and the zero-padded tail
LARGE = 64 * 1024
SMALL = 16 * 1024


def _make_dat(path: str, nbytes: int, seed: int = 0) -> bytes:
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, nbytes, dtype=np.uint8).tobytes()
    with open(path + ".dat", "wb") as f:
        f.write(data)
    return data


def _shards(base: str) -> list[bytes]:
    return [
        open(base + ec_files.to_ext(i), "rb").read()
        for i in range(ec_files.TOTAL_SHARDS)
    ]


def _write_classic(base: str, rs, want_crcs=False, stats=None):
    """The serial reference driver, forced via the kill switch."""
    os.environ["WEED_EC_PIPELINE"] = "0"
    try:
        ec_files.write_ec_files(
            base, rs=rs, large_block_size=LARGE, small_block_size=SMALL,
            stats=stats, want_crcs=want_crcs,
        )
    finally:
        os.environ.pop("WEED_EC_PIPELINE", None)


# ---------------------------------------------------------------------------
class TestCrcKernel:
    def test_rows_match_host_crc32c(self):
        from seaweedfs_tpu.ec import crc_kernel

        rng = np.random.default_rng(0)
        for n32 in (1, 4, 64, 1024):
            x = rng.integers(0, 2**32, (3, n32), dtype=np.uint32)
            got = np.asarray(crc_kernel.crc32c_rows(x))
            for r in range(3):
                assert int(got[r]) == crc32c(x[r].tobytes())

    def test_leading_batch_dims(self):
        from seaweedfs_tpu.ec import crc_kernel

        rng = np.random.default_rng(1)
        x = rng.integers(0, 2**32, (2, 5, 64), dtype=np.uint32)
        got = np.asarray(crc_kernel.crc32c_rows(x))
        for i in range(2):
            for j in range(5):
                assert int(got[i, j]) == crc32c(x[i, j].tobytes())

    def test_non_power_of_two_rejected(self):
        from seaweedfs_tpu.ec import crc_kernel

        assert not crc_kernel.crc_supported(12)  # 3 lanes
        assert not crc_kernel.crc_supported(6)  # partial lane
        assert crc_kernel.crc_supported(4096)
        with pytest.raises(ValueError):
            crc_kernel.crc_lin_rows(np.zeros((1, 3), dtype=np.uint32))

    def test_combine_matches_concatenation(self):
        rng = np.random.default_rng(2)
        for la, lb in ((0, 5), (7, 0), (13, 40), (4096, 100)):
            a = rng.integers(0, 256, la, dtype=np.uint8).tobytes()
            b = rng.integers(0, 256, lb, dtype=np.uint8).tobytes()
            assert crc32c_combine(crc32c(a), crc32c(b), lb) == crc32c(a + b)

    def test_fused_encode_crc_matches_host(self):
        from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

        kern = TpuCodecKernels(10, 4)
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, (10, 4096), dtype=np.uint8)
        parity, crcs = kern.encode_u32_crc(data.view(np.uint32))
        parity_h = np.asarray(parity).view(np.uint8)
        rs = new_encoder(backend="cpu")
        want = rs.encode([data[i].copy() for i in range(10)] + [None] * 4)
        crcs_h = np.asarray(crcs)
        for i in range(4):
            assert np.array_equal(parity_h[i], want[10 + i])
        full = np.concatenate([data, parity_h], axis=0)
        for i in range(14):
            assert int(crcs_h[i]) == crc32c(full[i].tobytes())

    def test_fused_reconstruct_crc_matches_host(self):
        from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

        kern = TpuCodecKernels(10, 4)
        rng = np.random.default_rng(4)
        data = rng.integers(0, 256, (10, 4096), dtype=np.uint8)
        parity = np.asarray(
            kern.encode_u32_crc(data.view(np.uint32))[0]
        ).view(np.uint8)
        all_shards = np.concatenate([data, parity], axis=0)
        survivors = tuple(range(2, 12))
        targets = (0, 1)
        tile = all_shards[list(survivors)]
        rebuilt, crcs = kern.reconstruct_u32_crc(
            survivors, targets, tile.view(np.uint32)
        )
        rebuilt_h = np.asarray(rebuilt).view(np.uint8)
        for j, t in enumerate(targets):
            assert np.array_equal(rebuilt_h[j], all_shards[t])
            assert int(np.asarray(crcs)[j]) == crc32c(all_shards[t].tobytes())


# ---------------------------------------------------------------------------
class TestPipelinedEncode:
    @pytest.mark.parametrize("nbytes", [10 * SMALL * 3 + 777, 10 * LARGE + 5])
    def test_bytes_and_crcs_match_serial(self, tmp_path, nbytes):
        rs = new_encoder(backend="cpu")
        piped = str(tmp_path / "p")
        serial = str(tmp_path / "s")
        data = _make_dat(piped, nbytes)
        with open(serial + ".dat", "wb") as f:
            f.write(data)
        _write_classic(serial, rs, want_crcs=True, stats=(sstats := {}))
        parity_fn, fetch_fn = ec_stream.local_encode_fns(rs, want_crcs=True)
        pstats: dict = {}
        ec_stream.stream_write_ec_files(
            piped, large_block_size=LARGE, small_block_size=SMALL,
            parity_fn=parity_fn, fetch_fn=fetch_fn, stats=pstats,
            want_crcs=True,
        )
        for i, (pb, sb) in enumerate(zip(_shards(piped), _shards(serial))):
            assert pb == sb, f"shard {i}"
            assert pstats["shard_crcs"][i] == crc32c(pb) == sstats["shard_crcs"][i]

    def test_stage_buckets_and_compute_charge(self, tmp_path):
        """Satellite fix: host-codec time lands in compute_s, not in
        the writer pool's writeback bucket."""
        rs = new_encoder(backend="cpu")
        base = str(tmp_path / "v")
        _make_dat(base, 10 * SMALL * 4)
        parity_fn, fetch_fn = ec_stream.local_encode_fns(rs)
        assert fetch_fn.charges == "compute_s"
        stats: dict = {}
        ec_stream.stream_write_ec_files(
            base, large_block_size=LARGE, small_block_size=SMALL,
            parity_fn=parity_fn, fetch_fn=fetch_fn, stats=stats,
        )
        for key in ("read_s", "stage_s", "device_s", "writeback_s",
                    "compute_s", "write_s", "pipeline_depth", "ring_slots"):
            assert key in stats, key
        assert stats["compute_s"] > 0  # the numpy encode ran somewhere
        assert stats["writeback_s"] == 0  # and NOT booked as D2H drain

    def test_injected_plain_fns_still_get_crcs(self, tmp_path):
        """A stage pair that never heard of CRCs (the test-injection
        contract) still yields shard_crcs — host fallback in the
        writer pool."""
        rs = new_encoder(backend="cpu")
        base = str(tmp_path / "v")
        _make_dat(base, 10 * SMALL * 2 + 99)

        def fetch(tile):
            return rs._apply(rs.parity_rows, tile)

        stats: dict = {}
        ec_stream.stream_write_ec_files(
            base, large_block_size=LARGE, small_block_size=SMALL,
            parity_fn=lambda t: t, fetch_fn=fetch, stats=stats,
            want_crcs=True,
        )
        for i, sb in enumerate(_shards(base)):
            assert stats["shard_crcs"][i] == crc32c(sb)

    def test_depth_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("WEED_EC_PIPELINE_DEPTH", "2")
        assert ec_stream.pipeline_depth() == 2
        monkeypatch.setenv("WEED_EC_PIPELINE_DEPTH", "1")
        assert ec_stream.pipeline_depth() == 2  # min 2: double buffering
        monkeypatch.setenv("WEED_EC_PIPELINE_DEPTH", "junk")
        assert ec_stream.pipeline_depth() == 3
        monkeypatch.setenv("WEED_EC_PIPELINE_DEPTH", "4")
        rs = new_encoder(backend="cpu")
        base = str(tmp_path / "v")
        _make_dat(base, 10 * SMALL * 2)
        parity_fn, fetch_fn = ec_stream.local_encode_fns(rs)
        stats: dict = {}
        ec_stream.stream_write_ec_files(
            base, large_block_size=LARGE, small_block_size=SMALL,
            parity_fn=parity_fn, fetch_fn=fetch_fn, stats=stats,
        )
        assert stats["pipeline_depth"] == 4

    def test_kill_switch_routes_serial(self, tmp_path, monkeypatch):
        """WEED_EC_PIPELINE=0 restores the classic loop wholesale:
        routing predicates decline, the classic stats shape comes
        back, and bytes + CRCs are unchanged."""
        rs = new_encoder(backend="cpu")
        rs._backend_name = "native"  # pretend: routing looks at the name
        monkeypatch.setenv("WEED_EC_PIPELINE", "0")
        assert not ec_files._stream_host_codec(rs)
        assert not ec_files._use_stream_driver(rs)
        base = str(tmp_path / "v")
        _make_dat(base, 10 * SMALL * 2 + 123)
        stats: dict = {}
        ec_files.write_ec_files(
            base, rs=rs, large_block_size=LARGE, small_block_size=SMALL,
            stats=stats, want_crcs=True,
        )
        assert "encode_s" in stats  # the classic driver's bucket
        assert "device_s" not in stats
        for i, sb in enumerate(_shards(base)):
            assert stats["shard_crcs"][i] == crc32c(sb)
        monkeypatch.delenv("WEED_EC_PIPELINE")
        assert ec_files._stream_host_codec(rs)


# ---------------------------------------------------------------------------
class TestPipelinedRebuild:
    def test_rebuild_crcs_match_files(self, tmp_path):
        rs = new_encoder(backend="cpu")
        base = str(tmp_path / "v")
        _make_dat(base, 10 * SMALL * 3 + 4321)
        parity_fn, fetch_fn = ec_stream.local_encode_fns(rs)
        ec_stream.stream_write_ec_files(
            base, large_block_size=LARGE, small_block_size=SMALL,
            parity_fn=parity_fn, fetch_fn=fetch_fn,
        )
        want0 = open(base + ec_files.to_ext(0), "rb").read()
        os.remove(base + ec_files.to_ext(0))
        os.remove(base + ec_files.to_ext(12))
        rebuild_fn, rfetch = ec_stream.local_rebuild_fns(rs, want_crcs=True)
        stats: dict = {}
        rebuilt = ec_stream.stream_rebuild_ec_files(
            base, rebuild_fn=rebuild_fn, fetch_fn=rfetch, stats=stats,
            want_crcs=True,
        )
        assert sorted(rebuilt) == [0, 12]
        assert open(base + ec_files.to_ext(0), "rb").read() == want0
        for i in (0, 12):
            got = open(base + ec_files.to_ext(i), "rb").read()
            assert stats["shard_crcs"][i] == crc32c(got)

    def test_classic_rebuild_crcs(self, tmp_path, monkeypatch):
        rs = new_encoder(backend="cpu")
        base = str(tmp_path / "v")
        _make_dat(base, 10 * SMALL * 2)
        _write_classic(base, rs)
        os.remove(base + ec_files.to_ext(3))
        stats: dict = {}
        rebuilt = ec_files.rebuild_ec_files(
            base, rs=rs, stats=stats, want_crcs=True
        )
        assert rebuilt == [3]
        got = open(base + ec_files.to_ext(3), "rb").read()
        assert stats["shard_crcs"][3] == crc32c(got)


# ---------------------------------------------------------------------------
class TestMeshBatchPipeline:
    def test_batch_matches_serial_per_volume(self, tmp_path):
        """The mesh batch arm (CPU mesh = the byte-identical fallback
        tier) against the serial classic driver, odd sizes included;
        fused CRCs against the files on disk."""
        rs = new_encoder(backend="cpu")
        bases, refs = [], []
        for v in range(3):
            base = str(tmp_path / f"v{v}")
            ref = str(tmp_path / f"r{v}")
            data = _make_dat(base, 10 * SMALL * (v + 1) + 101 * v, seed=v)
            with open(ref + ".dat", "wb") as f:
                f.write(data)
            _write_classic(ref, rs)
            bases.append(base)
            refs.append(ref)
        stats: dict = {}
        ec_stream.stream_write_ec_files_batch(
            bases, large_block_size=LARGE, small_block_size=SMALL,
            stats=stats, want_crcs=True,
        )
        assert stats["batch_volumes"] == 3
        for v in range(3):
            for i, (gb, wb) in enumerate(zip(_shards(bases[v]), _shards(refs[v]))):
                assert gb == wb, f"v{v} shard {i}"
                assert stats["shard_crcs"][v][i] == crc32c(gb)

    def test_batch_limit_knob_chunks(self, tmp_path, monkeypatch):
        monkeypatch.setenv("WEED_EC_PIPELINE_BATCH", "2")
        assert ec_stream.pipeline_batch_limit() == 2
        rs = new_encoder(backend="cpu")
        bases, refs = [], []
        for v in range(3):
            base = str(tmp_path / f"v{v}")
            ref = str(tmp_path / f"r{v}")
            data = _make_dat(base, 10 * SMALL + 7 * v, seed=10 + v)
            with open(ref + ".dat", "wb") as f:
                f.write(data)
            _write_classic(ref, rs)
            bases.append(base)
            refs.append(ref)
        stats: dict = {}
        ec_stream.stream_write_ec_files_batch(
            bases, large_block_size=LARGE, small_block_size=SMALL,
            stats=stats, want_crcs=True,
        )
        assert len(stats["shard_crcs"]) == 3
        # structural fields survive the chunk merge (the dryrun and
        # bench consumers read them on every run)
        assert stats["batch_volumes"] == 3
        assert "pipeline_depth" in stats and "mesh" in stats
        for v in range(3):
            for gb, wb in zip(_shards(bases[v]), _shards(refs[v])):
                assert gb == wb

    def test_empty_volumes(self, tmp_path):
        bases = []
        for v in range(2):
            base = str(tmp_path / f"e{v}")
            open(base + ".dat", "wb").close()
            bases.append(base)
        stats: dict = {}
        ec_stream.stream_write_ec_files_batch(
            bases, stats=stats, want_crcs=True
        )
        for base in bases:
            for i in range(14):
                assert os.path.getsize(base + ec_files.to_ext(i)) == 0
        assert stats["shard_crcs"] == [[0] * 14, [0] * 14]

    def test_routing_via_write_ec_files_batch(self, tmp_path, monkeypatch):
        """ec_files.write_ec_files_batch routes to the pipelined arm by
        default and the classic per-round loop under the kill switch —
        same bytes either way."""
        rs = new_encoder(backend="cpu")
        piped = str(tmp_path / "p")
        killed = str(tmp_path / "k")
        data = _make_dat(piped, 10 * SMALL * 2 + 55)
        with open(killed + ".dat", "wb") as f:
            f.write(data)
        st_p: dict = {}
        ec_files.write_ec_files_batch(
            [piped], large_block_size=LARGE, small_block_size=SMALL,
            stats=st_p, want_crcs=True,
        )
        assert "pipeline_depth" in st_p  # pipelined arm ran
        monkeypatch.setenv("WEED_EC_PIPELINE", "0")
        st_k: dict = {}
        ec_files.write_ec_files_batch(
            [killed], large_block_size=LARGE, small_block_size=SMALL,
            stats=st_k, want_crcs=True,
        )
        assert "pipeline_depth" not in st_k  # classic arm ran
        for gb, wb in zip(_shards(piped), _shards(killed)):
            assert gb == wb
        assert st_p["shard_crcs"] == st_k["shard_crcs"]

    def test_mesh_fused_crc_with_stripe_collective(self):
        """encode_batch_u32_crc on a vol×stripe mesh: the stripe-axis
        CRC composition (all_gather + Z-shift fold) must equal the
        host CRC of the full concatenated stream."""
        jax = pytest.importorskip("jax")
        from seaweedfs_tpu.parallel import MeshCodec, make_mesh

        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices")
        codec = MeshCodec(make_mesh(devs[:8]))  # 4 x 2
        assert codec.crc_supported(32 * 1024)
        assert not codec.crc_supported(32 * 1024 + 8)
        rng = np.random.default_rng(7)
        vols = rng.integers(0, 256, (4, 10, 32 * 1024), dtype=np.uint8)
        u32 = codec.shard_volumes(vols.view(np.uint32))
        parity, crcs = codec.encode_batch_u32_crc(u32)
        parity_h = np.asarray(parity).view(np.uint8)
        crcs_h = np.asarray(crcs)
        full = np.concatenate([vols, parity_h], axis=1)
        for v in range(4):
            for i in range(14):
                assert int(crcs_h[v, i]) == crc32c(full[v, i].tobytes())
        layout = codec.batch_layout(4, 32 * 1024)
        assert layout == {
            "vol": 4, "stripe": 2, "devices": 8,
            "per_device_volumes": 1, "per_device_bytes": 16 * 1024,
        }


# ---------------------------------------------------------------------------
class TestTileCacheScanResistance:
    def test_scan_does_not_churn_protected(self):
        """ROADMAP satellite: a sequential scan (one-touch puts) must
        not evict the promoted hot set."""
        c = TileCache(capacity_bytes=8 * 100, tile_bytes=4096)
        assert c.scan_resistant
        # hot set: put + second-touch get -> protected
        for off in (0, 4096):
            c.put(0, off, b"h" * 100)
            assert c.get(0, off) is not None
        # scan: 50 one-touch tiles, never touched again
        for i in range(50):
            c.put(1, i * 4096, b"s" * 100)
        assert c.get(0, 0) is not None, "scan churned the hot set"
        assert c.get(0, 4096) is not None
        assert c.total_bytes <= 8 * 100

    def test_plain_lru_churns_under_knob(self, monkeypatch):
        """WEED_EC_TILE_SCAN=0: the pre-PR behavior, where the same
        scan evicts everything — the regression control."""
        monkeypatch.setenv("WEED_EC_TILE_SCAN", "0")
        c = TileCache(capacity_bytes=8 * 100, tile_bytes=4096)
        assert not c.scan_resistant
        for off in (0, 4096):
            c.put(0, off, b"h" * 100)
            assert c.get(0, off) is not None
        for i in range(50):
            c.put(1, i * 4096, b"s" * 100)
        assert c.get(0, 0) is None  # plain LRU: scanned straight through
        assert c.get(0, 4096) is None

    def test_probation_bounded_small(self):
        c = TileCache(capacity_bytes=64 << 20, tile_bytes=256 * 1024)
        assert c.probation_bytes_cap == (64 << 20) // 8

    def test_second_touch_promotes(self):
        c = TileCache(capacity_bytes=4 * 100, tile_bytes=4096)
        c.put(0, 0, b"x" * 100)
        assert c.get(0, 0) is not None  # promotes
        assert (0, 0) in c._protected
        assert (0, 0) not in c._probation

    def test_covers_and_snapshot_span_probation(self):
        c = TileCache(capacity_bytes=1 << 20, tile_bytes=4096)
        c.put(3, 0, b"x" * 4096)  # probationary only
        assert c.covers(3, 100, 200)
        snap = c.snapshot(3)
        assert snap == [(0, b"x" * 4096)]

    def test_protected_reput_updates_in_place(self):
        c = TileCache(capacity_bytes=1 << 20, tile_bytes=4096)
        c.put(0, 0, b"a" * 100)
        c.get(0, 0)  # promote
        c.put(0, 0, b"b" * 200)
        assert c.get(0, 0) == b"b" * 200
        assert c.total_bytes == 200
