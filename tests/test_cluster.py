"""In-process multi-node cluster tests: master + N volume servers on
localhost ports, driven over the real HTTP + gRPC surfaces.

This is the integration harness the reference lacks (SURVEY §4
implication): assign → write → read → delete → vacuum → EC encode →
shard spread → degraded read, all through the wire.
"""

import json
import socket
import time
import urllib.request

import grpc
import pytest

from seaweedfs_tpu.pb import master_pb2, rpc, volume_pb2
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


def http_get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


def http_json(url: str):
    status, body = http_get(url)
    return status, json.loads(body)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """One master + 3 volume servers, heartbeating over gRPC."""
    master_port = free_port()
    master = MasterServer(port=master_port, volume_size_limit_mb=64)
    master.start()
    volume_servers = []
    for i in range(3):
        port = free_port()
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp(f"vs{i}"))],
            port=port,
            master=f"127.0.0.1:{master_port}",
            rack=f"rack{i % 2}",
            heartbeat_interval=0.2,
            # each grow request creates up to 7 volumes per collection
            # (find_volume_count); give the suite headroom
            max_volume_counts=[100],
        )
        vs.start()
        volume_servers.append(vs)
    deadline = time.time() + 45
    while time.time() < deadline and len(master.topology.data_nodes()) < 3:
        time.sleep(0.05)
    assert len(master.topology.data_nodes()) == 3
    yield master, volume_servers
    for vs in volume_servers:
        vs.stop()
    master.stop()


def master_url(master, path):
    return f"http://127.0.0.1:{master.port}{path}"


class TestAssignWriteRead:
    def test_full_cycle(self, cluster):
        master, _ = cluster
        status, assign = http_json(master_url(master, "/dir/assign"))
        assert status == 200, assign
        assert "fid" in assign and "url" in assign

        blob = b"the quick brown fox" * 100
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}?filename=fox.txt",
            data=blob,
            method="POST",
            headers={"Content-Type": "text/plain"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
            up = json.loads(r.read())
            assert up["size"] > 0

        status, body = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200
        assert body == blob

        # lookup through the master
        vid = assign["fid"].split(",")[0]
        status, lookup = http_json(master_url(master, f"/dir/lookup?volumeId={vid}"))
        assert status == 200
        assert any(l["url"] == assign["url"] for l in lookup["locations"])

    def test_etag_304(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}", data=b"etag me", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            etag = json.loads(r.read())["eTag"]
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}",
            headers={"If-None-Match": f'"{etag}"'},
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                status = r.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 304

    def test_wrong_cookie_404(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}", data=b"secret", method="POST"
        )
        urllib.request.urlopen(req, timeout=10).close()
        vid, key_cookie = assign["fid"].split(",")
        forged = f"{vid},{key_cookie[:-8]}{'0' * 8}"
        try:
            status, _ = http_get(f"http://{assign['url']}/{forged}")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404

    def test_delete(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        url = f"http://{assign['url']}/{assign['fid']}"
        urllib.request.urlopen(
            urllib.request.Request(url, data=b"doomed", method="POST"), timeout=10
        ).close()
        with urllib.request.urlopen(
            urllib.request.Request(url, method="DELETE"), timeout=10
        ) as r:
            assert r.status == 202
        try:
            status, _ = http_get(url)
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404

    def test_replicated_write_readable_from_all_copies(self, cluster):
        master, volume_servers = cluster
        status, assign = http_json(
            master_url(master, "/dir/assign?replication=001&collection=rep")
        )
        assert status == 200, assign
        url = f"http://{assign['url']}/{assign['fid']}"
        urllib.request.urlopen(
            urllib.request.Request(url, data=b"replicated!", method="POST"), timeout=10
        ).close()
        vid = int(assign["fid"].split(",")[0])
        deadline = time.time() + 5
        while time.time() < deadline:
            nodes = master.topology.lookup("rep", vid)
            if len(nodes) >= 2:
                break
            time.sleep(0.1)
        assert len(nodes) == 2
        for dn in nodes:
            status, body = http_get(f"http://{dn.url}/{assign['fid']}")
            assert status == 200 and body == b"replicated!"


class TestGrpcPlane:
    def test_lookup_and_statistics(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        vid = assign["fid"].split(",")[0]
        with grpc.insecure_channel(f"127.0.0.1:{master.grpc_port}") as ch:
            stub = rpc.master_stub(ch)
            resp = stub.LookupVolume(master_pb2.LookupVolumeRequest(vids=[vid]))
            assert resp.vid_locations[0].locations
            stats = stub.Statistics(master_pb2.StatisticsRequest())
            assert stats.total_size > 0

    def test_vacuum_via_grpc(self, cluster):
        master, volume_servers = cluster
        _, assign = http_json(master_url(master, "/dir/assign?collection=vac"))
        url = f"http://{assign['url']}/{assign['fid']}"
        urllib.request.urlopen(
            urllib.request.Request(url, data=b"x" * 5000, method="POST"), timeout=10
        ).close()
        urllib.request.urlopen(
            urllib.request.Request(url, method="DELETE"), timeout=10
        ).close()
        vid = int(assign["fid"].split(",")[0])
        vs = next(
            v for v in volume_servers if f"127.0.0.1:{v.port}" == assign["url"]
        )
        with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
            stub = rpc.volume_stub(ch)
            check = stub.VacuumVolumeCheck(
                volume_pb2.VacuumVolumeCheckRequest(volume_id=vid)
            )
            assert check.garbage_ratio > 0
            stub.VacuumVolumeCompact(
                volume_pb2.VacuumVolumeCompactRequest(volume_id=vid)
            )
            stub.VacuumVolumeCommit(
                volume_pb2.VacuumVolumeCommitRequest(volume_id=vid)
            )
            check = stub.VacuumVolumeCheck(
                volume_pb2.VacuumVolumeCheckRequest(volume_id=vid)
            )
            assert check.garbage_ratio == 0

    def test_submit_http(self, cluster):
        """POST /submit on the master: assign + proxied upload in one
        call (master_server.go:116), multipart and raw bodies."""
        master, _ = cluster
        payload = b"one-liner upload " * 100
        boundary = "testsubmitboundary"
        body = (
            f"--{boundary}\r\n"
            'Content-Disposition: form-data; name="file"; filename="hello.txt"\r\n'
            "Content-Type: text/plain\r\n\r\n"
        ).encode() + payload + f"\r\n--{boundary}--\r\n".encode()
        req = urllib.request.Request(
            master_url(master, "/submit?collection=sub"),
            data=body,
            method="POST",
            headers={"Content-Type": f"multipart/form-data; boundary={boundary}"},
        )
        with urllib.request.urlopen(req, timeout=20) as r:
            res = json.loads(r.read())
        assert res.get("fid") and res.get("size") == len(payload), res
        assert res.get("fileName") == "hello.txt"
        status, got = http_get(f"http://{res['fileUrl']}")
        assert status == 200 and got == payload

        # raw-body submit (no multipart): payload passes through whole
        req = urllib.request.Request(
            master_url(master, "/submit"), data=b"rawbytes", method="POST"
        )
        with urllib.request.urlopen(req, timeout=20) as r:
            res = json.loads(r.read())
        assert res.get("size") == len(b"rawbytes")
        status, got = http_get(f"http://{res['fileUrl']}")
        assert status == 200 and got == b"rawbytes"

    def test_vol_vacuum_http(self, cluster):
        """GET /vol/vacuum?garbageThreshold= forces a sweep now
        (master_server.go:117); live data survives, garbage is gone."""
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign?collection=vh"))
        dead_url = f"http://{assign['url']}/{assign['fid']}"
        urllib.request.urlopen(
            urllib.request.Request(dead_url, data=b"g" * 50_000, method="POST"),
            timeout=10,
        ).close()
        _, keep = http_json(master_url(master, "/dir/assign?collection=vh"))
        keep_url = f"http://{keep['url']}/{keep['fid']}"
        urllib.request.urlopen(
            urllib.request.Request(keep_url, data=b"live", method="POST"),
            timeout=10,
        ).close()
        urllib.request.urlopen(
            urllib.request.Request(dead_url, method="DELETE"), timeout=10
        ).close()

        _, res = http_json(
            master_url(master, "/vol/vacuum?garbageThreshold=0.001")
        )
        assert res.get("vacuumed", 0) >= 1, res
        assert "Topology" in res
        status, got = http_get(keep_url)
        assert status == 200 and got == b"live"
        with pytest.raises(urllib.error.HTTPError) as exc:
            http_get(dead_url)
        assert exc.value.code == 404

    def test_batch_delete(self, cluster):
        master, _ = cluster
        fids = []
        for _ in range(3):
            _, assign = http_json(master_url(master, "/dir/assign?collection=bd"))
            url = f"http://{assign['url']}/{assign['fid']}"
            urllib.request.urlopen(
                urllib.request.Request(url, data=b"bulk", method="POST"), timeout=10
            ).close()
            fids.append((assign["url"], assign["fid"]))
        by_server: dict[str, list[str]] = {}
        for url, fid in fids:
            by_server.setdefault(url, []).append(fid)
        for url, server_fids in by_server.items():
            host, _, port = url.partition(":")
            with grpc.insecure_channel(f"{host}:{int(port) + 10000}") as ch:
                resp = rpc.volume_stub(ch).BatchDelete(
                    volume_pb2.BatchDeleteRequest(file_ids=server_fids)
                )
            assert all(r.status == 202 for r in resp.results)


class TestEcLifecycle:
    def test_encode_spread_degraded_read(self, cluster):
        """The EC pipeline over the wire: seal → generate shards →
        copy/spread to peers → mount → delete source → read needle
        through remote-shard fan-in (command_ec_encode.go:25-36)."""
        master, volume_servers = cluster
        _, assign = http_json(master_url(master, "/dir/assign?collection=ecc"))
        url = f"http://{assign['url']}/{assign['fid']}"
        payload = b"erasure coded payload " * 500
        urllib.request.urlopen(
            urllib.request.Request(url, data=payload, method="POST"), timeout=10
        ).close()
        vid = int(assign["fid"].split(",")[0])
        source = next(
            v for v in volume_servers if f"127.0.0.1:{v.port}" == assign["url"]
        )
        others = [v for v in volume_servers if v is not source]

        with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
            stub = rpc.volume_stub(ch)
            stub.VolumeMarkReadonly(volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid))
            stub.VolumeEcShardsGenerate(
                volume_pb2.VolumeEcShardsGenerateRequest(volume_id=vid, collection="ecc")
            )

        # spread: shards 0-6 stay on source, 7-13 to the first peer
        peer = others[0]
        with grpc.insecure_channel(f"127.0.0.1:{peer.grpc_port}") as ch:
            rpc.volume_stub(ch).VolumeEcShardsCopy(
                volume_pb2.VolumeEcShardsCopyRequest(
                    volume_id=vid,
                    collection="ecc",
                    shard_ids=list(range(7, 14)),
                    copy_ecx_file=True,
                    source_data_node=f"127.0.0.1:{source.port}",
                )
            )
            rpc.volume_stub(ch).VolumeEcShardsMount(
                volume_pb2.VolumeEcShardsMountRequest(
                    volume_id=vid, collection="ecc", shard_ids=list(range(7, 14))
                )
            )
        with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
            stub = rpc.volume_stub(ch)
            stub.VolumeEcShardsDelete(
                volume_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection="ecc", shard_ids=list(range(7, 14))
                )
            )
            stub.VolumeEcShardsMount(
                volume_pb2.VolumeEcShardsMountRequest(
                    volume_id=vid, collection="ecc", shard_ids=list(range(0, 7))
                )
            )
            # remove the original volume (the EC set replaces it)
            stub.VolumeDelete(volume_pb2.VolumeDeleteRequest(volume_id=vid))

        # wait for heartbeats to report the shard split to the master
        deadline = time.time() + 45
        while time.time() < deadline:
            locs = master.topology.lookup_ec_shards(vid)
            if locs is not None and all(locs.locations[i] for i in range(14)):
                break
            time.sleep(0.1)
        locs = master.topology.lookup_ec_shards(vid)
        assert locs is not None

        # read through the source server: needs shards 7-13 remotely
        status, body = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200
        assert body == payload

        # and through the peer (needs 0-6 remotely)
        status, body = http_get(f"http://127.0.0.1:{peer.port}/{assign['fid']}")
        assert status == 200
        assert body == payload

        # EC DELETE must enforce the cookie like the normal path
        vid_str, key_cookie = assign["fid"].split(",")
        forged = f"{vid_str},{key_cookie[:-8]}{'f' * 8}"
        try:
            with urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{assign['url']}/{forged}", method="DELETE"
                ),
                timeout=10,
            ) as r:
                status = r.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 409
        # blob still readable after the rejected delete
        status, body = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200 and body == payload


class TestJwtSignedWrites:
    """With jwt signing enabled cluster-wide, internal writers (filer
    auto-chunk, submit) must carry the assign-issued write token —
    the reference returns `auth` in assign results and forwards it on
    upload (security.GenJwt; master_server_handlers.go + upload_content.go)."""

    @pytest.fixture()
    def jwt_cluster(self, tmp_path_factory):
        from seaweedfs_tpu.security.guard import Guard

        key = "test-signing-key"
        master_port = free_port()
        master = MasterServer(
            port=master_port,
            volume_size_limit_mb=64,
            guard=Guard(signing_key=key, expires_after_sec=30),
        )
        master.start()
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp("jwtvs"))],
            port=free_port(),
            master=f"127.0.0.1:{master_port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            guard=Guard(signing_key=key, expires_after_sec=30),
        )
        vs.start()
        deadline = time.time() + 45
        while time.time() < deadline and len(master.topology.data_nodes()) < 1:
            time.sleep(0.05)
        yield master, vs
        vs.stop()
        master.stop()

    def test_grpc_assign_carries_auth_and_upload_succeeds(self, jwt_cluster):
        from seaweedfs_tpu.client import operation as op

        master, vs = jwt_cluster
        ar = op.assign(f"127.0.0.1:{master.port}")
        assert ar.auth, "gRPC AssignResponse must carry the write JWT"

        # unauthenticated POST is rejected...
        bad = op.upload(f"{ar.url}/{ar.fid}", b"denied")
        assert bad.error
        # ...the assign-issued token is accepted
        good = op.upload(f"{ar.url}/{ar.fid}", b"hello jwt", jwt=ar.auth)
        assert not good.error and good.size > 0

    def test_replicated_signed_write_forwards_jwt_and_mime(
        self, jwt_cluster, tmp_path_factory
    ):
        """The replica hop must forward Authorization and Content-Type
        from the incoming request (store_replicate.go keeps the url and
        headers) — regression for the FastHeaders lowercased-key map
        silently dropping both on dict.get('Content-Type')."""
        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.security.guard import Guard

        master, vs = jwt_cluster
        vs2 = VolumeServer(
            [str(tmp_path_factory.mktemp("jwtvs2"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            guard=Guard(signing_key="test-signing-key", expires_after_sec=30),
        )
        vs2.start()
        try:
            deadline = time.time() + 45
            while time.time() < deadline and len(master.topology.data_nodes()) < 2:
                time.sleep(0.05)
            ar = op.assign(f"127.0.0.1:{master.port}", replication="001")
            ur = op.upload(
                f"{ar.url}/{ar.fid}", b"replicated+signed", jwt=ar.auth,
                mime="text/x-custom",
            )
            assert not ur.error, ur.error
            # readable from BOTH replicas, with the mime preserved
            for server in (vs, vs2):
                status, body = http_get(
                    f"http://127.0.0.1:{server.port}/{ar.fid}"
                )
                assert status == 200 and body == b"replicated+signed"
        finally:
            vs2.stop()

    def test_filer_writes_with_signing_enabled(self, jwt_cluster, tmp_path):
        import urllib.request

        from seaweedfs_tpu.server.filer_server import FilerServer

        master, vs = jwt_cluster
        filer = FilerServer(
            [f"127.0.0.1:{master.port}"], port=free_port(), store="memory"
        )
        filer.start()
        try:
            url = f"http://127.0.0.1:{filer.port}/dir/hello.txt"
            req = urllib.request.Request(url, data=b"filer payload", method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status in (200, 201)
            with urllib.request.urlopen(url, timeout=10) as r:
                assert r.read() == b"filer payload"
        finally:
            filer.stop()

    def test_submit_with_signing_enabled(self, jwt_cluster):
        from seaweedfs_tpu.client import operation as op

        master, vs = jwt_cluster
        res = op.submit_file(
            f"127.0.0.1:{master.port}", "sub.bin", b"x" * 2048, max_mb=0
        )
        assert not res.error
        assert res.fid

    def test_chunked_submit_with_signing_enabled(self, jwt_cluster):
        """The chunked branch: per-piece uploads and the chunk-manifest
        needle must each carry their assign-issued token."""
        from seaweedfs_tpu.client import operation as op

        master, vs = jwt_cluster
        payload = bytes(range(256)) * 8192  # 2 MiB > 1 MB chunk limit
        res = op.submit_file(
            f"127.0.0.1:{master.port}", "chunked.bin", payload, max_mb=1
        )
        assert not res.error
        assert res.fid


class TestDegradedParallelRead:
    """The needle's data lives in shard 0's stripe. Shard 0 is placed
    ONLY on a sacrificial server: healthy reads fetch it remotely;
    after killing that server the read must reconstruct shard 0's
    interval from the 13 surviving shards in one parallel fan-out
    round, and the dead location is forgotten
    (store_ec.go:319-359 + forgetShardId/cache tiers)."""

    def test_read_after_losing_shard_holder(self, cluster, tmp_path_factory):
        from seaweedfs_tpu.server.volume_server import VolumeServer

        master, volume_servers = cluster
        # write the needle BEFORE the sacrificial server joins, so the
        # assign can only land on the long-lived fixture servers
        _, assign = http_json(master_url(master, "/dir/assign?collection=ecd"))
        url = f"http://{assign['url']}/{assign['fid']}"
        payload = b"degraded parallel read " * 700
        urllib.request.urlopen(
            urllib.request.Request(url, data=payload, method="POST"), timeout=10
        ).close()
        vid = int(assign["fid"].split(",")[0])
        source = next(
            v for v in volume_servers if f"127.0.0.1:{v.port}" == assign["url"]
        )
        peer = next(v for v in volume_servers if v is not source)

        extra = VolumeServer(
            [str(tmp_path_factory.mktemp("sacrifice"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
        extra.start()
        deadline = time.time() + 45
        while time.time() < deadline and len(master.topology.data_nodes()) < 4:
            time.sleep(0.05)

        with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
            stub = rpc.volume_stub(ch)
            stub.VolumeMarkReadonly(volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid))
            stub.VolumeEcShardsGenerate(
                volume_pb2.VolumeEcShardsGenerateRequest(volume_id=vid, collection="ecd")
            )

        def copy_mount(target, shard_ids):
            with grpc.insecure_channel(f"127.0.0.1:{target.grpc_port}") as ch:
                rpc.volume_stub(ch).VolumeEcShardsCopy(
                    volume_pb2.VolumeEcShardsCopyRequest(
                        volume_id=vid,
                        collection="ecd",
                        shard_ids=shard_ids,
                        copy_ecx_file=True,
                        source_data_node=f"127.0.0.1:{source.port}",
                    )
                )
                rpc.volume_stub(ch).VolumeEcShardsMount(
                    volume_pb2.VolumeEcShardsMountRequest(
                        volume_id=vid, collection="ecd", shard_ids=shard_ids
                    )
                )

        # spread: shard 0 ONLY on the sacrifice, 10-13 on a peer,
        # 1-9 stay on the source
        copy_mount(extra, [0])
        copy_mount(peer, list(range(10, 14)))
        with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
            stub = rpc.volume_stub(ch)
            stub.VolumeEcShardsDelete(
                volume_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection="ecd", shard_ids=[0] + list(range(10, 14))
                )
            )
            stub.VolumeEcShardsMount(
                volume_pb2.VolumeEcShardsMountRequest(
                    volume_id=vid, collection="ecd", shard_ids=list(range(1, 10))
                )
            )
            stub.VolumeDelete(volume_pb2.VolumeDeleteRequest(volume_id=vid))

        # master must know all 14 shard locations before the read
        deadline = time.time() + 45
        while time.time() < deadline:
            locs = master.topology.lookup_ec_shards(vid)
            if locs is not None and all(locs.locations[i] for i in range(14)):
                break
            time.sleep(0.1)

        # healthy read: shard 0's interval is fetched from the sacrifice
        status, body = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200 and body == payload
        ev = source.store.find_ec_volume(vid)
        with ev.shard_locations_lock:
            assert any(
                f"127.0.0.1:{extra.port}" in urls
                for urls in ev.shard_locations.values()
            ), "healthy read should have cached the sacrifice's location"

        # kill the shard-0 holder: the read must reconstruct from the
        # 13 survivors (9 local + 4 on the peer) in one parallel round
        extra.stop()
        status, body = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200 and body == payload

        # the failed fetch forgot the dead location (or the refresh
        # already dropped it after the master unregistered the node)
        with ev.shard_locations_lock:
            assert not any(
                f"127.0.0.1:{extra.port}" in urls
                for urls in ev.shard_locations.values()
            )


class TestMultipartUploads:
    """`curl -F file=@x` form uploads (needle.go:85 ParseUpload)."""

    def _multipart_body(self, filename, payload, mime="text/plain"):
        boundary = "weedformboundary123"
        body = (
            f"--{boundary}\r\n"
            f'Content-Disposition: form-data; name="file"; filename="{filename}"\r\n'
            f"Content-Type: {mime}\r\n\r\n"
        ).encode() + payload + f"\r\n--{boundary}--\r\n".encode()
        return body, f"multipart/form-data; boundary={boundary}"

    def test_volume_multipart_post(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        payload = b"multipart payload bytes" * 40
        body, ctype = self._multipart_body("form.txt", payload)
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}",
            data=body,
            method="POST",
            headers={"Content-Type": ctype},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        status, got = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200
        assert got == payload  # boundary bytes must NOT be stored

    def test_raw_post_still_works(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}",
            data=b"raw body",
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=10).close()
        _, got = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert got == b"raw body"


class TestMultipartIntoDirectory:
    def test_form_upload_into_filer_directory(self, cluster, tmp_path_factory):
        """curl -F file=@x.txt http://filer/dir/ stores dir/x.txt."""
        from seaweedfs_tpu.server.filer_server import FilerServer

        master, _ = cluster
        filer = FilerServer(
            [f"127.0.0.1:{master.port}"], port=free_port(), store="memory"
        )
        filer.start()
        try:
            boundary = "bb123"
            payload = b"into the directory"
            body = (
                f"--{boundary}\r\n"
                'Content-Disposition: form-data; name="file"; filename="x.txt"\r\n'
                "Content-Type: text/plain\r\n\r\n"
            ).encode() + payload + f"\r\n--{boundary}--\r\n".encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{filer.port}/up/",
                data=body,
                method="POST",
                headers={
                    "Content-Type": f"multipart/form-data; boundary={boundary}"
                },
            )
            urllib.request.urlopen(req, timeout=10).close()
            status, got = http_get(f"http://127.0.0.1:{filer.port}/up/x.txt")
            assert status == 200 and got == payload
        finally:
            filer.stop()


class TestStatusUi:
    def test_master_and_volume_html_pages(self, cluster):
        master, volume_servers = cluster
        status, body = http_get(master_url(master, "/"))
        assert status == 200
        text = body.decode()
        assert "<html" in text and "Topology" in text
        assert f"127.0.0.1:{volume_servers[0].port}" in text

        status, body = http_get(f"http://127.0.0.1:{volume_servers[0].port}/ui/index.html")
        assert status == 200
        assert "Volume Server" in body.decode()


class TestNodeLiveness:
    """The master's liveness sweep: a volume server whose heartbeat
    STREAM never tears down (frozen process, half-open TCP) must still
    be unregistered after node_timeout of silence — stream teardown
    alone leaves writes routed at a dead node until kernel keepalive."""

    def test_silent_node_swept_and_locations_dropped(self):
        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, node_timeout=0.6
        )
        master.start()
        try:
            dn = master.topology.register_data_node(
                ip="127.0.0.1", port=65000, max_volumes=10
            )
            from seaweedfs_tpu.storage.store import VolumeInfo

            master.topology.sync_volumes(
                dn,
                [VolumeInfo(id=5, size=0, collection="", file_count=1,
                            delete_count=0, deleted_byte_count=0,
                            read_only=False, replica_placement=0,
                            version=3, ttl=0)],
            )
            assert master.topology.lookup("", 5), "volume 5 should be locatable"
            dn.last_seen = time.time() - 10  # silent for much longer than 0.6s

            deadline = time.time() + 10
            while time.time() < deadline and master.topology.data_nodes():
                time.sleep(0.05)
            assert not master.topology.data_nodes(), "silent node never swept"
            assert not master.topology.lookup("", 5), "stale location still served"
        finally:
            master.stop()

    def test_swept_node_reregisters_on_next_beat(self, tmp_path):
        """A frozen-then-woken server keeps its old stream: the
        Heartbeat loop must notice the sweep detached its node object
        and register afresh instead of mutating an orphan."""
        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, node_timeout=0
        )
        master.start()
        try:
            dn = master.topology.register_data_node(
                ip="127.0.0.1", port=65001, max_volumes=10
            )
            master.topology.unregister_data_node(dn)  # what the sweep does
            assert dn.parent is None, "unregister must mark detachment"

            # the live-stream path registers a fresh node on the next beat
            vs = VolumeServer(
                [str(tmp_path)],
                port=free_port(),
                master=f"127.0.0.1:{master.port}",
                heartbeat_interval=0.1,
                max_volume_counts=[10],
            )
            vs.start()
            try:
                deadline = time.time() + 15
                while time.time() < deadline:
                    nodes = master.topology.data_nodes()
                    if any(n.port == vs.port for n in nodes):
                        break
                    time.sleep(0.05)
                assert any(
                    n.port == vs.port and n.parent is not None
                    for n in master.topology.data_nodes()
                )
            finally:
                vs.stop()
        finally:
            master.stop()


class TestUrlAddressingForms:
    """The reference's public URL forms and read-path conditionals
    (server/common.go:152 parseURLPath, needle.go:149 ParsePath,
    volume_server_handlers_read.go:102-162): comma/slash addressing,
    extensions, explicit filenames, `_delta` fids, If-Modified-Since,
    ETag-MD5, pairs-as-headers, and stored-gzip serving."""

    def _put(self, cluster, data, suffix="", headers=None):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}{suffix}",
            data=data,
            method="POST",
            # octet-stream is never STORED as a mime (needle.go:96), so
            # the extension-guess path below stays reachable — urllib
            # would otherwise default to x-www-form-urlencoded
            headers=headers or {"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        return assign

    def test_slash_and_extension_forms(self, cluster):
        a = self._put(cluster, b"formful payload")
        vid, fid = a["fid"].split(",")
        base = f"http://{a['url']}"
        # comma form with extension
        status, got = http_get(f"{base}/{vid},{fid}.txt")
        assert (status, got) == (200, b"formful payload")
        # slash form, with and without extension
        status, got = http_get(f"{base}/{vid}/{fid}")
        assert (status, got) == (200, b"formful payload")
        status, got = http_get(f"{base}/{vid}/{fid}.txt")
        assert (status, got) == (200, b"formful payload")
        # slash form with an explicit filename: body + disposition +
        # mime guessed from the extension
        with urllib.request.urlopen(
            f"{base}/{vid}/{fid}/pretty%20name.txt", timeout=10
        ) as r:
            assert r.read() == b"formful payload"
            assert "pretty name.txt" in r.headers.get("Content-Disposition", "")
            assert r.headers["Content-Type"].startswith("text/plain")
        # dl=true flips the disposition to attachment
        with urllib.request.urlopen(
            f"{base}/{vid}/{fid}/x.txt?dl=true", timeout=10
        ) as r:
            assert r.headers["Content-Disposition"].startswith("attachment")

    def test_delta_fid_addressing(self, cluster):
        """`fid_N` reads needle id+N — the sub-fid scheme chunked
        uploads mint from one count=N assign (needle.go:149)."""
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign?count=3"))
        vid, fid = assign["fid"].split(",")
        base = f"http://{assign['url']}"
        for i, payload in enumerate([b"chunk zero", b"chunk one", b"chunk two"]):
            suffix = "" if i == 0 else f"_{i}"
            req = urllib.request.Request(
                f"{base}/{vid},{fid}{suffix}", data=payload, method="POST"
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 201
        for i, payload in enumerate([b"chunk zero", b"chunk one", b"chunk two"]):
            suffix = "" if i == 0 else f"_{i}"
            status, got = http_get(f"{base}/{vid},{fid}{suffix}")
            assert (status, got) == (200, payload), i

    def test_if_modified_since(self, cluster):
        a = self._put(cluster, b"conditional body")
        url = f"http://{a['url']}/{a['fid']}"
        with urllib.request.urlopen(url, timeout=10) as r:
            lm = r.headers["Last-Modified"]
        req = urllib.request.Request(url, headers={"If-Modified-Since": lm})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 304
        # an older validator still gets the body
        req = urllib.request.Request(
            url, headers={"If-Modified-Since": "Mon, 01 Jan 2001 00:00:00 GMT"}
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.read() == b"conditional body"

    def test_etag_md5_opt_in(self, cluster):
        import hashlib

        a = self._put(cluster, b"md5 etag body")
        url = f"http://{a['url']}/{a['fid']}"
        req = urllib.request.Request(url, headers={"ETag-MD5": "True"})
        with urllib.request.urlopen(req, timeout=10) as r:
            want = hashlib.md5(b"md5 etag body").hexdigest()
            assert r.headers["ETag"] == f'"{want}"'

    def test_pairs_surface_as_response_headers(self, cluster):
        """Stored extended pairs come back as response headers
        (volume_server_handlers_read.go:123-133)."""
        import json as _json

        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        url = f"http://{assign['url']}/{assign['fid']}"
        # store pairs via the gRPC write surface? the HTTP POST path
        # does not take pairs — write the needle directly through the
        # store like the reference's needle pairs tests do
        from seaweedfs_tpu.storage.file_id import FileId
        from seaweedfs_tpu.storage.needle import Needle

        fid = FileId.parse(assign["fid"])
        n = Needle(cookie=fid.cookie, id=fid.key, data=b"paired body")
        n.pairs = _json.dumps({"X-Custom-One": "alpha", "X-Custom-Two": "beta"}).encode()
        n.set_has_pairs()
        # find the owning in-process server and write through its store
        for vs in cluster[1]:
            if f"{vs.host}:{vs.port}" == assign["url"]:
                vs.store.write_needle(fid.volume_id, n)
                break
        with urllib.request.urlopen(url, timeout=10) as r:
            assert r.read() == b"paired body"
            assert r.headers["X-Custom-One"] == "alpha"
            assert r.headers["X-Custom-Two"] == "beta"

    def test_gzipped_needle_serving(self, cluster):
        """Stored-gzipped needles: gzip-accepting clients get the raw
        stream + Content-Encoding, others get transparent decompression,
        and an explicit .gz URL always gets the stored bytes."""
        import gzip as _gzip

        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        from seaweedfs_tpu.storage.file_id import FileId
        from seaweedfs_tpu.storage.needle import Needle

        fid = FileId.parse(assign["fid"])
        plain = b"gzip me please " * 50
        packed = _gzip.compress(plain)
        n = Needle(cookie=fid.cookie, id=fid.key, data=packed)
        n.set_gzipped()
        for vs in cluster[1]:
            if f"{vs.host}:{vs.port}" == assign["url"]:
                vs.store.write_needle(fid.volume_id, n)
                break
        url = f"http://{assign['url']}/{assign['fid']}"
        # no Accept-Encoding: transparently decompressed
        req = urllib.request.Request(url)
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.read() == plain
            assert r.headers.get("Content-Encoding") is None
        # gzip-accepting client: raw stream passthrough
        req = urllib.request.Request(url, headers={"Accept-Encoding": "gzip"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers.get("Content-Encoding") == "gzip"
            assert r.read() == packed
        # .gz extension: the stored bytes, no decoding header games
        vid, fid_hex = assign["fid"].split(",")
        req = urllib.request.Request(f"http://{assign['url']}/{vid},{fid_hex}.gz")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.read() == packed


class TestTransparentCompression:
    """The write path's server-side compression (util/compression.py,
    the reference's IsGzippable + parseMultipart auto-gzip): text
    uploads store gzipped+flagged, binary uploads stay raw, and every
    read surface round-trips the original bytes."""

    def _assign(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        return assign

    def _upload(self, assign, data, filename="", ctype="application/octet-stream"):
        url = f"http://{assign['url']}/{assign['fid']}"
        if filename:
            url += f"?filename={filename}"
        req = urllib.request.Request(
            url, data=data, method="POST", headers={"Content-Type": ctype}
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201

    def _stored_needle(self, cluster, assign):
        from seaweedfs_tpu.storage.file_id import FileId

        fid = FileId.parse(assign["fid"])
        for vs in cluster[1]:
            if f"{vs.host}:{vs.port}" == assign["url"]:
                return vs.store.read_needle(fid.volume_id, fid.key)
        raise AssertionError("owner not found")

    def test_text_upload_stored_gzipped_and_roundtrips(self, cluster):
        import gzip

        text = b"compress me, I repeat myself " * 100
        a = self._assign(cluster)
        self._upload(a, text, filename="notes.txt", ctype="text/plain")
        n = self._stored_needle(cluster, a)
        assert n.is_gzipped(), "text should be stored compressed"
        assert gzip.decompress(bytes(n.data)) == text
        # plain client gets the original bytes
        status, got = http_get(f"http://{a['url']}/{a['fid']}")
        assert (status, got) == (200, text)

    def test_binary_upload_stays_raw(self, cluster):
        blob = bytes(range(256)) * 20
        a = self._assign(cluster)
        self._upload(a, blob, filename="blob.bin")
        n = self._stored_needle(cluster, a)
        assert not n.is_gzipped()
        status, got = http_get(f"http://{a['url']}/{a['fid']}")
        assert (status, got) == (200, blob)

    def test_pre_gzipped_upload_respected(self, cluster):
        import gzip

        plain = b"pre-compressed content " * 40
        packed = gzip.compress(plain, mtime=0)
        a = self._assign(cluster)
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}",
            data=packed,
            method="POST",
            headers={
                "Content-Type": "text/plain",
                "Content-Encoding": "gzip",
            },
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        n = self._stored_needle(cluster, a)
        assert n.is_gzipped() and bytes(n.data) == packed
        status, got = http_get(f"http://{a['url']}/{a['fid']}")
        assert (status, got) == (200, plain)

    def test_seaweed_pair_headers_roundtrip(self, cluster):
        """Seaweed-* request headers persist as pairs and come back as
        response headers (needle.go PairNamePrefix)."""
        a = self._assign(cluster)
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}",
            data=bytes(range(256)),
            method="POST",
            headers={
                "Content-Type": "application/octet-stream",
                "Seaweed-Origin": "unit-test",
                "Seaweed-Tag": "42",
            },
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        with urllib.request.urlopen(
            f"http://{a['url']}/{a['fid']}", timeout=10
        ) as r:
            assert r.headers["origin"] == "unit-test"
            assert r.headers["tag"] == "42"

    def test_ts_param_overrides_mtime(self, cluster):
        a = self._assign(cluster)
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}?ts=1500000000",
            data=bytes(range(256)),
            method="POST",
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        assert self._stored_needle(cluster, a).last_modified == 1500000000

    def test_ttl_param_stored_and_expiry_enforced(self, cluster):
        a = self._assign(cluster)
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}?ttl=5m",
            data=bytes(range(256)),
            method="POST",
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        n = self._stored_needle(cluster, a)
        assert n.has_ttl() and str(n.ttl) == "5m"
        # a back-dated ts + ttl is already expired: the read path must
        # 404 it (read-path expiry semantics, storage/ttl.py)
        a2 = self._assign(cluster)
        req = urllib.request.Request(
            f"http://{a2['url']}/{a2['fid']}?ts=1500000000&ttl=5m",
            data=bytes(range(256)),
            method="POST",
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{a2['url']}/{a2['fid']}", timeout=10)
        assert ei.value.code == 404


class TestMasterRedirectAndVolStatus:
    """Master conveniences: GET /<fid> 301s to an owning volume server
    (master_server.go:121 redirectHandler) and /vol/status dumps the
    ToVolumeMap shape (topology_map.go:30)."""

    def test_fid_redirect(self, cluster):
        master, _ = cluster
        _, assign = http_json(master_url(master, "/dir/assign"))
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{assign['url']}/{assign['fid']}",
                data=bytes(range(256)),
                method="POST",
            ),
            timeout=10,
        ).read()
        # urllib follows the 301 chain master -> volume
        with urllib.request.urlopen(
            master_url(master, f"/{assign['fid']}"), timeout=10
        ) as r:
            assert r.read() == bytes(range(256))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(master_url(master, "/999,0123456789"), timeout=10)
        assert ei.value.code == 404

    def test_vol_status_shape(self, cluster):
        master, _ = cluster
        _, d = http_json(master_url(master, "/vol/status"))
        vols = d["Volumes"]
        assert vols["Max"] > 0 and "DataCenters" in vols
        some_rack = next(iter(next(iter(vols["DataCenters"].values())).values()))
        some_node_vols = next(iter(some_rack.values()))
        assert isinstance(some_node_vols, list)
        if some_node_vols:
            assert {"Id", "Size", "Collection"} <= set(some_node_vols[0])
