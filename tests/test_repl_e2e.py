"""Cross-cluster replication as a standing workload (ISSUE 17): S3
writes on a source cluster flow through the partitioned logqueue into
a SECOND live cluster that serves them byte-identical — then the chaos
legs: a network partition mid-replication (bounded failure, heal →
convergence, no acked-write loss), replication lag racing the vacuum,
the replication-lag SLO alert + `replication.lag` shell verb, and the
WEED_REPL kill switch.
"""

from __future__ import annotations

import io
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu import notification
from seaweedfs_tpu.analysis.chaos import ProxyPair
from seaweedfs_tpu.notification.logqueue import PartitionedLogQueue
from seaweedfs_tpu.replication.replicate_runner import (
    _consume_logqueue,
    repl_enabled,
    run_replicate,
)
from seaweedfs_tpu.replication.replicator import Replicator
from seaweedfs_tpu.replication.sink import FilerSink
from seaweedfs_tpu.replication.source import FilerSource
from seaweedfs_tpu.s3api import S3ApiServer
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.command_env import CommandEnv
from seaweedfs_tpu.shell.commands import run_command
from seaweedfs_tpu.util import deadline as _deadline
from seaweedfs_tpu.util.availability import free_port

from tests.chaos import wait_for

GROUP = "replicate"


class _Cluster:
    def __init__(self, tmp, name, telemetry=False):
        self.master = MasterServer(
            port=free_port(),
            volume_size_limit_mb=64,
            vacuum_interval=0,
            telemetry_interval=0.4 if telemetry else 0.0,
            telemetry_kwargs=(
                {"repl_lag_threshold": 2.0} if telemetry else None
            ),
        )
        self.master.start()
        maddr = f"127.0.0.1:{self.master.port}"
        self.vs = VolumeServer(
            [str(tmp.mktemp(f"{name}vol"))],
            port=free_port(),
            master=maddr,
            heartbeat_interval=0.2,
            max_volume_counts=[20],
        )
        self.vs.start()
        fport = free_port()
        self.filer = FilerServer(
            [maddr], port=fport, store="memory", announce_interval=0.3
        )
        self.filer.start()
        self.filer_addr = f"127.0.0.1:{fport}"
        assert wait_for(lambda: self.master.topology.data_nodes(), 45)

    def stop(self):
        self.filer.stop()
        self.vs.stop()
        self.master.stop()


@pytest.fixture(scope="module")
def repl_world(tmp_path_factory):
    """src cluster (telemetry master, S3 gateway, logqueue-armed filer
    — armed per-test) + dst cluster + the shared durable queue."""
    lq = PartitionedLogQueue(
        str(tmp_path_factory.mktemp("replq")), partitions=4
    )
    # the filer snapshots whether a notification queue exists when it
    # is constructed — arm it around the SOURCE build only, so just
    # the source publishes (the sink cluster must not echo applies
    # back into the queue)
    notification.queue = lq
    src = _Cluster(tmp_path_factory, "src", telemetry=True)
    notification.queue = None
    dst = _Cluster(tmp_path_factory, "dst")
    s3 = S3ApiServer(filer=src.filer_addr, port=free_port())
    s3.start()
    notification.queue = None
    try:
        yield lq, src, dst, s3
    finally:
        notification.queue = None
        s3.stop()
        dst.stop()
        src.stop()


class _armed:
    """Route src-filer mutations into the logqueue for the duration."""

    def __init__(self, lq):
        self.lq = lq

    def __enter__(self):
        notification.queue = self.lq

    def __exit__(self, *exc):
        notification.queue = None


def _req(url, method="GET", data=None, headers=None, timeout=15):
    r = urllib.request.Request(url, data=data, method=method)
    for k, v in (headers or {}).items():
        r.add_header(k, v)
    return urllib.request.urlopen(r, timeout=timeout)


def _replicator(src, dst_addr):
    return Replicator(
        FilerSource(src.filer_addr, directory="/buckets"),
        FilerSink(dst_addr, directory="/backup"),
    )


def _drain(lq, replicator, idle=0.5):
    return _consume_logqueue(
        lq, replicator, poll_interval=0.05, stop_after_idle=idle
    )


class TestS3WriteToRemoteCluster:
    def test_write_flows_and_remote_serves_byte_identical(self, repl_world):
        lq, src, dst, s3 = repl_world
        body = bytes((i * 37) & 0xFF for i in range(120_000))
        with _armed(lq):
            _req(f"http://127.0.0.1:{s3.port}/replbkt", "PUT").close()
            _req(
                f"http://127.0.0.1:{s3.port}/replbkt/pic.bin", "PUT", data=body
            ).close()
        assert lq.depth(GROUP) >= 1
        assert _drain(lq, _replicator(src, dst.filer_addr)) == 0
        assert lq.depth(GROUP) == 0
        # the REMOTE filer serves the object from its OWN volumes
        with _req(f"http://{dst.filer_addr}/backup/replbkt/pic.bin") as r:
            assert r.read() == body
        # …including through a remote S3 gateway over the mirror tree
        s3r = S3ApiServer(
            filer=dst.filer_addr, port=free_port(), buckets_path="/backup"
        )
        s3r.start()
        try:
            with _req(f"http://127.0.0.1:{s3r.port}/replbkt/pic.bin") as r:
                assert r.read() == body
            with _req(
                f"http://127.0.0.1:{s3r.port}/replbkt/pic.bin",
                headers={"Range": "bytes=100-299"},
            ) as r:
                assert r.status == 206
                assert r.read() == body[100:300]
        finally:
            s3r.stop()

    def test_delete_propagates(self, repl_world):
        lq, src, dst, s3 = repl_world
        with _armed(lq):
            _req(
                f"http://127.0.0.1:{s3.port}/replbkt/gone.bin",
                "PUT",
                data=b"to-be-deleted",
            ).close()
        assert _drain(lq, _replicator(src, dst.filer_addr)) == 0
        with _req(f"http://{dst.filer_addr}/backup/replbkt/gone.bin") as r:
            assert r.read() == b"to-be-deleted"
        with _armed(lq):
            _req(
                f"http://127.0.0.1:{s3.port}/replbkt/gone.bin", "DELETE"
            ).close()
        assert _drain(lq, _replicator(src, dst.filer_addr)) == 0
        with pytest.raises(urllib.error.HTTPError):
            _req(f"http://{dst.filer_addr}/backup/replbkt/gone.bin").close()

    def test_kill_switch_leaves_queue_intact(self, repl_world, monkeypatch):
        lq, src, dst, s3 = repl_world
        with _armed(lq):
            _req(
                f"http://127.0.0.1:{s3.port}/replbkt/later.bin",
                "PUT",
                data=b"after-reenable",
            ).close()
        depth = lq.depth(GROUP)
        assert depth >= 1
        monkeypatch.setenv("WEED_REPL", "0")
        assert not repl_enabled()
        # the consumer refuses to run — and consumes NOTHING, so
        # re-enabling later resumes from the committed cursor
        assert run_replicate(stop_after_idle=0.2) == 0
        assert lq.depth(GROUP) == depth
        monkeypatch.delenv("WEED_REPL")
        assert _drain(lq, _replicator(src, dst.filer_addr)) == 0
        with _req(f"http://{dst.filer_addr}/backup/replbkt/later.bin") as r:
            assert r.read() == b"after-reenable"


class TestPartitionMidReplication:
    def test_partition_stalls_then_heals_without_loss(self, repl_world):
        lq, src, dst, s3 = repl_world
        pair = ProxyPair(dst.filer_addr)
        try:
            repl = _replicator(src, pair.addr)
            payloads = {
                f"part{i}.bin": (f"partition-payload-{i} ".encode() * 500)
                for i in range(3)
            }
            with _armed(lq):
                for name, body in payloads.items():
                    _req(
                        f"http://127.0.0.1:{s3.port}/replbkt/{name}",
                        "PUT",
                        data=body,
                    ).close()
            pair.partition()
            # the sink's gRPC calls derive their timeout from the
            # ambient deadline — without it a blackholed connection
            # would park the drain forever
            with _deadline.scope(_deadline.Deadline.after(2.0)):
                rc = _consume_logqueue(
                    lq, repl, poll_interval=0.2, stop_after_idle=0.6
                )
            assert rc == 1  # stuck on failures, NOT clean-idle
            assert lq.depth(GROUP) > 0  # lag is visible, nothing lost
            pair.heal()
            assert _drain(lq, repl, idle=1.0) == 0
            assert lq.depth(GROUP) == 0
            # every acked write survived the partition
            for name, body in payloads.items():
                with _req(
                    f"http://{dst.filer_addr}/backup/replbkt/{name}"
                ) as r:
                    assert r.read() == body
        finally:
            pair.stop()


class TestLagVersusVacuum:
    def test_vacuum_during_lag_converges_without_acked_loss(self, repl_world):
        lq, src, dst, s3 = repl_world
        keep = b"survivor " * 3000
        with _armed(lq):
            _req(
                f"http://{src.filer_addr}/buckets/vac/keep.bin",
                "POST",
                data=keep,
            ).close()
            _req(
                f"http://{src.filer_addr}/buckets/vac/drop.bin",
                "POST",
                data=b"doomed " * 3000,
            ).close()
            # the replica is LAGGING (nothing drained yet) when the
            # source deletes drop.bin and vacuums its chunks away
            _req(
                f"http://{src.filer_addr}/buckets/vac/drop.bin", "DELETE"
            ).close()
        env = CommandEnv([f"127.0.0.1:{src.master.port}"])
        out = io.StringIO()
        run_command(env, "volume.vacuum -garbageThreshold 0.0001", out)
        # drain through the backlog: keep.bin must replicate intact;
        # drop.bin's create event can no longer fetch its vacuumed
        # chunks — it poisons out after the retry budget, then its
        # delete event applies, and BOTH clusters converge without it
        rc = _consume_logqueue(
            lq,
            _replicator(src, dst.filer_addr),
            poll_interval=0.05,
            stop_after_idle=4.0,
        )
        assert rc == 0
        assert lq.depth(GROUP) == 0
        with _req(f"http://{dst.filer_addr}/backup/vac/keep.bin") as r:
            assert r.read() == keep
        for filer, root in ((src.filer_addr, "/buckets"), (dst.filer_addr, "/backup")):
            with pytest.raises(urllib.error.HTTPError):
                _req(f"http://{filer}{root}/vac/drop.bin").close()


class TestLagAlertAndShell:
    def test_lag_gauge_alert_and_verb(self, repl_world):
        lq, src, dst, s3 = repl_world
        # stay armed for the whole test: the filer's /metrics prerender
        # hook samples notification.queue's consumer-group depth at
        # RENDER time, and the leader's collector scrapes on its own
        # schedule
        with _armed(lq):
            for i in range(4):
                _req(
                    f"http://127.0.0.1:{s3.port}/replbkt/lag{i}.bin",
                    "PUT",
                    data=b"backlog",
                ).close()
            assert lq.depth(GROUP) >= 3
            with _req(f"http://{src.filer_addr}/metrics") as r:
                metrics = r.read().decode()
            line = next(
                ln for ln in metrics.splitlines()
                if ln.startswith("weed_replication_lag_events")
            )
            assert float(line.rsplit(" ", 1)[1]) >= 3, line
            # the leader's collector trips RULE_REPL_LAG past the bound
            def alert_fired():
                alerts = src.master.telemetry.alerts.payload()
                return any(
                    a.get("Alert") == "replication_lag"
                    for a in alerts.get("Firing", [])
                )
            assert wait_for(alert_fired, 30), (
                src.master.telemetry.alerts.payload()
            )
            env = CommandEnv([f"127.0.0.1:{src.master.port}"])
            out = io.StringIO()
            run_command(env, "replication.lag", out)
            text = out.getvalue()
            assert "event(s) behind" in text, text
            assert "ALERT warning" in text, text
            # drain → lag falls to zero and the alert clears
            assert _drain(lq, _replicator(src, dst.filer_addr)) == 0
            assert wait_for(lambda: not alert_fired(), 30)
