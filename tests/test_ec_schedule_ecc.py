"""ISSUE 16 surfaces: compiled GF schedules (ec/schedule.py), the
`.ecc` scrub sidecar (ec/ecc_sidecar.py + scrub/verify.verify_ecc_stream
+ the ScrubEngine fast pass), the batched rebuild arms
(ec_stream.stream_rebuild_ec_files_batch: host pipeline + zero-thread
inline), and the 3-way host CRC-32C kernel (native/crc32c.c).
"""

import os
import random
import threading

import numpy as np
import pytest

from seaweedfs_tpu.ec import ec_files, ec_stream, ecc_sidecar
from seaweedfs_tpu.ec import schedule as sched
from seaweedfs_tpu.ec.codec import cpu_apply_matrix, new_encoder
from seaweedfs_tpu.scrub.verify import verify_ecc_stream
from seaweedfs_tpu.util.crc import _crc32c_py, crc32c, crc32c_combine


def _rs():
    try:
        return new_encoder(backend="native")
    except (ImportError, ValueError):
        return new_encoder(backend="cpu")


def _make_volume(d, name, size, rs, seed=5):
    base = os.path.join(str(d), name)
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    ec_files.write_ec_files(base, rs=rs)
    return base


def _shard_bytes(base):
    out = {}
    for sid in range(14):
        p = base + ec_files.to_ext(sid)
        if os.path.exists(p):
            with open(p, "rb") as f:
                out[sid] = f.read()
    return out


def _publish_sidecar(base, total=14):
    crcs = []
    for sid in range(total):
        with open(base + ec_files.to_ext(sid), "rb") as f:
            crcs.append(crc32c(f.read()))
    assert ecc_sidecar.write_sidecar(base, crcs, total_shards=total)
    return crcs


# ---------------------------------------------------------------------------
class TestSchedule:
    def test_matches_naive_apply(self):
        rs = _rs()
        rng = np.random.default_rng(3)
        inp = rng.integers(0, 256, (10, 8192), dtype=np.uint8)
        parity = np.asarray(rs.parity_rows, dtype=np.uint8)
        assert np.array_equal(
            sched.scheduled_apply_matrix(parity, inp),
            cpu_apply_matrix(parity, inp),
        )
        # an arbitrary (non-parity) matrix goes through the same CSE
        mat = rng.integers(0, 256, (4, 10), dtype=np.uint8)
        assert np.array_equal(
            sched.scheduled_apply_matrix(mat, inp),
            cpu_apply_matrix(mat, inp),
        )

    def test_parity_term_reduction(self):
        rs = _rs()
        cs = sched.compile_schedule(
            np.asarray(rs.parity_rows, dtype=np.uint8)
        )
        # the RS(10,4) parity matrix: 46 scheduled terms vs 156 naive
        assert cs.n_terms < cs.n_terms_naive
        assert cs.n_terms_naive == 156
        assert cs.n_terms <= 60

    def test_schedule_cache(self):
        rs = _rs()
        mat = np.asarray(rs.parity_rows, dtype=np.uint8)
        assert sched.compile_schedule(mat) is sched.compile_schedule(
            np.array(mat)  # equal bytes, different object
        )

    def test_kill_switch_byte_identical(self, tmp_path, monkeypatch):
        size = 64 * 1024 + 17
        rs_on = new_encoder(backend="cpu")
        base_on = _make_volume(tmp_path, "on", size, rs_on)
        monkeypatch.setenv("WEED_EC_SCHEDULE", "0")
        assert not sched.schedule_enabled()
        rs_off = new_encoder(backend="cpu")  # env read at construction
        base_off = _make_volume(tmp_path, "off", size, rs_off)
        on, off = _shard_bytes(base_on), _shard_bytes(base_off)
        assert set(on) == set(range(14))
        for sid in range(14):
            assert on[sid] == off[sid], f"shard {sid} diverged"


# ---------------------------------------------------------------------------
class TestCrc32c:
    def test_three_way_lane_boundaries(self):
        # the hw kernel switches to 3x1 KiB lanes at n >= 3072: cover
        # both sides of the boundary and multi-block + tail shapes
        rnd = random.Random(7)
        for sz in (0, 1, 8, 1023, 1024, 3071, 3072, 3073, 6144, 6145,
                   10000, 65537):
            data = rnd.randbytes(sz)
            assert crc32c(data) == _crc32c_py(data), sz

    def test_continuation_across_any_split(self):
        rnd = random.Random(11)
        data = rnd.randbytes(20000)
        want = _crc32c_py(data)
        for k in (0, 1, 3072, 9999, 20000):
            assert crc32c(data[k:], crc32c(data[:k])) == want, k

    def test_buffer_protocol_inputs(self):
        rnd = random.Random(13)
        data = rnd.randbytes(8192)
        want = crc32c(data)
        assert crc32c(bytearray(data)) == want
        assert crc32c(memoryview(bytearray(data))) == want
        assert crc32c(np.frombuffer(data, dtype=np.uint8)) == want
        # non-contiguous views still hash their logical bytes
        mv = memoryview(bytearray(data))[::2]
        assert crc32c(mv) == crc32c(data[::2])

    def test_combine_edges(self):
        a, b = b"hello ", b"world"
        ca, cb = crc32c(a), crc32c(b)
        assert crc32c_combine(ca, cb, len(b)) == crc32c(a + b)
        # zero-length second segment is the identity
        assert crc32c_combine(ca, crc32c(b""), 0) == ca & 0xFFFFFFFF
        # chained tile folds == one-shot
        data = random.Random(17).randbytes(30000)
        acc, off = 0, 0
        for step in (7, 4096, 10000, 15897):
            chunk = data[off:off + step]
            acc = crc32c_combine(acc, crc32c(chunk), len(chunk))
            off += step
        assert off == len(data) and acc == crc32c(data)

    def test_combine_zpow_thread_race(self):
        # the zero-byte transit operator memoizes powers per length:
        # racing first-use of a fresh length must not corrupt results
        data = random.Random(19).randbytes(2 * 77777)
        a, b = data[:77777], data[77777:]
        want = crc32c(data)
        ca, cb = crc32c(a), crc32c(b)
        results, errs = [], []

        def worker():
            try:
                results.append(crc32c_combine(ca, cb, len(b)))
            except Exception as e:  # pragma: no cover - diagnostic
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs and results == [want] * 8


# ---------------------------------------------------------------------------
class TestEccSidecar:
    def test_write_load_status_ok(self, tmp_path):
        rs = _rs()
        base = _make_volume(tmp_path, "v", 32 * 1024 + 3, rs)
        crcs = _publish_sidecar(base)
        doc = ecc_sidecar.load_sidecar(base)
        assert doc is not None and len(doc["shards"]) == 14
        for sid in range(14):
            ent = doc["shards"][str(sid)]
            assert ent["crc"] == crcs[sid]
            assert ent["size"] == os.path.getsize(
                base + ec_files.to_ext(sid)
            )
        paths = {s: base + ec_files.to_ext(s) for s in range(14)}
        status, _ = ecc_sidecar.sidecar_status(base, paths)
        assert status == "ok"

    def test_full_list_length_enforced(self, tmp_path):
        base = _make_volume(tmp_path, "v", 8 * 1024, _rs())
        with pytest.raises(ValueError):
            ecc_sidecar.write_sidecar(base, [1, 2, 3])

    def test_partial_merge_and_no_prior(self, tmp_path):
        base = _make_volume(tmp_path, "v", 16 * 1024 + 1, _rs())
        # partial update with no prior sidecar attests nothing
        assert ecc_sidecar.write_sidecar(base, {0: 123}) is None
        assert ecc_sidecar.load_sidecar(base) is None
        crcs = _publish_sidecar(base)
        # rebuild-verb shape: merge fresh CRCs for two shards over the
        # existing doc (byte-identical rebuild -> same values)
        assert ecc_sidecar.write_sidecar(
            base, {0: crcs[0], 13: crcs[13]}
        )
        doc = ecc_sidecar.load_sidecar(base)
        assert [doc["shards"][str(s)]["crc"] for s in range(14)] == crcs

    def test_status_stale_and_missing(self, tmp_path):
        base = _make_volume(tmp_path, "v", 16 * 1024, _rs())
        paths = {s: base + ec_files.to_ext(s) for s in range(14)}
        assert ecc_sidecar.sidecar_status(base, paths)[0] == "missing"
        _publish_sidecar(base)
        # a shard newer than the sidecar is indistinguishable from an
        # overwrite -> stale
        ecc_mtime = os.stat(ecc_sidecar.sidecar_path(base)).st_mtime_ns
        os.utime(paths[4], ns=(ecc_mtime + 10_000_000,) * 2)
        assert ecc_sidecar.sidecar_status(base, paths)[0] == "stale"
        os.utime(paths[4], ns=(ecc_mtime - 10_000_000,) * 2)
        assert ecc_sidecar.sidecar_status(base, paths)[0] == "ok"
        # size disagreement -> stale (attested bytes are gone)
        with open(paths[4], "ab") as f:
            f.write(b"x")
        os.utime(paths[4], ns=(ecc_mtime - 10_000_000,) * 2)
        assert ecc_sidecar.sidecar_status(base, paths)[0] == "stale"

    def test_torn_sidecar_degrades_not_crashes(self, tmp_path):
        base = _make_volume(tmp_path, "v", 16 * 1024, _rs())
        crcs = []
        for sid in range(14):
            with open(base + ec_files.to_ext(sid), "rb") as f:
                crcs.append(crc32c(f.read()))
        ecc_sidecar.write_sidecar(base, crcs, durable_publish=False)
        p = ecc_sidecar.sidecar_path(base)
        # tear the file mid-json (the crash shape durable_publish=False
        # exists to model): load must return None, never raise
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        assert ecc_sidecar.load_sidecar(base) is None
        paths = {s: base + ec_files.to_ext(s) for s in range(14)}
        assert ecc_sidecar.sidecar_status(base, paths)[0] == "missing"

    def test_knob(self, monkeypatch):
        assert ecc_sidecar.ecc_enabled()
        monkeypatch.setenv("WEED_EC_ECC", "0")
        assert not ecc_sidecar.ecc_enabled()


# ---------------------------------------------------------------------------
class TestVerifyEccStream:
    def _setup(self, tmp_path, size=96 * 1024 + 11):
        base = _make_volume(tmp_path, "v", size, _rs())
        _publish_sidecar(base)
        doc = ecc_sidecar.load_sidecar(base)
        paths = {s: base + ec_files.to_ext(s) for s in range(14)}
        return base, doc, paths

    def test_clean_complete(self, tmp_path):
        _, doc, paths = self._setup(tmp_path)
        res = verify_ecc_stream(paths, doc, tile_bytes=4096)
        assert res.complete and not res.corrupt
        assert res.bytes_scanned == sum(
            os.path.getsize(p) for p in paths.values()
        )

    def test_corruption_names_its_shard(self, tmp_path):
        _, doc, paths = self._setup(tmp_path)
        with open(paths[7], "r+b") as f:
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0x40]))
        res = verify_ecc_stream(paths, doc, tile_bytes=4096)
        assert res.complete and res.corrupt
        assert list(res.bad_shards) == [7]
        assert "crc" in res.bad_shards[7]

    def test_size_mismatch_detected(self, tmp_path):
        _, doc, paths = self._setup(tmp_path)
        with open(paths[2], "ab") as f:
            f.write(b"\x00")
        res = verify_ecc_stream(paths, doc, tile_bytes=4096)
        assert res.complete and list(res.bad_shards) == [2]
        assert "size" in res.bad_shards[2]

    def test_resume_cursor_triple(self, tmp_path):
        """A budgeted sweep resumes mid-shard from (shard, offset,
        run_crc) without re-reading the prefix and still converges to
        the same clean verdict."""
        _, doc, paths = self._setup(tmp_path)
        total = sum(os.path.getsize(p) for p in paths.values())
        shard, offset, run = 0, 0, 0
        scanned = segments = 0
        while True:
            res = verify_ecc_stream(
                paths, doc, start_shard=shard, start_offset=offset,
                run_crc=run, tile_bytes=4096, max_bytes=10_000,
            )
            scanned += res.bytes_scanned
            segments += 1
            if res.complete:
                assert not res.corrupt
                break
            shard, offset, run = res.shard_idx, res.offset, res.run_crc
            assert segments < 10_000
        assert scanned == total and segments > 3


# ---------------------------------------------------------------------------
class TestBatchRebuild:
    def _volumes(self, tmp_path, n, size, missing):
        rs = _rs()
        bases, golden = [], {}
        for i in range(n):
            base = _make_volume(
                tmp_path, f"v{i}", size + i * 7, rs, seed=i
            )
            bases.append(base)
            golden[base] = _shard_bytes(base)
            for sid in missing[i] if isinstance(missing, list) else missing:
                os.remove(base + ec_files.to_ext(sid))
        return bases, golden

    def test_inline_identity_and_crcs(self, tmp_path):
        bases, golden = self._volumes(tmp_path, 3, 40 * 1024 + 3, (0, 13))
        stats = {}
        rebuilt = ec_stream.stream_rebuild_ec_files_batch(
            bases, stats=stats, want_crcs=True
        )
        assert rebuilt == [[0, 13]] * 3
        assert stats.get("codec_arm") in ("host", None)
        if stats.get("codec_arm") == "host":
            assert stats.get("host_inline") is True
        for vi, base in enumerate(bases):
            for sid in (0, 13):
                with open(base + ec_files.to_ext(sid), "rb") as f:
                    got = f.read()
                assert got == golden[base][sid], (base, sid)
                if "shard_crcs" in stats:
                    assert stats["shard_crcs"][vi][sid] == crc32c(got)

    def test_threaded_host_arm_identity(self, tmp_path):
        # tiny tile -> >16 work items -> the shared-pipeline host arm
        bases, golden = self._volumes(tmp_path, 2, 100 * 1024 + 9, (1,))
        stats = {}
        ec_stream.stream_rebuild_ec_files_batch(
            bases, tile_bytes=1024, stats=stats, want_crcs=True
        )
        if stats.get("codec_arm") == "host":
            assert not stats.get("host_inline")
        for vi, base in enumerate(bases):
            with open(base + ec_files.to_ext(1), "rb") as f:
                got = f.read()
            assert got == golden[base][1]
            if "shard_crcs" in stats:
                assert stats["shard_crcs"][vi][1] == crc32c(got)

    def test_mixed_missing_sets_grouped(self, tmp_path):
        bases, golden = self._volumes(
            tmp_path, 2, 24 * 1024 + 1, [[0, 13], [3]]
        )
        stats = {}
        rebuilt = ec_stream.stream_rebuild_ec_files_batch(
            bases, stats=stats
        )
        assert rebuilt == [[0, 13], [3]]
        assert stats.get("batch_groups", 2) == 2
        for base, missing in zip(bases, [[0, 13], [3]]):
            for sid in missing:
                with open(base + ec_files.to_ext(sid), "rb") as f:
                    assert f.read() == golden[base][sid]

    def test_nothing_missing_is_a_noop(self, tmp_path):
        bases, _ = self._volumes(tmp_path, 2, 8 * 1024, ())
        assert ec_stream.stream_rebuild_ec_files_batch(bases) == [[], []]


# ---------------------------------------------------------------------------
class TestEngineEccFastPass:
    def _store(self, tmp_path):
        from tests.test_scrub import _local_ec_store

        return _local_ec_store(tmp_path)

    def test_fast_pass_clean_and_quarantines_by_crc(self, tmp_path):
        from seaweedfs_tpu.scrub.engine import ScrubEngine

        store, _ = self._store(tmp_path)
        base = os.path.join(str(tmp_path), "9")
        _publish_sidecar(base)
        eng = ScrubEngine(store, interval=3600, rate_mb_s=0)
        summary = eng.sweep_once()
        assert summary["corruptions"] == 0
        # rot a byte WITHOUT touching mtime (bit-rot doesn't utime) so
        # the sidecar stays fresh and the .ecc arm makes the call
        p = os.path.join(str(tmp_path), "9.ec06")
        st = os.stat(p)
        with open(p, "r+b") as f:
            f.seek(42)
            b = f.read(1)
            f.seek(42)
            f.write(bytes([b[0] ^ 0x01]))
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
        summary = eng.sweep_once()
        assert summary["corruptions"] >= 1
        assert store.quarantined[9][6].startswith("scrub .ecc:")
        store.close()

    def test_stale_sidecar_falls_back_loudly(self, tmp_path):
        from seaweedfs_tpu.scrub.engine import ScrubEngine
        from seaweedfs_tpu.stats.metrics import SCRUB_ECC_FALLBACK

        store, _ = self._store(tmp_path)
        base = os.path.join(str(tmp_path), "9")
        _publish_sidecar(base)
        # a shard mtime past the sidecar's (an overwrite) is stale, and
        # the sweep must take the parity path (which still verifies).
        # Explicit ns: a plain os.utime(p) can land in the SAME coarse
        # filesystem clock tick as the publish just above.
        p = os.path.join(str(tmp_path), "9.ec06")
        ecc_mtime = os.stat(ecc_sidecar.sidecar_path(base)).st_mtime_ns
        os.utime(p, ns=(ecc_mtime + 1_000_000, ecc_mtime + 1_000_000))
        eng = ScrubEngine(store, interval=3600, rate_mb_s=0)
        before = SCRUB_ECC_FALLBACK.value(eng.node_label, "stale")
        summary = eng.sweep_once()
        after = SCRUB_ECC_FALLBACK.value(eng.node_label, "stale")
        assert after == before + 1
        assert summary["corruptions"] == 0  # bytes are still fine
        store.close()
