"""On-read image resizing + JPEG EXIF orientation fixing.

Behavioral match of reference weed/images/:
  resized()             resizing.go:15 Resized — ?width=&height=&mode=
                        on volume GETs; only downscales (a source
                        smaller than the target passes through), with
                        fit / fill / default(thumbnail-or-resize) modes
  fix_jpg_orientation() orientation.go:14 FixJpgOrientation — applied
                        to .jpg uploads on the write path so stored
                        pixels are upright and EXIF rotation quirks
                        never reach clients

Pillow does the pixel work; when it is unavailable both functions
degrade to pass-through (the reference likewise returns the original
bytes on any decode error).
"""

from __future__ import annotations

import io

_IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".gif"}

_degrade_warned = False


def _pil():
    try:
        from PIL import Image

        return Image
    except ImportError:
        _warn_degraded()
        return None


def _warn_degraded() -> None:
    # The reference always ships its imaging dep (disintegration/imaging),
    # so a ?width= GET always resizes. Without Pillow we pass the original
    # bytes through with a 200 — make that deviation observable instead of
    # silent: one warning at first degrade, and /status reports it.
    global _degrade_warned
    if not _degrade_warned:
        _degrade_warned = True
        from seaweedfs_tpu.util import wlog

        wlog.warning(
            "Pillow unavailable: image resizing/orientation disabled; "
            "?width=/?height= requests will return original bytes"
        )


_resizing_enabled: bool | None = None


def resizing_enabled() -> bool:
    """True when Pillow is importable (does not emit the degrade
    warning). Cached: failed imports are not cached by Python, and this
    sits on the volume /status path."""
    global _resizing_enabled
    if _resizing_enabled is None:
        try:
            from PIL import Image  # noqa: F401

            _resizing_enabled = True
        except ImportError:
            _resizing_enabled = False
    return _resizing_enabled


def is_image_ext(ext: str) -> bool:
    return ext.lower() in _IMAGE_EXTS


def _format_for(ext: str) -> str:
    e = ext.lower()
    if e in (".jpg", ".jpeg"):
        return "JPEG"
    if e == ".png":
        return "PNG"
    if e == ".gif":
        return "GIF"
    return "PNG"


def resized(
    ext: str, data: bytes, width: int, height: int, mode: str = ""
) -> tuple[bytes, int, int]:
    """(bytes, w, h); pass-through when no resize applies
    (resizing.go:15 semantics, Lanczos filter)."""
    if width == 0 and height == 0:
        return data, 0, 0
    Image = _pil()
    if Image is None:
        return data, 0, 0
    try:
        src = Image.open(io.BytesIO(data))
        src.load()
    except Exception:  # noqa: BLE001 - undecodable: serve original bytes
        return data, 0, 0
    src_w, src_h = src.size
    needs = (src_w > width and width != 0) or (src_h > height and height != 0)
    if not needs:
        return data, src_w, src_h

    resample = Image.LANCZOS
    if mode == "fit":
        dst = src.copy()
        dst.thumbnail((width or src_w, height or src_h), resample)
    elif mode == "fill":
        from PIL import ImageOps

        dst = ImageOps.fit(src, (width or src_w, height or src_h), resample)
    else:
        if width == height and width != 0 and src_w != src_h:
            # square thumbnail: center-crop then scale (imaging.Thumbnail)
            from PIL import ImageOps

            dst = ImageOps.fit(src, (width, height), resample)
        else:
            # plain resize; 0 on one axis keeps aspect
            if width == 0:
                width = max(1, src_w * height // src_h)
            if height == 0:
                height = max(1, src_h * width // src_w)
            dst = src.resize((width, height), resample)

    buf = io.BytesIO()
    fmt = _format_for(ext)
    if fmt == "JPEG" and dst.mode not in ("RGB", "L"):
        dst = dst.convert("RGB")
    dst.save(buf, format=fmt)
    return buf.getvalue(), dst.size[0], dst.size[1]


def fix_jpg_orientation(data: bytes) -> bytes:
    """Bake the EXIF orientation into the pixels (orientation.go:14);
    returns the input unchanged when there is nothing to fix. Uses
    Pillow's canonical exif_transpose — a hand-rolled rotate/flip
    table is exactly the kind of thing that silently disagrees with
    the spec on half the orientation values."""
    Image = _pil()
    if Image is None:
        return data
    try:
        img = Image.open(io.BytesIO(data))
        orient = img.getexif().get(0x0112, 1)  # Orientation tag
    except Exception:  # noqa: BLE001
        return data
    if orient == 1 or orient not in range(2, 9):
        # 1 = upright; out-of-range tags (corrupt cameras) must pass
        # through untouched, not get generation-lossed by a no-op
        # re-encode
        return data
    try:
        from PIL import ImageOps

        fixed = ImageOps.exif_transpose(img)  # also clears the tag
        buf = io.BytesIO()
        if fixed.mode not in ("RGB", "L"):
            fixed = fixed.convert("RGB")
        # quality 95: the write path must not visibly degrade photos
        # just to bake in the rotation
        fixed.save(buf, format="JPEG", quality=95, exif=fixed.getexif().tobytes())
        return buf.getvalue()
    except Exception:  # noqa: BLE001
        return data
