"""Rack/DC-aware replica placement for new volumes.

Behavioral match of reference weed/topology/volume_growth.go: to place
one volume with replica placement "xyz" (x extra DCs, y extra racks,
z extra same-rack copies):

  1. pick a main DC (+x other DCs) whose rack/node structure can hold
     the full replica set (the nested possible-racks/nodes filter at
     volume_growth.go:100-120);
  2. inside the main DC, pick a main rack (+y other racks) with enough
     free nodes;
  3. inside the main rack, pick a main node (+z other nodes);
  4. one replica goes to each other DC/rack/node.

findVolumeCount: how many logical volumes one grow request creates
(7/6/3/1 for copy counts 1/2/3/more — volume_growth.go:50).
"""

from __future__ import annotations

import random

from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.topology.node import DataCenter, DataNode, Node, Rack


def find_volume_count(copy_count: int) -> int:
    return {1: 7, 2: 6, 3: 3}.get(copy_count, 1)


def find_empty_slots_for_one_volume(
    topo_root: Node,
    rp: ReplicaPlacement,
    data_center: str = "",
    rack: str = "",
    data_node: str = "",
    rng: random.Random | None = None,
) -> list[DataNode]:
    """Pick the replica node set for one new volume; raises ValueError
    when the topology cannot satisfy the placement."""
    rng = rng or random

    def dc_filter(node: Node):
        if data_center and node.id != data_center:
            return f"not preferred data center {data_center}"
        if len(node.children) < rp.diff_rack_count + 1:
            return f"only {len(node.children)} racks, need {rp.diff_rack_count + 1}"
        if node.free_space() < rp.diff_rack_count + rp.same_rack_count + 1:
            return f"free {node.free_space()} < {rp.diff_rack_count + rp.same_rack_count + 1}"
        possible_racks = sum(
            1
            for r in node.children.values()
            if sum(1 for n in r.children.values() if n.free_space() >= 1)
            >= rp.same_rack_count + 1
        )
        if possible_racks < rp.diff_rack_count + 1:
            return f"only {possible_racks} viable racks, need {rp.diff_rack_count + 1}"
        return None

    main_dc, other_dcs = topo_root.random_pick(
        rp.diff_data_center_count + 1, dc_filter, rng
    )

    def rack_filter(node: Node):
        if rack and node.id != rack:
            return f"not preferred rack {rack}"
        if node.free_space() < rp.same_rack_count + 1:
            return f"free {node.free_space()} < {rp.same_rack_count + 1}"
        viable = sum(1 for n in node.children.values() if n.free_space() >= 1)
        if viable < rp.same_rack_count + 1:
            return f"only {viable} free nodes, need {rp.same_rack_count + 1}"
        return None

    main_rack, other_racks = main_dc.random_pick(rp.diff_rack_count + 1, rack_filter, rng)

    def node_filter(node: Node):
        if data_node and node.id != data_node:
            return f"not preferred node {data_node}"
        if node.free_space() < 1:
            return "no free slot"
        return None

    main_node, other_nodes = main_rack.random_pick(
        rp.same_rack_count + 1, node_filter, rng
    )

    servers: list[DataNode] = [main_node]  # type: ignore[list-item]
    servers.extend(other_nodes)  # type: ignore[arg-type]
    for r in other_racks:
        n, _ = r.random_pick(1, node_filter, rng)
        servers.append(n)  # type: ignore[arg-type]
    for dc in other_dcs:
        assert isinstance(dc, DataCenter)
        candidate_racks = [
            r for r in dc.children.values() if any(
                n.free_space() >= 1 for n in r.children.values()
            )
        ]
        if not candidate_racks:
            raise ValueError(f"data center {dc.id} has no free node for a replica")
        r = rng.choice(candidate_racks)
        n, _ = r.random_pick(1, node_filter, rng)
        servers.append(n)  # type: ignore[arg-type]
    return servers
