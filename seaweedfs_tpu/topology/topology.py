"""Topology: the master's cluster state and assignment brain.

Behavioral match of reference weed/topology/topology.go +
topology_ec.go + topology_event_handling.go: the DC/rack/node tree,
per-(collection, rp, ttl) volume layouts, the EC shard registry
(vid → shard → nodes), heartbeat-driven registration, max-volume-id
allocation, and lookup/pick-for-write used by /dir/assign and
/dir/lookup.

The reference replicates NextVolumeId through raft
(cluster_commands.go); here the max-vid counter sits behind the same
single-method seam (`IdGenerator`) so a consensus-backed generator can
replace the in-memory one without touching callers (SURVEY §7 "keep
the command-log interface").
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from seaweedfs_tpu.storage.store import EcShardInfo, VolumeInfo
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.topology.node import DataCenter, DataNode, Node, Rack
from seaweedfs_tpu.topology.volume_layout import VolumeLayout
from seaweedfs_tpu.topology import volume_growth


class IdGenerator:
    """Monotonic volume-id allocator (raft MaxVolumeIdCommand seam)."""

    def __init__(self) -> None:
        self._max_vid = 0
        self._lock = threading.Lock()

    def next_volume_id(self) -> int:
        with self._lock:
            self._max_vid += 1
            return self._max_vid

    def peek(self) -> int:
        """Current max without allocating (raft leaders propose
        peek()+1 and let the replicated apply advance it)."""
        with self._lock:
            return self._max_vid

    def adjust_if_larger(self, vid: int) -> None:
        with self._lock:
            if vid > self._max_vid:
                self._max_vid = vid


@dataclass
class EcShardLocations:
    """vid → 14 lists of owning nodes (topology_ec.go EcShardLocations)."""

    collection: str
    locations: list[list[DataNode]]

    @classmethod
    def empty(cls, collection: str) -> "EcShardLocations":
        return cls(collection, [[] for _ in range(14)])


class Topology(Node):
    def __init__(self, volume_size_limit: int = 30 * 1024 * 1024 * 1024):
        super().__init__("")
        self.volume_size_limit = volume_size_limit
        self.id_gen = IdGenerator()
        # (collection, rp, ttl) -> VolumeLayout
        self._layouts: dict[tuple[str, str, str], VolumeLayout] = {}
        self.ec_shard_map: dict[int, EcShardLocations] = {}
        self._lock = threading.RLock()

    # --- tree ---
    def get_or_create_data_center(self, dc_id: str) -> DataCenter:
        return self.get_or_create(dc_id, DataCenter)  # type: ignore[return-value]

    def data_nodes(self) -> list[DataNode]:
        out = []
        for dc in self.children.values():
            for rack in dc.children.values():
                out.extend(rack.children.values())
        return out  # type: ignore[return-value]

    def get_layout(self, collection: str, rp: str, ttl: str) -> VolumeLayout:
        with self._lock:
            key = (collection, rp, ttl)
            layout = self._layouts.get(key)
            if layout is None:
                layout = VolumeLayout(rp, ttl, self.volume_size_limit)
                self._layouts[key] = layout
            return layout

    def collections(self) -> set[str]:
        with self._lock:
            names = {k[0] for k in self._layouts}
            names.update(loc.collection for loc in self.ec_shard_map.values())
            return names

    # --- heartbeat-driven registration (master_grpc_server.go:18) ---
    def register_data_node(
        self,
        ip: str,
        port: int,
        public_url: str = "",
        data_center: str = "DefaultDataCenter",
        rack: str = "DefaultRack",
        max_volumes: int = 7,
    ) -> DataNode:
        dc = self.get_or_create_data_center(data_center)
        r = dc.get_or_create_rack(rack)
        dn = r.new_data_node(
            f"{ip}:{port}", ip=ip, port=port, public_url=public_url, max_volumes=max_volumes
        )
        dn.last_seen = time.time()
        return dn

    def sync_volumes(self, dn: DataNode, infos: list[VolumeInfo]) -> tuple[list[VolumeInfo], list[VolumeInfo]]:
        """Full-state volume sync from one heartbeat.

        Layouts register BEFORE dn.volumes is replaced: an assign
        racing this sync reads free_space() from dn.volumes and
        writability from the layouts, and the old order (node map
        first) had a window where a full node counted against
        free_space while its volumes were not yet writable — a
        fresh-leader re-registration could answer "no free volumes
        left" for a perfectly healthy cluster. Registering layouts
        first errs the other way (at worst an unnecessary grow
        attempt, which is guarded), never a spurious hard failure."""
        for v in infos:
            self.id_gen.adjust_if_larger(v.id)
            self._layout_for(v).register_volume(v, dn)
        new, deleted = dn.update_volumes(infos)
        for v in deleted:
            self._layout_for(v).unregister_volume(v.id, dn)
        return new, deleted

    def delta_sync_volumes(
        self,
        dn: DataNode,
        new: list[VolumeInfo],
        deleted: list[VolumeInfo],
    ) -> None:
        """Incremental registration from a delta heartbeat
        (IncrementalSyncDataNodeRegistration role, master.proto:43-44):
        O(changes) instead of O(volumes) per beat."""
        for v in new:
            dn.volumes[v.id] = v
            self.id_gen.adjust_if_larger(v.id)
            self._layout_for(v).register_volume(v, dn)
        for v in deleted:
            dn.volumes.pop(v.id, None)
            self._layout_for(v).unregister_volume(v.id, dn)

    def _layout_for(self, v: VolumeInfo) -> VolumeLayout:
        rp = str(ReplicaPlacement.from_byte(v.replica_placement))
        ttl = str(TTL.from_uint32(v.ttl))
        return self.get_layout(v.collection, rp, ttl)

    def unregister_data_node(self, dn: DataNode) -> None:
        """Node lost (heartbeat stream broke, master_grpc_server.go:22,
        or declared dead by the master's liveness sweep)."""
        for v in dn.volumes.values():
            self._layout_for(v).unregister_volume(v.id, dn)
        for vid in list(dn.ec_shards):
            self.unregister_ec_shards(vid, dn)
        rack = dn.parent
        if rack is not None:
            rack.children.pop(dn.id, None)
        # detachment marker: a Heartbeat handler still holding this
        # object must re-register instead of mutating an orphan (whose
        # volumes would re-enter layouts referencing a detached node)
        dn.parent = None

    # --- scrub plane (docs/SCRUB.md) ---
    @staticmethod
    def sync_scrub_stats(dn: DataNode, infos: list) -> None:
        """Overwrite one node's scrub-health view from a heartbeat.
        Every beat carries the node's complete snapshot, so wholesale
        replacement is correct (rows for volumes the node no longer
        holds vanish with it)."""
        dn.scrub_stats = {(s.volume_id, s.is_ec): s for s in infos}

    def scrub_summary(self) -> dict:
        """Cluster-wide scrub rollup for status surfaces."""
        per_node: dict[str, dict] = {}
        for dn in self.data_nodes():
            stats = list(dn.scrub_stats.values())
            if not stats:
                continue
            per_node[dn.url] = {
                "Volumes": len(stats),
                "Corruptions": sum(s.corruptions_found for s in stats),
                "QuarantinedShards": sum(
                    bin(s.quarantined_shard_bits).count("1") for s in stats
                ),
                "ScannedBytes": sum(s.scanned_bytes for s in stats),
                "Errors": [
                    f"vid {s.volume_id}: {s.last_error}"
                    for s in stats
                    if s.last_error
                ][:10],
            }
        return per_node

    # --- EC shard registry (topology_ec.go) ---
    def sync_ec_shards(self, dn: DataNode, infos: list[EcShardInfo]) -> None:
        new_or_changed, deleted = dn.update_ec_shards(infos)
        for s in deleted:
            self.unregister_ec_shards(s.id, dn)
        for s in infos:
            self.register_ec_shards(s, dn)

    def register_ec_shards(self, info: EcShardInfo, dn: DataNode) -> None:
        with self._lock:
            locs = self.ec_shard_map.get(info.id)
            if locs is None:
                locs = EcShardLocations.empty(info.collection)
                self.ec_shard_map[info.id] = locs
            for shard_id in range(14):
                if info.ec_index_bits & (1 << shard_id):
                    if dn not in locs.locations[shard_id]:
                        locs.locations[shard_id].append(dn)
                elif dn in locs.locations[shard_id]:
                    # shard moved away from this node: drop the stale bit
                    locs.locations[shard_id].remove(dn)

    def unregister_ec_shards(self, vid: int, dn: DataNode) -> None:
        with self._lock:
            locs = self.ec_shard_map.get(vid)
            if locs is None:
                return
            for shard_list in locs.locations:
                if dn in shard_list:
                    shard_list.remove(dn)
            if all(not s for s in locs.locations):
                del self.ec_shard_map[vid]

    def lookup_ec_shards(self, vid: int) -> Optional[EcShardLocations]:
        return self.ec_shard_map.get(vid)

    # --- lookup / assign (topology.go:88-137) ---
    def lookup(self, collection: str, vid: int) -> list[DataNode]:
        with self._lock:
            if collection:
                keys = [k for k in self._layouts if k[0] == collection]
            else:
                keys = list(self._layouts)
        for key in keys:
            nodes = self._layouts[key].lookup(vid)
            if nodes:
                return nodes
        locs = self.lookup_ec_shards(vid)
        if locs is not None:
            nodes: list[DataNode] = []
            for shard_list in locs.locations:
                for dn in shard_list:
                    if dn not in nodes:
                        nodes.append(dn)
            return nodes
        return []

    def next_volume_id(self) -> int:
        return self.id_gen.next_volume_id()

    def has_writable_volume(self, collection: str, rp: str, ttl: str) -> bool:
        return self.get_layout(collection, rp, ttl).active_volume_count() > 0

    def pick_for_write(
        self,
        collection: str,
        rp: str,
        ttl: str,
        count: int = 1,
        data_center: str = "",
        policy: str = "p2c",
        health=None,
    ) -> tuple[int, int, list[DataNode]]:
        vid, nodes = self.get_layout(collection, rp, ttl).pick_for_write(
            data_center=data_center, policy=policy, health=health
        )
        return vid, count, nodes

    def find_empty_slots(
        self, rp: ReplicaPlacement, data_center: str = ""
    ) -> list[DataNode]:
        return volume_growth.find_empty_slots_for_one_volume(
            self, rp, data_center=data_center
        )

    @staticmethod
    def _volume_stat(v) -> dict:
        return {
            "Id": v.id,
            "Size": v.size,
            "Collection": v.collection,
            "FileCount": v.file_count,
            "DeleteCount": v.delete_count,
            "DeletedByteCount": v.deleted_byte_count,
            "ReadOnly": v.read_only,
            "Version": v.version,
            "ReplicaPlacement": v.replica_placement,
            "Ttl": v.ttl,
        }

    def to_volume_map(self) -> dict:
        """/vol/status shape (topology_map.go:30 ToVolumeMap): capacity
        totals plus dc -> rack -> node dicts of raw volume stats.

        Tree mutations happen under the MASTER's node lock (heartbeat
        delta sync, liveness sweeps), not self._lock, so this walk
        takes list() snapshots at every level — each is atomic under
        the GIL — instead of pretending a lock helps; a status dump may
        be a heartbeat out of date, never a RuntimeError."""
        dcs: dict = {}
        for dc in list(self.children.values()):
            racks: dict = {}
            for rack in list(dc.children.values()):
                nodes: dict = {}
                for dn in list(rack.children.values()):
                    nodes[dn.id] = [
                        self._volume_stat(v) for v in list(dn.volumes.values())
                    ]
                racks[rack.id] = nodes
            dcs[dc.id] = racks
        return {
            "Max": self.max_volume_count(),
            "Free": self.free_space(),
            "DataCenters": dcs,
        }

    def to_map(self) -> dict:
        """Status-UI topology dump (master_server_handlers_admin.go)."""
        return {
            "Max": self.max_volume_count(),
            "Free": self.free_space(),
            "DataCenters": [
                {
                    "Id": dc.id,
                    "Racks": [
                        {
                            "Id": rack.id,
                            "DataNodes": [
                                {
                                    "Url": dn.url,
                                    "PublicUrl": dn.public_url,
                                    "Volumes": dn.volume_count(),
                                    "EcShards": dn.ec_shard_count(),
                                    "Max": dn.max_volume_count(),
                                    # full per-volume detail so admin
                                    # planners (shell) can work from one
                                    # VolumeList call, like the
                                    # reference's TopologyInfo proto
                                    "VolumeInfos": [
                                        self._volume_stat(v)
                                        for v in list(dn.volumes.values())
                                    ],
                                    "EcShardInfos": [
                                        {
                                            "Id": s.id,
                                            "Collection": s.collection,
                                            "EcIndexBits": s.ec_index_bits,
                                        }
                                        for s in dn.ec_shards.values()
                                    ],
                                }
                                for dn in rack.children.values()  # type: ignore[attr-defined]
                            ],
                        }
                        for rack in dc.children.values()
                    ],
                }
                for dc in self.children.values()
            ],
        }
