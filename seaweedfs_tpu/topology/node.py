"""The DC → rack → data-node tree with aggregated capacity counts.

Behavioral match of reference weed/topology/node.go, data_center.go,
rack.go, data_node.go: each level aggregates volume counts, max-volume
capacity and EC shard counts from its children; placement walks pick
random children subject to a filter (RandomlyPickNodes). The reference
spreads this over an interface + embedded struct; here it is one small
class hierarchy.
"""

from __future__ import annotations

import random
from typing import Callable, Optional

from seaweedfs_tpu.storage.store import EcShardInfo, ScrubStatInfo, VolumeInfo


class Node:
    def __init__(self, node_id: str):
        self.id = node_id
        self.children: dict[str, "Node"] = {}
        self.parent: Optional["Node"] = None

    # --- capacity aggregation ---
    def max_volume_count(self) -> int:
        return sum(c.max_volume_count() for c in self.children.values())

    def volume_count(self) -> int:
        return sum(c.volume_count() for c in self.children.values())

    def ec_shard_count(self) -> int:
        return sum(c.ec_shard_count() for c in self.children.values())

    def free_space(self) -> int:
        """Free volume slots, with EC shards charged fractionally
        (reference data_node_ec.go: each 14-shard set ≈ one volume)."""
        return (
            self.max_volume_count()
            - self.volume_count()
            - self.ec_shard_count() // 14
        )

    def get_or_create(self, child_id: str, factory) -> "Node":
        child = self.children.get(child_id)
        if child is None:
            child = factory(child_id)
            child.parent = self
            self.children[child_id] = child
        return child

    def random_pick(
        self,
        count: int,
        filter_fn: Callable[["Node"], Optional[str]],
        rng: random.Random | None = None,
    ) -> tuple["Node", list["Node"]]:
        """Pick 1 main + (count-1) other children passing `filter_fn`
        (which returns an error string or None), reservoir-style
        (node.go RandomlyPickNodes). Raises ValueError if not enough."""
        rng = rng or random
        candidates = []
        errs = []
        for node in self.children.values():
            err = filter_fn(node)
            if err is None:
                candidates.append(node)
            else:
                errs.append(f"{node.id}: {err}")
        if len(candidates) < count:
            raise ValueError(
                f"only {len(candidates)} of {count} candidates at {self.id or 'root'}: "
                + "; ".join(errs[:5])
            )
        picked = rng.sample(candidates, count)
        return picked[0], picked[1:]


class DataNode(Node):
    """One volume-server process (data_node.go)."""

    def __init__(self, node_id: str, ip: str = "", port: int = 0, public_url: str = "", max_volumes: int = 7):
        super().__init__(node_id)
        self.ip = ip
        self.port = port
        self.public_url = public_url or (f"{ip}:{port}" if ip else node_id)
        self._max_volumes = max_volumes
        self.volumes: dict[int, VolumeInfo] = {}
        self.ec_shards: dict[int, EcShardInfo] = {}  # vid -> shard bits
        # scrub plane: (vid, is_ec) -> latest ScrubStat row from this
        # node's heartbeats; the repair scheduler reads corruption and
        # quarantine signals from here
        self.scrub_stats: dict[tuple[int, bool], ScrubStatInfo] = {}
        # QoS plane (docs/QOS.md): live load from the node's heartbeats
        # — in-flight HTTP dispatches and group-commit queue depth;
        # pick_for_write's power-of-two-choices weighs nodes by these
        self.in_flight = 0
        self.write_queue_depth = 0
        self.last_seen = 0.0

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}" if self.ip else self.id

    def queue_load(self) -> int:
        """The node's reported live load — what queue-depth-aware
        assignment compares (heartbeat-fresh, so at most one beat
        stale; ties break random in the layout's picker)."""
        return self.in_flight + self.write_queue_depth

    def max_volume_count(self) -> int:
        return self._max_volumes

    def volume_count(self) -> int:
        return len(self.volumes)

    def ec_shard_count(self) -> int:
        return sum(bin(s.ec_index_bits).count("1") for s in self.ec_shards.values())

    def update_volumes(self, infos: list[VolumeInfo]) -> tuple[list[VolumeInfo], list[VolumeInfo]]:
        """Full-state sync; returns (new, deleted) volume infos."""
        incoming = {v.id: v for v in infos}
        new = [v for vid, v in incoming.items() if vid not in self.volumes]
        deleted = [v for vid, v in self.volumes.items() if vid not in incoming]
        self.volumes = incoming
        return new, deleted

    def update_ec_shards(self, infos: list[EcShardInfo]) -> tuple[list[EcShardInfo], list[EcShardInfo]]:
        incoming = {s.id: s for s in infos}
        new_or_changed = [
            s
            for vid, s in incoming.items()
            if vid not in self.ec_shards or self.ec_shards[vid].ec_index_bits != s.ec_index_bits
        ]
        deleted = [s for vid, s in self.ec_shards.items() if vid not in incoming]
        self.ec_shards = incoming
        return new_or_changed, deleted

    def get_rack(self) -> "Rack":
        assert isinstance(self.parent, Rack)
        return self.parent

    def get_data_center(self) -> "DataCenter":
        return self.get_rack().get_data_center()


class Rack(Node):
    def new_data_node(self, node_id: str, **kw) -> DataNode:
        node = self.children.get(node_id)
        if node is None:
            node = DataNode(node_id, **kw)
            node.parent = self
            self.children[node_id] = node
        return node  # type: ignore[return-value]

    def get_data_center(self) -> "DataCenter":
        assert isinstance(self.parent, DataCenter)
        return self.parent


class DataCenter(Node):
    def get_or_create_rack(self, rack_id: str) -> Rack:
        return self.get_or_create(rack_id, Rack)  # type: ignore[return-value]
