"""VolumeLayout: writable-volume tracking per (collection, rp, ttl).

Behavioral match of reference weed/topology/volume_layout.go: vid →
location list, a writable set excluding readonly/oversized volumes,
random pick-for-write with optional DC/rack/node affinity, and
registration driven by heartbeats.
"""

from __future__ import annotations

import random
import threading
from typing import Optional

from seaweedfs_tpu.storage.store import VolumeInfo
from seaweedfs_tpu.topology.node import DataNode


class VolumeLayout:
    def __init__(self, rp_string: str, ttl_string: str, volume_size_limit: int):
        self.rp = rp_string
        self.ttl = ttl_string
        self.volume_size_limit = volume_size_limit
        self.vid2location: dict[int, list[DataNode]] = {}
        self.writables: list[int] = []
        self.readonly_vids: set[int] = set()
        self.oversized_vids: set[int] = set()
        self._lock = threading.RLock()

    def register_volume(self, v: VolumeInfo, dn: DataNode) -> None:
        with self._lock:
            nodes = self.vid2location.setdefault(v.id, [])
            if dn not in nodes:
                nodes.append(dn)
            # both directions: a vacuumed volume shrinking below the
            # limit or a readonly→writable flip must restore
            # writability (StartRefreshWritableVolumes role), not just
            # the degrading transitions
            if v.read_only:
                self.readonly_vids.add(v.id)
            else:
                self.readonly_vids.discard(v.id)
            if self._is_oversized(v):
                self.oversized_vids.add(v.id)
            else:
                self.oversized_vids.discard(v.id)
            self._refresh_writable(v.id)

    def unregister_volume(self, vid: int, dn: DataNode) -> None:
        with self._lock:
            nodes = self.vid2location.get(vid)
            if nodes and dn in nodes:
                nodes.remove(dn)
            if not nodes:
                self.vid2location.pop(vid, None)
                self._set_unwritable(vid)
                self.readonly_vids.discard(vid)
                self.oversized_vids.discard(vid)
            else:
                self._refresh_writable(vid)

    def _is_oversized(self, v: VolumeInfo) -> bool:
        return v.size >= self.volume_size_limit

    def _refresh_writable(self, vid: int) -> None:
        writable = (
            vid in self.vid2location
            and len(self.vid2location[vid]) > 0
            and vid not in self.readonly_vids
            and vid not in self.oversized_vids
        )
        if writable and vid not in self.writables:
            self.writables.append(vid)
        elif not writable:
            self._set_unwritable(vid)

    def _set_unwritable(self, vid: int) -> None:
        if vid in self.writables:
            self.writables.remove(vid)

    def set_oversized(self, vid: int) -> None:
        with self._lock:
            self.oversized_vids.add(vid)
            self._set_unwritable(vid)

    def set_readonly(self, vid: int, readonly: bool = True) -> None:
        with self._lock:
            if readonly:
                self.readonly_vids.add(vid)
            else:
                self.readonly_vids.discard(vid)
            self._refresh_writable(vid)

    def lookup(self, vid: int) -> list[DataNode]:
        with self._lock:
            return list(self.vid2location.get(vid, []))

    def active_volume_count(self) -> int:
        return len(self.writables)

    @staticmethod
    def _volume_load(nodes: list[DataNode]) -> int:
        """Cost of writing one volume: a write lands on EVERY replica
        (fan-out), so the slowest — most loaded — replica bounds it."""
        return max((dn.queue_load() for dn in nodes), default=0)

    def _health_filtered(self, health) -> list[int]:
        """Writable vids whose replicas are ALL assignable per the
        health plane (docs/HEALTH.md). Empty (or health None/disabled)
        → the caller falls back to the full writable set: availability
        beats precision when every volume touches a suspect node (the
        write may still succeed — hinted handoff covers the sick
        replica).

        The verdict is memoized per NODE for this pick: volumes number
        in the thousands while nodes number in the dozens, and each
        assignable() call walks a phi ring + env knobs — evaluating it
        per replica per vid under the layout lock would make assign
        latency scale with the volume count."""
        if health is None:
            return self.writables
        memo: dict[str, bool] = {}
        assignable = health.assignable

        def ok(dn) -> bool:
            v = memo.get(dn.url)
            if v is None:
                v = memo[dn.url] = assignable(dn.url)
            return v

        clean = [
            vid
            for vid in self.writables
            if all(ok(dn) for dn in self.vid2location.get(vid, ()))
        ]
        return clean or self.writables

    def pick_for_write(
        self,
        data_center: str = "",
        rack: str = "",
        data_node: str = "",
        rng: random.Random | None = None,
        policy: str = "p2c",
        health=None,
    ) -> tuple[int, list[DataNode]]:
        """Writable vid pick, optionally affine to a DC/rack/node
        (volume_layout.go:165 PickForWrite — reservoir sampling over
        matching replica locations when affinity is requested).

        `policy` (QoS plane, docs/QOS.md): "p2c" (default) runs
        power-of-two-choices over the writable set, weighted by the
        replica nodes' heartbeat-reported in-flight + write-queue
        depth — near-random load balance at random-pick cost, without
        the herd-to-the-idlest stampede a full argmin causes on stale
        signals. "random" is the pre-QoS pure-random pick
        (`-assignPolicy random`, and what WEED_QOS=0 restores).
        Affinity-constrained picks keep the reservoir path (the
        candidate set is already narrow).

        `health` (docs/HEALTH.md): the master's HealthPlane — volumes
        with a suspect/lame-duck/draining replica are excluded while a
        clean alternative exists, under BOTH policies (WEED_HEALTH=0
        makes every verdict healthy, restoring the old pool)."""
        rng = rng or random
        with self._lock:
            if not self.writables:
                raise ValueError("no writable volumes")
            candidates = self._health_filtered(health)
            if not data_center:
                if policy == "p2c" and len(candidates) > 1:
                    a, b = rng.sample(candidates, 2)
                    la = self._volume_load(self.vid2location[a])
                    lb = self._volume_load(self.vid2location[b])
                    if la == lb:
                        vid = a if rng.random() < 0.5 else b
                    else:
                        vid = a if la < lb else b
                    # least-loaded replica leads: callers route the
                    # first hop at locations[0]
                    nodes = sorted(
                        self.vid2location[vid],
                        key=lambda dn: dn.queue_load(),
                    )
                    return vid, nodes
                vid = rng.choice(candidates)
                return vid, list(self.vid2location[vid])
            chosen: Optional[tuple[int, DataNode]] = None
            # two passes at most: the health-filtered pool first, the
            # full writable set if the filter emptied THIS affinity
            # slice (availability beats precision, as above)
            for pool in (set(candidates), set(self.writables)):
                counter = 0
                for vid in self.writables:
                    if vid not in pool:
                        continue
                    for dn in self.vid2location.get(vid, []):
                        if dn.get_data_center().id != data_center:
                            continue
                        if rack and dn.get_rack().id != rack:
                            continue
                        if data_node and dn.id != data_node:
                            continue
                        counter += 1
                        if rng.randrange(counter) < 1:
                            chosen = (vid, dn)
                if chosen is not None:
                    break
            if chosen is None:
                raise ValueError(
                    f"no writable volumes in dc={data_center} rack={rack}"
                )
            # the affinity-matched node leads the location list, so
            # callers using locations[0] honor the requested placement
            vid, matched = chosen
            others = [d for d in self.vid2location[vid] if d is not matched]
            return vid, [matched, *others]
