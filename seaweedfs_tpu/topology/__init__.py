"""Cluster control plane: the master's view of the world.

DC → rack → data-node tree with capacity accounting, per-(collection,
rp, ttl) volume layouts, rack-aware replica placement, the EC shard
registry, and the file-id sequencer — the logic behind /dir/assign,
/dir/lookup and heartbeat processing (reference weed/topology/,
SURVEY.md §2.2)."""

from seaweedfs_tpu.topology.topology import Topology  # noqa: F401
from seaweedfs_tpu.topology.node import DataNode  # noqa: F401
