"""AWS SQS + Google Pub/Sub notification queues over their wire APIs.

Behavioral match of the reference's SDK-backed queues, speaking the
service protocols directly so the gate is credentials/connectivity,
not a library (the notification/kafka.py convention):

  SqsQueue     weed/notification/aws_sqs/aws_sqs_pub.go — the AWS
               Query protocol (GetQueueUrl at init, then SendMessage
               with MessageBody = the event's text-proto form and a
               `key` message attribute, DelaySeconds 10) signed with
               SigV4 (service "sqs", the same derivation the s3api
               gateway implements)
  PubSubQueue  weed/notification/google_pub_sub/google_pub_sub.go —
               the Pub/Sub REST publish endpoint
               (projects/{p}/topics/{t}:publish) with Data = the
               serialized proto and a `key` attribute, Bearer auth

Both are testable offline against tests/cloud_fakes.py
(FakeSqs / FakePubSub) via their endpoint overrides.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import json
import urllib.error
import urllib.parse
import urllib.request

from seaweedfs_tpu.pb import filer_pb2 as fpb


def _post(url: str, body: bytes, headers: dict, timeout: float = 30.0):
    req = urllib.request.Request(url, data=body, method="POST", headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class SqsQueue:
    """notification.aws_sqs over the Query protocol + SigV4."""

    name = "aws_sqs"

    def __init__(
        self,
        access_key: str,
        secret_key: str,
        region: str,
        queue_name: str,
        endpoint: str = "",  # default https://sqs.{region}.amazonaws.com
    ):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region or "us-east-1"
        self.endpoint = (
            endpoint.rstrip("/")
            or f"https://sqs.{self.region}.amazonaws.com"
        )
        # GetQueueUrl first, like the reference's initialize()
        try:
            status, body = self._call(
                {"Action": "GetQueueUrl", "QueueName": queue_name}
            )
        except OSError as e:  # DNS / refused / timeout, not an HTTP reply
            raise RuntimeError(
                f"notification queue 'aws_sqs' cannot reach {self.endpoint} "
                f"({e}); check the endpoint/network, or use the embedded "
                "[notification.logqueue]"
            ) from e
        if status != 200:
            raise RuntimeError(
                f"notification queue 'aws_sqs' cannot resolve queue "
                f"{queue_name!r} at {self.endpoint} (http {status} "
                f"{body[:200]!r}); check credentials/region, or use the "
                "embedded [notification.logqueue]"
            )
        import re

        m = re.search(rb"<QueueUrl>([^<]+)</QueueUrl>", body)
        if not m:
            raise RuntimeError(f"aws_sqs: no QueueUrl in {body[:200]!r}")
        self.queue_url = m.group(1).decode()

    def _call(self, params: dict) -> tuple[int, bytes]:
        """One signed Query-protocol POST to the endpoint root."""
        from seaweedfs_tpu.s3api.auth import sigv4_sign

        params = {"Version": "2012-11-05", **params}
        body = urllib.parse.urlencode(sorted(params.items())).encode()
        host = urllib.parse.urlparse(self.endpoint).netloc
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ"
        )
        headers = {
            "host": host,
            "x-amz-date": amz_date,
            "content-type": "application/x-www-form-urlencoded",
        }
        headers["Authorization"] = sigv4_sign(
            "POST",
            "/",
            "",
            headers,
            hashlib.sha256(body).hexdigest(),
            self.access_key,
            self.secret_key,
            self.region,
            "sqs",
            amz_date,
        )
        del headers["host"]  # urllib sets it
        return _post(f"{self.endpoint}/", body, headers)

    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        from google.protobuf import text_format

        status, body = self._call(
            {
                "Action": "SendMessage",
                "QueueUrl": self.queue_url,
                "MessageBody": text_format.MessageToString(message),
                "DelaySeconds": "10",
                "MessageAttribute.1.Name": "key",
                "MessageAttribute.1.Value.DataType": "String",
                "MessageAttribute.1.Value.StringValue": key,
            }
        )
        if status != 200:
            raise RuntimeError(f"aws_sqs send {key}: http {status} {body[:200]!r}")


class PubSubQueue:
    """notification.google_pub_sub over the REST publish endpoint."""

    name = "google_pub_sub"

    def __init__(
        self,
        project_id: str,
        topic: str,
        token: str = "",
        token_file: str = "",
        endpoint: str = "https://pubsub.googleapis.com",
    ):
        self.endpoint = endpoint.rstrip("/")
        self.path = f"/v1/projects/{project_id}/topics/{topic}"
        # token_file is re-read per request so an external refresher
        # (e.g. a cron running `gcloud auth print-access-token`) keeps
        # publishes working past the ~1 h OAuth token lifetime — the
        # role the reference's SDK credential auto-refresh plays
        self._token_file = token_file
        self._token = token
        if not token and not token_file and "googleapis.com" in self.endpoint:
            raise RuntimeError(
                "notification queue 'google_pub_sub' needs an OAuth bearer "
                "`token` or a `token_file` (or a custom `endpoint` for an "
                "emulator); or use the embedded [notification.logqueue]"
            )
        # existence probe, the role of the reference's topic.Exists →
        # CreateTopic flow: GET the topic; 404 → try to create it;
        # 403 → proceed (publisher-only credentials can publish but not
        # get/create — hard-failing would reject a valid config)
        status, body = self._get_topic()
        if status == 404:
            status, body = self._request(
                "PUT", self.path, json.dumps({}).encode()
            )
            if status not in (200, 409):
                raise RuntimeError(
                    f"google_pub_sub: topic missing and create failed "
                    f"(http {status} {body[:200]!r})"
                )
        elif status == 403:
            from seaweedfs_tpu.util import wlog

            wlog.warning(
                "google_pub_sub: cannot GET topic %s (403; publisher-only "
                "credentials?) — proceeding, publishes will tell",
                self.path,
            )
        elif status != 200:
            raise RuntimeError(
                f"google_pub_sub: topic at {self.endpoint}{self.path} not "
                f"usable (http {status} {body[:200]!r})"
            )

    def _headers_now(self) -> dict:
        headers = {"Content-Type": "application/json"}
        token = self._token
        if self._token_file:
            try:
                with open(self._token_file) as f:
                    token = f.read().strip()
            except OSError:
                pass  # fall back to the static token, if any
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    def _request(self, method: str, path: str, body: bytes | None):
        headers = self._headers_now()
        if body is None:
            headers.pop("Content-Type", None)
        req = urllib.request.Request(
            f"{self.endpoint}{path}", data=body, method=method, headers=headers
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except OSError as e:
            raise RuntimeError(
                f"notification queue 'google_pub_sub' cannot reach "
                f"{self.endpoint} ({e}); check the endpoint/network, or "
                "use the embedded [notification.logqueue]"
            ) from e

    def _get_topic(self):
        return self._request("GET", self.path, None)

    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        payload = {
            "messages": [
                {
                    "data": base64.b64encode(
                        message.SerializeToString()
                    ).decode(),
                    "attributes": {"key": key},
                }
            ]
        }
        status, body = self._request(
            "POST", f"{self.path}:publish", json.dumps(payload).encode()
        )
        if status != 200:
            raise RuntimeError(
                f"google_pub_sub publish {key}: http {status} {body[:200]!r}"
            )
