"""Partitioned durable log queue: the broker the reference outsources.

The reference's cross-cluster replication rides an external broker
(Kafka/SQS/PubSub, weed/notification/configuration.go); this is the
same capability as an embedded component, so replication runs durably
with zero external services:

  partitions  fixed count; a message goes to partition
              blake2b(key) % P (stable across processes — the same
              key always lands in the same partition, preserving
              per-path event order like Kafka's key partitioning)
  segments    per-partition append-only files named by base offset,
              rolled past `segment_bytes`; records are
              (len, crc32, payload) so torn tails and corruption are
              detected and cut at replay
  offsets     per-(group, partition) committed offset files, swapped
              atomically — consumer groups poll from their offset and
              commit after processing (at-least-once, Kafka semantics)
  trim()      drops whole segments below the minimum committed offset
              across all groups (retention by consumption)

Everything is plain files under one directory, so producer (filer
process) and consumers (`weed filer.replicate` processes) coordinate
cross-process through the filesystem the way the reference's
processes coordinate through a broker.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
import zlib

from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.util import durable
from seaweedfs_tpu.util import wlog

_REC = struct.Struct("<II")  # payload length, crc32


def _partition_of(key: str, partitions: int) -> int:
    d = hashlib.blake2b(key.encode(), digest_size=4).digest()
    return int.from_bytes(d, "little") % partitions


class _Partition:
    """One partition: segment files + append head. Offsets are logical
    record indices, monotonic from 0."""

    def __init__(self, directory: str, segment_bytes: int):
        self.dir = directory
        self.segment_bytes = segment_bytes
        os.makedirs(os.path.join(directory, "offsets"), exist_ok=True)
        self._lock = threading.Lock()
        # (base_offset, path, record_count) oldest → newest
        self.segments: list[tuple[int, str, int]] = []
        self._scan()
        self._active: "object | None" = None  # open file for appends

    def _scan(self) -> None:
        names = sorted(
            n for n in os.listdir(self.dir) if n.endswith(".seg")
        )
        for name in names:
            base = int(name.split(".")[0])
            path = os.path.join(self.dir, name)
            count = sum(1 for _ in _read_segment(path))
            self.segments.append((base, path, count))

    def _refresh(self) -> None:
        """Re-sync the segment view with the directory: a consumer
        process must see segments rolled — and records appended to the
        tail segment — by the producer process after open. Sealed
        segments are immutable, so only the cached tail is re-counted.
        Caller holds self._lock."""
        if self.segments:
            base, path, _ = self.segments[-1]
            self.segments[-1] = (
                base,
                path,
                sum(1 for _ in _read_segment(path)),
            )
        known = {path for _, path, _ in self.segments}
        names = sorted(n for n in os.listdir(self.dir) if n.endswith(".seg"))
        for name in names:
            path = os.path.join(self.dir, name)
            if path in known:
                continue
            base = int(name.split(".")[0])
            if self.segments and base < self.segments[-1][0]:
                continue  # trimmed-then-recreated can't happen; ignore stragglers
            count = sum(1 for _ in _read_segment(path))
            self.segments.append((base, path, count))

    @property
    def next_offset(self) -> int:
        if not self.segments:
            return 0
        base, _, count = self.segments[-1]
        return base + count

    def refreshed_next_offset(self) -> int:
        """next_offset after syncing with segments written by other
        processes (consumer-side lag accounting)."""
        with self._lock:
            self._refresh()
            return self.next_offset

    def append(self, payload: bytes) -> int:
        with self._lock:
            offset = self.next_offset
            if (
                self._active is None
                or self._active_size() >= self.segment_bytes
            ):
                self._roll(offset)
            self._active.write(
                _REC.pack(len(payload), zlib.crc32(payload)) + payload
            )
            self._active.flush()
            base, path, count = self.segments[-1]
            self.segments[-1] = (base, path, count + 1)
            return offset

    def _active_size(self) -> int:
        return self._active.tell() if self._active else 0

    def _roll(self, base_offset: int) -> None:
        if self._active is not None:
            self._active.close()
        path = os.path.join(self.dir, f"{base_offset:020d}.seg")
        self._active = open(path, "ab")
        if not self.segments or self.segments[-1][1] != path:
            self.segments.append((base_offset, path, 0))

    def read_from(self, offset: int, max_records: int):
        """[(offset, payload)] starting at logical `offset`."""
        out = []
        with self._lock:
            self._refresh()
            segs = list(self.segments)
        for base, path, count in segs:
            if base + count <= offset:
                continue
            for i, payload in enumerate(_read_segment(path)):
                o = base + i
                if o < offset:
                    continue
                out.append((o, payload))
                if len(out) >= max_records:
                    return out
        return out

    # --- consumer-group offsets ---

    def _offset_path(self, group: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in group)
        return os.path.join(self.dir, "offsets", safe)

    def committed(self, group: str) -> int:
        try:
            with open(self._offset_path(group)) as f:
                return int(f.read().strip() or "0")
        except (OSError, ValueError):
            return 0

    def register(self, group: str) -> None:
        """Materialize a zero offset for a group that has never
        committed, so trim()'s low-water mark accounts for it from its
        first poll — otherwise its unread segments could be deleted out
        from under it by groups that are further ahead. (A group that
        has never even polled still starts at the oldest retained
        segment, Kafka-style retention-by-consumption.)

        For an existing offset file this refreshes its mtime: polling
        is the liveness signal trim()'s staleness cutoff reads, so an
        abandoned group (one-off diagnostic poll, decommissioned
        consumer) stops pinning retention once it goes quiet."""
        p = self._offset_path(group)
        if os.path.exists(p):
            try:
                os.utime(p)
            except OSError:
                pass
        else:
            self.commit(group, 0)

    def commit(self, group: str, offset: int) -> None:
        p = self._offset_path(group)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(offset))
        # durable publish: a lost cursor re-delivers from the previous
        # commit (at-least-once holds), but a TORN one parse-fails and
        # restarts the group from zero
        durable.publish(tmp, p)

    def groups(self) -> list[str]:
        return os.listdir(os.path.join(self.dir, "offsets"))

    def trim(self, stale_after: float | None = None) -> int:
        """Delete whole segments every live group has consumed. Returns
        the number of segments removed. Never removes the active
        segment. Groups whose offset file hasn't been touched (by a
        commit or a poll's register) in `stale_after` seconds are
        treated as abandoned and stop pinning retention."""
        groups = self.groups()
        if not groups:
            return 0
        now = time.time()
        low = None
        for g in groups:
            if stale_after is not None:
                try:
                    mtime = os.stat(self._offset_path(g)).st_mtime
                except OSError:
                    continue
                if now - mtime > stale_after:
                    continue
            off = self.committed(g)
            low = off if low is None else min(low, off)
        if low is None:
            return 0
        removed = 0
        with self._lock:
            while len(self.segments) > 1:
                base, path, count = self.segments[0]
                if base + count > low:
                    break
                try:
                    os.unlink(path)
                except OSError:
                    pass
                self.segments.pop(0)
                removed += 1
        return removed

    def close(self) -> None:
        with self._lock:
            if self._active is not None:
                self._active.close()
                self._active = None


def _read_segment(path: str):
    """Yield payloads; stop at a torn or corrupt record (and warn)."""
    try:
        with open(path, "rb") as f:
            while True:
                hdr = f.read(_REC.size)
                if len(hdr) < _REC.size:
                    break
                length, crc = _REC.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length:
                    wlog.warning("logqueue: torn record tail in %s", path)
                    break
                if zlib.crc32(payload) != crc:
                    wlog.warning("logqueue: crc mismatch in %s; cut here", path)
                    break
                yield payload
    except OSError:
        return


class PartitionedLogQueue:
    """NotificationQueue + consumer API (see module docstring)."""

    def __init__(
        self,
        directory: str,
        partitions: int = 4,
        segment_bytes: int = 8 * 1024 * 1024,
        stale_group_seconds: float = 24 * 3600.0,
    ):
        self.stale_group_seconds = stale_group_seconds
        if partitions < 1:
            raise ValueError("partitions must be >= 1")
        self.dir = directory
        # the partition count is a property of the on-disk queue, not of
        # whoever opens it: key→partition routing and the p* directory
        # set are fixed at creation, so a later config change must not
        # silently strand messages in unreferenced partition dirs
        os.makedirs(directory, exist_ok=True)
        meta_path = os.path.join(directory, "meta.json")
        try:
            with open(meta_path) as f:
                existing = int(json.load(f)["partitions"])
        except (OSError, ValueError, KeyError):
            existing = 0
        if existing:
            if existing != partitions:
                wlog.warning(
                    "logqueue %s was created with %d partitions; "
                    "ignoring configured %d",
                    directory,
                    existing,
                    partitions,
                )
            partitions = existing
        else:
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"partitions": partitions}, f)
            # partition count is immutable once chosen; the meta file
            # must survive the crash or a restart re-partitions and
            # strands every queued message
            durable.publish(tmp, meta_path)
        self.partitions = [
            _Partition(os.path.join(directory, f"p{i:03d}"), segment_bytes)
            for i in range(partitions)
        ]

    # --- producer side (notification.Queue role) ---

    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        header = json.dumps({"key": key, "ts": time.time()}).encode()
        payload = (
            len(header).to_bytes(4, "big") + header + message.SerializeToString()
        )
        self.partitions[_partition_of(key, len(self.partitions))].append(payload)

    # --- consumer side ---

    @staticmethod
    def _decode(payload: bytes) -> tuple[str, fpb.EventNotification]:
        hlen = int.from_bytes(payload[:4], "big")
        header = json.loads(payload[4 : 4 + hlen])
        msg = fpb.EventNotification()
        msg.ParseFromString(payload[4 + hlen :])
        return header["key"], msg

    def poll(self, group: str, max_records: int = 256):
        """[(partition, offset, key, message)] after `group`'s committed
        offsets; at-least-once — call commit() per partition after
        processing. Fairness: each partition first gets an equal share
        of max_records (so one hot partition can't starve the rest),
        then leftover budget is filled from whatever has more."""
        quota = max(1, max_records // len(self.partitions))
        out = []
        budget = max_records
        leftovers = []
        for p in self.partitions:
            p.register(group)  # first poll pins the trim low-water mark
        for i, p in enumerate(self.partitions):
            if budget <= 0:
                break
            take = min(quota, budget)
            got = p.read_from(p.committed(group), take + 1)
            for o, payload in got[:take]:
                key, msg = self._decode(payload)
                out.append((i, o, key, msg))
                budget -= 1
            if len(got) > take:  # partition has more than its share
                leftovers.append(i)
        for i in leftovers:
            if budget <= 0:
                break
            p = self.partitions[i]
            start = max(
                (o for pt, o, _, _ in out if pt == i), default=p.committed(group) - 1
            ) + 1
            for o, payload in p.read_from(start, budget):
                key, msg = self._decode(payload)
                out.append((i, o, key, msg))
                budget -= 1
        return out

    def commit(self, group: str, partition: int, next_offset: int) -> None:
        """Record that `group` has processed everything below
        `next_offset` in `partition`."""
        self.partitions[partition].commit(group, next_offset)

    def committed(self, group: str, partition: int) -> int:
        return self.partitions[partition].committed(group)

    def trim(self) -> int:
        return sum(p.trim(self.stale_group_seconds) for p in self.partitions)

    def depth(self, group: str) -> int:
        """Unconsumed record count for a group (lag), synced with
        segments written by other processes."""
        return sum(
            max(0, p.refreshed_next_offset() - p.committed(group))
            for p in self.partitions
        )

    def close(self) -> None:
        for p in self.partitions:
            p.close()
