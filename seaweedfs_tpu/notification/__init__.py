"""Filer update-event notification queues.

Behavioral match of weed/notification/configuration.go: a process-wide
`queue` that the filer's NotifyUpdateEvent pushes (key,
EventNotification) messages into (filer2/filer_notify.go:9-39).
Backends here: log (glog-style), memory (in-process, subscribable),
dirqueue (durable file-per-message directory), logqueue (embedded
partitioned segmented log with consumer groups — the Kafka-role broker,
notification/logqueue.py), kafka (real wire-protocol producer,
notification/kafka.py), and aws_sqs / google_pub_sub (the AWS Query
protocol with SigV4 and the Pub/Sub REST publish endpoint,
notification/cloud_queues.py). None need client libraries; the gates
are connectivity and credentials.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.util import durable
from seaweedfs_tpu.util import wlog

queue = None  # process-wide, set by configure() (notification.Queue role)


class NotificationQueue:
    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        raise NotImplementedError


class LogQueue(NotificationQueue):
    """notification/log: prints events (debugging aid)."""

    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        wlog.info(
            "notify %s: old=%s new=%s delete_chunks=%s",
            key,
            message.old_entry.name or None,
            message.new_entry.name or None,
            message.delete_chunks,
        )


class MemoryQueue(NotificationQueue):
    """In-process queue with blocking subscription (test + single-node
    replication without external brokers)."""

    def __init__(self, maxlen: int = 65536):
        self._messages: deque = deque(maxlen=maxlen)
        self._cond = threading.Condition()

    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        with self._cond:
            self._messages.append((key, message))
            self._cond.notify_all()

    def receive(self, timeout: float | None = None):
        """Pop one (key, message); None on timeout."""
        with self._cond:
            if not self._messages:
                self._cond.wait(timeout)
            if not self._messages:
                return None
            return self._messages.popleft()

    def __len__(self) -> int:
        return len(self._messages)


class DirQueue(NotificationQueue):
    """Durable directory queue: one file per message, named by a
    monotonically increasing sequence so consumers replay in order.
    Fills the Kafka/SQS role for cross-process replication without
    external brokers."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._seq = self._max_existing_seq()

    def _max_existing_seq(self) -> int:
        best = 0
        for name in os.listdir(self.dir):
            if name.endswith(".msg"):
                try:
                    best = max(best, int(name.split(".")[0]))
                except ValueError:
                    pass
        return best

    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        with self._lock:
            self._seq += 1
            seq = self._seq
        payload = json.dumps({"key": key, "ts": time.time()}).encode()
        blob = message.SerializeToString()
        tmp = os.path.join(self.dir, f".{seq:020d}.tmp")
        final = os.path.join(self.dir, f"{seq:020d}.msg")
        with open(tmp, "wb") as f:
            f.write(len(payload).to_bytes(4, "big") + payload + blob)
        # atomic + durable publish: consumers treat presence of the
        # .msg name as "event fired"; a crash must not un-fire it
        durable.publish(tmp, final)

    def consume(self, after_seq: int = 0):
        """Yield (seq, key, message) for every message with seq >
        after_seq, in order."""
        names = sorted(n for n in os.listdir(self.dir) if n.endswith(".msg"))
        for name in names:
            seq = int(name.split(".")[0])
            if seq <= after_seq:
                continue
            with open(os.path.join(self.dir, name), "rb") as f:
                hlen = int.from_bytes(f.read(4), "big")
                header = json.loads(f.read(hlen))
                msg = fpb.EventNotification()
                msg.ParseFromString(f.read())
            yield seq, header["key"], msg


# kafka / aws_sqs / google_pub_sub live in kafka.py and cloud_queues.py
# — real wire-protocol implementations, gated on connectivity or
# credentials rather than on client libraries.


def configure(cfg) -> NotificationQueue | None:
    """Build the process queue from a notification.toml Configuration
    (server/filer_server.go:28-32 LoadConfiguration)."""
    global queue
    if cfg.get_bool("notification.log.enabled"):
        queue = LogQueue()
    elif cfg.get_bool("notification.memory.enabled"):
        queue = MemoryQueue()
    elif cfg.get_bool("notification.dirqueue.enabled"):
        queue = DirQueue(cfg.get_string("notification.dirqueue.dir", "./notifications"))
    elif cfg.get_bool("notification.logqueue.enabled"):
        from seaweedfs_tpu.notification.logqueue import PartitionedLogQueue

        queue = PartitionedLogQueue(
            cfg.get_string("notification.logqueue.dir", "./notifications"),
            partitions=cfg.get_int("notification.logqueue.partitions", 4),
        )
    elif cfg.get_bool("notification.kafka.enabled"):
        # real wire-protocol producer (notification/kafka.py); the gate
        # is connectivity, not a library — constructing raises with
        # guidance when no broker answers
        from seaweedfs_tpu.notification.kafka import KafkaQueue

        queue = KafkaQueue(
            cfg.get_string("notification.kafka.hosts", "localhost:9092"),
            topic=cfg.get_string("notification.kafka.topic", "seaweedfs_filer"),
        )
    elif cfg.get_bool("notification.aws_sqs.enabled"):
        from seaweedfs_tpu.notification.cloud_queues import SqsQueue

        queue = SqsQueue(
            cfg.get_string("notification.aws_sqs.aws_access_key_id", ""),
            cfg.get_string("notification.aws_sqs.aws_secret_access_key", ""),
            cfg.get_string("notification.aws_sqs.region", "us-east-1"),
            cfg.get_string("notification.aws_sqs.sqs_queue_name", ""),
            endpoint=cfg.get_string("notification.aws_sqs.endpoint", ""),
        )
    elif cfg.get_bool("notification.google_pub_sub.enabled"):
        from seaweedfs_tpu.notification.cloud_queues import PubSubQueue

        queue = PubSubQueue(
            cfg.get_string("notification.google_pub_sub.project_id", ""),
            cfg.get_string("notification.google_pub_sub.topic", "seaweedfs_filer_topic"),
            token=cfg.get_string("notification.google_pub_sub.token", ""),
            token_file=cfg.get_string(
                "notification.google_pub_sub.token_file", ""
            ),
            endpoint=cfg.get_string(
                "notification.google_pub_sub.endpoint",
                "https://pubsub.googleapis.com",
            ),
        )
    else:
        queue = None
    return queue
