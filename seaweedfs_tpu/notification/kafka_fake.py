"""In-repo fake Kafka broker: the offline test peer for kafka.py.

Implements exactly the protocol surface the client speaks — Metadata
v0, Produce v3, Fetch v4 with record-batch v2 — over a threaded TCP
server, storing records per (topic, partition) in memory. Base offsets
are assigned on append like a real log; Fetch returns re-encoded
batches from the requested offset. The point is an end-to-end wire
test (replication e2e over a real socket) without a JVM in the image;
it is NOT a broker (no groups, no replication, no retention).

Runnable standalone for manual poking:
    python -m seaweedfs_tpu.notification.kafka_fake [port]
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

from seaweedfs_tpu.notification.kafka import (
    API_FETCH,
    API_METADATA,
    API_PRODUCE,
    _Reader,
    _bytes,
    _str,
    decode_record_batches,
    encode_record_batch,
)


class FakeKafkaBroker:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, partitions: int = 2):
        self.partitions = partitions
        # ApiVersions ranges advertised to clients; tests shrink these
        # to exercise the client's unsupported-version gate
        self.api_ranges = {0: (0, 8), 1: (0, 11), 3: (0, 9), 18: (0, 0)}
        # drop connections on the ApiVersions probe like a pre-0.10
        # broker (tests of the client's optimistic fallback)
        self.drop_api_versions = False
        # (topic, partition) -> list[(key, value)]; index == offset
        self.logs: dict[tuple[str, int], list] = {}
        self._lock = threading.Lock()
        broker = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, True
                )
                rfile = self.request.makefile("rb")
                while True:
                    raw = rfile.read(4)
                    if len(raw) < 4:
                        return
                    (size,) = struct.unpack(">i", raw)
                    payload = rfile.read(size)
                    if len(payload) < size:
                        return
                    r = _Reader(payload)
                    api_key, api_version, corr = r.i16(), r.i16(), r.i32()
                    r.string()  # client id
                    if api_key == 18:  # ApiVersions
                        if broker.drop_api_versions:
                            return  # pre-0.10 behavior: kill the conn
                        body = struct.pack(">hi", 0, len(broker.api_ranges))
                        for k, (lo, hi) in sorted(broker.api_ranges.items()):
                            body += struct.pack(">hhh", k, lo, hi)
                    elif api_key == API_METADATA:
                        body = broker._metadata(r)
                    elif api_key == API_PRODUCE:
                        body = broker._produce(r)
                    elif api_key == API_FETCH:
                        body = broker._fetch(r)
                    else:
                        return  # unsupported: drop the connection
                    resp = struct.pack(">i", corr) + body
                    self.request.sendall(struct.pack(">i", len(resp)) + resp)

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address

    # --- api bodies -----------------------------------------------------
    def _metadata(self, r: _Reader) -> bytes:
        topics = [r.string() for _ in range(r.i32())]
        out = struct.pack(">i", 1)  # one broker
        out += struct.pack(">i", 0) + _str(self.host) + struct.pack(">i", self.port)
        out += struct.pack(">i", len(topics))
        for t in topics:
            out += struct.pack(">h", 0) + _str(t)
            out += struct.pack(">i", self.partitions)
            for p in range(self.partitions):
                out += struct.pack(">hiii", 0, p, 0, 1)  # err, id, leader, nreplicas
                out += struct.pack(">i", 0)  # replica 0
                out += struct.pack(">ii", 1, 0)  # isr [0]
        return out

    def _produce(self, r: _Reader) -> bytes:
        r.string()  # transactional id
        r.i16()  # acks
        r.i32()  # timeout
        out_topics = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _p in range(r.i32()):
                pid = r.i32()
                blob = r.nbytes() or b""
                records = decode_record_batches(blob)
                with self._lock:
                    log = self.logs.setdefault((topic, pid), [])
                    base = len(log)
                    log.extend((k, v) for _off, k, v in records)
                parts.append((pid, 0, base))
            out_topics.append((topic, parts))
        out = struct.pack(">i", len(out_topics))
        for topic, parts in out_topics:
            out += _str(topic) + struct.pack(">i", len(parts))
            for pid, err, base in parts:
                out += struct.pack(">ihqq", pid, err, base, -1)
        out += struct.pack(">i", 0)  # throttle
        return out

    def _fetch(self, r: _Reader) -> bytes:
        r.i32(), r.i32(), r.i32(), r.i32()  # replica, max_wait, min, max
        r.i8()  # isolation
        reqs = []
        for _ in range(r.i32()):
            topic = r.string()
            for _p in range(r.i32()):
                pid = r.i32()
                off = r.i64()
                r.i32()  # partition max bytes
                reqs.append((topic, pid, off))
        out = struct.pack(">i", 0)  # throttle
        by_topic: dict[str, list] = {}
        for topic, pid, off in reqs:
            by_topic.setdefault(topic, []).append((pid, off))
        out += struct.pack(">i", len(by_topic))
        for topic, parts in by_topic.items():
            out += _str(topic) + struct.pack(">i", len(parts))
            for pid, off in parts:
                with self._lock:
                    log = list(self.logs.get((topic, pid), []))
                high = len(log)
                slice_ = log[off:]
                if slice_:
                    blob = bytearray(
                        encode_record_batch([(k, v) for k, v in slice_], 0)
                    )
                    struct.pack_into(">q", blob, 0, off)  # base offset
                    blob = bytes(blob)
                else:
                    blob = b""
                out += struct.pack(">ihqq", pid, 0, high, high)
                out += struct.pack(">i", 0)  # no aborted txns
                out += _bytes(blob)
        return out

    # --- lifecycle ------------------------------------------------------
    def start(self) -> None:
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


if __name__ == "__main__":
    import sys

    port = int(sys.argv[1]) if len(sys.argv) > 1 else 9092
    b = FakeKafkaBroker(port=port)
    b.start()
    print(f"fake kafka broker on {b.host}:{b.port} (ctrl-c to stop)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        b.stop()
