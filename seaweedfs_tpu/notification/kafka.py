"""Kafka wire-protocol client: the notification/kafka role, no library.

Behavioral match of weed/notification/kafka/kafka_queue.go (producer:
filer events → topic, partitioned by the entry path as the message
key) and weed/replication/sub/notification_kafka.go (consumer feeding
`weed filer.replicate`). The reference rides the sarama library; this
module speaks the broker protocol directly over one TCP connection —
the pieces the role needs, at pinned versions implemented end-to-end
(and mirrored by the in-repo fake broker, kafka_fake.py, so the whole
path is testable offline):

  ApiVersions — not sent; versions are pinned (below)
  Metadata v0 (api_key 3) — topic → partition leaders
  Produce  v3 (api_key 0) — record-batch v2 (magic 2) with crc32c,
               acks=1, one batch per send
  Fetch    v4 (api_key 1) — record-batch v2 decode from an offset

Consumer-group coordination (JoinGroup/OffsetCommit…) is deliberately
absent: the replicate runner owns its offsets durably on its side the
same way the embedded logqueue consumer does, so a single subscriber
per topic needs no broker-side group state. Connectivity is the gate:
constructing KafkaQueue dials the broker and raises with guidance when
nothing is listening (notification/__init__.py configure()).

Wire primitives are big-endian; record-batch internals use zigzag
varints (the v2 format).
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from seaweedfs_tpu.pb import filer_pb2 as fpb

API_PRODUCE, API_FETCH, API_METADATA, API_VERSIONS = 0, 1, 3, 18
# the pinned wire versions this client speaks (module docstring)
PINNED_VERSIONS = {API_PRODUCE: 3, API_FETCH: 4, API_METADATA: 0}
_CLIENT_ID = "seaweedfs-tpu"


# --- primitive codecs -------------------------------------------------------


def _str(s: str | None) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _varint(n: int) -> bytes:
    u = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def take(self, n: int) -> bytes:
        d = self.data[self.off : self.off + n]
        if len(d) < n:
            raise ValueError("kafka: short buffer")
        self.off += n
        return d

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]

    def string(self) -> str | None:
        n = self.i16()
        return None if n < 0 else self.take(n).decode()

    def nbytes(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self.take(n)

    def varint(self) -> int:
        shift = u = 0
        while True:
            b = self.data[self.off]
            self.off += 1
            u |= (b & 0x7F) << shift
            if not b & 0x80:
                return _unzigzag(u)
            shift += 7


# --- record batch v2 (magic 2) ----------------------------------------------


def _crc32c(data: bytes) -> int:
    from seaweedfs_tpu.native import crc32c

    return crc32c(data)


def encode_record_batch(
    records: list[tuple[bytes | None, bytes]], timestamp_ms: int
) -> bytes:
    """[(key, value)] → one record-batch v2 blob (base offset 0; the
    broker rewrites it on append)."""
    body = bytearray()
    for i, (key, value) in enumerate(records):
        rec = bytearray(b"\x00")  # attributes
        rec += _varint(0)  # timestamp delta
        rec += _varint(i)  # offset delta
        if key is None:
            rec += _varint(-1)
        else:
            rec += _varint(len(key)) + key
        rec += _varint(len(value)) + value
        rec += _varint(0)  # headers
        body += _varint(len(rec)) + rec
    n = len(records)
    head = struct.pack(
        ">hiqqqhii",
        0,  # attributes (no compression, create-time)
        n - 1,  # last offset delta
        timestamp_ms,  # first timestamp
        timestamp_ms,  # max timestamp
        -1,  # producer id
        -1,  # producer epoch
        -1,  # base sequence
        n,  # record count
    )
    crc_payload = head + bytes(body)
    crc = _crc32c(crc_payload)
    after_length = struct.pack(">iB I", 0, 2, crc) + crc_payload
    #                 partitionLeaderEpoch^ magic^  ^crc
    return struct.pack(">qi", 0, len(after_length)) + after_length


def decode_record_batches(blob: bytes):
    """record-set bytes → [(offset, key, value)] across all batches."""
    out = []
    r = _Reader(blob)
    while r.off + 61 <= len(r.data):
        base_offset = r.i64()
        batch_len = r.i32()
        end = r.off + batch_len
        if end > len(r.data):
            break  # partial batch at the tail (Fetch may truncate)
        r.i32()  # partition leader epoch
        magic = r.i8()
        if magic != 2:
            raise ValueError(f"kafka: unsupported magic {magic}")
        r.u32()  # crc (trusted: in-process / tested path)
        attrs = r.i16()
        if attrs & 0x07:
            # a real broker with compression.type set re-compresses on
            # append; walking the varint parser over a gzip/zstd blob
            # would die opaquely (or misparse) — fail diagnosably
            raise ValueError(
                "kafka: compressed record batches unsupported "
                f"(attributes={attrs:#x}); set compression.type=none "
                "on the topic"
            )
        r.i32()  # last offset delta
        r.i64()  # first timestamp
        r.i64()  # max timestamp
        r.i64()  # producer id
        r.i16()  # producer epoch
        r.i32()  # base sequence
        count = r.i32()
        for _ in range(count):
            r.varint()  # record length
            r.i8()  # attributes
            r.varint()  # timestamp delta
            delta = r.varint()
            klen = r.varint()
            key = None if klen < 0 else r.take(klen)
            vlen = r.varint()
            value = b"" if vlen < 0 else r.take(vlen)
            hdrs = r.varint()
            for _h in range(hdrs):
                hk = r.varint()
                r.take(hk)
                hv = r.varint()
                if hv > 0:
                    r.take(hv)
            out.append((base_offset + delta, key, value))
        r.off = end
    return out


# --- connection -------------------------------------------------------------


class KafkaConnection:
    """One broker connection: framed request/response, correlation ids."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        self._rfile = self.sock.makefile("rb")
        self._corr = 0
        self._lock = threading.Lock()

    def close(self) -> None:
        for c in (self._rfile.close, self.sock.close):
            try:
                c()
            except OSError:
                pass

    def call(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            req = (
                struct.pack(">hhi", api_key, api_version, corr)
                + _str(_CLIENT_ID)
                + body
            )
            self.sock.sendall(struct.pack(">i", len(req)) + req)
            raw = self._rfile.read(4)
            if len(raw) < 4:
                raise ConnectionError("kafka: broker closed connection")
            (size,) = struct.unpack(">i", raw)
            payload = self._rfile.read(size)
            if len(payload) < size:
                raise ConnectionError("kafka: short response")
        r = _Reader(payload)
        got = r.i32()
        if got != corr:
            raise ValueError(f"kafka: correlation mismatch {got} != {corr}")
        return r


class KafkaError(RuntimeError):
    """A broker-reported error code."""

    OFFSET_OUT_OF_RANGE = 1

    def __init__(self, api: str, code: int, high_watermark: int = -1):
        super().__init__(f"kafka {api} error {code}")
        self.code = code
        self.high_watermark = high_watermark


class KafkaClient:
    """Metadata + Produce + Fetch against one bootstrap broker."""

    def __init__(self, hosts: str, timeout: float = 10.0):
        host, _, port = hosts.split(",")[0].strip().partition(":")
        self.host, self.port = host, int(port or 9092)
        self.timeout = timeout
        self._conn: KafkaConnection | None = None
        self._versions_checked = False

    def _connection(self) -> KafkaConnection:
        if self._conn is None:
            conn = KafkaConnection(self.host, self.port, self.timeout)
            if not self._versions_checked:
                conn = self._negotiate(conn)
                self._versions_checked = True
            self._conn = conn
        return self._conn

    def _negotiate(self, conn: KafkaConnection) -> KafkaConnection:
        """ApiVersions handshake at dial (sarama negotiates the same
        way behind the reference's kafka queue): confirm the broker
        supports the pinned Metadata/Produce/Fetch versions, raising
        with guidance when it does not — a graceful gate instead of a
        mid-publish protocol error against a too-new/too-old broker.
        Brokers that kill the connection on the probe (pre-0.10, or
        proxies dropping unknown api keys) get the pinned versions
        optimistically on a fresh dial."""
        try:
            r = conn.call(API_VERSIONS, 0, b"")
            if r.i16() != 0:  # e.g. 35 UNSUPPORTED_VERSION — proceed
                return conn
            ranges = {}
            for _ in range(r.i32()):
                key, lo, hi = r.i16(), r.i16(), r.i16()
                ranges[key] = (lo, hi)
        except (OSError, ValueError, ConnectionError, struct.error, IndexError):
            # no/odd ApiVersions support (pre-0.10 broker, proxy with
            # strange framing): optimistic pinned versions, fresh dial
            conn.close()
            return KafkaConnection(self.host, self.port, self.timeout)
        names = {API_PRODUCE: "Produce", API_FETCH: "Fetch", API_METADATA: "Metadata"}
        for key, pinned in PINNED_VERSIONS.items():
            lo, hi = ranges.get(key, (None, None))
            if lo is None or not lo <= pinned <= hi:
                conn.close()
                raise RuntimeError(
                    f"kafka broker {self.host}:{self.port} does not support "
                    f"{names[key]} v{pinned} (broker offers "
                    f"{'nothing' if lo is None else f'v{lo}..v{hi}'}); this "
                    "client speaks pinned versions (Metadata v0 / Produce "
                    "v3 / Fetch v4, notification/kafka.py) — use a broker "
                    "in that range or bridge through one"
                )
        return conn

    def _call(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        """call() with reconnect: a dead or desynced connection (broker
        restart, timeout mid-read leaving stale bytes, correlation
        mismatch) is dropped and the request retried once on a fresh
        dial — never cached forever."""
        for attempt in (0, 1):
            try:
                return self._connection().call(api_key, api_version, body)
            except (OSError, ValueError):
                self.close()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def metadata(self, topic: str) -> list[int]:
        """Partition ids of `topic` (Metadata v0)."""
        body = struct.pack(">i", 1) + _str(topic)
        r = self._call(API_METADATA, 0, body)
        for _ in range(r.i32()):  # brokers
            r.i32(), r.string(), r.i32()
        partitions: list[int] = []
        for _ in range(r.i32()):  # topics
            err = r.i16()
            name = r.string()
            for _p in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                r.i32()  # leader
                for _x in range(r.i32()):
                    r.i32()  # replicas
                for _x in range(r.i32()):
                    r.i32()  # isr
                if name == topic and err == 0 and perr == 0:
                    partitions.append(pid)
        return sorted(partitions)

    def produce(
        self,
        topic: str,
        partition: int,
        records: list[tuple[bytes | None, bytes]],
    ) -> int:
        """Produce v3, acks=1; returns the base offset assigned."""
        batch = encode_record_batch(records, int(time.time() * 1000))
        body = (
            _str(None)  # transactional_id
            + struct.pack(">hi", 1, int(self.timeout * 1000))  # acks, timeout
            + struct.pack(">i", 1)  # one topic
            + _str(topic)
            + struct.pack(">i", 1)  # one partition
            + struct.pack(">i", partition)
            + _bytes(batch)
        )
        # retried via _call on transport failure: acks=1 retry-after-send
        # can duplicate, the same at-least-once contract sarama's default
        # producer retries give the reference
        r = self._call(API_PRODUCE, 3, body)
        base_offset = -1
        for _ in range(r.i32()):  # topics
            r.string()
            for _p in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                base_offset = r.i64()
                r.i64()  # log append time
                if err:
                    raise KafkaError("produce", err)
        r.i32()  # throttle_time_ms
        return base_offset

    def fetch(
        self, topic: str, partition: int, offset: int, max_bytes: int = 1 << 20
    ):
        """Fetch v4 from `offset`: ([(offset, key, value)], high_watermark)."""
        body = (
            struct.pack(">iiii", -1, 100, 1, max_bytes)  # replica, wait, min, max
            + struct.pack(">b", 0)  # isolation level: read_uncommitted
            + struct.pack(">i", 1)
            + _str(topic)
            + struct.pack(">i", 1)
            + struct.pack(">iqi", partition, offset, max_bytes)
        )
        r = self._call(API_FETCH, 4, body)
        r.i32()  # throttle
        records, high = [], 0
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                high = r.i64()
                r.i64()  # last stable offset
                for _a in range(r.i32()):  # aborted transactions
                    r.i64(), r.i64()
                blob = r.nbytes() or b""
                if err:
                    raise KafkaError("fetch", err, high_watermark=high)
                records.extend(
                    x for x in decode_record_batches(blob) if x[0] >= offset
                )
        return records, high


# --- the notification queue -------------------------------------------------


# stable key → partition slot: the SAME blake2b router the embedded
# logqueue uses (one implementation — they must never drift; sarama's
# default hash partitioner differs, a documented deviation: both give
# per-key ordering, which is the contract)
from seaweedfs_tpu.notification.logqueue import _partition_of  # noqa: E402


class KafkaQueue:
    """notification.kafka: filer events → a Kafka topic
    (notification/kafka/kafka_queue.go SendMessage: proto payload,
    path as the key)."""

    def __init__(self, hosts: str, topic: str = "seaweedfs_filer"):
        self.topic = topic
        self.client = KafkaClient(hosts)
        try:
            self.partitions = self.client.metadata(topic) or [0]
        except OSError as e:
            raise RuntimeError(
                f"notification queue 'kafka' cannot reach a broker at "
                f"{hosts!r} ({e}); start one (or the in-repo fake: "
                "python -m seaweedfs_tpu.notification.kafka_fake), or use "
                "the embedded [notification.logqueue]"
            ) from e

    def send_message(self, key: str, message: fpb.EventNotification) -> None:
        # index into the partition-ID list: metadata() can return a
        # non-contiguous set (a partition mid-leader-election is
        # skipped), so the hash picks a slot, not an id
        pid = self.partitions[_partition_of(key, len(self.partitions))]
        self.client.produce(
            self.topic, pid, [(key.encode(), message.SerializeToString())]
        )

    def close(self) -> None:
        self.client.close()


class KafkaSubscriber:
    """replication/sub/notification_kafka.go role: poll (key, event)
    pairs from the topic, offsets owned by the caller."""

    def __init__(self, hosts: str, topic: str = "seaweedfs_filer"):
        self.topic = topic
        self.client = KafkaClient(hosts)
        try:
            self.partitions = self.client.metadata(topic) or [0]
        except OSError as e:
            raise RuntimeError(
                f"filer.replicate cannot reach a kafka broker at "
                f"{hosts!r} ({e}); start one (or the in-repo fake: "
                "python -m seaweedfs_tpu.notification.kafka_fake), or use "
                "the embedded [notification.logqueue]"
            ) from e
        self.offsets = {p: 0 for p in self.partitions}

    def poll(self, max_records: int = 256):
        """[(partition, offset, key, EventNotification)] after the
        current offsets; advance with commit()."""
        from seaweedfs_tpu.util import wlog

        out = []
        for p in self.partitions:
            if len(out) >= max_records:
                break
            try:
                records, _high = self.client.fetch(
                    self.topic, p, self.offsets[p]
                )
            except KafkaError as e:
                if e.code != KafkaError.OFFSET_OUT_OF_RANGE:
                    raise
                # broker retention trimmed past our durable offset: a
                # crash-loop helps nobody — resume at the log end and
                # say loudly what was skipped (no ListOffsets in the
                # pinned protocol subset, so log-start isn't knowable)
                wlog.error(
                    "kafka partition %d: offset %d out of range "
                    "(broker retention?); resetting to high watermark %d "
                    "— events in between are NOT replicated",
                    p, self.offsets[p], e.high_watermark,
                )
                self.offsets[p] = max(e.high_watermark, 0)
                continue
            for off, key, value in records[: max_records - len(out)]:
                ev = fpb.EventNotification()
                ev.ParseFromString(value)
                out.append((p, off, (key or b"").decode(), ev))
        return out

    def commit(self, partition: int, next_offset: int) -> None:
        self.offsets[partition] = next_offset

    def close(self) -> None:
        self.client.close()
