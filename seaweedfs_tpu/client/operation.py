"""Client SDK: assign / upload / lookup / delete / submit / tail.

Behavioral match of weed/operation/:
  * assign            — master Assign gRPC (assign_file_id.go:33)
  * upload            — POST bytes to a volume server (upload_content.go)
  * lookup            — master LookupVolume with a TTL cache (lookup.go:36)
  * delete_files      — vid-grouped batch delete via volume-server
                        BatchDelete gRPC (delete_content.go:43)
  * submit_files      — assign+upload, auto-splitting big payloads into
                        chunks behind a chunk-manifest needle
                        (submit.go:40,112, chunked_file.go)
  * tail_volume       — VolumeIncrementalCopy stream replay
                        (tail_volume.go, volume_backup.go:170)
"""

from __future__ import annotations

import functools
import http.client
import json
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

import grpc

from seaweedfs_tpu.client import retry as _retry
from seaweedfs_tpu.pb import master_pb2, rpc, volume_pb2
from seaweedfs_tpu.pb.rpc import grpc_address
from seaweedfs_tpu.util import deadline as _deadline
from seaweedfs_tpu.util.deadline import DeadlineExceeded


# ----------------------------------------------------------------------
# HA master failover


def _is_retryable_master_error(e: Exception) -> bool:
    """Transport failures and leaderless windows rotate to the next
    master; in-band application errors (e.g. 'no free volumes') come
    from the leader itself — every master proxies to the same place,
    so retrying them elsewhere just multiplies the same failure."""
    if isinstance(e, DeadlineExceeded):
        return False  # the caller's budget is gone wherever we turn
    if isinstance(e, (OSError, grpc.RpcError)):
        return True
    return "no leader" in str(e)


class AllMastersFailed(Exception):
    """One full rotation through the seed list failed retryably."""

    def __init__(self, last: Exception):
        super().__init__(str(last))
        self.last = last


# Bounded, jittered rounds over the seed list: a leader SIGKILL lands
# mid-election, so the first rotation often finds only "no leader yet"
# followers — the backoff is sized to span one election timeout
# (cluster/raft.py defaults 0.4-0.8 s) without hammering the survivors.
_MASTER_POLICY = _retry.RetryPolicy(
    backoff_ms=150,
    backoff_max_ms=1500,
    retry_on=(AllMastersFailed,),
    label="master-failover",
)


def with_master_failover(masters, fn, start_idx: int = 0, policy=None):
    """Run fn(master) against the first master that answers, rotating
    through the seed list on connection/RPC failure (any live master
    serves: non-leaders proxy writes to the leader). Returns
    (result, index_of_master_used); raises the last error when every
    master stays down. The single home for try-each-master logic.

    Rotation is wrapped in the unified RetryPolicy (client/retry.py):
    a whole-list failure — the signature of a leader kill with the new
    election still in flight — retries with exponential backoff + full
    jitter, charged to the process-wide retry budget and bounded by
    the ambient request deadline, instead of surfacing the raw
    connection error to the caller after one pass."""
    policy = policy or _MASTER_POLICY
    n = len(masters)

    def one_round(attempt):
        last: Exception | None = None
        for i in range(n):
            idx = (start_idx + i) % n
            try:
                return fn(masters[idx]), idx
            except (RuntimeError, OSError, grpc.RpcError) as e:
                if not _is_retryable_master_error(e):
                    raise
                last = e
        if last is None:
            raise RuntimeError("no masters configured")
        raise AllMastersFailed(last)

    try:
        return policy.run(one_round, idempotent=True)
    except AllMastersFailed as e:
        raise e.last


# ----------------------------------------------------------------------
# assign


@dataclass
class AssignResult:
    fid: str
    url: str
    public_url: str
    count: int
    error: str = ""
    auth: str = ""  # write-JWT for the fid; pass as upload(jwt=...)


@functools.lru_cache(maxsize=1024)
def _upload_query(filename: str, ttl: str, is_chunk_manifest: bool) -> str:
    """Encoded upload query params, memoized (filenames repeat heavily
    in bulk ingest: the benchmark, filer chunk uploads)."""
    q: dict[str, str] = {}
    if filename:
        q["filename"] = filename
    if ttl:
        q["ttl"] = ttl
    if is_chunk_manifest:
        q["cm"] = "true"
    return urllib.parse.urlencode(q)


@functools.lru_cache(maxsize=1024)
def _assign_query(
    count: int, replication: str, collection: str, ttl: str, data_center: str
) -> str:
    """Encoded /dir/assign query, memoized — writers issue the same
    parameter tuple per call, and urllib quoting is a measurable share
    of the client's per-write CPU."""
    params = {"count": str(count)}
    if replication:
        params["replication"] = replication
    if collection:
        params["collection"] = collection
    if ttl:
        params["ttl"] = ttl
    if data_center:
        params["dataCenter"] = data_center
    return urllib.parse.urlencode(params)


def assign(
    master: str,
    count: int = 1,
    replication: str = "",
    collection: str = "",
    ttl: str = "",
    data_center: str = "",
) -> AssignResult:
    """Assign over the pooled keep-alive HTTP plane (/dir/assign).

    The reference's operation.Assign rides gRPC; in Python, a unary
    grpc call costs several times a pooled http.client round-trip on
    the CPython side (measured: the benchmark writer spends more in
    grpc channel machinery than in the upload itself), so the hot
    path uses HTTP and `assign_grpc` remains for gRPC-plane parity."""
    q = _assign_query(count, replication, collection, ttl, data_center)
    status, _, body = http_call("GET", f"{master}/dir/assign?{q}", timeout=30)
    try:
        # decode first: json.loads(bytes) runs detect_encoding per call
        d = json.loads(body.decode("utf-8", "replace"))
    except ValueError:
        raise RuntimeError(f"assign: bad response {body[:200]!r}")
    if status != 200 or d.get("error"):
        raise RuntimeError(f"assign: {d.get('error', f'http {status}')}")
    return AssignResult(
        d["fid"],
        d["url"],
        d.get("publicUrl", d["url"]),
        d.get("count", count),
        auth=d.get("auth", ""),
    )


def assign_grpc(
    master: str,
    count: int = 1,
    replication: str = "",
    collection: str = "",
    ttl: str = "",
    data_center: str = "",
) -> AssignResult:
    """gRPC Assign (the reference's wire, master_grpc_server.go)."""
    ch = rpc.cached_channel(grpc_address(master))
    resp = rpc.master_stub(ch).Assign(
        master_pb2.AssignRequest(
            count=count,
            replication=replication,
            collection=collection,
            ttl=ttl,
            data_center=data_center,
        )
    )
    if resp.error:
        raise RuntimeError(f"assign: {resp.error}")
    return AssignResult(
        resp.fid, resp.url, resp.public_url, resp.count, auth=resp.auth
    )


# ----------------------------------------------------------------------
# upload


@dataclass
class UploadResult:
    name: str = ""
    size: int = 0
    etag: str = ""
    error: str = ""


# --- pooled keep-alive HTTP (the Go http.Client role) ----------------
#
# urllib.request opens and closes a TCP connection per call; the
# servers all speak HTTP/1.1 keep-alive, so the data plane's hot path
# (assign→upload, lookup→download) was paying a handshake plus
# TIME_WAIT churn per blob. One http.client.HTTPConnection per
# (thread, host) fixes that — thread-local because HTTPConnection is
# not thread-safe. A pooled connection can go stale between calls
# (server restart, idle timeout); one retry on a fresh connection
# mirrors Go's transport behavior.

_http_pool = threading.local()


class _RawHTTPConnection:
    """Minimal HTTP/1.1 client connection on a raw socket.

    http.client routes every response through the email-parser header
    machinery (policy objects, MIME content-type parsing); under the
    write benchmark that parsing costs more CPU than the needle append
    being benchmarked. This class composes the request in one buffer
    (one sendall — with Nagle disabled so nothing waits on a delayed
    ACK) and parses responses with a split-on-colon loop into the
    case-insensitive FastHeaders map. Supports what the cluster's own
    servers speak: HTTP/1.1 keep-alive, Content-Length and chunked
    bodies, 100-continue interim responses."""

    def __init__(self, host: str, port: int, timeout: float):
        from seaweedfs_tpu.util.httpd import _BufReader

        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        self.rfile = _BufReader(self.sock)
        self.timeout = timeout
        self._host = host if port == 80 else f"{host}:{port}"

    def settimeout(self, timeout: float) -> None:
        self.timeout = timeout
        self.sock.settimeout(timeout)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def send_request(
        self, method: str, path: str, body: bytes | None, headers: dict
    ) -> None:
        buf = bytearray(
            f"{method} {path} HTTP/1.1\r\nHost: {self._host}\r\n".encode("latin-1")
        )
        for k, v in headers.items():
            buf += f"{k}: {v}\r\n".encode("latin-1")
        if body is not None or method in ("POST", "PUT"):
            buf += b"Content-Length: %d\r\n" % (len(body) if body else 0)
        buf += b"\r\n"
        if body:
            buf += body
        self.sock.sendall(buf)

    def _read_exact(self, n: int) -> bytes:
        data = self.rfile.read(n)
        if len(data) != n:
            raise http.client.IncompleteRead(data, n - len(data))
        return data

    def read_response(self, method: str):
        """(status, FastHeaders, body, will_close)."""
        from seaweedfs_tpu.util.httpd import FastHeaders

        while True:
            # whole head in one buffer scan + ONE decode: readline-per-
            # header and per-line bytes strip/lower/decode were the
            # client hot loop's biggest Python cost after syscalls
            head = self.rfile.read_head()
            if not head:
                raise http.client.RemoteDisconnected("no status line")
            lines = head[:-4].decode("iso-8859-1").split("\r\n")
            line = lines[0]
            if (
                (line[:9] == "HTTP/1.1 " or line[:9] == "HTTP/1.0 ")
                and line[9:12].isdigit()
            ):
                version = "HTTP/1.1" if line[7] == "1" else "HTTP/1.0"
                status = int(line[9:12])
            else:
                parts = line.split(None, 2)
                if len(parts) < 2 or not parts[0].startswith("HTTP/"):
                    raise http.client.BadStatusLine(line)
                try:
                    version, status = parts[0], int(parts[1])
                except ValueError:
                    raise http.client.BadStatusLine(line) from None
            headers = FastHeaders()
            for hline in lines[1:]:
                key, sep, value = hline.partition(":")
                if sep:
                    headers[key.strip().lower()] = value.strip()
            if status != 100:
                break
            # 100 Continue: interim — the real response follows
        conn_tok = headers.get("connection", "").lower()
        will_close = conn_tok == "close" or (
            version == "HTTP/1.0" and conn_tok != "keep-alive"
        )
        body = b""
        if method != "HEAD" and status not in (204, 304):
            if "chunked" in headers.get("transfer-encoding", "").lower():
                pieces = []
                while True:
                    szline = self.rfile.readline(65537).strip()
                    if not szline:
                        # EOF mid-body is truncation, NOT a terminal
                        # 0-size chunk — callers must never get a
                        # partial body under a 200
                        raise http.client.IncompleteRead(
                            b"".join(pieces)
                        )
                    try:
                        size = int(szline.split(b";")[0], 16)
                    except ValueError:
                        raise http.client.HTTPException(
                            f"bad chunk size {szline[:32]!r}"
                        ) from None
                    if size == 0:
                        while True:  # trailers until blank line
                            t = self.rfile.readline(65537)
                            if t in (b"\r\n", b"\n", b""):
                                break
                        break
                    pieces.append(self._read_exact(size))
                    self.rfile.readline(65537)  # CRLF after each chunk
                body = b"".join(pieces)
            elif "content-length" in headers:
                try:
                    n = int(headers["content-length"])
                except ValueError:
                    raise http.client.HTTPException(
                        f"bad Content-Length {headers['content-length']!r}"
                    ) from None
                body = self._read_exact(n)
            else:
                # EOF-delimited HTTP/1.0-style body: unbounded by spec;
                # the pooled socket carries a recv deadline, so a dead
                # peer trips the timeout, not an infinite park
                # weedlint: ignore[hot-loop-unbounded-read] — EOF framing is the protocol here and the socket timeout bounds every recv
                body = self.rfile.read()
                will_close = True
        return status, headers, body, will_close


def _pooled_conn(netloc: str, timeout: float):
    """Returns (connection, reused): reused=True only when an already-
    established socket came out of the pool — the one case where a
    send failure means 'idle connection went stale' rather than 'the
    server is down or slow'."""
    conns = getattr(_http_pool, "conns", None)
    if conns is None:
        conns = _http_pool.conns = {}
    c = conns.get(netloc)
    if c is None:
        host, _, port = netloc.partition(":")
        c = _RawHTTPConnection(host, int(port or 80), timeout=timeout)
        conns[netloc] = c
        return c, False
    if c.timeout != timeout:
        # the pool caches the connection, not the first caller's
        # deadline: re-arm per call
        c.settimeout(timeout)
    return c, True


def _drop_conn(netloc: str) -> None:
    c = getattr(_http_pool, "conns", {}).pop(netloc, None)
    if c is not None:
        c.close()


# whole-request wall bound for calls with NO propagated deadline: the
# per-socket-op `timeout` still governs each recv, but the request as
# a whole may not outlive timeout × this factor — a server trickling
# one byte per timeout window used to hold the caller indefinitely
_WALL_FACTOR = 4.0


def http_call(
    method: str,
    url: str,
    body: bytes | None = None,
    headers: dict | None = None,
    timeout: float = 30.0,
    max_redirects: int = 3,
    shed_retries: int = 2,
    deadline=None,
) -> tuple[int, dict, bytes]:
    """Keep-alive request; returns (status, headers, body). Follows
    redirects (volume read-redirect 302s). `url` may omit the scheme.

    Deadline plane (docs/CHAOS.md): `deadline` (else the ambient
    request deadline a serving funnel installed) bounds the WHOLE call
    — every socket operation's timeout is derived from the remaining
    budget, the `X-Weed-Deadline` hop header is re-stamped per attempt
    so downstream daemons share the clock, and an exhausted budget
    raises DeadlineExceeded. Calls with no deadline anywhere still get
    a whole-request wall bound of timeout × 4: `timeout` alone is
    per-socket-op, so a trickling response used to reset it forever.

    QoS plane (docs/QOS.md): a 503 carrying Retry-After is admission
    control shedding load, NOT a dead server — the request was never
    processed, so any method is safe to re-send. Up to `shed_retries`
    retries honor the server's hint with jitter (so a shed thundering
    herd doesn't re-arrive in phase), each charged to the process-wide
    retry budget (client/retry.py) so shed clients cannot storm;
    `WEED_QOS=0` (or shed_retries=0) returns the 503 untouched."""

    if "://" in url:
        scheme, _, url = url.partition("://")
        if scheme != "http":
            raise ValueError(f"pooled transport is http-only, got {scheme!r}")
    headers = dict(headers or {})
    # tracing plane: every pooled-transport hop (assign, upload,
    # lookup-download, filer chunk writes, worker proxying) carries the
    # current span's context so the receiving daemon parents under it
    from seaweedfs_tpu import trace as _trace

    _trace.inject(headers)
    dl = _deadline.effective(deadline)
    if dl is not None:
        # span evidence for the deadline plane: how much budget this
        # hop entered with (the 504-fast-reject test reads it back)
        _trace.annotate("deadline_ms", round(dl.remaining() * 1000.0, 1))
    # the wall clock bounds everything below — redirects, shed waits,
    # every socket op; only a REAL deadline rides the hop header
    wall = dl if dl is not None else _deadline.Deadline.after(
        timeout * _WALL_FACTOR
    )
    # retry-budget deposit: FIRST-ATTEMPT calls only (a RetryPolicy
    # retry runs under the in_retry marker) — retried requests
    # crediting themselves would re-earn part of their own cost and
    # drift the amplification cap from ~1+r toward 1/(1-k·r)
    if not _retry.in_retry():
        _retry.DEFAULT_BUDGET.note_request()
    else:
        # weedscope hop marker: the serving side's flight recorder
        # flags this wide-event as a retried attempt (the x-weed-hedge
        # twin lives in qos/hedge — trace/blackbox.request_flags parses
        # both)
        headers["x-weed-retry"] = "1"
    hops = 0
    while hops <= max_redirects:
        netloc, slash, rest = url.partition("/")
        path = slash + rest or "/"
        idempotent = method in ("GET", "HEAD", "PUT", "DELETE", "OPTIONS")
        while True:
            c, reused = _pooled_conn(netloc, timeout)
            sent = False
            try:
                if dl is not None:
                    # re-stamp per attempt: remaining shrinks
                    headers[_deadline.DEADLINE_HEADER] = dl.header_value()
                # arm the whole-request bound: sendall gets one
                # deadline-capped window (CPython computes a single
                # deadline for the full sendall), and every response
                # recv re-arms through the reader
                c.sock.settimeout(wall.cap(timeout))
                c.rfile.deadline = wall
                c.rfile.op_timeout = timeout
                c.send_request(method, path, body, headers)
                sent = True
                status, rheaders, data, will_close = c.read_response(method)
                c.rfile.deadline = None
                break
            except (http.client.HTTPException, OSError) as e:
                _drop_conn(netloc)
                # Retry exactly the Go-transport case: an idle POOLED
                # connection that turned out stale. A fresh dial that
                # fails means the server is down; a timeout means it is
                # slow — re-sending there doubles the wait and can
                # double-apply a non-idempotent request. And once the
                # request went out in full (`sent`), the server may have
                # processed it even though the response never arrived —
                # replaying is only safe for idempotent methods (a POST
                # replayed there double-applies).
                if (
                    reused
                    and not isinstance(e, TimeoutError)
                    and (idempotent or not sent)
                ):
                    continue  # next _pooled_conn dials fresh (sock is gone)
                raise
        if status == 503 and shed_retries > 0:
            retry_after = rheaders.get("retry-after", "")
            if retry_after:
                from seaweedfs_tpu import qos as _qos

                if _qos.enabled():
                    import random as _random

                    try:
                        ra = float(retry_after)
                    except ValueError:
                        ra = 1.0
                    # jittered, bounded wait: 50–100% of the server's
                    # hint so retries from many shed clients de-phase
                    wait = min(ra, 2.0) * (0.5 + _random.random() * 0.5)
                    # a retry the caller's budget can't pay for — or
                    # one the process-wide retry budget refuses — hands
                    # the 503 back instead of adding load
                    if (
                        wall.remaining() > wait
                        and _retry.DEFAULT_BUDGET.try_spend()
                    ):
                        from seaweedfs_tpu.stats.metrics import RETRY_TOTAL

                        RETRY_TOTAL.labels("http-shed").inc()
                        if will_close:
                            _drop_conn(netloc)
                        shed_retries -= 1
                        time.sleep(wait)
                        continue
        if status in (301, 302, 303, 307, 308):
            loc = rheaders.get("Location", "")
            if loc:
                if will_close:
                    _drop_conn(netloc)
                target = urllib.parse.urljoin(f"http://{url}", loc)
                t_scheme, _, t_rest = target.partition("://")
                if t_scheme != "http":
                    # never silently downgrade an https redirect target
                    raise RuntimeError(
                        f"{method} {url}: redirect to non-http target {loc!r}"
                    )
                if t_rest.partition("/")[0] != netloc:
                    # a redirect that changes host must not carry the
                    # caller's write JWT to the new host
                    headers.pop("Authorization", None)
                if status in (301, 302, 303) and method == "POST":
                    # urllib/Go both redirect POST as a body-less GET
                    # for 301/302/303; only 307/308 preserve the method
                    method, body = "GET", None
                    headers.pop("Content-Type", None)
                url = t_rest
                hops += 1
                continue
        if will_close or status >= 400:
            # >=400: error handlers may reply before draining the
            # request body, leaving body bytes in the socket — reusing
            # the connection would parse them as the next request line
            _drop_conn(netloc)
        return status, rheaders, data
    raise RuntimeError(f"{method} {url}: too many redirects")


def upload(
    url: str,
    data: bytes,
    filename: str = "",
    mime: str = "",
    ttl: str = "",
    jwt: str = "",
    is_chunk_manifest: bool = False,
    timeout: float = 30.0,
) -> UploadResult:
    """POST a blob to ``http://<url>`` (url is "host:port/fid")."""
    q = _upload_query(filename, ttl, is_chunk_manifest)
    full = url
    if q:
        full += ("&" if "?" in full else "?") + q
    headers = {"Content-Type": mime or "application/octet-stream"}
    if jwt:
        headers["Authorization"] = f"BEARER {jwt}"
    try:
        status, _, raw = http_call("POST", full, body=data, headers=headers, timeout=timeout)
    except (OSError, http.client.HTTPException, RuntimeError) as e:
        # urllib wrapped every transport failure as URLError(OSError);
        # the pooled transport surfaces HTTPException (e.g.
        # IncompleteRead) and RuntimeError (redirect loop) too — all of
        # them are "the upload failed", not caller crashes
        return UploadResult(error=str(e))
    try:
        body = json.loads(raw.decode("utf-8", "replace") if raw else "{}")
    except ValueError:
        body = {}
    if status >= 300:
        return UploadResult(error=body.get("error", f"HTTP {status}"))
    if body.get("error"):
        return UploadResult(error=body["error"])
    return UploadResult(
        name=body.get("name", ""), size=int(body.get("size", 0)), etag=body.get("eTag", "")
    )


def download(fid_url: str, timeout: float = 30.0) -> tuple[bytes, dict]:
    """GET a blob; returns (bytes, headers)."""
    status, headers, data = http_call("GET", fid_url, timeout=timeout)
    if status >= 300:
        import io

        # keep the server's error body readable via e.read(), like the
        # urllib HTTPErrors this replaces
        raise urllib.error.HTTPError(
            f"http://{fid_url}", status, f"HTTP {status}", headers, io.BytesIO(data)
        )
    return data, headers


def delete(fid_url: str, timeout: float = 30.0, jwt: str = "") -> None:
    """DELETE a blob. Pass the assign-issued write JWT on signed
    clusters; auth failures raise (a swallowed 401 would silently leak
    the blob), while 404s stay idempotent no-ops."""
    headers = {}
    if jwt:
        headers["Authorization"] = f"BEARER {jwt}"
    status, _, _ = http_call("DELETE", fid_url, headers=headers, timeout=timeout)
    if status in (401, 403):
        raise RuntimeError(f"delete {fid_url}: not authorized ({status})")
    # 404 etc.: delete is idempotent


# ----------------------------------------------------------------------
# lookup (+cache)


@dataclass
class LookupResult:
    vid: str
    locations: list[dict] = field(default_factory=list)
    error: str = ""


class _CacheEntry:
    __slots__ = ("result", "expires")

    def __init__(self, result: LookupResult, ttl: float):
        self.result = result
        self.expires = time.time() + ttl


_lookup_cache: dict[tuple[str, str], _CacheEntry] = {}
_lookup_lock = threading.Lock()
LOOKUP_CACHE_TTL = 10 * 60  # lookup.go:18 (10 min)


def lookup(master: str, vid: str, collection: str = "") -> LookupResult:
    key = (master, vid)
    with _lookup_lock:
        entry = _lookup_cache.get(key)
        if entry and entry.expires > time.time():
            return entry.result
    ch = rpc.cached_channel(grpc_address(master))
    resp = rpc.master_stub(ch).LookupVolume(
        master_pb2.LookupVolumeRequest(vids=[vid], collection=collection)
    )
    result = LookupResult(vid=vid, error=f"volume {vid} not found")
    for e in resp.vid_locations:
        if e.vid == vid:
            result = LookupResult(
                vid=vid,
                # `suspect` (health plane, docs/HEALTH.md): the master
                # marks replicas it currently suspects; the filer read
                # path hedges eagerly when only suspects remain
                locations=[
                    {
                        "url": l.url,
                        "publicUrl": l.public_url,
                        "suspect": l.suspect,
                    }
                    for l in e.locations
                ],
                error=e.error,
            )
    if not result.error:
        # a result naming a SUSPECT replica is cached briefly: the
        # health verdict changes on heartbeat timescales, and pinning
        # it for the full 10 min would demote a healed node (or keep
        # routing at a sick one) long after the master knows better
        ttl = (
            10.0
            if any(loc.get("suspect") for loc in result.locations)
            else LOOKUP_CACHE_TTL
        )
        with _lookup_lock:
            _lookup_cache[key] = _CacheEntry(result, ttl)
    return result


def lookup_file_id(master: str, fid: str) -> str:
    """fid → "host:port/fid" of one replica."""
    vid = fid.split(",")[0]
    result = lookup(master, vid)
    if result.error:
        raise RuntimeError(result.error)
    if not result.locations:
        raise RuntimeError(f"volume {vid} has no locations")
    return f"{result.locations[0]['url']}/{fid}"


# ----------------------------------------------------------------------
# batch delete


def delete_files(master: str, fids: list[str]) -> list[dict]:
    """Group fids by volume id, resolve each volume once, then issue one
    BatchDelete gRPC per server (delete_content.go:43)."""
    by_vid: dict[str, list[str]] = {}
    results: list[dict] = []
    for fid in fids:
        parts = fid.split(",")
        if len(parts) != 2:
            results.append({"fid": fid, "status": 400, "error": "invalid fid"})
            continue
        by_vid.setdefault(parts[0], []).append(fid)

    # every replica location gets the batch (delete_content.go sends to
    # all locations so no replica keeps the data)
    by_server: dict[str, list[str]] = {}
    primary: dict[str, str] = {}  # fid -> primary server (reported result)
    for vid, vid_fids in by_vid.items():
        res = lookup(master, vid)
        if res.error or not res.locations:
            for fid in vid_fids:
                results.append({"fid": fid, "status": 404, "error": res.error})
            continue
        for i, loc in enumerate(res.locations):
            by_server.setdefault(loc["url"], []).extend(vid_fids)
            if i == 0:
                for fid in vid_fids:
                    primary[fid] = loc["url"]

    for server, server_fids in by_server.items():
        try:
            with rpc.dial(grpc_address(server)) as ch:
                resp = rpc.volume_stub(ch).BatchDelete(
                    volume_pb2.BatchDeleteRequest(file_ids=server_fids)
                )
            for r in resp.results:
                if primary.get(r.file_id) == server:
                    results.append(
                        {
                            "fid": r.file_id,
                            "status": r.status,
                            "error": r.error,
                            "size": r.size,
                        }
                    )
        except grpc.RpcError as e:
            for fid in server_fids:
                if primary.get(fid) == server:
                    results.append({"fid": fid, "status": 500, "error": str(e)})
    return results


# ----------------------------------------------------------------------
# submit (auto-chunking behind a chunk manifest)


@dataclass
class SubmitResult:
    file_name: str
    fid: str
    file_url: str
    size: int
    error: str = ""


def submit_file(
    master: str,
    filename: str,
    data: bytes,
    replication: str = "",
    collection: str = "",
    ttl: str = "",
    mime: str = "",
    max_mb: int = 0,
) -> SubmitResult:
    """Assign one fid and upload; payloads over max_mb are split into
    chunks uploaded under their own fids and tied together by a
    chunk-manifest needle (submit.go:112 upload with chunking)."""
    ar = assign(master, count=1, replication=replication, collection=collection, ttl=ttl)
    chunk_size = max_mb * 1024 * 1024
    if chunk_size and len(data) > chunk_size:
        chunks = []
        offset = 0
        idx = 0
        while offset < len(data):
            piece = data[offset : offset + chunk_size]
            car = assign(
                master, count=1, replication=replication, collection=collection, ttl=ttl
            )
            ur = upload(
                f"{car.url}/{car.fid}",
                piece,
                filename=f"{filename}_{idx}",
                ttl=ttl,
                jwt=car.auth,
            )
            if ur.error:
                return SubmitResult(filename, ar.fid, "", 0, ur.error)
            chunks.append({"fid": car.fid, "offset": offset, "size": len(piece)})
            offset += len(piece)
            idx += 1
        manifest = json.dumps(
            {"name": filename, "mime": mime, "size": len(data), "chunks": chunks}
        ).encode()
        ur = upload(
            f"{ar.url}/{ar.fid}",
            manifest,
            filename=filename,
            ttl=ttl,
            mime="application/json",
            is_chunk_manifest=True,
            jwt=ar.auth,
        )
    else:
        ur = upload(
            f"{ar.url}/{ar.fid}", data, filename=filename, mime=mime, ttl=ttl,
            jwt=ar.auth,
        )
    if ur.error:
        return SubmitResult(filename, ar.fid, "", 0, ur.error)
    return SubmitResult(filename, ar.fid, f"{ar.public_url}/{ar.fid}", len(data))


# ----------------------------------------------------------------------
# tail


def tail_volume(volume_server_url: str, vid: int, since_ns: int = 0):
    """Yield (needle_bytes_chunk) from the server's incremental-copy
    stream; the caller reassembles needles (tail_volume.go)."""
    with rpc.dial(grpc_address(volume_server_url)) as ch:
        stream = rpc.volume_stub(ch).VolumeIncrementalCopy(
            volume_pb2.VolumeIncrementalCopyRequest(volume_id=vid, since_ns=since_ns)
        )
        for resp in stream:
            yield resp.file_content
