from seaweedfs_tpu.client.masterclient import MasterClient
from seaweedfs_tpu.client.vid_map import Location, VidMap

__all__ = ["MasterClient", "Location", "VidMap"]
