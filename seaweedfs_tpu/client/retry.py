"""Unified retry policy + process-wide retry budget (docs/CHAOS.md).

Before this module every retry loop in the tree was hand-rolled: the
master-failover rotation made exactly one pass, `http_call` had its own
shed-retry counter, and nothing bounded the AGGREGATE retry volume a
process could emit. Under a partial failure (one replica blackholed,
a leader election in flight) those ad-hoc loops turn degraded latency
into multiplied load — the retry storm the Facebook warehouse study
(arXiv:1309.0186) measures colliding with recovery traffic.

Two pieces:

  * `RetryPolicy` — attempt cap, exponential backoff with FULL jitter
    (each wait is uniform in [0, base * 2^attempt], the AWS
    architecture-blog result: full jitter de-phases a shed thundering
    herd strictly better than equal jitter), idempotency awareness
    (non-idempotent work is never replayed after it may have been
    applied), and deadline awareness (never sleep past the request's
    remaining budget — a retry the caller gave up on is pure load).

  * `RetryBudget` — a process-wide token bucket CREDITED by
    FIRST-ATTEMPT operations (each RetryPolicy.run — retried attempts
    deliberately deposit nothing, or every granted retry would earn
    back part of its own cost and the amplification cap would drift
    from 1+r toward 1/(1-k·r)) and DEBITED by retries, capping retries
    at ~10% of recent first-attempt volume (`WEED_RETRY_BUDGET_RATIO`).
    When the cluster is healthy the budget is a no-op; when a
    dependency blackholes, the budget empties after the first wave and
    every later failure degrades to a plain error instead of
    multiplying upstream load. This is the gRPC/Finagle "retry budget"
    design, not a circuit breaker: a probe retry every couple of
    seconds keeps flowing even when dry, so recovery is noticed.

Knobs (OPERATIONS.md "Environment knobs"): `WEED_RETRY_ATTEMPTS`,
`WEED_RETRY_BACKOFF_MS`, `WEED_RETRY_BACKOFF_MAX_MS`,
`WEED_RETRY_BUDGET_RATIO`; `WEED_RETRY_BUDGET_RATIO=0` disables the
budget gate (every policy-approved retry fires).
"""

from __future__ import annotations

import os
import random
import threading
import time

from seaweedfs_tpu.stats.metrics import RETRY_BUDGET_EXHAUSTED, RETRY_TOTAL
from seaweedfs_tpu.util import deadline as _deadline


def _attempts_default() -> int:
    try:
        return max(1, int(os.environ.get("WEED_RETRY_ATTEMPTS", "4")))
    except ValueError:
        return 4


def _backoff_ms_default() -> float:
    try:
        return float(os.environ.get("WEED_RETRY_BACKOFF_MS", "50"))
    except ValueError:
        return 50.0


def _backoff_max_ms_default() -> float:
    try:
        return float(os.environ.get("WEED_RETRY_BACKOFF_MAX_MS", "2000"))
    except ValueError:
        return 2000.0


def _budget_ratio_default() -> float:
    try:
        return float(os.environ.get("WEED_RETRY_BUDGET_RATIO", "0.1"))
    except ValueError:
        return 0.1


# first-attempt vs retry marker: RetryPolicy.run sets this around
# retried attempts so the TRANSPORT (http_call) can credit the shared
# budget for first-attempt traffic only — retried requests crediting
# themselves is exactly the feedback loop the budget exists to cut
_tls = threading.local()


def in_retry() -> bool:
    return getattr(_tls, "in_retry", False)


class RetryBudget:
    """Process-wide retries-as-a-fraction-of-requests token bucket."""

    def __init__(
        self,
        ratio: float | None = None,
        min_reserve: float = 3.0,
        # burst ceiling: tokens banked during healthy traffic that a
        # fresh fault may spend at once. Kept SMALL on purpose — a
        # large bank lets the first seconds of an outage retry-storm
        # on saved credit and blows the ≤1.15× amplification bound the
        # chaos bench enforces; refill is continuous (ratio × request
        # rate), so sustained retry capacity is unaffected
        max_tokens: float = 16.0,
    ):
        # ratio None = read the env knob PER SPEND, so tests and
        # operators can retune a live process
        self._ratio = ratio
        self.min_reserve = min_reserve
        self.max_tokens = max_tokens
        self._lock = threading.Lock()
        self._tokens = min_reserve
        self._last_probe = 0.0
        self.spent = 0  # lifetime retries granted (operator surface)
        self.denied = 0  # lifetime retries refused

    def ratio(self) -> float:
        return self._ratio if self._ratio is not None else _budget_ratio_default()

    def note_request(self, n: int = 1) -> None:
        """Credit the budget for `n` first-attempt requests."""
        r = self.ratio()
        if r <= 0:
            return
        with self._lock:
            self._tokens = min(self.max_tokens, self._tokens + r * n)

    # dry-bucket probe cadence: frequent enough to notice a dependency
    # recovering, rare enough that probes stay noise against any real
    # request rate (the ≤1.15× amplification bound counts them too)
    probe_interval_s: float = 2.0

    def try_spend(self, now: float | None = None, cost: float = 1.0) -> bool:
        """Take `cost` retry tokens (cost ≈ the number of upstream
        requests this retry will reissue, so the ratio stays a bound on
        retried REQUEST volume, not on coarse-grained operations). When
        the bucket is dry, a probe retry is still granted once per
        probe interval — the budget throttles storms, it must not blind
        the process to the dependency recovering."""
        r = self.ratio()
        if r <= 0:
            return True
        now = now if now is not None else time.monotonic()
        with self._lock:
            if self._tokens >= cost:
                self._tokens -= cost
                self.spent += 1
                return True
            if now - self._last_probe >= self.probe_interval_s:
                self._last_probe = now
                self.spent += 1
                return True
            self.denied += 1
        RETRY_BUDGET_EXHAUSTED.inc()
        return False

    def status(self) -> dict:
        with self._lock:
            return {
                "Tokens": round(self._tokens, 3),
                "Ratio": self.ratio(),
                "Spent": self.spent,
                "Denied": self.denied,
            }


# the process-wide budget every RetryPolicy shares by default: the
# whole point is that ALL retry sites drain one pool, so a blackholed
# replica can't multiply load just by being hit from many call sites
DEFAULT_BUDGET = RetryBudget()


class RetryPolicy:
    """One retry discipline for every internal client hop.

    `run(fn)` calls `fn(attempt)` up to `attempts` times. `fn` raises
    to signal a retryable failure (any exception type in `retry_on`)
    and returns normally on success. Between attempts the policy
    sleeps full-jitter exponential backoff, charges the shared
    RetryBudget, and checks the ambient/explicit deadline — whichever
    gate fails first ends the loop with the last error."""

    def __init__(
        self,
        attempts: int | None = None,
        backoff_ms: float | None = None,
        backoff_max_ms: float | None = None,
        retry_on: tuple = (OSError,),
        budget: RetryBudget | None = DEFAULT_BUDGET,
        label: str = "generic",
        rng: random.Random | None = None,
        cost: float = 1.0,
    ):
        # `cost`: budget tokens one retry spends ≈ upstream requests it
        # reissues (an assign+upload write op retried whole is cost 2)
        self.attempts = attempts if attempts is not None else _attempts_default()
        self.backoff_s = (
            backoff_ms if backoff_ms is not None else _backoff_ms_default()
        ) / 1000.0
        self.backoff_max_s = (
            backoff_max_ms
            if backoff_max_ms is not None
            else _backoff_max_ms_default()
        ) / 1000.0
        self.retry_on = retry_on
        self.budget = budget
        self.label = label
        self.cost = cost
        self._rng = rng or random

    # ------------------------------------------------------------------
    def backoff_for(self, attempt: int) -> float:
        """Full-jitter wait before attempt `attempt` (1-based retries:
        attempt 0 is the first try and never waits)."""
        if attempt <= 0:
            return 0.0
        ceiling = min(self.backoff_max_s, self.backoff_s * (2 ** (attempt - 1)))
        return self._rng.uniform(0.0, ceiling)

    def _may_retry(
        self,
        attempt: int,
        exc: Exception,
        idempotent: bool,
        applied: bool,
        dl: _deadline.Deadline | None,
    ) -> float | None:
        """None = give up; else the jittered sleep before the retry."""
        if attempt + 1 >= self.attempts:
            return None
        if not isinstance(exc, self.retry_on):
            return None
        # an exhausted budget is terminal however it surfaces — the
        # caller's clock ran out, more attempts only add load
        if isinstance(exc, _deadline.DeadlineExceeded):
            return None
        if applied and not idempotent:
            # the request may have been processed (bytes fully sent,
            # response lost): replaying a non-idempotent request there
            # double-applies
            return None
        wait = self.backoff_for(attempt + 1)
        if dl is not None and dl.remaining() <= wait + _deadline.MIN_OP_TIMEOUT_S:
            return None  # the caller will be gone before the retry lands
        if self.budget is not None and not self.budget.try_spend(
            cost=self.cost
        ):
            return None
        return wait

    def run(
        self,
        fn,
        idempotent: bool = True,
        deadline: _deadline.Deadline | None = None,
        applied=None,
    ):
        """Drive `fn(attempt)` under the policy. `applied` (optional
        callable) reports whether the failed attempt may have reached
        the server (e.g. the request bytes fully went out) — consulted
        for non-idempotent work.

        Budget crediting happens at the TRANSPORT (http_call deposits
        for every non-retry call), not here: retried attempts run
        under the `in_retry` marker so their own requests deposit
        nothing, and an op whose attempts never touch the pooled
        transport simply doesn't feed the pool."""
        dl = _deadline.effective(deadline)
        attempt = 0
        while True:
            try:
                if attempt == 0:
                    return fn(attempt)
                _tls.in_retry = True
                try:
                    return fn(attempt)
                finally:
                    _tls.in_retry = False
            except Exception as e:  # noqa: BLE001 - classified below
                was_applied = bool(applied(e)) if applied is not None else False
                wait = self._may_retry(attempt, e, idempotent, was_applied, dl)
                if wait is None:
                    raise
                RETRY_TOTAL.labels(self.label).inc()
                if wait > 0:
                    time.sleep(wait)
                attempt += 1
