"""Client-side volume-id → locations map.

Behavioral match of the reference's wdclient vidMap
(weed/wdclient/vid_map.go): thread-safe map updated from the master's
KeepConnected push stream, with round-robin pick over replicas.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class Location:
    url: str
    public_url: str


class VidMap:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._vid2locations: dict[int, list[Location]] = {}
        self._counter = itertools.count()

    def lookup(self, vid: int) -> list[Location]:
        with self._lock:
            return list(self._vid2locations.get(vid, ()))

    def lookup_file_id(self, fid: str) -> list[str]:
        """fid "3,0144b2c3" → ["host:port/3,0144b2c3", ...] full urls
        (wdclient/vid_map.go LookupFileId)."""
        parts = fid.split(",")
        if len(parts) != 2 or not parts[0].isdigit():
            raise ValueError(f"invalid file id {fid!r}")
        locations = self.lookup(int(parts[0]))
        if not locations:
            raise KeyError(f"volume {parts[0]} not found")
        # rotate so repeated reads spread over replicas
        start = next(self._counter) % len(locations)
        ordered = locations[start:] + locations[:start]
        return [f"http://{loc.url}/{fid}" for loc in ordered]

    def add_location(self, vid: int, loc: Location) -> None:
        with self._lock:
            locs = self._vid2locations.setdefault(vid, [])
            if loc not in locs:
                locs.append(loc)

    def delete_location(self, vid: int, url: str) -> None:
        with self._lock:
            locs = self._vid2locations.get(vid)
            if not locs:
                return
            locs[:] = [l for l in locs if l.url != url]
            if not locs:
                del self._vid2locations[vid]

    def delete_server(self, url: str) -> None:
        """Drop every vid entry pointing at a dead server."""
        with self._lock:
            for vid in list(self._vid2locations):
                self.delete_location(vid, url)

    def __len__(self) -> int:
        with self._lock:
            return len(self._vid2locations)
