"""Client-side volume-id → locations map.

Behavioral match of the reference's wdclient vidMap
(weed/wdclient/vid_map.go): thread-safe map updated from the master's
KeepConnected push stream, with round-robin pick over replicas — plus
a tiny circuit breaker (QoS plane, docs/QOS.md): replicas with a
recent connection error are demoted to the end of the candidate list
for a short TTL, so a dead node costs one timeout per TTL instead of
one per lookup.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class Location:
    url: str
    public_url: str


# ----------------------------------------------------------------------
# replica circuit breaker — shared module-level registry so every
# consumer of replica lists (VidMap round-robin, the hedge driver,
# filer chunk reads) sees the same health view in one process
_breaker_lock = threading.Lock()
_broken_until: dict[str, float] = {}


def _breaker_ttl_s() -> float:
    """How long one connection error demotes a replica
    (WEED_QOS_BREAKER_TTL_S, default 5 s; 0 disables)."""
    try:
        return float(os.environ.get("WEED_QOS_BREAKER_TTL_S", "5"))
    except ValueError:
        return 5.0


def note_failure(url: str, now: float | None = None) -> None:
    """Record a connection error against `url` ("host:port")."""
    ttl = _breaker_ttl_s()
    if ttl <= 0:
        return
    with _breaker_lock:
        _broken_until[url] = (now if now is not None else time.time()) + ttl
        if len(_broken_until) > 1024:
            cutoff = time.time()
            for k in [k for k, v in _broken_until.items() if v <= cutoff]:
                del _broken_until[k]


def note_success(url: str) -> None:
    """A working round-trip clears the penalty immediately."""
    with _breaker_lock:
        _broken_until.pop(url, None)


def penalized(url: str, now: float | None = None) -> bool:
    with _breaker_lock:
        until = _broken_until.get(url)
    if until is None:
        return False
    return (now if now is not None else time.time()) < until


def _partition_healthy(items: list, netloc_of) -> list:
    """Stable-partition recently-failed replicas to the tail; when
    EVERY candidate is penalized the original order stands (a fully
    demoted list must still be tried, not emptied). The ONE home for
    the demotion rule — url-string and Location callers both route
    here so the edge cases can't drift apart."""
    now = time.time()
    good = [it for it in items if not penalized(netloc_of(it), now)]
    if not good or len(good) == len(items):
        return items
    return good + [it for it in items if it not in good]


def order_by_health(urls: list[str]) -> list[str]:
    """Breaker ordering for "host:port/fid" candidate urls."""
    return _partition_healthy(urls, lambda u: u.partition("/")[0])


class VidMap:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._vid2locations: dict[int, list[Location]] = {}
        self._counter = itertools.count()

    def lookup(self, vid: int) -> list[Location]:
        with self._lock:
            return list(self._vid2locations.get(vid, ()))

    def lookup_file_id(self, fid: str) -> list[str]:
        """fid "3,0144b2c3" → ["host:port/3,0144b2c3", ...] full urls
        (wdclient/vid_map.go LookupFileId)."""
        parts = fid.split(",")
        if len(parts) != 2 or not parts[0].isdigit():
            raise ValueError(f"invalid file id {fid!r}")
        locations = self.lookup(int(parts[0]))
        if not locations:
            raise KeyError(f"volume {parts[0]} not found")
        # rotate so repeated reads spread over replicas, then demote
        # replicas with a recent connection error (circuit breaker):
        # fixed round-robin was health-blind, so a dead node ate one
        # timeout on every other lookup
        start = next(self._counter) % len(locations)
        ordered = _partition_healthy(
            locations[start:] + locations[:start], lambda loc: loc.url
        )
        return [f"http://{loc.url}/{fid}" for loc in ordered]

    def note_failure(self, url: str) -> None:
        """Callers report a connection error against a replica url so
        subsequent lookups demote it for the breaker TTL."""
        note_failure(url)

    def note_success(self, url: str) -> None:
        note_success(url)

    def add_location(self, vid: int, loc: Location) -> None:
        with self._lock:
            locs = self._vid2locations.setdefault(vid, [])
            if loc not in locs:
                locs.append(loc)

    def delete_location(self, vid: int, url: str) -> None:
        with self._lock:
            locs = self._vid2locations.get(vid)
            if not locs:
                return
            locs[:] = [l for l in locs if l.url != url]
            if not locs:
                del self._vid2locations[vid]

    def delete_server(self, url: str) -> None:
        """Drop every vid entry pointing at a dead server."""
        with self._lock:
            for vid in list(self._vid2locations):
                self.delete_location(vid, url)

    def __len__(self) -> int:
        with self._lock:
            return len(self._vid2locations)
