"""Long-lived master client.

Behavioral match of weed/wdclient/masterclient.go: a background thread
holds a KeepConnected bidirectional stream to the current master
leader, folds the pushed VolumeLocationDelta messages into a VidMap,
and fails over to the next seed master (or the pushed leader hint) when
the stream breaks (masterclient.go:44-117).
"""

from __future__ import annotations

import queue
import threading
import time

import grpc

from seaweedfs_tpu.client.vid_map import Location, VidMap
from seaweedfs_tpu.pb import master_pb2, rpc
from seaweedfs_tpu.pb.rpc import grpc_address as master_grpc_address


class MasterClient:
    """vid→location cache fed by the master's KeepConnected stream."""

    def __init__(self, name: str, masters: list[str]):
        self.name = name
        self.masters = list(masters)
        self.vid_map = VidMap()
        self.current_master: str = ""
        self._stop = threading.Event()
        self._connected = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._keep_connected_loop, daemon=True, name=f"mc-{self.name}"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def wait_until_connected(self, timeout: float = 10.0) -> bool:
        return self._connected.wait(timeout)

    # ------------------------------------------------------------------
    def lookup_file_id(self, fid: str) -> list[str]:
        try:
            return self.vid_map.lookup_file_id(fid)
        except KeyError:
            self._refresh(fid.split(",")[0])
            return self.vid_map.lookup_file_id(fid)

    def lookup_volume(self, vid: int) -> list[Location]:
        locs = self.vid_map.lookup(vid)
        if not locs:
            self._refresh(str(vid))
            locs = self.vid_map.lookup(vid)
        return locs

    def _refresh(self, vid_str: str) -> None:
        """Fallback unary LookupVolume when the push stream hasn't
        caught up yet (wdclient falls back the same way via
        LookupVolumeId)."""
        master = self.current_master or self.masters[0]
        with rpc.dial(master_grpc_address(master)) as ch:
            resp = rpc.master_stub(ch).LookupVolume(
                master_pb2.LookupVolumeRequest(vids=[vid_str])
            )
        for entry in resp.vid_locations:
            if entry.error:
                continue
            for loc in entry.locations:
                self.vid_map.add_location(
                    int(entry.vid), Location(loc.url, loc.public_url)
                )

    # ------------------------------------------------------------------
    def _keep_connected_loop(self) -> None:
        idx = 0
        while not self._stop.is_set():
            master = self.masters[idx % len(self.masters)]
            idx += 1
            leader = self._try_connect(master)
            if self._stop.is_set():
                return
            if leader and leader in self.masters:
                # follow the leader hint instead of round-robin
                idx = self.masters.index(leader)
            time.sleep(0.2)

    def _try_connect(self, master: str) -> str | None:
        """Run one KeepConnected stream until it breaks. Returns the
        leader hint if the master redirected us."""
        hello = queue.Queue()
        hello.put(master_pb2.ClientHello(name=self.name))

        def requests():
            while not self._stop.is_set():
                try:
                    yield hello.get(timeout=0.5)
                except queue.Empty:
                    continue

        try:
            with rpc.dial(master_grpc_address(master)) as ch:
                stream = rpc.master_stub(ch).KeepConnected(requests())
                for delta in stream:
                    if self._stop.is_set():
                        return None
                    if (
                        delta.leader
                        and delta.leader != master
                        and delta.leader in self.masters
                    ):
                        # genuine redirect to another seed; a leader
                        # self-identity that merely spells the address
                        # differently (localhost vs 127.0.0.1) is not one
                        return delta.leader
                    self.current_master = master
                    self._connected.set()
                    loc = delta.location
                    if loc.url:
                        for vid in loc.new_vids:
                            self.vid_map.add_location(
                                vid, Location(loc.url, loc.public_url)
                            )
                        for vid in loc.deleted_vids:
                            self.vid_map.delete_location(vid, loc.url)
        except grpc.RpcError:
            pass
        self._connected.clear()
        return None
