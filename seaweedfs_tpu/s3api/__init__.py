from seaweedfs_tpu.s3api.s3api_server import S3ApiServer

__all__ = ["S3ApiServer"]
