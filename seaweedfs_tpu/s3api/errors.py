"""S3 API error codes and XML error bodies.

Behavioral match of weed/s3api/s3api_errors.go: each error is
(Code, Description, HTTPStatusCode) rendered as the standard
<Error> XML document AWS clients parse.
"""

from __future__ import annotations

from xml.sax.saxutils import escape


class S3Error(Exception):
    def __init__(self, code: str, status: int, message: str):
        super().__init__(message)
        self.code = code
        self.status = status
        self.message = message

    def to_xml(self, resource: str = "", request_id: str = "") -> bytes:
        return (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<Error><Code>{self.code}</Code>"
            f"<Message>{escape(self.message)}</Message>"
            f"<Resource>{escape(resource)}</Resource>"
            f"<RequestId>{request_id}</RequestId></Error>"
        ).encode()


def _err(code: str, status: int, message: str):
    return lambda: S3Error(code, status, message)


ERRORS = {
    "NoSuchBucket": _err("NoSuchBucket", 404, "The specified bucket does not exist"),
    "NoSuchKey": _err("NoSuchKey", 404, "The specified key does not exist."),
    "NoSuchUpload": _err(
        "NoSuchUpload",
        404,
        "The specified multipart upload does not exist.",
    ),
    "BucketAlreadyExists": _err(
        "BucketAlreadyExists", 409, "The requested bucket name is not available."
    ),
    "BucketNotEmpty": _err(
        "BucketNotEmpty", 409, "The bucket you tried to delete is not empty"
    ),
    "InvalidBucketName": _err(
        "InvalidBucketName", 400, "The specified bucket is not valid."
    ),
    "InvalidMaxKeys": _err(
        "InvalidMaxKeys", 400, "Argument maxKeys must be an integer >= 0"
    ),
    "InvalidPart": _err(
        "InvalidPart",
        400,
        "One or more of the specified parts could not be found.",
    ),
    "InvalidRange": _err(
        "InvalidRange", 416, "The requested range is not satisfiable"
    ),
    "InvalidPartOrder": _err(
        "InvalidPartOrder",
        400,
        "The list of parts was not in ascending order.",
    ),
    "EntityTooSmall": _err(
        "EntityTooSmall",
        400,
        "Your proposed upload is smaller than the minimum allowed object size.",
    ),
    "InternalError": _err(
        "InternalError", 500, "We encountered an internal error, please try again."
    ),
    "RequestTimeout": _err(
        "RequestTimeout",
        400,
        "Your request's X-Weed-Deadline budget expired before it "
        "could be completed.",
    ),
    "AccessDenied": _err("AccessDenied", 403, "Access Denied."),
    "SignatureDoesNotMatch": _err(
        "SignatureDoesNotMatch",
        403,
        "The request signature we calculated does not match the signature you provided.",
    ),
    "InvalidAccessKeyId": _err(
        "InvalidAccessKeyId",
        403,
        "The AWS Access Key Id you provided does not exist in our records.",
    ),
    "MissingFields": _err("MissingFields", 400, "Missing fields in request."),
    "AuthorizationHeaderMalformed": _err(
        "AuthorizationHeaderMalformed",
        400,
        "The authorization header is malformed.",
    ),
    "MalformedXML": _err(
        "MalformedXML",
        400,
        "The XML you provided was not well-formed or did not validate against "
        "our published schema.",
    ),
    "NotImplemented": _err(
        "NotImplemented", 501, "A header you provided implies functionality "
        "that is not implemented"
    ),
    "AuthorizationQueryParametersError": _err(
        "AuthorizationQueryParametersError",
        400,
        "X-Amz-Expires must be an integer between 1 and 604800 seconds.",
    ),
    "InvalidArgument": _err(
        "InvalidArgument",
        400,
        "Part number must be an integer between 1 and 10000, inclusive",
    ),
    "RequestTimeTooSkewed": _err(
        "RequestTimeTooSkewed",
        403,
        "The difference between the request time and the server's time is too large.",
    ),
}


def s3_error(code: str) -> S3Error:
    return ERRORS[code]()
