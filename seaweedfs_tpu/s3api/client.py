"""Minimal SigV4 S3 client: PUT/GET(range)/DELETE objects.

Role match: the aws-sdk calls inside the reference's S3 tier backend
(weed/storage/backend/s3_backend/s3_sessions.go + s3_backend.go) and
replication S3 sink — a tiny header-auth V4 client over urllib,
path-style addressing, suitable for any S3-compatible endpoint
including this repo's own gateway (tests use exactly that)."""

from __future__ import annotations

import datetime
import hashlib
import urllib.error
import urllib.parse
import urllib.request



class _ProgressReader:
    """File-like wrapper reporting read progress to a callback."""

    def __init__(self, f, total: int, progress):
        self._f = f
        self._total = total
        self._done = 0
        self._progress = progress

    def read(self, n: int = -1) -> bytes:
        chunk = self._f.read(n)
        if chunk:
            self._done += len(chunk)
            pct = 100.0 * self._done / self._total if self._total else 0.0
            self._progress(self._done, pct)
        return chunk


class S3ClientError(IOError):
    def __init__(self, status: int, body: bytes = b""):
        super().__init__(f"s3 request failed: HTTP {status} {body[:200]!r}")
        self.status = status


class S3Client:
    def __init__(
        self,
        endpoint: str,  # "host:port"
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
        timeout: float = 60.0,
    ):
        self.endpoint = endpoint
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = timeout

    # ------------------------------------------------------------------
    def _request(
        self,
        method: str,
        bucket: str,
        key: str,
        data=None,  # bytes or file-like (file-like => unsigned payload)
        extra_headers: dict | None = None,
        payload_hash: str | None = None,
        query: dict | None = None,
    ):
        path = "/" + bucket + ("/" + key.lstrip("/") if key else "")
        query_string = "&".join(
            f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
            for k, v in sorted((query or {}).items())
        )
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = now.strftime("%Y%m%d")
        if payload_hash is None:
            if data is None or isinstance(data, (bytes, bytearray)):
                payload_hash = hashlib.sha256(data or b"").hexdigest()
            else:
                # streaming body: don't buffer the payload to hash it
                payload_hash = "UNSIGNED-PAYLOAD"

        headers = {
            "host": self.endpoint,
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
        }
        if extra_headers:
            headers.update({k.lower(): v for k, v in extra_headers.items()})

        from seaweedfs_tpu.s3api.auth import sigv4_sign

        auth = sigv4_sign(
            method,
            urllib.parse.quote(path),
            query_string,
            headers,
            payload_hash,
            self.access_key,
            self.secret_key,
            self.region,
            "s3",
            amz_date,
        )

        url = f"http://{self.endpoint}{urllib.parse.quote(path)}"
        if query_string:
            url += "?" + query_string
        req = urllib.request.Request(url, data=data, method=method)
        for k, v in headers.items():
            if k != "host":
                req.add_header(k, v)
        req.add_header("Authorization", auth)
        try:
            return urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            raise S3ClientError(e.code, e.read()) from e

    # ------------------------------------------------------------------
    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        with self._request("PUT", bucket, key, data=data):
            pass

    def put_object_stream(
        self, bucket: str, key: str, file_obj, length: int, progress=None
    ) -> None:
        """Streamed PUT (unsigned payload): the body never lives in
        memory as one buffer. progress(done, pct) per read chunk."""
        src = file_obj
        if progress is not None:
            src = _ProgressReader(file_obj, length, progress)
        with self._request(
            "PUT",
            bucket,
            key,
            data=src,
            extra_headers={"content-length": str(length)},
        ):
            pass

    def get_object(
        self, bucket: str, key: str, offset: int = 0, length: int | None = None
    ) -> bytes:
        headers = {}
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["range"] = f"bytes={offset}-{end}"
        with self._request("GET", bucket, key, extra_headers=headers) as r:
            return r.read()

    def get_object_to_file(
        self, bucket: str, key: str, local_path: str, progress=None
    ) -> int:
        """Streamed GET: chunked reads straight to disk."""
        done = 0
        with self._request("GET", bucket, key) as r:
            total = int(r.headers.get("Content-Length", 0) or 0)
            with open(local_path, "wb") as out:
                while True:
                    chunk = r.read(8 * 1024 * 1024)
                    if not chunk:
                        break
                    out.write(chunk)
                    done += len(chunk)
                    if progress is not None:
                        pct = 100.0 * done / total if total else 0.0
                        progress(done, pct)
        return done

    def list_objects(self, bucket: str, prefix: str = "") -> list[str]:
        """Object keys under a prefix (ListObjects V1 XML)."""
        import xml.etree.ElementTree as ET

        query = {"prefix": prefix} if prefix else None
        with self._request("GET", bucket, "", query=query) as r:
            tree = ET.fromstring(r.read())
        ns = ""
        if tree.tag.startswith("{"):
            ns = tree.tag.split("}")[0] + "}"
        return [
            c.findtext(f"{ns}Key")
            for c in tree.findall(f"{ns}Contents")
            if c.findtext(f"{ns}Key")
        ]

    def head_object(self, bucket: str, key: str) -> dict:
        with self._request("HEAD", bucket, key) as r:
            return dict(r.headers)

    def delete_object(self, bucket: str, key: str) -> None:
        try:
            with self._request("DELETE", bucket, key):
                pass
        except S3ClientError as e:
            if e.status != 404:
                raise

    def create_bucket(self, bucket: str) -> None:
        try:
            with self._request("PUT", bucket, ""):
                pass
        except S3ClientError as e:
            if e.status != 409:  # already exists
                raise
