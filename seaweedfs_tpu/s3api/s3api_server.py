"""S3-compatible gateway over the filer.

Behavioral match of weed/s3api/s3api_server.go:31-70 (route table) and
its handlers: buckets are directories under `/buckets` on the filer
(s3api_bucket_handlers.go), object bytes are proxied to the filer HTTP
server (s3api_object_handlers.go PutObjectHandler→putToFiler), metadata
ops ride the filer gRPC service, and multipart uploads stage parts in
`/buckets/<bucket>/.uploads/<uploadId>/` then splice every part's
chunks into one entry on complete (filer_multipart.go:56-120).

Route dispatch (the gorilla/mux table, s3api_server.go:42-79):
  HEAD   /b            HeadBucket           HEAD   /b/k  HeadObject
  PUT    /b            PutBucket            PUT    /b/k  PutObject | PutObjectPart(partNumber&uploadId) | CopyObject(X-Amz-Copy-Source)
  DELETE /b            DeleteBucket         DELETE /b/k  DeleteObject | AbortMultipartUpload(uploadId)
  GET    /             ListBuckets          GET    /b/k  GetObject | ListObjectParts(uploadId)
  GET    /b            ListObjectsV1 | ListObjectsV2(list-type=2) | ListMultipartUploads(uploads)
  POST   /b            DeleteMultipleObjects(delete)
  POST   /b/k          NewMultipartUpload(uploads) | CompleteMultipartUpload(uploadId)
"""

from __future__ import annotations

import hashlib
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
import xml.etree.ElementTree as ET
from xml.sax.saxutils import escape

import grpc

from seaweedfs_tpu import trace
from seaweedfs_tpu.util import deadline as _deadline
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.util.httpd import FastHandler, WeedHTTPServer
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.s3api import auth as s3auth
from seaweedfs_tpu.s3api import chunked_reader
from seaweedfs_tpu.s3api.errors import S3Error, s3_error

S3_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
MAX_OBJECT_LIST_SIZE = 1000  # s3api_objects_list_handlers.go:21


class S3ApiServer:
    def __init__(
        self,
        filer: str,
        host: str = "127.0.0.1",
        port: int = 8333,
        buckets_path: str = "/buckets",
        iam: s3auth.IdentityAccessManagement | None = None,
        masters: list[str] | None = None,
        announce_interval: float = 10.0,
        reuse_port: bool = False,
        serve_idle_ms: int = 0,
        serve_max_reqs: int = 0,
        admission_rate: float = 0.0,
        admission_burst: float = 0.0,
        admission_inflight: int = 0,
        admission_procs: int = 1,
        admission_shm_path: str = "",
    ):
        self.filer = filer
        self.host = host
        self.port = port
        self.buckets_path = buckets_path.rstrip("/")
        self.iam = iam or s3auth.IdentityAccessManagement()
        # telemetry plane: masters to announce this gateway to (the S3
        # gateway only knows its filer; the operator passes -master so
        # the cluster collector can scrape it)
        self.masters = list(masters or [])
        self.announce_interval = announce_interval
        # `s3 -serveProcs N`: every process of the group binds the port
        # with SO_REUSEPORT so the kernel spreads accepted connections
        # (docs/SERVING.md); the keep-alive knobs ride to the loop
        self.reuse_port = reuse_port
        self.serve_idle_ms = serve_idle_ms
        self.serve_max_reqs = serve_max_reqs
        # QoS plane (docs/QOS.md): per-client admission control, keyed
        # by S3 access key when the request is signed (else remote
        # addr). `admission_procs` = the -serveProcs group size, so each
        # sibling process enforces its share of the global budget.
        self.admission = None
        if admission_rate > 0 or admission_inflight > 0:
            from seaweedfs_tpu.qos.admission import AdmissionController

            self.admission = AdmissionController(
                rate=admission_rate,
                burst=admission_burst,
                max_inflight=admission_inflight,
                procs=admission_procs,
                label="s3",
                shm_path=admission_shm_path,
            )
        self._announce: threading.Thread | None = None
        self._http_server: WeedHTTPServer | None = None
        self._channel: grpc.Channel | None = None
        self._channel_lock = threading.Lock()

    # ------------------------------------------------------------------
    # filer access
    def _stub(self):
        with self._channel_lock:
            if self._channel is None:
                self._channel = rpc.dial(rpc.grpc_address(self.filer))
            return rpc.filer_stub(self._channel)

    def _lookup(self, directory: str, name: str):
        try:
            return self._stub().LookupDirectoryEntry(
                fpb.LookupDirectoryEntryRequest(directory=directory, name=name)
            ).entry
        except grpc.RpcError:
            return None

    def _mkdir(self, parent: str, name: str, extended: dict | None = None) -> None:
        entry = fpb.Entry(
            name=name,
            is_directory=True,
            attributes=fpb.Attributes(mtime=int(time.time()), file_mode=0o40777),
        )
        for k, v in (extended or {}).items():
            entry.extended[k] = v
        self._stub().CreateEntry(fpb.CreateEntryRequest(directory=parent, entry=entry))

    def _mkfile(self, parent: str, name: str, chunks, mime: str = "") -> None:
        entry = fpb.Entry(
            name=name,
            is_directory=False,
            chunks=chunks,
            attributes=fpb.Attributes(
                mtime=int(time.time()), file_mode=0o660, mime=mime
            ),
        )
        self._stub().CreateEntry(fpb.CreateEntryRequest(directory=parent, entry=entry))

    def _list(self, directory: str, prefix: str = "", start: str = "",
              inclusive: bool = False, limit: int = MAX_OBJECT_LIST_SIZE):
        try:
            return [
                resp.entry
                for resp in self._stub().ListEntries(
                    fpb.ListEntriesRequest(
                        directory=directory,
                        prefix=prefix,
                        start_from_file_name=start,
                        inclusive_start_from=inclusive,
                        limit=limit,
                    )
                )
            ]
        except grpc.RpcError:
            return []

    def _rm(self, directory: str, name: str, delete_data: bool = True) -> None:
        try:
            self._stub().DeleteEntry(
                fpb.DeleteEntryRequest(
                    directory=directory,
                    name=name,
                    is_delete_data=delete_data,
                    is_recursive=True,
                )
            )
        except grpc.RpcError:
            pass

    def _filer_url(self, *segments: str) -> str:
        path = "/".join(urllib.parse.quote(s) for s in segments if s)
        return f"http://{self.filer}/{path}"

    def _filer_hop_timeout(self, req) -> float:
        """Deadline plane (docs/CHAOS.md): the gateway→filer hop runs
        under the request's ambient budget — the X-Weed-Deadline header
        rides along (the filer 504-fast-rejects expired work) and the
        socket timeout shrinks to the remaining budget, so a
        partitioned filer costs a bounded failure, not a 60 s park.
        Deadline-less requests keep the fixed 60 s cap."""
        dl = _deadline.effective(None)
        if dl is None:
            return 60.0
        req.add_header(_deadline.DEADLINE_HEADER, dl.header_value())
        try:
            return dl.cap(60.0)
        except _deadline.DeadlineExceeded:
            # budget spent mid-request (body read + SigV4 check ate
            # it): answer a proper S3 error — letting the TimeoutError
            # propagate would be swallowed at the connection loop and
            # close the socket with no response at all
            raise s3_error("RequestTimeout") from None

    def _put_to_filer(self, path_segments: list[str], body: bytes, mime: str) -> None:
        """Store object bytes through the filer HTTP write path (which
        auto-chunks) — the putToFiler proxy in the reference."""
        req = urllib.request.Request(
            self._filer_url(*path_segments), data=body, method="POST"
        )
        if mime:
            req.add_header("Content-Type", mime)
        trace.inject_request(req)  # gateway→filer hop, same trace
        # weedlint: ignore[no-deadline] — deadline-aware via _filer_hop_timeout; streaming Request bodies don't fit the pooled transport yet
        with urllib.request.urlopen(
            req, timeout=self._filer_hop_timeout(req)
        ) as r:
            if r.status >= 300:
                raise s3_error("InternalError")

    def _get_from_filer(self, path_segments: list[str]) -> tuple[bytes, str]:
        try:
            req = urllib.request.Request(self._filer_url(*path_segments))
            trace.inject_request(req)
            # weedlint: ignore[no-deadline] — deadline-aware via _filer_hop_timeout; migrating GETs to http_call rides with the PUT path above
            with urllib.request.urlopen(
                req, timeout=self._filer_hop_timeout(req)
            ) as r:
                return r.read(), r.headers.get("Content-Type", "")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise s3_error("NoSuchKey") from None
            raise s3_error("InternalError") from None

    def _uploads_folder(self, bucket: str) -> str:
        # genUploadsFolder (s3api_object_multipart_handlers.go:219)
        return f"{self.buckets_path}/{bucket}/.uploads"

    # ------------------------------------------------------------------
    # lifecycle
    def start(self) -> None:
        handler = self._handler_class()
        if self.reuse_port:
            from seaweedfs_tpu.util.httpd import ReusePortWeedHTTPServer

            server_cls = ReusePortWeedHTTPServer
        else:
            server_cls = WeedHTTPServer
        self._http_server = server_cls((self.host, self.port), handler)
        self._http_server.serve_idle_ms = self.serve_idle_ms
        self._http_server.serve_max_reqs = self.serve_max_reqs
        # tracing + metrics plane: span per request in the mini loop,
        # request counters/histograms under the "s3" label, and the
        # /metrics exposition the gateway previously lacked (served by
        # the loop — exact-path GET /metrics, so bucket routing keeps
        # every other path)
        self._http_server.trace_name = "s3"
        self._http_server.trace_node = f"{self.host}:{self.port}"
        self._http_server.gateway_metrics = True
        # the S3 gateway is the one auth-fronted daemon: with
        # identities configured, /debug/* and /metrics would otherwise
        # leak object keys/latencies to unauthenticated peers (and
        # shadow a bucket literally named "debug"/"metrics"), so only
        # loopback operators keep the unauthenticated surface
        self._http_server.debug_gate = (
            lambda h: not self.iam.is_enabled
            or h.client_address[0] in ("127.0.0.1", "::1")
        )
        self._http_server.admission = self.admission
        threading.Thread(
            target=self._http_server.serve_forever, daemon=True, name="s3-http"
        ).start()
        from seaweedfs_tpu.telemetry import profiler
        from seaweedfs_tpu.telemetry.announce import start_announce_loop

        profiler.ensure_started()
        self._announce = start_announce_loop(
            "s3", f"{self.host}:{self.port}", self.masters,
            interval=self.announce_interval,
        )

    def stop(self) -> None:
        if self._announce is not None:
            self._announce.stop_event.set()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._channel is not None:
            self._channel.close()

    # ------------------------------------------------------------------
    def _handler_class(self):
        server = self

        class Handler(FastHandler):
            # rides the util/httpd mini request loop like every other
            # serving path (one-buffer head parse, FastHeaders, dict
            # dispatch, keep-alive semantics, fast_reply one-write
            # responses) — the S3 data path no longer pays the stdlib
            # email-parser/send_header-per-line overhead the volume
            # server shed two rounds ago

            # ---------- plumbing ----------
            def _send(self, status: int, body: bytes = b"", headers: dict | None = None):
                out = {k: v for k, v in (headers or {}).items() if v}
                self.fast_reply(status, body, out or None)

            def _send_xml(self, root: ET.Element, status: int = 200):
                body = b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)
                self._send(status, body, {"Content-Type": "application/xml"})

            def _send_error(self, err: S3Error):
                self._send(
                    err.status,
                    err.to_xml(resource=self.path),
                    {"Content-Type": "application/xml"},
                )

            def _route(self):
                url = urllib.parse.urlparse(self.path)
                raw = urllib.parse.unquote(url.path)
                query = urllib.parse.parse_qs(url.query, keep_blank_values=True)
                parts = raw.lstrip("/").split("/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                return bucket, key, query, url.path

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length", "0") or "0")
                return self.rfile.read(length) if length else b""

            def _authenticate(self, body: bytes | None):
                url = urllib.parse.urlparse(self.path)
                query = urllib.parse.parse_qs(url.query, keep_blank_values=True)
                server.iam.authenticate(
                    self.command,
                    urllib.parse.unquote(url.path),
                    query,
                    self.headers,
                    body,
                )

            # ---------- verbs ----------
            def do_GET(self):
                try:
                    bucket, key, query, _ = self._route()
                    self._authenticate(b"")
                    if not bucket:
                        return self._list_buckets()
                    if key:
                        if "uploadId" in query:
                            return self._list_object_parts(bucket, key, query)
                        return self._get_object(bucket, key)
                    if "uploads" in query:
                        return self._list_multipart_uploads(bucket)
                    return self._list_objects(bucket, query)
                except S3Error as e:
                    self._send_error(e)

            def do_HEAD(self):
                try:
                    bucket, key, query, _ = self._route()
                    self._authenticate(b"")
                    if key:
                        return self._head_object(bucket, key)
                    return self._head_bucket(bucket)
                except S3Error as e:
                    self._send_error(e)

            def do_PUT(self):
                try:
                    bucket, key, query, _ = self._route()
                    body = self._read_body()
                    sha_hdr = self.headers.get("x-amz-content-sha256", "")
                    if sha_hdr == s3auth.STREAMING_PAYLOAD:
                        # Verify the header V4 signature (method, path,
                        # headers, date — payload hash is the STREAMING
                        # sentinel) BEFORE trusting the seed signature
                        # the per-chunk signatures chain from.
                        self._authenticate(None)
                        body = self._decode_streaming(body)
                    else:
                        self._authenticate(body)
                    if not key:
                        return self._put_bucket(bucket)
                    if "partNumber" in query and "uploadId" in query:
                        return self._put_object_part(bucket, key, query, body)
                    if self.headers.get("X-Amz-Copy-Source"):
                        return self._copy_object(bucket, key)
                    return self._put_object(bucket, key, body)
                except S3Error as e:
                    self._send_error(e)

            def do_POST(self):
                try:
                    bucket, key, query, _ = self._route()
                    body = self._read_body()
                    self._authenticate(body)
                    if key and "uploads" in query:
                        return self._new_multipart_upload(bucket, key)
                    if key and "uploadId" in query:
                        return self._complete_multipart_upload(bucket, key, query, body)
                    if "delete" in query:
                        return self._delete_multiple_objects(bucket, body)
                    raise s3_error("NotImplemented")
                except S3Error as e:
                    self._send_error(e)

            def do_DELETE(self):
                try:
                    bucket, key, query, _ = self._route()
                    self._authenticate(b"")
                    if key and "uploadId" in query:
                        return self._abort_multipart_upload(bucket, key, query)
                    if key:
                        return self._delete_object(bucket, key)
                    return self._delete_bucket(bucket)
                except S3Error as e:
                    self._send_error(e)

            # ---------- streaming sigv4 ----------
            def _decode_streaming(self, raw: bytes) -> bytes:
                import io

                if server.iam.is_enabled:
                    url = urllib.parse.urlparse(self.path)
                    query = urllib.parse.parse_qs(url.query, keep_blank_values=True)
                    key, seed, amz_date, scope = server.iam.seed_signature(
                        self.command,
                        urllib.parse.unquote(url.path),
                        query,
                        self.headers,
                    )
                    try:
                        return chunked_reader.decode_chunked_payload(
                            io.BytesIO(raw),
                            signing_key=key,
                            seed_signature=seed,
                            amz_date=amz_date,
                            scope=scope,
                        )
                    except chunked_reader.ChunkSignatureMismatch:
                        raise s3_error("SignatureDoesNotMatch") from None
                return chunked_reader.decode_chunked_payload(io.BytesIO(raw))

            # ---------- buckets ----------
            def _list_buckets(self):
                entries = server._list(server.buckets_path)
                root = ET.Element("ListAllMyBucketsResult", xmlns=S3_XMLNS)
                owner = ET.SubElement(root, "Owner")
                ET.SubElement(owner, "ID").text = ""
                buckets = ET.SubElement(root, "Buckets")
                for e in entries:
                    if not e.is_directory:
                        continue
                    b = ET.SubElement(buckets, "Bucket")
                    ET.SubElement(b, "Name").text = e.name
                    ET.SubElement(b, "CreationDate").text = _iso(e.attributes.mtime)
                self._send_xml(root)

            def _put_bucket(self, bucket: str):
                if not _valid_bucket_name(bucket):
                    raise s3_error("InvalidBucketName")
                if server._lookup(server.buckets_path, bucket) is not None:
                    raise s3_error("BucketAlreadyExists")
                server._mkdir(server.buckets_path, bucket)
                self._send(200, headers={"Location": f"/{bucket}"})

            def _head_bucket(self, bucket: str):
                if server._lookup(server.buckets_path, bucket) is None:
                    raise s3_error("NoSuchBucket")
                self._send(200)

            def _delete_bucket(self, bucket: str):
                if server._lookup(server.buckets_path, bucket) is None:
                    raise s3_error("NoSuchBucket")
                # the reference deletes the whole collection then the dir
                # (s3api_bucket_handlers.go DeleteBucketHandler)
                try:
                    server._stub().DeleteCollection(
                        fpb.DeleteCollectionRequest(collection=bucket)
                    )
                except grpc.RpcError:
                    pass
                server._rm(server.buckets_path, bucket, delete_data=False)
                self._send(204)

            # ---------- objects ----------
            def _put_object(self, bucket: str, key: str, body: bytes):
                if server._lookup(server.buckets_path, bucket) is None:
                    raise s3_error("NoSuchBucket")
                mime = self.headers.get("Content-Type", "")
                server._put_to_filer(
                    [server.buckets_path.lstrip("/"), bucket] + key.split("/"),
                    body,
                    mime,
                )
                etag = hashlib.md5(body).hexdigest()
                self._send(200, headers={"ETag": f'"{etag}"'})

            def _copy_object(self, bucket: str, key: str):
                src = urllib.parse.unquote(self.headers["X-Amz-Copy-Source"])
                src = src.lstrip("/")
                src_bucket, _, src_key = src.partition("/")
                data, mime = server._get_from_filer(
                    [server.buckets_path.lstrip("/"), src_bucket] + src_key.split("/")
                )
                server._put_to_filer(
                    [server.buckets_path.lstrip("/"), bucket] + key.split("/"),
                    data,
                    mime,
                )
                root = ET.Element("CopyObjectResult", xmlns=S3_XMLNS)
                ET.SubElement(root, "ETag").text = f'"{hashlib.md5(data).hexdigest()}"'
                ET.SubElement(root, "LastModified").text = _iso(int(time.time()))
                self._send_xml(root)

            def _get_object(self, bucket: str, key: str):
                data, mime = server._get_from_filer(
                    [server.buckets_path.lstrip("/"), bucket] + key.split("/")
                )
                headers = {
                    "Content-Type": mime or "application/octet-stream",
                    "ETag": f'"{hashlib.md5(data).hexdigest()}"',
                    "Accept-Ranges": "bytes",
                }
                from seaweedfs_tpu.util.http_range import (
                    RangeNotSatisfiable,
                    parse_range,
                )

                total = len(data)
                try:
                    span = parse_range(self.headers.get("Range", ""), total)
                except RangeNotSatisfiable:
                    self._send(416, b"", {"Content-Range": f"bytes */{total}"})
                    return
                if span is not None:
                    start, end = span
                    headers["Content-Range"] = f"bytes {start}-{end}/{total}"
                    self._send(206, data[start : end + 1], headers)
                    return
                self._send(200, data, headers)

            def _head_object(self, bucket: str, key: str):
                directory, _, name = f"{server.buckets_path}/{bucket}/{key}".rpartition("/")
                entry = server._lookup(directory, name)
                if entry is None or entry.is_directory:
                    raise s3_error("NoSuchKey")
                size = sum(c.size for c in entry.chunks)
                self._send(
                    200,
                    headers={
                        "Content-Type": entry.attributes.mime
                        or "application/octet-stream",
                        "Content-Length-Hint": str(size),
                        "Last-Modified": _http_date(entry.attributes.mtime),
                    },
                )

            def _delete_object(self, bucket: str, key: str):
                directory, _, name = f"{server.buckets_path}/{bucket}/{key}".rpartition("/")
                server._rm(directory, name, delete_data=True)
                self._send(204)

            def _delete_multiple_objects(self, bucket: str, body: bytes):
                try:
                    root = ET.fromstring(body)
                except ET.ParseError:
                    raise s3_error("MalformedXML") from None
                deleted, errors = [], []
                ns = ""
                if root.tag.startswith("{"):
                    ns = root.tag[: root.tag.index("}") + 1]
                for obj in root.findall(f"{ns}Object"):
                    key_el = obj.find(f"{ns}Key")
                    if key_el is None or not key_el.text:
                        continue
                    key = key_el.text
                    directory, _, name = (
                        f"{server.buckets_path}/{bucket}/{key}".rpartition("/")
                    )
                    server._rm(directory, name, delete_data=True)
                    deleted.append(key)
                out = ET.Element("DeleteResult", xmlns=S3_XMLNS)
                for key in deleted:
                    d = ET.SubElement(out, "Deleted")
                    ET.SubElement(d, "Key").text = key
                self._send_xml(out)

            # ---------- listing ----------
            def _list_objects(self, bucket: str, query: dict):
                if server._lookup(server.buckets_path, bucket) is None:
                    raise s3_error("NoSuchBucket")
                v2 = query.get("list-type", [""])[0] == "2"
                prefix = query.get("prefix", [""])[0]
                delimiter = query.get("delimiter", [""])[0]
                if v2:
                    marker = query.get("continuation-token", [""])[0] or query.get(
                        "start-after", [""]
                    )[0]
                else:
                    marker = query.get("marker", [""])[0]
                try:
                    max_keys = int(query.get("max-keys", ["1000"])[0])
                except ValueError:
                    raise s3_error("InvalidMaxKeys") from None
                if max_keys < 0:
                    raise s3_error("InvalidMaxKeys")
                if delimiter not in ("", "/"):
                    raise s3_error("NotImplemented")

                # split the prefix into directory part + entry-name prefix
                # (listFilerEntries, s3api_objects_list_handlers.go:92-100)
                slash = prefix.rfind("/")
                dir_part = prefix[: slash + 1] if slash >= 0 else ""
                name_prefix = prefix[slash + 1:] if slash >= 0 else prefix
                directory = f"{server.buckets_path}/{bucket}"
                if dir_part:
                    directory += "/" + dir_part.rstrip("/")
                rel_marker = marker[len(dir_part):] if marker.startswith(dir_part) else marker

                limit = min(max_keys, MAX_OBJECT_LIST_SIZE)
                contents, common = [], []
                keys = []
                last = ""
                truncated = False
                if delimiter == "/":
                    entries = server._list(
                        directory,
                        prefix=name_prefix,
                        start=rel_marker,
                        inclusive=False,
                        limit=limit + 1,
                    )
                    truncated = len(entries) > max_keys
                    entries = entries[:max_keys]
                    for e in entries:
                        last = f"{dir_part}{e.name}"
                        if e.is_directory:
                            if e.name != ".uploads":
                                common.append(f"{dir_part}{e.name}/")
                        else:
                            contents.append(e)
                            keys.append(last)
                else:
                    # flat listing: recurse into subdirectories so nested
                    # keys appear as Contents (S3 semantics when no
                    # delimiter is given)
                    def walk(dirpath, rel):
                        nonlocal truncated
                        sub = server._list(
                            dirpath,
                            prefix=name_prefix if rel == dir_part else "",
                            limit=MAX_OBJECT_LIST_SIZE + 1,
                        )
                        for e in sub:
                            if len(contents) > limit:
                                truncated = True
                                return
                            k = f"{rel}{e.name}"
                            if e.is_directory:
                                if e.name == ".uploads" and rel == "":
                                    continue
                                # prune subtrees wholly <= marker
                                if marker and not (
                                    f"{k}/" > marker
                                    or marker.startswith(f"{k}/")
                                ):
                                    continue
                                walk(f"{dirpath}/{e.name}", f"{k}/")
                            elif k > marker:
                                contents.append(e)
                                keys.append(k)

                    walk(directory, dir_part)
                    truncated = truncated or len(contents) > limit
                    contents = contents[:limit]
                    keys = keys[:limit]
                    if keys:
                        last = keys[-1]

                root = ET.Element("ListBucketResult", xmlns=S3_XMLNS)
                ET.SubElement(root, "Name").text = bucket
                ET.SubElement(root, "Prefix").text = prefix
                ET.SubElement(root, "Marker").text = marker
                ET.SubElement(root, "NextMarker").text = last if truncated else ""
                ET.SubElement(root, "MaxKeys").text = str(max_keys)
                if delimiter:
                    ET.SubElement(root, "Delimiter").text = delimiter
                ET.SubElement(root, "IsTruncated").text = (
                    "true" if truncated else "false"
                )
                if v2:
                    ET.SubElement(root, "KeyCount").text = str(len(contents))
                    if truncated:
                        ET.SubElement(root, "NextContinuationToken").text = last
                for e, full_key in zip(contents, keys):
                    c = ET.SubElement(root, "Contents")
                    ET.SubElement(c, "Key").text = full_key
                    ET.SubElement(c, "LastModified").text = _iso(e.attributes.mtime)
                    etag = e.chunks[0].e_tag if len(e.chunks) == 1 else ""
                    ET.SubElement(c, "ETag").text = f'"{etag}"'
                    ET.SubElement(c, "Size").text = str(
                        sum(ch.size for ch in e.chunks)
                    )
                    ET.SubElement(c, "StorageClass").text = "STANDARD"
                for p in common:
                    cp = ET.SubElement(root, "CommonPrefixes")
                    ET.SubElement(cp, "Prefix").text = p
                self._send_xml(root)

            # ---------- multipart ----------
            def _new_multipart_upload(self, bucket: str, key: str):
                upload_id = str(uuid.uuid4())
                # parent dirs (.../.uploads) auto-create on the filer side
                server._mkdir(
                    server._uploads_folder(bucket),
                    upload_id,
                    extended={"key": key.encode()},
                )
                root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_XMLNS)
                ET.SubElement(root, "Bucket").text = bucket
                ET.SubElement(root, "Key").text = key
                ET.SubElement(root, "UploadId").text = upload_id
                self._send_xml(root)

            def _put_object_part(self, bucket, key, query, body):
                upload_id = query["uploadId"][0]
                try:
                    part_num = int(query["partNumber"][0])
                except ValueError:
                    raise s3_error("InvalidArgument") from None
                if not 1 <= part_num <= 10000:
                    raise s3_error("InvalidArgument")
                if server._lookup(server._uploads_folder(bucket), upload_id) is None:
                    raise s3_error("NoSuchUpload")
                server._put_to_filer(
                    [
                        server.buckets_path.lstrip("/"),
                        bucket,
                        ".uploads",
                        upload_id,
                        f"{part_num:04d}.part",
                    ],
                    body,
                    "application/octet-stream",
                )
                etag = hashlib.md5(body).hexdigest()
                # remember the md5 on the staged entry so complete can
                # validate the client's part manifest (the chunk e_tag
                # the volume assigns is a needle etag, not this md5)
                part_entry = server._lookup(
                    f"{server._uploads_folder(bucket)}/{upload_id}",
                    f"{part_num:04d}.part",
                )
                if part_entry is not None:
                    part_entry.extended["s3-md5"] = etag.encode()
                    try:
                        server._stub().UpdateEntry(
                            fpb.UpdateEntryRequest(
                                directory=(
                                    f"{server._uploads_folder(bucket)}"
                                    f"/{upload_id}"
                                ),
                                entry=part_entry,
                            )
                        )
                    except grpc.RpcError:
                        pass  # validation degrades to existence-only
                self._send(200, headers={"ETag": f'"{etag}"'})

            def _complete_multipart_upload(self, bucket, key, query, body):
                upload_id = query["uploadId"][0]
                upload_dir = f"{server._uploads_folder(bucket)}/{upload_id}"
                entries = server._list(upload_dir)
                if not entries:
                    raise s3_error("NoSuchUpload")
                # splice every part's chunks into one chunk list at
                # running offsets (filer_multipart.go:67-84)
                # numeric part order — lexical sort would splice part
                # 10000 ("10000.part") between 1000 and 1001
                parts = [
                    e for e in entries
                    if e.name.endswith(".part") and not e.is_directory
                ]
                manifest = _parse_complete_body(body)
                if manifest is not None:
                    # client sent the CompleteMultipartUpload manifest:
                    # validate it like real S3 before splicing —
                    # ascending part order (InvalidPartOrder), every
                    # listed part staged with a matching ETag
                    # (InvalidPart) — so a client that lost a part PUT
                    # gets a typed error, not a silently short object
                    if [n for n, _ in manifest] != sorted(
                        n for n, _ in manifest
                    ):
                        raise s3_error("InvalidPartOrder")
                    staged = {int(e.name[:-5]): e for e in parts}
                    chosen = []
                    for num, etag in manifest:
                        entry = staged.get(num)
                        if entry is None:
                            raise s3_error("InvalidPart")
                        if etag:
                            want = etag.strip('"')
                            have = _entry_part_etag(entry)
                            if have is not None and want != have:
                                raise s3_error("InvalidPart")
                        chosen.append(entry)
                    parts = chosen
                final_chunks = []
                offset = 0
                for entry in sorted(parts, key=lambda e: int(e.name[:-5])):
                    for chunk in entry.chunks:
                        final_chunks.append(
                            fpb.FileChunk(
                                fid=chunk.fid,
                                offset=offset,
                                size=chunk.size,
                                mtime=chunk.mtime,
                                e_tag=chunk.e_tag,
                            )
                        )
                        offset += chunk.size
                dir_name = f"{server.buckets_path}/{bucket}"
                entry_name = key
                if "/" in key:
                    sub, _, entry_name = key.rpartition("/")
                    dir_name = f"{dir_name}/{sub}"
                server._mkfile(dir_name, entry_name, final_chunks)
                # drop the staging dir but keep the part chunks alive —
                # the final entry references them
                server._rm(
                    server._uploads_folder(bucket), upload_id, delete_data=False
                )
                root = ET.Element("CompleteMultipartUploadResult", xmlns=S3_XMLNS)
                ET.SubElement(root, "Location").text = (
                    f"http://{server.filer}{dir_name}/{entry_name}"
                )
                ET.SubElement(root, "Bucket").text = bucket
                ET.SubElement(root, "Key").text = key
                ET.SubElement(root, "ETag").text = f'"{_chunks_etag(final_chunks)}"'
                self._send_xml(root)

            def _abort_multipart_upload(self, bucket, key, query):
                upload_id = query["uploadId"][0]
                if server._lookup(server._uploads_folder(bucket), upload_id) is None:
                    # unknown (or already aborted/completed) upload id
                    # gets the typed error, not a silent 204
                    raise s3_error("NoSuchUpload")
                # delete_data=True: the staged part chunks are orphans
                # once the staging dir goes — abort must reclaim them,
                # not leak volume space until vacuum
                server._rm(server._uploads_folder(bucket), upload_id, delete_data=True)
                self._send(204)

            def _list_multipart_uploads(self, bucket):
                uploads = server._list(server._uploads_folder(bucket))
                root = ET.Element("ListMultipartUploadsResult", xmlns=S3_XMLNS)
                ET.SubElement(root, "Bucket").text = bucket
                for u in uploads:
                    if not u.is_directory:
                        continue
                    el = ET.SubElement(root, "Upload")
                    ET.SubElement(el, "UploadId").text = u.name
                    key = u.extended.get("key", b"").decode()
                    ET.SubElement(el, "Key").text = key
                self._send_xml(root)

            def _list_object_parts(self, bucket, key, query):
                upload_id = query["uploadId"][0]
                upload_dir = f"{server._uploads_folder(bucket)}/{upload_id}"
                entries = server._list(upload_dir)
                if server._lookup(server._uploads_folder(bucket), upload_id) is None:
                    raise s3_error("NoSuchUpload")
                root = ET.Element("ListPartsResult", xmlns=S3_XMLNS)
                ET.SubElement(root, "Bucket").text = bucket
                ET.SubElement(root, "Key").text = key
                ET.SubElement(root, "UploadId").text = upload_id
                parts = [e for e in entries if e.name.endswith(".part")]
                for entry in sorted(parts, key=lambda e: int(e.name[:-5])):
                    p = ET.SubElement(root, "Part")
                    ET.SubElement(p, "PartNumber").text = str(
                        int(entry.name[:-5])
                    )
                    ET.SubElement(p, "LastModified").text = _iso(entry.attributes.mtime)
                    ET.SubElement(p, "Size").text = str(
                        sum(c.size for c in entry.chunks)
                    )
                self._send_xml(root)

        return Handler


# ----------------------------------------------------------------------
def _iso(epoch_sec: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(epoch_sec or 0))


def _http_date(epoch_sec: int) -> str:
    return time.strftime(
        "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(epoch_sec or 0)
    )


def _parse_complete_body(body: bytes) -> list[tuple[int, str]] | None:
    """Parse a CompleteMultipartUpload request body into
    [(part_number, etag), ...] in document order, or None when the
    client sent no manifest (legacy callers: assemble all staged
    parts). A malformed manifest is a malformed request."""
    if not body or not body.strip():
        return None
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise s3_error("MalformedXML") from None
    out: list[tuple[int, str]] = []
    for part in root.iter():
        if not part.tag.endswith("Part"):
            continue
        num, etag = None, ""
        for child in part:
            if child.tag.endswith("PartNumber"):
                try:
                    num = int((child.text or "").strip())
                except ValueError:
                    raise s3_error("MalformedXML") from None
            elif child.tag.endswith("ETag"):
                etag = (child.text or "").strip()
        if num is not None:
            out.append((num, etag))
    return out or None


def _entry_part_etag(entry) -> str | None:
    """The md5 ETag the part PUT responded with, recorded on the
    staged entry; None if the UpdateEntry that records it was lost
    (validation then degrades to part existence + order)."""
    raw = entry.extended.get("s3-md5", b"")
    return raw.decode() if raw else None


def _chunks_etag(chunks) -> str:
    h = hashlib.md5()
    for c in chunks:
        h.update(c.e_tag.encode() or c.fid.encode())
    return f"{h.hexdigest()}-{len(chunks)}"


def _valid_bucket_name(name: str) -> bool:
    if not 3 <= len(name) <= 63:
        return False
    return all(c.islower() or c.isdigit() or c in "-." for c in name) and (
        name[0].isalnum() and name[-1].isalnum()
    )
