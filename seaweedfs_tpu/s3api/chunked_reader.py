"""AWS streaming-SigV4 ("aws-chunked") payload decoder.

Behavioral match of weed/s3api/chunked_reader_v4.go: the body is a
sequence of

    <hex-size>;chunk-signature=<sig>\r\n<data>\r\n

frames ending with a zero-length chunk. The reference decodes the
framing and records each chunk signature; this build additionally
*verifies* the per-chunk signature chain when a signing key is supplied
(the full AWS spec the reference's minio-derived code stubs out):

    sig_n = HMAC(key, "AWS4-HMAC-SHA256-PAYLOAD\n{date}\n{scope}\n
                       {sig_{n-1}}\nSHA256("")\nSHA256(chunk_data)")
"""

from __future__ import annotations

import hashlib
import hmac
import io

from seaweedfs_tpu.s3api.errors import s3_error

MAX_LINE_LENGTH = 4096
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


class ChunkSignatureMismatch(Exception):
    pass


def decode_chunked_payload(
    stream: io.BufferedIOBase,
    signing_key: bytes | None = None,
    seed_signature: str = "",
    amz_date: str = "",
    scope: str = "",
) -> bytes:
    """Decode (and optionally verify) an aws-chunked body; returns the
    raw payload bytes."""
    out = bytearray()
    prev_sig = seed_signature
    while True:
        line = _read_line(stream)
        if not line:
            raise s3_error("MalformedXML")
        size_hex, _, token = line.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise s3_error("MalformedXML") from None
        chunk_sig = ""
        if token.startswith("chunk-signature="):
            chunk_sig = token[len("chunk-signature="):]
        data = stream.read(size)
        if len(data) != size:
            raise s3_error("MalformedXML")
        crlf = stream.read(2)
        if crlf != b"\r\n":
            raise s3_error("MalformedXML")
        if signing_key is not None:
            expect = _chunk_signature(
                signing_key, amz_date, scope, prev_sig, bytes(data)
            )
            if not hmac.compare_digest(expect, chunk_sig):
                raise ChunkSignatureMismatch(
                    f"chunk signature mismatch at offset {len(out)}"
                )
            prev_sig = chunk_sig
        if size == 0:
            return bytes(out)
        out.extend(data)


def _read_line(stream) -> str:
    buf = bytearray()
    while len(buf) < MAX_LINE_LENGTH:
        c = stream.read(1)
        if not c:
            break
        if c == b"\n":
            if buf and buf[-1:] == b"\r":
                del buf[-1]
            return buf.decode("ascii", "replace")
        buf.extend(c)
    return buf.decode("ascii", "replace")


def _chunk_signature(
    key: bytes, amz_date: str, scope: str, prev_sig: str, data: bytes
) -> str:
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256-PAYLOAD",
            amz_date,
            scope,
            prev_sig,
            EMPTY_SHA256,
            hashlib.sha256(data).hexdigest(),
        ]
    )
    return hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()


def encode_chunked_payload(
    data: bytes,
    chunk_size: int,
    signing_key: bytes | None = None,
    seed_signature: str = "",
    amz_date: str = "",
    scope: str = "",
) -> bytes:
    """Client-side encoder (test harness): frame `data` as aws-chunked."""
    out = bytearray()
    prev = seed_signature
    pieces = [data[i:i + chunk_size] for i in range(0, len(data), chunk_size)]
    pieces.append(b"")
    for piece in pieces:
        if signing_key is not None:
            sig = _chunk_signature(signing_key, amz_date, scope, prev, piece)
            prev = sig
            out.extend(f"{len(piece):x};chunk-signature={sig}\r\n".encode())
        else:
            out.extend(f"{len(piece):x}\r\n".encode())
        out.extend(piece)
        out.extend(b"\r\n")
    return bytes(out)
