"""AWS Signature Version 4 verification.

The reference's weed/s3api/s3api_auth.go only *classifies* requests
(V4 / V2 / presigned / anonymous / JWT) — the v0 snapshot performs no
credential checking. This build implements real verification as a
strict superset: when identities are configured the gateway recomputes
the V4 signature (canonical request → string-to-sign → derived signing
key, per the AWS SigV4 spec) for both header auth and presigned URLs;
with no identities configured every request is allowed, matching the
reference's effective open behavior.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse

from seaweedfs_tpu.s3api.errors import s3_error

SIGN_V4_ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
MAX_CLOCK_SKEW_SEC = 15 * 60
MAX_PRESIGNED_EXPIRES_SEC = 7 * 24 * 3600


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def derive_signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def sigv4_sign(
    method: str,
    path: str,
    query_string: str,
    headers: dict,
    payload_hash: str,
    access_key: str,
    secret_key: str,
    region: str,
    service: str,
    amz_date: str,
) -> str:
    """The client-side SigV4 Authorization header value — the single
    home of the canonical-request → string-to-sign → signature chain
    for every AWS-protocol client in the repo (S3 data plane, SQS
    notifications). `headers` must already include host and x-amz-date;
    values are trimmed per the spec."""
    date = amz_date[:8]
    signed = sorted(k.lower() for k in headers)
    lower = {k.lower(): str(v).strip() for k, v in headers.items()}
    canonical = "\n".join(
        [
            method,
            path,
            query_string,
            "".join(f"{k}:{lower[k]}\n" for k in signed),
            ";".join(signed),
            payload_hash,
        ]
    )
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ]
    )
    signature = hmac.new(
        derive_signing_key(secret_key, date, region, service),
        string_to_sign.encode(),
        hashlib.sha256,
    ).hexdigest()
    return (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={signature}"
    )


def uri_encode(value: str, encode_slash: bool = True) -> str:
    safe = "-_.~" + ("" if encode_slash else "/")
    return urllib.parse.quote(value, safe=safe)


def canonical_query_string(query: dict[str, list[str]], skip: tuple[str, ...] = ()) -> str:
    pairs = []
    for k in sorted(query):
        if k in skip:
            continue
        for v in sorted(query[k]):
            pairs.append(f"{uri_encode(k)}={uri_encode(v)}")
    return "&".join(pairs)


def canonical_request(
    method: str,
    path: str,
    query: dict[str, list[str]],
    headers,
    signed_headers: list[str],
    payload_hash: str,
    skip_query: tuple[str, ...] = (),
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(str(headers.get(h, '')).split())}\n" for h in signed_headers
    )
    return "\n".join(
        [
            method,
            uri_encode(path, encode_slash=False) or "/",
            canonical_query_string(query, skip=skip_query),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(amz_date: str, scope: str, canon_req: str) -> str:
    return "\n".join(
        [SIGN_V4_ALGORITHM, amz_date, scope, _sha256_hex(canon_req.encode())]
    )


class Identity:
    def __init__(self, name: str, access_key: str, secret_key: str, actions=("Admin",)):
        self.name = name
        self.access_key = access_key
        self.secret_key = secret_key
        self.actions = tuple(actions)


class IdentityAccessManagement:
    """access-key registry + V4 verifier. No identities = open gateway."""

    def __init__(self, identities: list[Identity] | None = None):
        self._by_access_key = {i.access_key: i for i in (identities or [])}

    @property
    def is_enabled(self) -> bool:
        return bool(self._by_access_key)

    def lookup(self, access_key: str) -> Identity:
        ident = self._by_access_key.get(access_key)
        if ident is None:
            raise s3_error("InvalidAccessKeyId")
        return ident

    # ------------------------------------------------------------------
    def authenticate(self, method: str, path: str, query: dict, headers, body: bytes | None):
        """Verify the request; returns the Identity (or None when open /
        anonymous). Raises S3Error on failure.

        `body` may be None for streaming payloads (the seed signature is
        checked against STREAMING-AWS4-HMAC-SHA256-PAYLOAD; per-chunk
        signatures are the chunked reader's job)."""
        if not self.is_enabled:
            return None
        auth_header = headers.get("Authorization", "")
        if auth_header.startswith(SIGN_V4_ALGORITHM):
            return self._verify_header_v4(method, path, query, headers, body, auth_header)
        if "X-Amz-Credential" in query:
            return self._verify_presigned_v4(method, path, query, headers)
        raise s3_error("AccessDenied")

    def seed_signature(self, method: str, path: str, query: dict, headers) -> tuple[bytes, str, str, str]:
        """For aws-chunked uploads: (signing_key, seed_signature,
        amz_date, scope) the chunked reader chains from."""
        auth_header = headers.get("Authorization", "")
        credential, signed_headers, signature = _parse_auth_header(auth_header)
        access_key, date, region, service = _parse_credential(credential)
        ident = self.lookup(access_key)
        key = derive_signing_key(ident.secret_key, date, region, service)
        scope = f"{date}/{region}/{service}/aws4_request"
        return key, signature, headers.get("x-amz-date", ""), scope

    # ------------------------------------------------------------------
    def _verify_header_v4(self, method, path, query, headers, body, auth_header):
        credential, signed_headers, signature = _parse_auth_header(auth_header)
        access_key, date, region, service = _parse_credential(credential)
        ident = self.lookup(access_key)
        amz_date = headers.get("x-amz-date") or headers.get("X-Amz-Date", "")
        _check_skew(amz_date)
        payload_hash = headers.get("x-amz-content-sha256", UNSIGNED_PAYLOAD)
        if payload_hash not in (UNSIGNED_PAYLOAD, STREAMING_PAYLOAD) and body is not None:
            if _sha256_hex(body) != payload_hash:
                raise s3_error("SignatureDoesNotMatch")
        canon = canonical_request(
            method, path, query, _LowerHeaders(headers), signed_headers, payload_hash
        )
        scope = f"{date}/{region}/{service}/aws4_request"
        sts = string_to_sign(amz_date, scope, canon)
        key = derive_signing_key(ident.secret_key, date, region, service)
        expect = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expect, signature):
            raise s3_error("SignatureDoesNotMatch")
        return ident

    def _verify_presigned_v4(self, method, path, query, headers):
        try:
            credential = query["X-Amz-Credential"][0]
            amz_date = query["X-Amz-Date"][0]
            signed_headers = query["X-Amz-SignedHeaders"][0].split(";")
            signature = query["X-Amz-Signature"][0]
        except (KeyError, IndexError):
            raise s3_error("MissingFields") from None
        access_key, date, region, service = _parse_credential(credential)
        ident = self.lookup(access_key)
        # Presigned URLs are bounded by their own expiry window, not the
        # 15-minute header-auth skew check (X-Amz-Expires may validly be
        # up to 7 days).
        try:
            expires = int(query.get("X-Amz-Expires", ["900"])[0])
        except ValueError:
            raise s3_error("AuthorizationQueryParametersError") from None
        if not 1 <= expires <= MAX_PRESIGNED_EXPIRES_SEC:
            raise s3_error("AuthorizationQueryParametersError")
        try:
            t = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc
            )
        except ValueError:
            raise s3_error("AuthorizationHeaderMalformed") from None
        now = datetime.datetime.now(datetime.timezone.utc)
        if now > t + datetime.timedelta(seconds=expires):
            raise s3_error("AccessDenied")
        canon = canonical_request(
            method,
            path,
            query,
            _LowerHeaders(headers),
            signed_headers,
            UNSIGNED_PAYLOAD,
            skip_query=("X-Amz-Signature",),
        )
        scope = f"{date}/{region}/{service}/aws4_request"
        sts = string_to_sign(amz_date, scope, canon)
        key = derive_signing_key(ident.secret_key, date, region, service)
        expect = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expect, signature):
            raise s3_error("SignatureDoesNotMatch")
        return ident


class _LowerHeaders:
    """case-insensitive header view with lower-case canonical keys."""

    def __init__(self, headers):
        self._h = {str(k).lower(): v for k, v in dict(headers).items()}

    def get(self, key, default=""):
        return self._h.get(key.lower(), default)


def _parse_auth_header(auth_header: str) -> tuple[str, list[str], str]:
    rest = auth_header[len(SIGN_V4_ALGORITHM):].strip()
    parts = {}
    for piece in rest.split(","):
        k, _, v = piece.strip().partition("=")
        parts[k] = v
    try:
        credential = parts["Credential"]
        signed_headers = parts["SignedHeaders"].split(";")
        signature = parts["Signature"]
    except KeyError:
        raise s3_error("AuthorizationHeaderMalformed") from None
    return credential, signed_headers, signature


def _parse_credential(credential: str) -> tuple[str, str, str, str]:
    bits = credential.split("/")
    if len(bits) != 5 or bits[4] != "aws4_request":
        raise s3_error("AuthorizationHeaderMalformed")
    return bits[0], bits[1], bits[2], bits[3]


def _check_skew(amz_date: str) -> None:
    if not amz_date:
        raise s3_error("MissingFields")
    try:
        t = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
    except ValueError:
        raise s3_error("AuthorizationHeaderMalformed") from None
    now = datetime.datetime.now(datetime.timezone.utc)
    if abs((now - t).total_seconds()) > MAX_CLOCK_SKEW_SEC:
        raise s3_error("RequestTimeTooSkewed")


def sign_request_v4(
    method: str,
    path: str,
    query: dict[str, list[str]],
    headers: dict[str, str],
    payload: bytes,
    access_key: str,
    secret_key: str,
    region: str = "us-east-1",
    service: str = "s3",
    now: datetime.datetime | None = None,
) -> dict[str, str]:
    """Client-side signer (test harness + replication sinks): returns
    the headers to add (Authorization, x-amz-date, x-amz-content-sha256)."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = _sha256_hex(payload)
    all_headers = dict(headers)
    all_headers["x-amz-date"] = amz_date
    all_headers["x-amz-content-sha256"] = payload_hash
    signed = sorted(
        k.lower()
        for k in all_headers
        if k.lower() in ("host", "content-type") or k.lower().startswith("x-amz-")
    )
    canon = canonical_request(
        method, path, query, _LowerHeaders(all_headers), signed, payload_hash
    )
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = string_to_sign(amz_date, scope, canon)
    key = derive_signing_key(secret_key, date, region, service)
    signature = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"{SIGN_V4_ALGORITHM} Credential={access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={signature}"
        ),
    }
