"""Batched RS(k,p) over a device mesh via shard_map.

Per-device work on TPU meshes is the SWAR Horner Pallas kernel on
u32 lanes (the same ~100 GB/s/chip fast path the single-chip tier
runs — encode_batch_u32 / reconstruct_batch_u32); CPU meshes and the
byte-layout APIs use the portable bitsliced XOR-matmul kernel
(codec_tpu.apply_matrix_bits — lowers everywhere; on a real TPU slice
XLA maps the int8 dot onto the MXU per chip). Both are byte-identical.
Shardings:

  volumes  [B, k, N]  P("vol", None, "stripe")
  parity   [B, p, N]  P("vol", None, "stripe")
  residual [B]        P("vol")  (after psum over "stripe")

Batched-encode role: the spread/encode fan-out of the reference's
shell command_ec_encode.go:153 + ec_encoder.go:173, lifted from
goroutine-per-volume to one SPMD program. Degraded-read fan-in role:
store_ec.go:344-373 (goroutine-per-shard gather + ReconstructData),
lifted to "reconstruct in one pmap" (SURVEY §2.6.5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map
except ImportError:  # pre-0.4.4x jax: experimental home + old kwarg name
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, **kwargs):
        # the modern API spells the replication-check flag check_vma;
        # the experimental one calls it check_rep — translate so call
        # sites can stay on the current spelling
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _exp_shard_map(f, **kwargs)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seaweedfs_tpu.ec.codec_tpu import (
    TpuCodecKernels,
    apply_matrix_bits_batch,
    apply_matrix_bits_u32_batch,
    gf_matrix_to_bits,
    swar_apply_matrix_u32_batch,
    swar_verify_matrix_u32_batch,
)

VOL_AXIS = "vol"
STRIPE_AXIS = "stripe"


def make_mesh(
    devices: list | None = None, stripe: int | None = None
) -> Mesh:
    """Build a (vol × stripe) mesh over `devices` (default: all).

    stripe=None picks 2 when the device count is even, else 1 — volume
    parallelism first (independent work), stripe parallelism to split
    streams too long for one device's HBM."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if stripe is None:
        stripe = 2 if n % 2 == 0 else 1
    if n % stripe:
        raise ValueError(f"{n} devices do not split into stripe={stripe}")
    return Mesh(
        np.array(devices).reshape(n // stripe, stripe), (VOL_AXIS, STRIPE_AXIS)
    )


class MeshCodec:
    """RS(k,p) batched encode / rebuild / verify over a Mesh."""

    def __init__(self, mesh: Mesh, data_shards: int = 10, parity_shards: int = 4):
        self.mesh = mesh
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        # single-chip kernels own the code matrix and the decode-row
        # bit-matrix construction; MeshCodec lifts them over the mesh
        # and keeps its own device-array cache (jnp.asarray per call
        # would re-upload the bit-matrix host->device every rebuild)
        self._kern = TpuCodecKernels(data_shards, parity_shards)
        self.matrix = self._kern.matrix
        self._parity_bits = self._kern.encode_bits
        self._decode_bits_dev: dict[tuple[int, ...], jnp.ndarray] = {}
        self.block_sharding = NamedSharding(mesh, P(VOL_AXIS, None, STRIPE_AXIS))
        self.vol_sharding = NamedSharding(mesh, P(VOL_AXIS))
        # fast path per device: the SWAR Horner Pallas kernel lowers
        # only via Mosaic-TPU, so it serves TPU meshes; CPU meshes
        # (tests, the driver's virtual-device dryrun) fall back to the
        # byte-identical bit-matmul. _swar_interpret=True forces the
        # SWAR kernel through the Pallas interpreter on CPU meshes —
        # minutes-slow at real sizes, for equality tests only.
        self._tpu_mesh = all(
            getattr(d, "platform", "cpu") == "tpu"
            for d in np.asarray(mesh.devices).flat
        )
        self._swar_interpret = False
        self._sharded_u32_cache: dict[bytes, object] = {}

    # --- sharding helpers ---
    def shard_volumes(self, host_volumes: np.ndarray) -> jnp.ndarray:
        """[B, C, N] host → device array sharded P(vol, None, stripe).
        B must divide by the vol axis, N by the stripe axis."""
        return jax.device_put(host_volumes, self.block_sharding)

    # --- batched encode ---
    @functools.cached_property
    def _encode_sharded(self):
        def per_device(bits, vols):  # vols [Bb, k, Nb]
            return apply_matrix_bits_batch(bits, vols)

        fn = shard_map(
            per_device,
            mesh=self.mesh,
            in_specs=(P(), P(VOL_AXIS, None, STRIPE_AXIS)),
            out_specs=P(VOL_AXIS, None, STRIPE_AXIS),
        )
        return jax.jit(fn)

    def _swar_ok(self, n_bytes: int) -> bool:
        """True when the byte-layout APIs route through the SWAR u32
        kernel — interpret mode only (byte-identity tests). On REAL TPU
        meshes the byte APIs keep the bit-matmul tier: materializing a
        device-side u8↔u32 view around a pallas call costs a relayout
        copy whose (8,128)-tiled padding measured 12.8× the array size
        on v5e (a 2.5 GB block tried to allocate 34 GB) — byte views
        are free on the HOST (np.view), so production TPU callers use
        the *_u32 APIs end-to-end (ec_files.py serving batch path,
        verify_batch_u32) and the byte layout stays a host-edge/test
        convenience."""
        stripe = self.mesh.shape[STRIPE_AXIS]
        if n_bytes % stripe:
            return False
        per_dev = n_bytes // stripe
        return (
            self._swar_interpret
            and not self._tpu_mesh  # never device-side byte views on TPU
            and per_dev % 4 == 0
            and (per_dev // 4) % 256 == 0
        )

    def _swar_bytes_per_device(self, rows: np.ndarray):
        """One device's byte-tile apply: u8 [Bb, C, Nb] → u8 [Bb, R, Nb]
        through the SWAR u32 kernel, bitcast views at the edges. The
        single home of the byte↔u32 packing contract — encode,
        reconstruct, and verify all ride this."""
        interpret = not self._tpu_mesh

        def per_device(vols_u8):  # [Bb, C, Nb]
            b, c, nb = vols_u8.shape
            u32 = jax.lax.bitcast_convert_type(
                vols_u8.reshape(b, c, nb // 4, 4), jnp.uint32
            )
            out32 = swar_apply_matrix_u32_batch(rows, u32, interpret)
            out8 = jax.lax.bitcast_convert_type(out32, jnp.uint8)
            return out8.reshape(b, out32.shape[1], nb)

        return per_device

    def _apply_sharded_bytes(self, rows: np.ndarray):
        """Sharded byte-layout [B, C, N] u8 → [B, R, N] u8 program that
        runs the SWAR u32 kernel per device with bitcast views at the
        edges — interpret-mode only (byte-identity tests; see _swar_ok
        for why real TPU meshes keep the bit-matmul on byte layouts and
        do their fast-tier work through the *_u32 APIs)."""
        rows = np.asarray(rows, dtype=np.uint8)
        key = b"u8" + rows.tobytes() + bytes(rows.shape)
        fn = self._sharded_u32_cache.get(key)
        if fn is not None:
            return fn
        per_device = self._swar_bytes_per_device(rows)
        fn = jax.jit(
            shard_map(
                per_device,
                mesh=self.mesh,
                in_specs=P(VOL_AXIS, None, STRIPE_AXIS),
                out_specs=P(VOL_AXIS, None, STRIPE_AXIS),
                check_vma=False,
            )
        )
        self._sharded_u32_cache[key] = fn
        return fn

    def encode_batch(self, volumes: jnp.ndarray) -> jnp.ndarray:
        """volumes [B, k, N] (sharded) → parity [B, p, N] (sharded).

        Positionwise GF math: no collectives; each device encodes its
        (volume-block × stripe-block) tile independently. Production
        TPU callers use encode_batch_u32 (u32 lanes are the native
        device layout — _swar_ok); this byte-layout API runs the
        bit-matmul tier on device meshes, SWAR under interpret mode."""
        if self._swar_ok(volumes.shape[-1]):
            return self._apply_sharded_bytes(self.matrix[self.data_shards :])(
                volumes
            )
        return self._encode_sharded(self._parity_bits, volumes)

    # --- u32-lane fast path (SWAR per device on TPU meshes) ---
    def _swar_tier(self) -> tuple[bool, bool]:
        """(use_swar, interpret): the ONE u32 tier-dispatch predicate —
        SWAR Pallas kernels on TPU meshes (interpreted under the test
        flag), bit-matmul otherwise. _per_device_u32_apply (encode /
        reconstruct) and _verify_sharded_u32 (the fused verify kernel)
        both dispatch through this."""
        return (self._tpu_mesh or self._swar_interpret, not self._tpu_mesh)

    def _per_device_u32_apply(self, rows: np.ndarray):
        """u32 apply for encode/reconstruct on the _swar_tier dispatch.
        Verify does NOT build on this on the SWAR tier — it uses the
        fused recompute-compare-count kernel (_verify_sharded_u32)
        instead of recompute-then-compare."""
        rows = np.asarray(rows, dtype=np.uint8)
        use_swar, interpret = self._swar_tier()
        if use_swar:

            def per_device(vols_u32):
                return swar_apply_matrix_u32_batch(rows, vols_u32, interpret)

        else:
            bits = gf_matrix_to_bits(rows)

            def per_device(vols_u32):
                return apply_matrix_bits_u32_batch(jnp.asarray(bits), vols_u32)

        return per_device

    def _apply_sharded_u32(self, rows: np.ndarray):
        """Sharded [B, k, N32] u32 → [B, R, N32] u32 program for one
        GF coefficient matrix, cached per matrix. Per-device kernel is
        the SWAR Pallas kernel on TPU meshes (the ~4× fast path the
        single-chip tier runs), the bit-matmul elsewhere."""
        rows = np.asarray(rows, dtype=np.uint8)
        key = rows.tobytes() + bytes(rows.shape)
        fn = self._sharded_u32_cache.get(key)
        if fn is not None:
            return fn
        per_device = self._per_device_u32_apply(rows)
        fn = jax.jit(
            shard_map(
                per_device,
                mesh=self.mesh,
                in_specs=P(VOL_AXIS, None, STRIPE_AXIS),
                out_specs=P(VOL_AXIS, None, STRIPE_AXIS),
                # pallas_call's out_shape carries no varying-mesh-axes
                # annotation; the program is collective-free (positionwise
                # GF math), so the vma check adds nothing here
                check_vma=False,
            )
        )
        self._sharded_u32_cache[key] = fn
        return fn

    def encode_batch_u32(self, volumes_u32: jnp.ndarray) -> jnp.ndarray:
        """volumes [B, k, N32] uint32 (the byte stream viewed 4 bytes
        per lane, sharded P(vol, None, stripe)) → parity [B, p, N32]
        uint32 (same packing, sharded). Per-device N32 must divide the
        stripe axis and stay a multiple of 256 lanes."""
        return self._apply_sharded_u32(self.matrix[self.data_shards :])(volumes_u32)

    # --- fused encode + CRC (the streaming pipeline's batch stage) ---
    def crc_supported(self, n_bytes: int) -> bool:
        """True when the fused Castagnoli pass serves streams of
        n_bytes: whole u32 lanes per device, power-of-two lane count
        (ec/crc_kernel.py's halving reduction)."""
        from seaweedfs_tpu.ec import crc_kernel

        stripe = self.mesh.shape[STRIPE_AXIS]
        if n_bytes % stripe:
            return False
        return crc_kernel.crc_supported(n_bytes // stripe)

    def batch_layout(self, batch: int, n_bytes: int) -> dict:
        """Per-device work split for a [batch, k, n_bytes] encode —
        the numbers the MULTICHIP dryrun asserts: volumes per device
        along 'vol', stream bytes per device along 'stripe'."""
        vol = self.mesh.shape[VOL_AXIS]
        stripe = self.mesh.shape[STRIPE_AXIS]
        if batch % vol:
            raise ValueError(f"batch {batch} does not shard {vol}-way")
        if n_bytes % stripe:
            raise ValueError(f"stream {n_bytes} does not stripe {stripe}-way")
        return {
            "vol": vol,
            "stripe": stripe,
            "devices": vol * stripe,
            "per_device_volumes": batch // vol,
            "per_device_bytes": n_bytes // stripe,
        }

    @functools.cached_property
    def _encode_crc_sharded(self):
        """Sharded fused encode+CRC program: parity per device plus the
        standard CRC-32C of every shard ROW of the full global stream.
        Per device: encode its tile, run the crc_kernel bit-matmul
        accumulation over the tile while it is VMEM/HBM-resident, then
        COMPOSE the per-device raw CRCs across the stripe axis (an
        all_gather + Z-shift fold — CRCs of stream segments combine
        linearly, util/crc) so the host receives whole-row CRCs and
        never re-touches the bytes. Data rows are checksummed too —
        they are already device-resident."""
        from seaweedfs_tpu.ec import crc_kernel

        rows = np.asarray(self.matrix[self.data_shards :], dtype=np.uint8)
        per_device_apply = self._per_device_u32_apply(rows)
        stripe = self.mesh.shape[STRIPE_AXIS]

        def per_device(vols_u32):  # [Bb, k, Nb]
            parity = per_device_apply(vols_u32)
            full = jnp.concatenate([vols_u32, parity], axis=1)
            lin = crc_kernel.crc_lin_rows(full)  # [Bb, k+p] raw CRCs
            seg_bytes = full.shape[-1] * 4
            if stripe > 1:
                segs = jax.lax.all_gather(lin, STRIPE_AXIS)  # [S, Bb, R]
                zbits = jnp.asarray(crc_kernel._shift_bitmat(seg_bytes))
                acc = segs[0]
                for s in range(1, stripe):
                    acc = crc_kernel._apply_bits(acc, zbits) ^ segs[s]
                lin = acc
            crcs = crc_kernel.finalize_rows(lin, seg_bytes * stripe)
            return parity, crcs

        return jax.jit(
            shard_map(
                per_device,
                mesh=self.mesh,
                in_specs=P(VOL_AXIS, None, STRIPE_AXIS),
                out_specs=(
                    P(VOL_AXIS, None, STRIPE_AXIS),
                    # the stripe fold replicates the CRCs along the
                    # stripe axis; one copy per vol block comes home
                    P(VOL_AXIS, None),
                ),
                check_vma=False,
            )
        )

    def encode_batch_u32_crc(
        self, volumes_u32: jnp.ndarray
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Fused batch encode + Castagnoli pass: [B, k, N32] uint32 →
        (parity [B, p, N32] sharded, crcs [B, k+p] uint32 — standard
        CRC-32C of every shard row's full N32*4-byte stream,
        bit-identical to util/crc.crc32c). Requires
        crc_supported(N32 * 4)."""
        if not self.crc_supported(volumes_u32.shape[-1] * 4):
            raise ValueError(
                f"stream of {volumes_u32.shape[-1]} lanes unsupported by "
                f"the fused CRC pass (per-device lanes must be a power "
                f"of two)"
            )
        return self._encode_crc_sharded(volumes_u32)

    def reconstruct_batch_u32(
        self,
        survivors: tuple[int, ...],
        targets: tuple[int, ...],
        shard_data_u32: jnp.ndarray,
    ) -> jnp.ndarray:
        """u32-lane variant of reconstruct_batch: survivor blocks
        [B, k, N32] uint32 (in `survivors` order) → rebuilt targets
        [B, len(targets), N32] uint32."""
        return self._apply_sharded_u32(
            self._kern.decode_rows_for(survivors, targets)
        )(shard_data_u32)

    # --- batched degraded rebuild ---
    def _decode_bits(
        self, survivors: tuple[int, ...], targets: tuple[int, ...]
    ) -> jnp.ndarray:
        key = survivors + (256,) + targets
        bits = self._decode_bits_dev.get(key)
        if bits is None:
            bits = jnp.asarray(self._kern.decode_bits_for(survivors, targets))
            self._decode_bits_dev[key] = bits
        return bits

    def reconstruct_batch(
        self,
        survivors: tuple[int, ...],
        targets: tuple[int, ...],
        shard_data: jnp.ndarray,
    ) -> jnp.ndarray:
        """shard_data [B, k, N] survivor blocks (in `survivors` order,
        sharded) → [B, len(targets), N] rebuilt blocks (sharded).

        The gather of surviving shards into `shard_data` rides DCN
        (gRPC shard reads); the decode is one SPMD program — the
        store_ec.go:364 ReconstructData hot path, batched."""
        if self._swar_ok(shard_data.shape[-1]):
            return self._apply_sharded_bytes(
                self._kern.decode_rows_for(survivors, targets)
            )(shard_data)
        return self._encode_sharded(self._decode_bits(survivors, targets), shard_data)

    # --- verify with a stripe-axis collective ---
    @functools.cached_property
    def _verify_sharded(self):
        def per_device(bits, vols, parity):
            # [Bb, p, Nb] recomputed on this device's tile; residual =
            # COUNT of mismatched bytes (a byte-value sum would overflow
            # int32 on the multi-MiB blocks the SWAR tier serves)
            recomputed = apply_matrix_bits_batch(bits, vols)
            local = jnp.sum(
                (recomputed != parity).astype(jnp.int32), axis=(1, 2)
            )  # [Bb]
            return jax.lax.psum(local, STRIPE_AXIS)

        fn = shard_map(
            per_device,
            mesh=self.mesh,
            in_specs=(
                P(),
                P(VOL_AXIS, None, STRIPE_AXIS),
                P(VOL_AXIS, None, STRIPE_AXIS),
            ),
            out_specs=P(VOL_AXIS),
        )
        return jax.jit(fn)

    @functools.cached_property
    def _verify_sharded_swar(self):
        recompute = self._swar_bytes_per_device(
            np.asarray(self.matrix[self.data_shards :], dtype=np.uint8)
        )

        def per_device(vols_u8, parity):
            recomputed = recompute(vols_u8)
            local = jnp.sum(
                (recomputed != parity).astype(jnp.int32), axis=(1, 2)
            )  # [Bb] — mismatched-byte count, identical to the matmul tier
            return jax.lax.psum(local, STRIPE_AXIS)

        return jax.jit(
            shard_map(
                per_device,
                mesh=self.mesh,
                in_specs=(
                    P(VOL_AXIS, None, STRIPE_AXIS),
                    P(VOL_AXIS, None, STRIPE_AXIS),
                ),
                out_specs=P(VOL_AXIS),
                check_vma=False,
            )
        )

    @functools.cached_property
    def _verify_sharded_u32(self):
        """Tier dispatch mirrors _per_device_u32_apply: on TPU meshes
        (and under the interpret test flag) the FUSED SWAR verify
        kernel — recompute, compare, and count in one pallas call, no
        HBM round-trip for the recomputed parity, which is what held
        the unfused chain to a third of the encode rate — and the
        bit-matmul recompute + XLA compare on CPU meshes."""
        rows = np.asarray(self.matrix[self.data_shards :], dtype=np.uint8)
        use_swar, interpret = self._swar_tier()
        if use_swar:

            def per_device(vols_u32, parity_u32):
                local = swar_verify_matrix_u32_batch(
                    rows, vols_u32, parity_u32, interpret
                )  # [Bb] — mismatched-LANE count (u32 lanes; 0 = verified)
                return jax.lax.psum(local, STRIPE_AXIS)

        else:
            recompute = self._per_device_u32_apply(rows)

            def per_device(vols_u32, parity_u32):
                local = jnp.sum(
                    (recompute(vols_u32) != parity_u32).astype(jnp.int32),
                    axis=(1, 2),
                )  # [Bb]
                return jax.lax.psum(local, STRIPE_AXIS)

        return jax.jit(
            shard_map(
                per_device,
                mesh=self.mesh,
                in_specs=(
                    P(VOL_AXIS, None, STRIPE_AXIS),
                    P(VOL_AXIS, None, STRIPE_AXIS),
                ),
                out_specs=P(VOL_AXIS),
                check_vma=False,
            )
        )

    def verify_batch_u32(
        self, volumes_u32: jnp.ndarray, parity_u32: jnp.ndarray
    ) -> jnp.ndarray:
        """u32-lane verify at the SWAR encode rate (measured: 93 GB/s
        vs 89-104 encode on one v5e chip, BENCH_r05 / docs/EC_KERNEL.md
        round-5 section): the fused pallas kernel recomputes each
        parity tile in VMEM, compares in register, and accumulates the
        mismatched-lane count; the psum over the stripe axis reduces
        the per-device counts. [B] int32, 0 = verified. This is the
        TPU production tier — the u32 packing is the native device
        layout (see _swar_ok). Shape contract matches encode_batch_u32:
        per-device N32 must divide the stripe axis and stay a multiple
        of 256 lanes."""
        return self._verify_sharded_u32(volumes_u32, parity_u32)

    def verify_batch(
        self, volumes: jnp.ndarray, parity: jnp.ndarray
    ) -> jnp.ndarray:
        """Per-volume mismatched-byte count between recomputed and
        given parity: [B] int32, 0 = verified. The stripe-axis psum is
        the mesh collective of the degraded-read fan-in story (§2.6.5).
        The SWAR-rate tier is verify_batch_u32; this byte-layout API
        recomputes via the bit-matmul on device meshes (_swar_ok)."""
        if self._swar_ok(volumes.shape[-1]):
            return self._verify_sharded_swar(volumes, parity)
        return self._verify_sharded(self._parity_bits, volumes, parity)
