"""Mesh-parallel EC codec paths (the ICI tier of SURVEY §2.7).

The reference scales EC work with goroutines × TCP (store_ec.go:344,
command_ec_encode.go:202). The TPU-native equivalent keeps gRPC/DCN
for control and blob traffic between hosts, and runs the bulk GF math
as SPMD programs over a `jax.sharding.Mesh`:

  axis "vol"    — volume parallelism (DP analogue): independent sealed
                  volumes spread across devices (BASELINE's batched
                  256-volume encode config).
  axis "stripe" — byte-stream parallelism (SP analogue): EC is
                  positionwise, so the N dimension shards freely; a
                  30 GB volume becomes per-device stripe blocks
                  (SURVEY §5 long-context analogue).

Collectives: encode/rebuild need none (positionwise math — the whole
point of laying the stream out along the mesh); verify reduces a
per-volume residual with a `psum` over the stripe axis, the degraded-
read fan-in of SURVEY §2.6.5 ("reconstruct in one pmap").
"""

from seaweedfs_tpu.parallel.mesh_codec import (  # noqa: F401
    MeshCodec,
    make_mesh,
)
