"""HS256 JSON Web Tokens carrying a file-id claim, stdlib-only.

Behavioral match of the reference's weed/security/jwt.go: tokens sign
the claim set {"fid": <file id>} with optional "exp"/"nbf" Unix-seconds
claims (jwt.go:20-41); empty signing key means security is off and
gen_jwt returns "" (jwt.go:22-24). Verification rejects non-HMAC algs
(jwt.go:60-65), bad signatures, and expired / not-yet-valid tokens.
The token travels as `?jwt=` query param or `Authorization: BEARER`
header (jwt.go:43-57).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


class JwtError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def gen_jwt(signing_key: bytes | str, expires_after_sec: int, file_id: str) -> str:
    """Sign {"fid": file_id} with HS256; "" when no key is configured."""
    if not signing_key:
        return ""
    if isinstance(signing_key, str):
        signing_key = signing_key.encode()
    claims: dict = {"fid": file_id}
    if expires_after_sec > 0:
        claims["exp"] = int(time.time()) + expires_after_sec
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(signing_key, signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def decode_jwt(signing_key: bytes | str, token: str) -> dict:
    """Verify signature + exp/nbf; returns the claims dict or raises JwtError."""
    if isinstance(signing_key, str):
        signing_key = signing_key.encode()
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    header_b64, payload_b64, sig_b64 = parts
    try:
        header = json.loads(_b64url_decode(header_b64))
        claims = json.loads(_b64url_decode(payload_b64))
        sig = _b64url_decode(sig_b64)
    except (ValueError, json.JSONDecodeError) as e:
        raise JwtError(f"undecodable token: {e}") from e
    if header.get("alg") != "HS256":
        raise JwtError("unknown token method")
    expect = hmac.new(
        signing_key, f"{header_b64}.{payload_b64}".encode(), hashlib.sha256
    ).digest()
    if not hmac.compare_digest(sig, expect):
        raise JwtError("bad signature")
    now = time.time()
    if "exp" in claims and now > float(claims["exp"]):
        raise JwtError("token expired")
    if "nbf" in claims and now < float(claims["nbf"]):
        raise JwtError("token not yet valid")
    return claims


def jwt_from_headers(query: dict, headers) -> str:
    """Extract the token the way the reference's GetJwt does: `?jwt=`
    first, then `Authorization: BEARER <t>` (jwt.go:43-57)."""
    vals = query.get("jwt")
    if vals:
        return vals[0] if isinstance(vals, list) else vals
    bearer = headers.get("Authorization", "") if headers is not None else ""
    if len(bearer) > 7 and bearer[:6].upper() == "BEARER":
        return bearer[7:]
    return ""
