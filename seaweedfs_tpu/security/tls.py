"""gRPC TLS/mTLS for cluster services.

Behavioral match of reference weed/security/tls.go: per-service
certificate config from security.toml —

    [grpc]
    ca = "/etc/ssl/ca.crt"

    [grpc.volume]   # also grpc.master / grpc.filer / grpc.client
    cert = "..."
    key  = "..."

A configured CA makes servers require client certificates (mTLS, the
reference's tls.RequireAndVerifyClientCert) and makes clients verify
servers against it. The process-wide dial/serve helpers in pb/rpc.py
consult this module so every channel and listening port honors one
config."""

from __future__ import annotations

from dataclasses import dataclass

import grpc


@dataclass
class TlsConfig:
    ca_pem: bytes | None = None
    cert_pem: bytes | None = None
    key_pem: bytes | None = None

    @property
    def is_enabled(self) -> bool:
        return bool(self.cert_pem and self.key_pem)


def _read(path: str) -> bytes | None:
    if not path:
        return None
    with open(path, "rb") as f:
        return f.read()


def load_tls_config(cfg, component: str) -> TlsConfig | None:
    """security.toml [grpc] + [grpc.<component>] → TlsConfig
    (LoadServerTLS/LoadClientTLS, tls.go)."""
    cert = cfg.get_string(f"grpc.{component}.cert") or cfg.get_string("grpc.cert")
    key = cfg.get_string(f"grpc.{component}.key") or cfg.get_string("grpc.key")
    ca = cfg.get_string("grpc.ca")
    if not cert and not key and not ca:
        return None
    if not cert or not key:
        # a partial config silently downgrading to plaintext would be a
        # security misconfiguration; refuse to start instead
        raise ValueError(
            f"incomplete gRPC TLS config for {component!r}: both cert and "
            f"key are required (got cert={bool(cert)}, key={bool(key)}, "
            f"ca={bool(ca)})"
        )
    return TlsConfig(
        ca_pem=_read(ca), cert_pem=_read(cert), key_pem=_read(key)
    )


def server_credentials(tls: TlsConfig) -> grpc.ServerCredentials:
    return grpc.ssl_server_credentials(
        [(tls.key_pem, tls.cert_pem)],
        root_certificates=tls.ca_pem,
        require_client_auth=tls.ca_pem is not None,
    )


def client_credentials(tls: TlsConfig) -> grpc.ChannelCredentials:
    return grpc.ssl_channel_credentials(
        root_certificates=tls.ca_pem,
        private_key=tls.key_pem,
        certificate_chain=tls.cert_pem,
    )
