from seaweedfs_tpu.security.jwt import (
    decode_jwt,
    gen_jwt,
    jwt_from_headers,
    JwtError,
)
from seaweedfs_tpu.security.guard import Guard, UnauthorizedError

__all__ = [
    "Guard",
    "UnauthorizedError",
    "JwtError",
    "decode_jwt",
    "gen_jwt",
    "jwt_from_headers",
]
