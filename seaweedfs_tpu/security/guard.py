"""Access guard: IP white list first, then JWT.

Behavioral match of weed/security/guard.go: a Guard holds a white list
(IPs or CIDRs), a write signing key and a read signing key; security is
inactive (everything passes) when neither white list nor key is set
(guard.go:62, 70-72). The white list is checked before the JWT because
it is cheap (guard.go:28). CIDR entries match by network containment;
"*" matches anything (reference uses exact-IP match only; CIDR is a
strict superset kept for operator convenience).
"""

from __future__ import annotations

import ipaddress

from seaweedfs_tpu.security import jwt as jwt_mod


class UnauthorizedError(Exception):
    pass


class Guard:
    def __init__(
        self,
        white_list: list[str] | None = None,
        signing_key: str = "",
        expires_after_sec: int = 10,
        read_signing_key: str = "",
        read_expires_after_sec: int = 60,
    ):
        self.white_list = list(white_list or [])
        self.signing_key = signing_key
        self.expires_after_sec = expires_after_sec
        self.read_signing_key = read_signing_key
        self.read_expires_after_sec = read_expires_after_sec
        self._networks = []
        for entry in self.white_list:
            if entry == "*":
                self._networks.append(None)
                continue
            try:
                self._networks.append(ipaddress.ip_network(entry, strict=False))
            except ValueError:
                self._networks.append(entry)  # hostname literal, exact match

    @property
    def is_write_active(self) -> bool:
        return bool(self.white_list) or bool(self.signing_key)

    @property
    def is_read_active(self) -> bool:
        return bool(self.read_signing_key)

    def white_list_ok(self, remote_ip: str) -> bool:
        if not self.white_list:
            return False
        try:
            addr = ipaddress.ip_address(remote_ip)
        except ValueError:
            return remote_ip in self.white_list
        for net in self._networks:
            if net is None:
                return True
            if isinstance(net, str):
                if net == remote_ip:
                    return True
            elif addr in net:
                return True
        return False

    def sign_write(self, file_id: str) -> str:
        return jwt_mod.gen_jwt(self.signing_key, self.expires_after_sec, file_id)

    def sign_read(self, file_id: str) -> str:
        return jwt_mod.gen_jwt(
            self.read_signing_key, self.read_expires_after_sec, file_id
        )

    def check_write(self, remote_ip: str, token: str, file_id: str = "") -> None:
        """Raise UnauthorizedError unless the request may write.
        White list passes outright; otherwise the JWT must verify and,
        when it carries a fid claim, match the target file id."""
        self._check(remote_ip, token, file_id, self.signing_key, self.is_write_active)

    def check_read(self, remote_ip: str, token: str, file_id: str = "") -> None:
        self._check(
            remote_ip, token, file_id, self.read_signing_key, self.is_read_active
        )

    def _check(
        self, remote_ip: str, token: str, file_id: str, key: str, active: bool
    ) -> None:
        if not active:
            return
        if self.white_list_ok(remote_ip):
            return
        if not key:
            raise UnauthorizedError(f"ip {remote_ip} not in white list")
        if not token:
            raise UnauthorizedError("no jwt token")
        try:
            claims = jwt_mod.decode_jwt(key, token)
        except jwt_mod.JwtError as e:
            raise UnauthorizedError(str(e)) from e
        claimed = claims.get("fid", "")
        if file_id and claimed and claimed != file_id:
            raise UnauthorizedError(f"jwt is for {claimed}, not {file_id}")
