/* SIMD GF(2^8) coefficient-matrix apply: the RS codec's CPU hot path.
 *
 * Role match: the reference's EC hot loop is klauspost/reedsolomon's
 * vendored AVX2 assembly (the enc.Encode call at
 * weed/storage/erasure_coding/ec_encoder.go:173). This is the same
 * component as a small C library: out[r] = XOR_c gfmul(M[r][c], in[c])
 * over the 0x11D field (generator 2, matching ec/gf256.py).
 *
 * Four paths, chosen once at load time:
 *   - GFNI+AVX512: GF2P8AFFINEQB, 64 bytes/instruction. Multiplication
 *     by a constant c is GF(2)-linear — an 8x8 bit-matrix (the same
 *     B(c) the TPU bitsliced kernel uses, codec_tpu.py) — and the
 *     affine instruction applies an arbitrary bit-matrix per byte, so
 *     it handles our 0x11D field even though the ISA's fixed-poly
 *     GF2P8MULB (0x11B) would not. Matrix packing is verified against
 *     gf_mul at load; on mismatch the path disables itself.
 *   - AVX2:  PSHUFB low/high-nibble product tables, 32 bytes/step
 *   - SSSE3: same scheme at 16 bytes/step
 *   - portable: per-coefficient 256-entry product table, 1 byte/step
 *
 * The nibble-table trick: gfmul(c, x) for a byte x = lo^hi where
 * lo = gfmul(c, x & 0xF) and hi = gfmul(c, x & 0xF0); each half has
 * only 16 possible values, so both fit in one 16-lane shuffle register
 * and one PSHUFB computes 16 (AVX2: 32) products at once.
 *
 * Work is blocked over the stream so the k input rows and r output
 * rows of one block stay L2-resident across the r*k coefficient passes.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#define HAVE_X86 1
#endif

static uint8_t gf_exp[512];
static uint8_t gf_log[256];
static int have_avx2 = 0;
static int have_ssse3 = 0;
static int have_gfni512 = 0;

#ifdef HAVE_X86
static int gfni_selftest(void);
#endif

/* constructor: runs once at dlopen, before any caller thread exists */
__attribute__((constructor)) static void gf_init(void) {
    int x = 1;
    for (int i = 0; i < 255; i++) {
        gf_exp[i] = (uint8_t)x;
        gf_log[x] = (uint8_t)i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; i++) gf_exp[i] = gf_exp[i - 255];
#ifdef HAVE_X86
    {
        unsigned int a, b, c, d;
        int f512 = 0, bw = 0, gfni = 0, osxsave = 0;
        uint64_t xcr0 = 0;
        if (__get_cpuid(1, &a, &b, &c, &d)) {
            have_ssse3 = (c >> 9) & 1;
            osxsave = (c >> 27) & 1;
        }
        if (__get_cpuid_count(7, 0, &a, &b, &c, &d)) {
            have_avx2 = (b >> 5) & 1;
            f512 = (b >> 16) & 1;
            bw = (b >> 30) & 1;
            gfni = (c >> 8) & 1;
        }
        /* CPUID feature bits alone don't mean the OS saves the wide
         * registers: confirm via XCR0 (xgetbv) that YMM (bits 1-2) and,
         * for the 512-bit path, opmask+ZMM (bits 5-7) state is enabled —
         * else an EVEX/VEX instruction in the constructor is a SIGILL
         * that no ImportError fallback can catch. */
        if (osxsave) {
            unsigned int lo, hi;
            __asm__ volatile("xgetbv" : "=a"(lo), "=d"(hi) : "c"(0));
            xcr0 = ((uint64_t)hi << 32) | lo;
        }
        if ((xcr0 & 0x6) != 0x6) have_avx2 = 0;
        have_gfni512 =
            f512 && bw && gfni && (xcr0 & 0xE6) == 0xE6;
        if (have_gfni512 && !gfni_selftest()) have_gfni512 = 0;
    }
#endif
}

static inline uint8_t gf_mul(uint8_t a, uint8_t b) {
    if (!a || !b) return 0;
    return gf_exp[(int)gf_log[a] + (int)gf_log[b]];
}

/* 16-entry product tables for one coefficient: lo[x]=c·x, hi[x]=c·(x<<4) */
static void nibble_tables(uint8_t c, uint8_t lo[16], uint8_t hi[16]) {
    for (int x = 0; x < 16; x++) {
        lo[x] = gf_mul(c, (uint8_t)x);
        hi[x] = gf_mul(c, (uint8_t)(x << 4));
    }
}

static void row_scalar(uint8_t *out, const uint8_t *in, size_t n, uint8_t c) {
    uint8_t tbl[256];
    for (int x = 0; x < 256; x++) tbl[x] = gf_mul(c, (uint8_t)x);
    for (size_t i = 0; i < n; i++) out[i] ^= tbl[in[i]];
}

#ifdef HAVE_X86
__attribute__((target("ssse3"))) static void row_ssse3(uint8_t *out,
                                                      const uint8_t *in,
                                                      size_t n,
                                                      const uint8_t lo[16],
                                                      const uint8_t hi[16]) {
    __m128i vlo = _mm_loadu_si128((const __m128i *)lo);
    __m128i vhi = _mm_loadu_si128((const __m128i *)hi);
    __m128i mask = _mm_set1_epi8(0x0F);
    size_t i = 0;
    for (; i + 16 <= n; i += 16) {
        __m128i v = _mm_loadu_si128((const __m128i *)(in + i));
        __m128i l = _mm_and_si128(v, mask);
        __m128i h = _mm_and_si128(_mm_srli_epi64(v, 4), mask);
        __m128i p = _mm_xor_si128(_mm_shuffle_epi8(vlo, l),
                                  _mm_shuffle_epi8(vhi, h));
        __m128i o = _mm_loadu_si128((const __m128i *)(out + i));
        _mm_storeu_si128((__m128i *)(out + i), _mm_xor_si128(o, p));
    }
    for (; i < n; i++) out[i] ^= lo[in[i] & 0xF] ^ hi[in[i] >> 4];
}

__attribute__((target("avx2"))) static void row_avx2(uint8_t *out,
                                                    const uint8_t *in,
                                                    size_t n,
                                                    const uint8_t lo[16],
                                                    const uint8_t hi[16]) {
    __m256i vlo =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)lo));
    __m256i vhi =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)hi));
    __m256i mask = _mm256_set1_epi8(0x0F);
    size_t i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i *)(in + i));
        __m256i l = _mm256_and_si256(v, mask);
        __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, l),
                                     _mm256_shuffle_epi8(vhi, h));
        __m256i o = _mm256_loadu_si256((const __m256i *)(out + i));
        _mm256_storeu_si256((__m256i *)(out + i), _mm256_xor_si256(o, p));
    }
    for (; i < n; i++) out[i] ^= lo[in[i] & 0xF] ^ hi[in[i] >> 4];
}
#endif

#ifdef HAVE_X86
/* Pack the multiply-by-c bit-matrix for GF2P8AFFINEQB: output bit i is
 * parity(matrix byte (7-i) & x), so qword byte (7-i) holds row i,
 * whose bit j is bit i of c·2^j. Verified against gf_mul at load. */
static uint64_t affine_matrix(uint8_t c) {
    uint64_t m = 0;
    for (int i = 0; i < 8; i++) {
        uint8_t row = 0;
        for (int j = 0; j < 8; j++)
            row |= (uint8_t)(((gf_mul(c, (uint8_t)(1 << j)) >> i) & 1) << j);
        m |= (uint64_t)row << (8 * (7 - i));
    }
    return m;
}

__attribute__((target("gfni,avx512f,avx512bw"))) static void row_gfni(
    uint8_t *out, const uint8_t *in, size_t n, uint64_t mat,
    const uint8_t lo[16], const uint8_t hi[16]) {
    __m512i A = _mm512_set1_epi64((long long)mat);
    size_t i = 0;
    for (; i + 64 <= n; i += 64) {
        __m512i v = _mm512_loadu_si512((const void *)(in + i));
        __m512i p = _mm512_gf2p8affine_epi64_epi8(v, A, 0);
        __m512i o = _mm512_loadu_si512((const void *)(out + i));
        _mm512_storeu_si512((void *)(out + i), _mm512_xor_si512(o, p));
    }
    for (; i < n; i++) out[i] ^= lo[in[i] & 0xF] ^ hi[in[i] >> 4];
}

__attribute__((target("gfni,avx512f,avx512bw"))) static int gfni_selftest(void) {
    uint8_t in[64], out[64], lo[16], hi[16];
    const uint8_t cs[3] = {0x02, 0x57, 0xE3};
    for (int t = 0; t < 3; t++) {
        for (int i = 0; i < 64; i++) {
            in[i] = (uint8_t)(i * 5 + t);
            out[i] = 0;
        }
        nibble_tables(cs[t], lo, hi);
        row_gfni(out, in, 64, affine_matrix(cs[t]), lo, hi);
        for (int i = 0; i < 64; i++)
            if (out[i] != gf_mul(cs[t], in[i])) return 0;
    }
    return 1;
}
#endif

static void row_mul_xor(uint8_t *out, const uint8_t *in, size_t n, uint8_t c) {
    uint8_t lo[16], hi[16];
#ifdef HAVE_X86
    if (have_gfni512 || have_avx2 || have_ssse3) {
        nibble_tables(c, lo, hi);
        if (have_gfni512)
            row_gfni(out, in, n, affine_matrix(c), lo, hi);
        else if (have_avx2)
            row_avx2(out, in, n, lo, hi);
        else
            row_ssse3(out, in, n, lo, hi);
        return;
    }
#endif
    (void)lo;
    (void)hi;
    row_scalar(out, in, n, c);
}

/* active SIMD tier, for diagnostics: 3=gfni512, 2=avx2, 1=ssse3, 0=scalar */
int32_t weed_gf_caps(void) {
    if (have_gfni512) return 3;
    if (have_avx2) return 2;
    if (have_ssse3) return 1;
    return 0;
}

/* out[r][i] = XOR_c gfmul(matrix[r*k+c], in[c][i]); outputs are
 * overwritten (zeroed first). Rows must not alias. */
void weed_gf_apply(const uint8_t *matrix, int32_t r, int32_t k,
                   const uint8_t *const *inputs, uint8_t *const *outputs,
                   size_t n) {
/* 256 KiB: inputs+outputs of one block span ~3.5 MiB — L2/L3-resident
 * on anything modern, long enough for the prefetcher to stream.
 * Swept 64K/256K/1M/8M on the dev Xeon: 256K best (steady-state). */
#ifndef WEED_GF_BLK
#define WEED_GF_BLK (256 * 1024)
#endif
    const size_t BLK = WEED_GF_BLK;
    for (int32_t ri = 0; ri < r; ri++) memset(outputs[ri], 0, n);
    for (size_t off = 0; off < n; off += BLK) {
        size_t len = n - off < BLK ? n - off : BLK;
        for (int32_t ri = 0; ri < r; ri++) {
            uint8_t *out = outputs[ri] + off;
            for (int32_t ci = 0; ci < k; ci++) {
                uint8_t c = matrix[(size_t)ri * (size_t)k + (size_t)ci];
                if (c) row_mul_xor(out, inputs[ci] + off, len, c);
            }
        }
    }
}
