/* serve.c — the event-driven serving core: one epoll loop that owns
 * accept/read/parse/respond for a listening socket.
 *
 * PR-5's stage traces put the serving residue in syscalls and loop
 * machinery (pwrite ~358 us of a ~500 us write; the Python mini-loop
 * and thread-per-connection dispatch are what's left around it), and
 * thread-per-connection cannot survive past a few thousand concurrent
 * connections.  This loop replaces that edge:
 *
 *   - non-blocking accept4 drain on every listen event (the kernel
 *     backlog is deep; the loop must never leave it full),
 *   - a per-connection read state machine: request heads are scanned
 *     out of one growing buffer, keep-alive and HTTP pipelining are
 *     native (the next pipelined head is parsed the moment the
 *     previous response drains — no extra epoll round trip),
 *   - a zero-copy GET fast path: the embedder's resolve() callback
 *     maps a request to (fd, offset, count) and the loop sendfile()s
 *     the bytes straight from the volume file to the socket, with
 *     short-write resumption on EAGAIN,
 *   - everything else HANDS THE CONNECTION OFF to the embedder
 *     (handoff() transfers the fd plus any unconsumed buffered bytes),
 *     so the one Python request parser keeps serving every slow path
 *     and the two paths cannot drift: this loop never formats an error
 *     response of its own.
 *
 * The loop knows no HTTP beyond what routing requires: request line,
 * the handful of headers that gate the fast path, and Connection
 * semantics.  Response bytes come from the embedder pre-formatted
 * except the Connection/Content-Length tail, which the loop appends
 * exactly like the Python fast_reply does — byte identity between the
 * C and Python serving paths is a construction, not a test hope.
 *
 * Pure C, no Python.h: serve_ext.c binds it the way needle_ext.c
 * binds post.c.  Callbacks are function pointers; the glue re-takes
 * the GIL inside them.
 */

#ifndef WEED_SERVE_C
#define WEED_SERVE_C

#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

/* matches util/httpd._BufReader.read_head's 431 limit: a head this
 * large is handed off so the Python loop applies its own cap */
#define WEED_SERVE_HEAD_LIMIT 131072
#define WEED_SERVE_RBUF_INIT 4096
#define WEED_SERVE_SENDFILE_CHUNK (1u << 20)
#define WEED_SERVE_EVENTS 256

typedef struct {
    const char *method; size_t method_len;
    const char *path;   size_t path_len;
    const char *range;  size_t range_len;   /* NULL when absent */
    const char *trace;  size_t trace_len;   /* x-weed-trace value    */
    const char *inm;    size_t inm_len;     /* if-none-match value   */
    int has_auth;                           /* Authorization present */
    int head_only;                          /* method == HEAD        */
} weed_req;

typedef struct {
    const uint8_t *prefix; size_t prefix_len; /* status line + headers,
                                                 WITHOUT Connection /
                                                 Content-Length tail  */
    const uint8_t *body;   size_t body_len;   /* in-memory body (fd<0) */
    int fd; int64_t off; size_t count;        /* sendfile body (fd>=0) */
    int close_fd;                             /* loop closes fd after  */
    int status;
    /* conditional-GET arm: the needle's (strong) entity-tag plus the
     * pre-rendered 304 prefix the Python arm would send for it; absent
     * (len 0) on plans that have no validator (404s, legacy plans) */
    const uint8_t *etag;      size_t etag_len;
    const uint8_t *prefix304; size_t prefix304_len;
    /* plan-cache admission: the resolver's generation snapshot, and
     * whether this plan may be cached at all (single-process servers
     * only — a sibling's writes can't bump this process's counter) */
    uint64_t gen;
    int cacheable;
} weed_resp;

typedef struct weed_serve_cbs {
    void *ctx;
    /* One parsed GET/HEAD request.  Return 1 = resp filled (serve it
     * here), 0 = decline (hand the connection off), -1 = abort the
     * connection.  `token` rides to the matching complete(). */
    int (*resolve)(void *ctx, const weed_req *req, weed_resp *resp,
                   void **token);
    /* Ownership of `fd` (plus `len` unconsumed buffered bytes starting
     * at the current request head) transfers to the embedder.
     * `nreqs` = responses this loop already served on the connection,
     * so the embedder's max-requests accounting continues instead of
     * restarting. */
    void (*handoff)(void *ctx, int fd, const uint8_t *pending, size_t len,
                    const char *ip, int port, long nreqs);
    /* The fast-path response finished (ok=1: fully written; ok=0: the
     * connection died first).  Always called exactly once per
     * successful resolve() — it releases `token`. */
    void (*complete)(void *ctx, void *token, int status, size_t resp_bytes,
                     double t_parse, double t_resolve, double t_send, int ok);
} weed_serve_cbs;

typedef struct weed_conn {
    int fd;
    char ip[48];
    int port;
    uint8_t *rbuf;
    size_t rcap, rlen, rpos;  /* rpos = start of the current head */
    size_t scan;              /* head-end scan resume point        */
    uint8_t *wbuf;
    size_t wcap, wlen, wpos;
    int body_fd;
    int64_t body_off;
    size_t body_left;
    int close_body_fd;
    void *token;
    int status;
    size_t resp_bytes;
    int writing;  /* a response is in flight (interest = EPOLLOUT) */
    int closing;  /* close once the in-flight response drains      */
    int eof;      /* peer sent FIN; drain buffered pipeline, then close
                     (the Python loop serves buffered requests after
                     EOF too — byte-identity includes shutdown order) */
    long nreqs;
    double t_parse, t_resolve, t_send0;
    int64_t last_ms;
    struct weed_conn *prev, *next;  /* idle LRU; most recent at tail */
} weed_conn;

/* ---- per-loop plan cache -------------------------------------------
 * Direct-mapped, keyed by request path (the fid): a hit serves a hot
 * GET without calling into Python at all.  Entries are stamped with
 * the process-wide generation counter the storage layer bumps on any
 * volume mutation (write/delete/vacuum-swap/remount); a stale stamp
 * evicts on the next lookup, so the whole cache invalidates in O(1).
 * Sendfile entries own ONE dup of the volume fd; each response dups it
 * again so an eviction can never yank the fd from an in-flight
 * sendfile. */
#define WEED_SERVE_CACHE_SLOTS 512
#define WEED_SERVE_CACHE_KEYMAX 64
#define WEED_SERVE_CACHE_BODYMAX 16384

typedef struct {
    size_t key_len;            /* 0 = empty slot */
    char key[WEED_SERVE_CACHE_KEYMAX];
    uint64_t gen;
    int status;
    int fd;                    /* cache-owned dup for sendfile, or -1 */
    int64_t off; size_t count;
    uint8_t *buf;              /* prefix | body | etag | prefix304    */
    size_t prefix_len, body_len, etag_len, p304_len;
} weed_cache_slot;

typedef struct weed_loop {
    int epfd, listen_fd, wake_fd;
    long idle_ms, max_reqs;
    weed_serve_cbs *cbs;
    weed_conn lru;  /* sentinel */
    int stop;
    int use_adm;    /* shed via the shared-memory admission bucket */
    weed_cache_slot *cache;  /* lazily allocated on first insert */
    int64_t listen_paused_until_ms;  /* 0 = listen fd armed; else the
                                        re-arm deadline after EMFILE
                                        (a level-triggered listen event
                                        that can never accept would
                                        busy-spin the loop) */
} weed_loop;

static double weed_now_s(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

static int64_t weed_now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

static int64_t weed_now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000ll + ts.tv_nsec;
}

/* ---- counters / generation ----------------------------------------- */

/* process-wide fast-path counters (weedload scrapes these via /status
 * to report the fast-path hit + 304 ratios); relaxed atomics because a
 * process can run several loops (public + internal listeners) */
static long weed_stat_served;        /* responses the C loop wrote    */
static long weed_stat_304;           /* ... of which were 304s        */
static long weed_stat_cache_hits;    /* served without calling Python */
static long weed_stat_cache_inserts;
static long weed_stat_shed;          /* 503s from the shared bucket   */
static long weed_stat_handoffs;      /* connections left for Python   */

/* plan-cache invalidation: the storage layer bumps this on ANY volume
 * mutation (write, delete, vacuum fd-swap, remount); resolvers stamp
 * plans with the value they observed before reading */
static uint64_t weed_serve_gen_counter;

static uint64_t weed_gen_get(void) {
    return __atomic_load_n(&weed_serve_gen_counter, __ATOMIC_RELAXED);
}

static uint64_t weed_gen_bump(void) {
    return __atomic_fetch_add(&weed_serve_gen_counter, 1, __ATOMIC_RELAXED) + 1;
}

static uint64_t weed_hash(const char *s, size_t n) {
    uint64_t h = 1469598103934665603ull;  /* FNV-1a */
    size_t i;
    for (i = 0; i < n; i++) {
        h ^= (uint8_t)s[i];
        h *= 1099511628211ull;
    }
    return h;
}

/* ---- If-None-Match --------------------------------------------------
 * RFC 9110 §13.1.2 against the resolver's entity-tag: `*` matches any,
 * otherwise a quote-aware scan of the comma-separated list with WEAK
 * comparison (W/ ignored on both sides).  This is the exact scanner
 * util/httpd.etag_matches implements — keep the two in lockstep; the
 * C-vs-Python identity matrix in tests/ diffs them. */
static int weed_etag_match(const char *hdr, size_t hn,
                           const uint8_t *etag, size_t en) {
    while (hn > 0 && (hdr[0] == ' ' || hdr[0] == '\t')) { hdr++; hn--; }
    while (hn > 0 && (hdr[hn - 1] == ' ' || hdr[hn - 1] == '\t')) hn--;
    if (hn == 0) return 0;
    if (hn == 1 && hdr[0] == '*') return 1;
    const uint8_t *target = etag;
    size_t tn = en;
    if (en >= 2 && etag[0] == 'W' && etag[1] == '/') { target += 2; tn -= 2; }
    size_t i = 0;
    while (i < hn) {
        while (i < hn && (hdr[i] == ' ' || hdr[i] == '\t' || hdr[i] == ','))
            i++;
        if (i >= hn) break;
        if (i + 1 < hn && hdr[i] == 'W' && hdr[i + 1] == '/') i += 2;
        if (i < hn && hdr[i] == '"') {
            const char *q = memchr(hdr + i + 1, '"', hn - i - 1);
            if (q == NULL) return 0;
            size_t clen = (size_t)(q - (hdr + i)) + 1;
            if (clen == tn && memcmp(hdr + i, target, tn) == 0) return 1;
            i += clen;
        } else {
            const char *cm = memchr(hdr + i, ',', hn - i);
            if (cm == NULL) return 0;
            i = (size_t)(cm - hdr) + 1;
        }
    }
    return 0;
}

/* ---- shared-memory admission ----------------------------------------
 * One token bucket per client key, shared by every `-serveProcs` /
 * `-workers` sibling through an mmap'd file, replacing the rate/N
 * per-process split (exact only under uniform connection spread).
 * Each slot is a single int64 GCRA theoretical-arrival-time in
 * CLOCK_MONOTONIC ns — the token bucket (rate r, burst b) expressed
 * as virtual time, so admit is ONE lock-free CAS: crash-safe (a
 * sibling killed mid-check holds no lock) where a shm mutex is not.
 * Key collisions merge budgets toward the conservative side
 * (documented in docs/QOS.md). */
#define WEED_SHM_MAGIC 0x5745454441444d31ull /* "WEEDADM1" */

typedef struct {
    uint64_t magic;
    uint32_t nslots;
    uint32_t pad_;
    double rate;        /* tokens/second, GLOBAL across siblings */
    double burst;       /* bucket size */
    double retry_floor; /* minimum Retry-After seconds */
} weed_shm_hdr;

static struct {
    weed_shm_hdr *hdr;
    int64_t *tat;
} weed_shm;

static int weed_shm_active(void) { return weed_shm.hdr != NULL; }

/* attach (creating + initializing when first): flock serializes the
 * header init race between siblings; first writer's parameters win */
static int weed_shm_attach(const char *path, double rate, double burst,
                           double retry_floor, uint32_t nslots) {
    if (weed_shm.hdr != NULL) return 0;  /* process-global, attach once */
    if (nslots == 0) nslots = 1024;
    int fd = open(path, O_RDWR | O_CREAT | O_CLOEXEC, 0600);
    if (fd < 0) return -errno;
    if (flock(fd, LOCK_EX) != 0) {
        int e = errno;
        close(fd);
        return -e;
    }
    struct stat st;
    weed_shm_hdr init;
    size_t need;
    if (fstat(fd, &st) != 0) goto fail_errno;
    if (st.st_size < (off_t)sizeof(weed_shm_hdr)) {
        need = sizeof(weed_shm_hdr) + (size_t)nslots * sizeof(int64_t);
        if (ftruncate(fd, (off_t)need) != 0) goto fail_errno;
        memset(&init, 0, sizeof(init));
        init.magic = WEED_SHM_MAGIC;
        init.nslots = nslots;
        init.rate = rate;
        init.burst = burst;
        init.retry_floor = retry_floor;
        if (pwrite(fd, &init, sizeof(init), 0) != (ssize_t)sizeof(init))
            goto fail_errno;
    } else {
        if (pread(fd, &init, sizeof(init), 0) != (ssize_t)sizeof(init) ||
            init.magic != WEED_SHM_MAGIC || init.nslots == 0) {
            flock(fd, LOCK_UN);
            close(fd);
            return -EINVAL;
        }
        need = sizeof(weed_shm_hdr) + (size_t)init.nslots * sizeof(int64_t);
    }
    flock(fd, LOCK_UN);
    void *m = mmap(NULL, need, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);  /* the mapping keeps the file alive */
    if (m == MAP_FAILED) return -errno;
    weed_shm.tat = (int64_t *)((uint8_t *)m + sizeof(weed_shm_hdr));
    weed_shm.hdr = (weed_shm_hdr *)m;
    return 0;
fail_errno:
    {
        int e = errno;
        flock(fd, LOCK_UN);
        close(fd);
        return -e;
    }
}

static void weed_shm_detach(void) {
    if (weed_shm.hdr == NULL) return;
    size_t need = sizeof(weed_shm_hdr) +
                  (size_t)weed_shm.hdr->nslots * sizeof(int64_t);
    weed_shm.hdr = NULL;
    munmap((void *)((uint8_t *)weed_shm.tat - sizeof(weed_shm_hdr)), need);
    weed_shm.tat = NULL;
}

/* 0.0 = admitted (one token consumed); > 0 = shed, the Retry-After
 * seconds (same formula as the Python gate: time until one token). */
static double weed_shm_admit(const char *key, size_t klen) {
    weed_shm_hdr *h = weed_shm.hdr;
    if (h == NULL || h->rate <= 0.0) return 0.0;
    int64_t T = (int64_t)(1e9 / h->rate);
    if (T < 1) T = 1;
    double b = h->burst < 1.0 ? 1.0 : h->burst;
    int64_t tau = (int64_t)((b - 1.0) * 1e9 / h->rate);
    int64_t *slot = &weed_shm.tat[weed_hash(key, klen) % h->nslots];
    for (;;) {
        int64_t now = weed_now_ns();
        int64_t tat = __atomic_load_n(slot, __ATOMIC_RELAXED);
        if (tat - now > tau) {
            double retry = (double)(tat - now - tau) / 1e9;
            return retry < h->retry_floor ? h->retry_floor : retry;
        }
        int64_t base = tat > now ? tat : now;
        if (__atomic_compare_exchange_n(slot, &tat, base + T, 0,
                                        __ATOMIC_RELAXED, __ATOMIC_RELAXED))
            return 0.0;
    }
}

/* byte-for-byte the Python gate's shed body (qos/admission._shed) */
static const char weed_shed_body[] =
    "{\"error\": \"admission control: over per-client budget\"}";

/* ---- idle LRU ------------------------------------------------------ */

static void weed_lru_unlink(weed_conn *c) {
    c->prev->next = c->next;
    c->next->prev = c->prev;
}

static void weed_lru_touch(weed_loop *lp, weed_conn *c) {
    weed_lru_unlink(c);
    c->prev = lp->lru.prev;
    c->next = &lp->lru;
    lp->lru.prev->next = c;
    lp->lru.prev = c;
    c->last_ms = weed_now_ms();
}

/* ---- connection lifecycle ------------------------------------------ */

static void weed_conn_release_resp(weed_loop *lp, weed_conn *c, int ok) {
    if (c->close_body_fd && c->body_fd >= 0) close(c->body_fd);
    c->body_fd = -1;
    c->body_left = 0;
    c->close_body_fd = 0;
    if (c->token != NULL) {
        double t_send = weed_now_s() - c->t_send0;
        lp->cbs->complete(lp->cbs->ctx, c->token, c->status, c->resp_bytes,
                          c->t_parse, c->t_resolve, t_send, ok);
        c->token = NULL;
    }
}

static void weed_conn_destroy(weed_loop *lp, weed_conn *c, int close_fd) {
    weed_conn_release_resp(lp, c, 0);
    weed_lru_unlink(c);
    epoll_ctl(lp->epfd, EPOLL_CTL_DEL, c->fd, NULL);
    if (close_fd) close(c->fd);
    free(c->rbuf);
    free(c->wbuf);
    free(c);
}

/* the connection leaves this loop alive: the embedder now owns the fd
 * and the unconsumed bytes (the current head onward) */
static void weed_conn_handoff(weed_loop *lp, weed_conn *c) {
    int fd = c->fd;
    __atomic_fetch_add(&weed_stat_handoffs, 1, __ATOMIC_RELAXED);
    /* detach BEFORE the callback: the embedder may start reading from
     * another thread immediately */
    epoll_ctl(lp->epfd, EPOLL_CTL_DEL, fd, NULL);
    lp->cbs->handoff(lp->cbs->ctx, fd, c->rbuf + c->rpos, c->rlen - c->rpos,
                     c->ip, c->port, c->nreqs);
    weed_lru_unlink(c);
    free(c->rbuf);
    free(c->wbuf);
    free(c);
}

static int weed_conn_interest(weed_loop *lp, weed_conn *c, uint32_t events) {
    struct epoll_event ev;
    memset(&ev, 0, sizeof(ev));
    /* RDHUP only while reading: a half-closed peer that is still
     * draining its response would otherwise level-trigger RDHUP every
     * epoll round while the send buffer is full (busy spin); in the
     * writing state a dead peer surfaces as EPOLLERR/HUP or EPIPE */
    ev.events = events | ((events & EPOLLIN) ? EPOLLRDHUP : 0);
    ev.data.ptr = c;
    return epoll_ctl(lp->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

/* ---- buffers ------------------------------------------------------- */

static int weed_rbuf_reserve(weed_conn *c, size_t want) {
    if (c->rcap - c->rlen >= want) return 0;
    /* compact first: everything before rpos is consumed */
    if (c->rpos > 0) {
        memmove(c->rbuf, c->rbuf + c->rpos, c->rlen - c->rpos);
        if (c->scan >= c->rpos) c->scan -= c->rpos; else c->scan = 0;
        c->rlen -= c->rpos;
        c->rpos = 0;
        if (c->rcap - c->rlen >= want) return 0;
    }
    size_t cap = c->rcap ? c->rcap : WEED_SERVE_RBUF_INIT;
    while (cap - c->rlen < want) cap *= 2;
    uint8_t *nb = realloc(c->rbuf, cap);
    if (nb == NULL) return -1;
    c->rbuf = nb;
    c->rcap = cap;
    return 0;
}

static int weed_wbuf_append(weed_conn *c, const void *data, size_t n) {
    if (c->wcap - c->wlen < n) {
        size_t cap = c->wcap ? c->wcap : 1024;
        while (cap - c->wlen < n) cap *= 2;
        uint8_t *nb = realloc(c->wbuf, cap);
        if (nb == NULL) return -1;
        c->wbuf = nb;
        c->wcap = cap;
    }
    memcpy(c->wbuf + c->wlen, data, n);
    c->wlen += n;
    return 0;
}

/* ---- plan cache ---------------------------------------------------- */

static void weed_cache_slot_clear(weed_cache_slot *s) {
    if (s->fd >= 0) close(s->fd);
    free(s->buf);
    memset(s, 0, sizeof(*s));
    s->fd = -1;
}

static weed_cache_slot *weed_cache_get(weed_loop *lp, const char *path,
                                       size_t plen) {
    if (lp->cache == NULL || plen == 0 || plen > WEED_SERVE_CACHE_KEYMAX)
        return NULL;
    weed_cache_slot *s =
        &lp->cache[weed_hash(path, plen) % WEED_SERVE_CACHE_SLOTS];
    if (s->key_len != plen || memcmp(s->key, path, plen) != 0) return NULL;
    if (s->gen != weed_gen_get()) {
        weed_cache_slot_clear(s);  /* the storage layer bumped: stale */
        return NULL;
    }
    return s;
}

static void weed_cache_put(weed_loop *lp, const weed_req *req,
                           const weed_resp *resp) {
    if (!resp->cacheable || resp->status != 200 || req->range != NULL)
        return;
    if (req->path_len == 0 || req->path_len > WEED_SERVE_CACHE_KEYMAX)
        return;
    if (resp->fd < 0 && resp->body_len > WEED_SERVE_CACHE_BODYMAX)
        return;
    if (resp->gen != weed_gen_get())
        return;  /* raced an invalidation during the resolve */
    if (lp->cache == NULL) {
        lp->cache = calloc(WEED_SERVE_CACHE_SLOTS, sizeof(weed_cache_slot));
        if (lp->cache == NULL) return;
        for (size_t i = 0; i < WEED_SERVE_CACHE_SLOTS; i++)
            lp->cache[i].fd = -1;
    }
    weed_cache_slot *s =
        &lp->cache[weed_hash(req->path, req->path_len) %
                   WEED_SERVE_CACHE_SLOTS];
    weed_cache_slot_clear(s);
    size_t blen = resp->fd >= 0 ? 0 : resp->body_len;
    size_t total =
        resp->prefix_len + blen + resp->etag_len + resp->prefix304_len;
    uint8_t *buf = malloc(total ? total : 1);
    if (buf == NULL) return;
    uint8_t *w = buf;
    memcpy(w, resp->prefix, resp->prefix_len); w += resp->prefix_len;
    if (blen) { memcpy(w, resp->body, blen); w += blen; }
    if (resp->etag_len) { memcpy(w, resp->etag, resp->etag_len); w += resp->etag_len; }
    if (resp->prefix304_len) memcpy(w, resp->prefix304, resp->prefix304_len);
    if (resp->fd >= 0) {
        int dfd = fcntl(resp->fd, F_DUPFD_CLOEXEC, 0);
        if (dfd < 0) {
            free(buf);
            return;
        }
        s->fd = dfd;
        s->off = resp->off;
        s->count = resp->count;
    }
    memcpy(s->key, req->path, req->path_len);
    s->key_len = req->path_len;
    s->gen = resp->gen;
    s->status = resp->status;
    s->buf = buf;
    s->prefix_len = resp->prefix_len;
    s->body_len = blen;
    s->etag_len = resp->etag_len;
    s->p304_len = resp->prefix304_len;
    __atomic_fetch_add(&weed_stat_cache_inserts, 1, __ATOMIC_RELAXED);
}

static void weed_cache_free(weed_loop *lp) {
    if (lp->cache == NULL) return;
    for (size_t i = 0; i < WEED_SERVE_CACHE_SLOTS; i++)
        if (lp->cache[i].key_len) weed_cache_slot_clear(&lp->cache[i]);
    free(lp->cache);
    lp->cache = NULL;
}

/* ---- parsing ------------------------------------------------------- */

/* find "\r\n\r\n" in buf[from..len); returns offset of its first byte
 * or -1.  memchr-based so no _GNU_SOURCE memmem dependency. */
static ssize_t weed_find_head_end(const uint8_t *buf, size_t len, size_t from) {
    while (from + 4 <= len) {
        const uint8_t *p = memchr(buf + from, '\r', len - from - 3);
        if (p == NULL) return -1;
        if (p[1] == '\n' && p[2] == '\r' && p[3] == '\n')
            return (ssize_t)(p - buf);
        from = (size_t)(p - buf) + 1;
    }
    return -1;
}

static int weed_token_eq_ci(const char *p, size_t n, const char *lit) {
    size_t i;
    for (i = 0; i < n; i++) {
        char a = p[i];
        if (a >= 'A' && a <= 'Z') a += 32;
        if (a != lit[i]) return 0;
    }
    return lit[n] == '\0';
}

static void weed_trim(const char **p, size_t *n) {
    while (*n > 0 && ((*p)[0] == ' ' || (*p)[0] == '\t')) { (*p)++; (*n)--; }
    while (*n > 0 && ((*p)[*n - 1] == ' ' || (*p)[*n - 1] == '\t')) (*n)--;
}

/* Parse one request head (head_len bytes including the blank line).
 * Returns 1 = fast-path candidate (req filled, keep_alive set),
 *         0 = hand off (anything this loop does not fully model).   */
static int weed_parse_head(const uint8_t *head, size_t head_len,
                           weed_req *req, int *keep_alive) {
    const char *p = (const char *)head;
    const char *end = p + head_len - 2;  /* final CRLF of blank line */
    const char *eol = memchr(p, '\r', (size_t)(end - p));
    if (eol == NULL || eol[1] != '\n') return 0;

    /* request line: METHOD SP PATH SP HTTP/1.x */
    const char *sp1 = memchr(p, ' ', (size_t)(eol - p));
    if (sp1 == NULL) return 0;
    const char *sp2 = memchr(sp1 + 1, ' ', (size_t)(eol - sp1 - 1));
    if (sp2 == NULL) return 0;
    size_t mlen = (size_t)(sp1 - p);
    size_t vlen = (size_t)(eol - sp2 - 1);
    if (memchr(sp1 + 1, ' ', (size_t)(sp2 - sp1 - 1)) != NULL) return 0;
    int head_only;
    if (mlen == 3 && memcmp(p, "GET", 3) == 0) head_only = 0;
    else if (mlen == 4 && memcmp(p, "HEAD", 4) == 0) head_only = 1;
    else return 0;
    int http11;
    if (vlen == 8 && memcmp(sp2 + 1, "HTTP/1.1", 8) == 0) http11 = 1;
    else if (vlen == 8 && memcmp(sp2 + 1, "HTTP/1.0", 8) == 0) http11 = 0;
    else return 0;  /* 0.9 / exotic versions: the Python parser decides */

    memset(req, 0, sizeof(*req));
    req->method = p;
    req->method_len = mlen;
    req->path = sp1 + 1;
    req->path_len = (size_t)(sp2 - sp1 - 1);
    req->head_only = head_only;
    if (req->path_len == 0) return 0;

    int ka = http11;
    const char *line = eol + 2;
    while (line < end) {
        const char *le = memchr(line, '\r', (size_t)(end - line));
        if (le == NULL) le = end;
        const char *colon = memchr(line, ':', (size_t)(le - line));
        if (colon != NULL) {
            const char *k = line;
            size_t kn = (size_t)(colon - line);
            const char *v = colon + 1;
            size_t vn = (size_t)(le - colon - 1);
            weed_trim(&k, &kn);
            weed_trim(&v, &vn);
            if (weed_token_eq_ci(k, kn, "connection")) {
                if (weed_token_eq_ci(v, vn, "close")) ka = 0;
                else if (weed_token_eq_ci(v, vn, "keep-alive")) ka = 1;
            } else if (weed_token_eq_ci(k, kn, "content-length")) {
                /* a GET with a body: let Python frame and drain it */
                if (!(vn == 1 && v[0] == '0')) return 0;
            } else if (weed_token_eq_ci(k, kn, "transfer-encoding") ||
                       weed_token_eq_ci(k, kn, "expect") ||
                       weed_token_eq_ci(k, kn, "if-modified-since") ||
                       weed_token_eq_ci(k, kn, "etag-md5") ||
                       weed_token_eq_ci(k, kn, "x-weed-deadline")) {
                /* date-conditional / framing / deadline semantics live
                 * in Python (the mini loop parses the budget, 504-
                 * fast-rejects expired ones, and scopes the ambient
                 * deadline around dispatch — docs/CHAOS.md).
                 * If-None-Match stays HERE: the resolver supplies the
                 * entity-tag and the loop answers 304 itself. */
                return 0;
            } else if (weed_token_eq_ci(k, kn, "if-none-match")) {
                if (req->inm != NULL) return 0;  /* duplicate header */
                req->inm = v;
                req->inm_len = vn;
            } else if (weed_token_eq_ci(k, kn, "authorization")) {
                /* admission keys authenticated requests by access key,
                 * which only the Python gate parses */
                req->has_auth = 1;
            } else if (weed_token_eq_ci(k, kn, "range")) {
                if (req->range != NULL) return 0;  /* duplicate Range */
                req->range = v;
                req->range_len = vn;
            } else if (weed_token_eq_ci(k, kn, "x-weed-trace")) {
                req->trace = v;
                req->trace_len = vn;
            }
        }
        line = le + 2;
    }
    *keep_alive = ka;
    return 1;
}

/* ---- response writing ---------------------------------------------- */

/* 1 = fully written, 0 = would block (EPOLLOUT pending), -1 = dead */
static int weed_conn_flush(weed_conn *c) {
    while (c->wpos < c->wlen) {
        ssize_t n = send(c->fd, c->wbuf + c->wpos, c->wlen - c->wpos,
                         MSG_NOSIGNAL);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
            if (errno == EINTR) continue;
            return -1;
        }
        c->wpos += (size_t)n;
    }
    while (c->body_left > 0) {
        off_t off = (off_t)c->body_off;
        size_t chunk = c->body_left < WEED_SERVE_SENDFILE_CHUNK
                           ? c->body_left
                           : WEED_SERVE_SENDFILE_CHUNK;
        ssize_t n = sendfile(c->fd, c->body_fd, &off, chunk);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
            if (errno == EINTR) continue;
            return -1;
        }
        if (n == 0) return -1;  /* source truncated under us: the
                                   promised Content-Length cannot be
                                   met — kill the connection so the
                                   client sees a short read, never
                                   silent corruption */
        c->body_off = (int64_t)off;
        c->body_left -= (size_t)n;
    }
    return 1;
}

/* First flush of a staged response: ONE gathering sendmsg over the
 * head pieces + inline body (the writev reply — no memcpy into wbuf
 * unless the kernel leaves a remainder), then the shared flush for any
 * sendfile body.  The staged buffers are only borrowed for the
 * duration of this call: a blocked remainder is copied into wbuf
 * before returning, so resolver-token and cache-slot lifetimes never
 * extend into the EPOLLOUT machinery.
 * Returns 0 = fully sent (connection stays, pipeline may continue),
 *         1 = blocked (EPOLLOUT armed, caller must return),
 *        -1 = connection left the loop. */
static int weed_conn_send_staged(weed_loop *lp, weed_conn *c,
                                 const struct iovec *iov, int niov) {
    c->writing = 1;
    c->t_send0 = weed_now_s();
    size_t total = 0;
    for (int i = 0; i < niov; i++) total += iov[i].iov_len;
    struct msghdr mh;
    memset(&mh, 0, sizeof(mh));
    mh.msg_iov = (struct iovec *)iov;
    mh.msg_iovlen = (size_t)niov;
    ssize_t sent;
    do {
        sent = sendmsg(c->fd, &mh, MSG_NOSIGNAL);
    } while (sent < 0 && errno == EINTR);
    if (sent < 0) {
        if (errno != EAGAIN && errno != EWOULDBLOCK) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
        sent = 0;
    }
    c->wlen = c->wpos = 0;
    if ((size_t)sent < total) {
        size_t skip = (size_t)sent;
        int oom = 0;
        for (int i = 0; i < niov && !oom; i++) {
            if (skip >= iov[i].iov_len) {
                skip -= iov[i].iov_len;
                continue;
            }
            oom = weed_wbuf_append(
                c, (const uint8_t *)iov[i].iov_base + skip,
                iov[i].iov_len - skip);
            skip = 0;
        }
        if (oom) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
    }
    int wr = weed_conn_flush(c);
    if (wr < 0) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    if (wr == 0) {
        if (weed_conn_interest(lp, c, EPOLLOUT) < 0) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
        return 1;
    }
    weed_conn_release_resp(lp, c, 1);
    c->writing = 0;
    c->wlen = c->wpos = 0;
    if (c->closing) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    return 0;
}

/* process buffered requests until blocked.  Returns 0 to keep the
 * connection in the loop, -1 when it left (destroyed or handed off). */
static int weed_conn_process(weed_loop *lp, weed_conn *c) {
    while (!c->writing) {
        size_t avail = c->rlen - c->rpos;
        if (avail < 4) break;
        if (c->scan < c->rpos) c->scan = c->rpos;
        ssize_t he = weed_find_head_end(c->rbuf, c->rlen, c->scan);
        if (he < 0) {
            c->scan = c->rlen >= 3 ? c->rlen - 3 : 0;
            if (avail > WEED_SERVE_HEAD_LIMIT) {
                weed_conn_handoff(lp, c);  /* Python replies 431 */
                return -1;
            }
            break;
        }
        size_t head_len = (size_t)he + 4 - c->rpos;
        if (head_len > WEED_SERVE_HEAD_LIMIT) {
            /* a COMPLETE head past the cap: the incomplete-head check
             * above never fires when the whole head coalesced into one
             * buffered read — hand off so Python's read_head replies
             * 431 instead of serving the oversized request as 200 */
            weed_conn_handoff(lp, c);
            return -1;
        }

        double tp0 = weed_now_s();
        weed_req req;
        int keep_alive = 1;
        if (!weed_parse_head(c->rbuf + c->rpos, head_len, &req, &keep_alive) ||
            lp->cbs->resolve == NULL) {
            weed_conn_handoff(lp, c);
            return -1;
        }
        c->t_parse = weed_now_s() - tp0;

        int use_adm = lp->use_adm && weed_shm_active();
        if (use_adm && req.has_auth) {
            /* Authorization must be keyed by access key; only the
             * Python gate parses it — the handoff re-gates there */
            weed_conn_handoff(lp, c);
            return -1;
        }

        weed_resp resp;
        memset(&resp, 0, sizeof(resp));
        resp.fd = -1;
        void *token = NULL;
        int from_cache = 0;
        double tr0 = weed_now_s();
        if (req.range == NULL) {
            weed_cache_slot *s = weed_cache_get(lp, req.path, req.path_len);
            if (s != NULL) {
                resp.status = s->status;
                resp.prefix = s->buf;
                resp.prefix_len = s->prefix_len;
                resp.body = s->buf + s->prefix_len;
                resp.body_len = s->body_len;
                resp.etag = s->buf + s->prefix_len + s->body_len;
                resp.etag_len = s->etag_len;
                resp.prefix304 =
                    s->buf + s->prefix_len + s->body_len + s->etag_len;
                resp.prefix304_len = s->p304_len;
                from_cache = 1;
                if (s->fd >= 0) {
                    /* per-response dup: an eviction must never yank
                     * the fd out of an in-flight sendfile */
                    int dfd = fcntl(s->fd, F_DUPFD_CLOEXEC, 0);
                    if (dfd < 0) {
                        from_cache = 0;  /* fall back to the resolver */
                        memset(&resp, 0, sizeof(resp));
                        resp.fd = -1;
                    } else {
                        resp.fd = dfd;
                        resp.off = s->off;
                        resp.count = s->count;
                        resp.close_fd = 1;
                    }
                }
                if (from_cache)
                    __atomic_fetch_add(&weed_stat_cache_hits, 1,
                                       __ATOMIC_RELAXED);
            }
        }
        if (!from_cache) {
            int rc = lp->cbs->resolve(lp->cbs->ctx, &req, &resp, &token);
            c->t_resolve = weed_now_s() - tr0;
            if (rc == 0) {
                weed_conn_handoff(lp, c);
                return -1;
            }
            if (rc < 0) {
                weed_conn_destroy(lp, c, 1);
                return -1;
            }
            weed_cache_put(lp, &req, &resp);
        } else {
            c->t_resolve = weed_now_s() - tr0;
        }

        c->rpos += head_len;
        c->scan = c->rpos;
        c->nreqs++;
        int close_now =
            !keep_alive || (lp->max_reqs > 0 && c->nreqs >= lp->max_reqs);
        c->closing = close_now;

        if (use_adm) {
            double retry = weed_shm_admit(c->ip, strlen(c->ip));
            if (retry > 0.0) {
                /* shared-bucket shed, entirely in C: drop the plan,
                 * reply the exact bytes the Python gate's _shed sends */
                if (resp.fd >= 0 && resp.close_fd) close(resp.fd);
                if (token != NULL) {
                    /* releases the resolver token and records the 503
                     * on the request counter like the threaded arm */
                    lp->cbs->complete(lp->cbs->ctx, token, 503, 0,
                                      c->t_parse, c->t_resolve, 0.0, 1);
                    token = NULL;
                }
                __atomic_fetch_add(&weed_stat_shed, 1, __ATOMIC_RELAXED);
                char shed_head[192];
                int sn = snprintf(
                    shed_head, sizeof(shed_head),
                    "HTTP/1.1 503 Service Unavailable\r\n"
                    "Content-Type: application/json\r\n"
                    "Retry-After: %.3f\r\n"
                    "%s"
                    "Content-Length: %zu\r\n\r\n",
                    retry, close_now ? "Connection: close\r\n" : "",
                    sizeof(weed_shed_body) - 1);
                struct iovec siov[2];
                int sniov = 0;
                siov[sniov].iov_base = shed_head;
                siov[sniov++].iov_len = (size_t)sn;
                if (!req.head_only) {
                    siov[sniov].iov_base = (void *)weed_shed_body;
                    siov[sniov++].iov_len = sizeof(weed_shed_body) - 1;
                }
                c->token = NULL;
                c->status = 503;
                c->resp_bytes = (size_t)sn +
                    (req.head_only ? 0 : sizeof(weed_shed_body) - 1);
                int sr = weed_conn_send_staged(lp, c, siov, sniov);
                if (sr < 0) return -1;
                if (sr > 0) return 0;
                if (c->rpos == c->rlen)
                    c->rpos = c->rlen = c->scan = 0;
                continue;
            }
        }

        /* assemble head exactly as fast_reply does: resolver prefix
         * (status line + headers), optional Connection: close, then
         * Content-Length last.  If-None-Match beats Range (the Python
         * arm checks it before range handling): a validator match
         * answers 304 from the pre-rendered prefix and drops the plan
         * body, whatever the plan's status was. */
        int not_modified =
            req.inm != NULL && resp.etag_len > 0 && resp.prefix304_len > 0 &&
            weed_etag_match(req.inm, req.inm_len, resp.etag, resp.etag_len);
        char tail[64];
        int tn;
        struct iovec iov[4];
        int niov = 0;
        if (not_modified) {
            if (resp.fd >= 0 && resp.close_fd) close(resp.fd);
            resp.fd = -1;
            tn = snprintf(tail, sizeof(tail), "Content-Length: 0\r\n\r\n");
            iov[niov].iov_base = (void *)resp.prefix304;
            iov[niov++].iov_len = resp.prefix304_len;
            __atomic_fetch_add(&weed_stat_304, 1, __ATOMIC_RELAXED);
        } else {
            size_t body_total = resp.fd >= 0 ? resp.count : resp.body_len;
            tn = snprintf(tail, sizeof(tail),
                          "Content-Length: %zu\r\n\r\n", body_total);
            iov[niov].iov_base = (void *)resp.prefix;
            iov[niov++].iov_len = resp.prefix_len;
        }
        if (close_now) {
            iov[niov].iov_base = (void *)"Connection: close\r\n";
            iov[niov++].iov_len = 19;
        }
        iov[niov].iov_base = tail;
        iov[niov++].iov_len = (size_t)tn;
        if (!not_modified && !req.head_only && resp.fd < 0 &&
            resp.body_len > 0) {
            iov[niov].iov_base = (void *)resp.body;
            iov[niov++].iov_len = resp.body_len;
        }
        size_t head_bytes = 0;
        for (int i = 0; i < niov; i++) head_bytes += iov[i].iov_len;
        c->token = token;
        c->status = not_modified ? 304 : resp.status;
        c->resp_bytes = head_bytes +
            ((req.head_only || not_modified) ? 0
                 : (resp.fd >= 0 ? resp.count : 0));
        if (!not_modified && !req.head_only && resp.fd >= 0 &&
            resp.count > 0) {
            c->body_fd = resp.fd;
            c->body_off = resp.off;
            c->body_left = resp.count;
            c->close_body_fd = resp.close_fd;
        } else if (resp.fd >= 0 && resp.close_fd) {
            close(resp.fd);  /* HEAD / empty body: nothing to send */
        }
        __atomic_fetch_add(&weed_stat_served, 1, __ATOMIC_RELAXED);
        int sr = weed_conn_send_staged(lp, c, iov, niov);
        if (sr < 0) return -1;
        if (sr > 0) return 0;
        if (c->rpos == c->rlen) {
            c->rpos = c->rlen = c->scan = 0;  /* cheap full reset */
        }
    }
    if (c->eof && !c->writing) {
        /* pipeline drained (or never complete) after FIN: done */
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    return 0;
}

/* One write attempt on an in-flight response, shared by the EPOLLOUT
 * handler and the idle-reaper's drain probe.  Returns -1 when the
 * connection left the loop (destroyed or handed off), else 0; partial
 * progress touches the idle LRU (a slow-but-draining client is active,
 * not idle), completion finishes the response and resumes the
 * pipeline. */
static int weed_conn_flush_step(weed_loop *lp, weed_conn *c) {
    size_t wpos0 = c->wpos, left0 = c->body_left;
    int wr = weed_conn_flush(c);
    if (wr < 0) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    if (wr == 0) {
        if (c->wpos != wpos0 || c->body_left != left0)
            weed_lru_touch(lp, c);
        return 0;
    }
    weed_conn_release_resp(lp, c, 1);
    c->writing = 0;
    c->wlen = c->wpos = 0;
    weed_lru_touch(lp, c);
    if (c->closing) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    if (weed_conn_interest(lp, c, EPOLLIN) < 0) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    return weed_conn_process(lp, c);
}

static int weed_conn_read(weed_loop *lp, weed_conn *c) {
    for (;;) {
        if (weed_rbuf_reserve(c, 4096) < 0) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
        ssize_t n = recv(c->fd, c->rbuf + c->rlen, c->rcap - c->rlen, 0);
        if (n > 0) {
            c->rlen += (size_t)n;
            if (c->rlen < c->rcap) break;  /* short read: drained */
            continue;
        }
        if (n == 0) {  /* FIN: serve what is buffered, then close */
            c->eof = 1;
            break;
        }
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        if (errno == EINTR) continue;
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    weed_lru_touch(lp, c);
    return weed_conn_process(lp, c);
}

static void weed_accept_drain(weed_loop *lp) {
    for (;;) {
        struct sockaddr_storage ss;
        socklen_t slen = sizeof(ss);
        int fd = accept4(lp->listen_fd, (struct sockaddr *)&ss, &slen,
                         SOCK_NONBLOCK | SOCK_CLOEXEC);
        if (fd < 0) {
            if (errno == EINTR) continue;
            if (errno == EMFILE || errno == ENFILE) {
                /* fd exhaustion: the backlog stays non-empty, so the
                 * level-triggered listen event would re-fire every
                 * epoll round in a hot spin — park the listen fd and
                 * re-arm after a beat */
                epoll_ctl(lp->epfd, EPOLL_CTL_DEL, lp->listen_fd, NULL);
                lp->listen_paused_until_ms = weed_now_ms() + 100;
            }
            return;  /* EAGAIN / ECONNABORTED: next listen event retries */
        }
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        weed_conn *c = calloc(1, sizeof(weed_conn));
        if (c == NULL) {
            close(fd);
            continue;
        }
        c->fd = fd;
        c->body_fd = -1;
        c->ip[0] = '\0';
        if (ss.ss_family == AF_INET) {
            const struct sockaddr_in *a = (const struct sockaddr_in *)&ss;
            const uint8_t *b = (const uint8_t *)&a->sin_addr;
            snprintf(c->ip, sizeof(c->ip), "%u.%u.%u.%u", b[0], b[1], b[2],
                     b[3]);
            c->port = (int)ntohs(a->sin_port);
        } else if (ss.ss_family == AF_INET6) {
            const struct sockaddr_in6 *a6 = (const struct sockaddr_in6 *)&ss;
            const uint8_t *b = (const uint8_t *)&a6->sin6_addr;
            /* enough fidelity for logs/ACL checks on the data plane */
            snprintf(c->ip, sizeof(c->ip),
                     "%x:%x:%x:%x:%x:%x:%x:%x",
                     (b[0] << 8) | b[1], (b[2] << 8) | b[3],
                     (b[4] << 8) | b[5], (b[6] << 8) | b[7],
                     (b[8] << 8) | b[9], (b[10] << 8) | b[11],
                     (b[12] << 8) | b[13], (b[14] << 8) | b[15]);
            c->port = (int)ntohs(a6->sin6_port);
        }
        /* link into LRU tail */
        c->prev = lp->lru.prev;
        c->next = &lp->lru;
        lp->lru.prev->next = c;
        lp->lru.prev = c;
        c->last_ms = weed_now_ms();
        struct epoll_event ev;
        memset(&ev, 0, sizeof(ev));
        ev.events = EPOLLIN | EPOLLRDHUP;
        ev.data.ptr = c;
        if (epoll_ctl(lp->epfd, EPOLL_CTL_ADD, fd, &ev) < 0) {
            weed_lru_unlink(c);
            close(fd);
            free(c);
        }
    }
}

static void weed_expire_idle(weed_loop *lp) {
    if (lp->idle_ms <= 0) return;
    int64_t cutoff = weed_now_ms() - lp->idle_ms;
    while (lp->lru.next != &lp->lru && lp->lru.next->last_ms < cutoff) {
        weed_conn *c = lp->lru.next;
        if (c->writing) {
            /* EPOLLOUT cadence cannot prove drain progress: TCP only
             * reports writable once the send queue falls below HALF
             * full, so a client sipping a multi-MB buffered body sees
             * zero events for whole idle windows.  send()/sendfile()
             * have no such threshold — they accept bytes whenever ANY
             * space exists — so probe by flushing: moved bytes = a
             * live, draining client (flush_step touches the LRU);
             * zero bytes across a full idle window = a true stall.
             * A stalled writer therefore dies within two idle
             * windows, mirroring the threaded arm's stall-retry
             * sendall. */
            if (weed_conn_flush_step(lp, c) < 0)
                continue;  /* left the loop (done+closing, or dead) */
            if (c->last_ms >= cutoff)
                continue;  /* progressed (or completed): re-read next */
        }
        weed_conn_destroy(lp, c, 1);
    }
}

/* tags for the two non-connection epoll registrations */
static int weed_tag_listen;
static int weed_tag_wake;

/* Run the loop until a byte arrives on wake_fd.  Returns 0 on clean
 * shutdown, -errno when setup fails.  listen_fd and wake_fd are NOT
 * closed (the embedder owns them); every connection fd is. */
static int weed_serve_loop(int listen_fd, int wake_fd, weed_serve_cbs *cbs,
                           long idle_ms, long max_reqs, int use_adm) {
    weed_loop lp;
    memset(&lp, 0, sizeof(lp));
    lp.listen_fd = listen_fd;
    lp.wake_fd = wake_fd;
    lp.cbs = cbs;
    lp.idle_ms = idle_ms;
    lp.max_reqs = max_reqs;
    lp.use_adm = use_adm;
    lp.lru.next = lp.lru.prev = &lp.lru;
    lp.epfd = epoll_create1(EPOLL_CLOEXEC);
    if (lp.epfd < 0) return -errno;

    int fl = fcntl(listen_fd, F_GETFL, 0);
    if (fl >= 0) fcntl(listen_fd, F_SETFL, fl | O_NONBLOCK);

    struct epoll_event ev;
    memset(&ev, 0, sizeof(ev));
    ev.events = EPOLLIN;
    ev.data.ptr = &weed_tag_listen;
    if (epoll_ctl(lp.epfd, EPOLL_CTL_ADD, listen_fd, &ev) < 0) {
        int e = errno;
        close(lp.epfd);
        return -e;
    }
    memset(&ev, 0, sizeof(ev));
    ev.events = EPOLLIN;
    ev.data.ptr = &weed_tag_wake;
    if (epoll_ctl(lp.epfd, EPOLL_CTL_ADD, wake_fd, &ev) < 0) {
        int e = errno;
        close(lp.epfd);
        return -e;
    }

    struct epoll_event events[WEED_SERVE_EVENTS];
    while (!lp.stop) {
        int timeout = -1;
        if (lp.idle_ms > 0 && lp.lru.next != &lp.lru) {
            int64_t dl = lp.lru.next->last_ms + lp.idle_ms - weed_now_ms();
            timeout = dl < 0 ? 0 : (dl > 1000 ? 1000 : (int)dl);
        }
        if (lp.listen_paused_until_ms) {
            int64_t dl = lp.listen_paused_until_ms - weed_now_ms();
            if (dl <= 0) {
                memset(&ev, 0, sizeof(ev));
                ev.events = EPOLLIN;
                ev.data.ptr = &weed_tag_listen;
                epoll_ctl(lp.epfd, EPOLL_CTL_ADD, listen_fd, &ev);
                lp.listen_paused_until_ms = 0;
            } else if (timeout < 0 || dl < timeout) {
                timeout = (int)dl;
            }
        }
        int n = epoll_wait(lp.epfd, events, WEED_SERVE_EVENTS, timeout);
        if (n < 0) {
            if (errno == EINTR) continue;
            break;
        }
        for (int i = 0; i < n && !lp.stop; i++) {
            void *tag = events[i].data.ptr;
            if (tag == &weed_tag_wake) {
                char drain[64];
                while (read(wake_fd, drain, sizeof(drain)) > 0) {}
                lp.stop = 1;
                break;
            }
            if (tag == &weed_tag_listen) {
                weed_accept_drain(&lp);
                continue;
            }
            weed_conn *c = (weed_conn *)tag;
            uint32_t evs = events[i].events;
            if (evs & (EPOLLERR | EPOLLHUP)) {
                weed_conn_destroy(&lp, c, 1);
                continue;
            }
            if (c->writing) {
                if (evs & EPOLLOUT) weed_conn_flush_step(&lp, c);
                continue;
            }
            if (evs & (EPOLLIN | EPOLLRDHUP)) weed_conn_read(&lp, c);
        }
        weed_expire_idle(&lp);
    }

    while (lp.lru.next != &lp.lru) weed_conn_destroy(&lp, lp.lru.next, 1);
    weed_cache_free(&lp);
    close(lp.epfd);
    return 0;
}

#endif /* WEED_SERVE_C */
