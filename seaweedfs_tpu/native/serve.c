/* serve.c — the event-driven serving core: one epoll loop that owns
 * accept/read/parse/respond for a listening socket.
 *
 * PR-5's stage traces put the serving residue in syscalls and loop
 * machinery (pwrite ~358 us of a ~500 us write; the Python mini-loop
 * and thread-per-connection dispatch are what's left around it), and
 * thread-per-connection cannot survive past a few thousand concurrent
 * connections.  This loop replaces that edge:
 *
 *   - non-blocking accept4 drain on every listen event (the kernel
 *     backlog is deep; the loop must never leave it full),
 *   - a per-connection read state machine: request heads are scanned
 *     out of one growing buffer, keep-alive and HTTP pipelining are
 *     native (the next pipelined head is parsed the moment the
 *     previous response drains — no extra epoll round trip),
 *   - a zero-copy GET fast path: the embedder's resolve() callback
 *     maps a request to (fd, offset, count) and the loop sendfile()s
 *     the bytes straight from the volume file to the socket, with
 *     short-write resumption on EAGAIN,
 *   - everything else HANDS THE CONNECTION OFF to the embedder
 *     (handoff() transfers the fd plus any unconsumed buffered bytes),
 *     so the one Python request parser keeps serving every slow path
 *     and the two paths cannot drift: this loop never formats an error
 *     response of its own.
 *
 * The loop knows no HTTP beyond what routing requires: request line,
 * the handful of headers that gate the fast path, and Connection
 * semantics.  Response bytes come from the embedder pre-formatted
 * except the Connection/Content-Length tail, which the loop appends
 * exactly like the Python fast_reply does — byte identity between the
 * C and Python serving paths is a construction, not a test hope.
 *
 * Pure C, no Python.h: serve_ext.c binds it the way needle_ext.c
 * binds post.c.  Callbacks are function pointers; the glue re-takes
 * the GIL inside them.
 */

#ifndef WEED_SERVE_C
#define WEED_SERVE_C

#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

/* matches util/httpd._BufReader.read_head's 431 limit: a head this
 * large is handed off so the Python loop applies its own cap */
#define WEED_SERVE_HEAD_LIMIT 131072
#define WEED_SERVE_RBUF_INIT 4096
#define WEED_SERVE_SENDFILE_CHUNK (1u << 20)
#define WEED_SERVE_EVENTS 256

typedef struct {
    const char *method; size_t method_len;
    const char *path;   size_t path_len;
    const char *range;  size_t range_len;   /* NULL when absent */
    const char *trace;  size_t trace_len;   /* x-weed-trace value    */
    int head_only;                          /* method == HEAD        */
} weed_req;

typedef struct {
    const uint8_t *prefix; size_t prefix_len; /* status line + headers,
                                                 WITHOUT Connection /
                                                 Content-Length tail  */
    const uint8_t *body;   size_t body_len;   /* in-memory body (fd<0) */
    int fd; int64_t off; size_t count;        /* sendfile body (fd>=0) */
    int close_fd;                             /* loop closes fd after  */
    int status;
} weed_resp;

typedef struct weed_serve_cbs {
    void *ctx;
    /* One parsed GET/HEAD request.  Return 1 = resp filled (serve it
     * here), 0 = decline (hand the connection off), -1 = abort the
     * connection.  `token` rides to the matching complete(). */
    int (*resolve)(void *ctx, const weed_req *req, weed_resp *resp,
                   void **token);
    /* Ownership of `fd` (plus `len` unconsumed buffered bytes starting
     * at the current request head) transfers to the embedder.
     * `nreqs` = responses this loop already served on the connection,
     * so the embedder's max-requests accounting continues instead of
     * restarting. */
    void (*handoff)(void *ctx, int fd, const uint8_t *pending, size_t len,
                    const char *ip, int port, long nreqs);
    /* The fast-path response finished (ok=1: fully written; ok=0: the
     * connection died first).  Always called exactly once per
     * successful resolve() — it releases `token`. */
    void (*complete)(void *ctx, void *token, int status, size_t resp_bytes,
                     double t_parse, double t_resolve, double t_send, int ok);
} weed_serve_cbs;

typedef struct weed_conn {
    int fd;
    char ip[48];
    int port;
    uint8_t *rbuf;
    size_t rcap, rlen, rpos;  /* rpos = start of the current head */
    size_t scan;              /* head-end scan resume point        */
    uint8_t *wbuf;
    size_t wcap, wlen, wpos;
    int body_fd;
    int64_t body_off;
    size_t body_left;
    int close_body_fd;
    void *token;
    int status;
    size_t resp_bytes;
    int writing;  /* a response is in flight (interest = EPOLLOUT) */
    int closing;  /* close once the in-flight response drains      */
    int eof;      /* peer sent FIN; drain buffered pipeline, then close
                     (the Python loop serves buffered requests after
                     EOF too — byte-identity includes shutdown order) */
    long nreqs;
    double t_parse, t_resolve, t_send0;
    int64_t last_ms;
    struct weed_conn *prev, *next;  /* idle LRU; most recent at tail */
} weed_conn;

typedef struct weed_loop {
    int epfd, listen_fd, wake_fd;
    long idle_ms, max_reqs;
    weed_serve_cbs *cbs;
    weed_conn lru;  /* sentinel */
    int stop;
    int64_t listen_paused_until_ms;  /* 0 = listen fd armed; else the
                                        re-arm deadline after EMFILE
                                        (a level-triggered listen event
                                        that can never accept would
                                        busy-spin the loop) */
} weed_loop;

static double weed_now_s(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

static int64_t weed_now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

/* ---- idle LRU ------------------------------------------------------ */

static void weed_lru_unlink(weed_conn *c) {
    c->prev->next = c->next;
    c->next->prev = c->prev;
}

static void weed_lru_touch(weed_loop *lp, weed_conn *c) {
    weed_lru_unlink(c);
    c->prev = lp->lru.prev;
    c->next = &lp->lru;
    lp->lru.prev->next = c;
    lp->lru.prev = c;
    c->last_ms = weed_now_ms();
}

/* ---- connection lifecycle ------------------------------------------ */

static void weed_conn_release_resp(weed_loop *lp, weed_conn *c, int ok) {
    if (c->close_body_fd && c->body_fd >= 0) close(c->body_fd);
    c->body_fd = -1;
    c->body_left = 0;
    c->close_body_fd = 0;
    if (c->token != NULL) {
        double t_send = weed_now_s() - c->t_send0;
        lp->cbs->complete(lp->cbs->ctx, c->token, c->status, c->resp_bytes,
                          c->t_parse, c->t_resolve, t_send, ok);
        c->token = NULL;
    }
}

static void weed_conn_destroy(weed_loop *lp, weed_conn *c, int close_fd) {
    weed_conn_release_resp(lp, c, 0);
    weed_lru_unlink(c);
    epoll_ctl(lp->epfd, EPOLL_CTL_DEL, c->fd, NULL);
    if (close_fd) close(c->fd);
    free(c->rbuf);
    free(c->wbuf);
    free(c);
}

/* the connection leaves this loop alive: the embedder now owns the fd
 * and the unconsumed bytes (the current head onward) */
static void weed_conn_handoff(weed_loop *lp, weed_conn *c) {
    int fd = c->fd;
    /* detach BEFORE the callback: the embedder may start reading from
     * another thread immediately */
    epoll_ctl(lp->epfd, EPOLL_CTL_DEL, fd, NULL);
    lp->cbs->handoff(lp->cbs->ctx, fd, c->rbuf + c->rpos, c->rlen - c->rpos,
                     c->ip, c->port, c->nreqs);
    weed_lru_unlink(c);
    free(c->rbuf);
    free(c->wbuf);
    free(c);
}

static int weed_conn_interest(weed_loop *lp, weed_conn *c, uint32_t events) {
    struct epoll_event ev;
    memset(&ev, 0, sizeof(ev));
    /* RDHUP only while reading: a half-closed peer that is still
     * draining its response would otherwise level-trigger RDHUP every
     * epoll round while the send buffer is full (busy spin); in the
     * writing state a dead peer surfaces as EPOLLERR/HUP or EPIPE */
    ev.events = events | ((events & EPOLLIN) ? EPOLLRDHUP : 0);
    ev.data.ptr = c;
    return epoll_ctl(lp->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

/* ---- buffers ------------------------------------------------------- */

static int weed_rbuf_reserve(weed_conn *c, size_t want) {
    if (c->rcap - c->rlen >= want) return 0;
    /* compact first: everything before rpos is consumed */
    if (c->rpos > 0) {
        memmove(c->rbuf, c->rbuf + c->rpos, c->rlen - c->rpos);
        if (c->scan >= c->rpos) c->scan -= c->rpos; else c->scan = 0;
        c->rlen -= c->rpos;
        c->rpos = 0;
        if (c->rcap - c->rlen >= want) return 0;
    }
    size_t cap = c->rcap ? c->rcap : WEED_SERVE_RBUF_INIT;
    while (cap - c->rlen < want) cap *= 2;
    uint8_t *nb = realloc(c->rbuf, cap);
    if (nb == NULL) return -1;
    c->rbuf = nb;
    c->rcap = cap;
    return 0;
}

static int weed_wbuf_append(weed_conn *c, const void *data, size_t n) {
    if (c->wcap - c->wlen < n) {
        size_t cap = c->wcap ? c->wcap : 1024;
        while (cap - c->wlen < n) cap *= 2;
        uint8_t *nb = realloc(c->wbuf, cap);
        if (nb == NULL) return -1;
        c->wbuf = nb;
        c->wcap = cap;
    }
    memcpy(c->wbuf + c->wlen, data, n);
    c->wlen += n;
    return 0;
}

/* ---- parsing ------------------------------------------------------- */

/* find "\r\n\r\n" in buf[from..len); returns offset of its first byte
 * or -1.  memchr-based so no _GNU_SOURCE memmem dependency. */
static ssize_t weed_find_head_end(const uint8_t *buf, size_t len, size_t from) {
    while (from + 4 <= len) {
        const uint8_t *p = memchr(buf + from, '\r', len - from - 3);
        if (p == NULL) return -1;
        if (p[1] == '\n' && p[2] == '\r' && p[3] == '\n')
            return (ssize_t)(p - buf);
        from = (size_t)(p - buf) + 1;
    }
    return -1;
}

static int weed_token_eq_ci(const char *p, size_t n, const char *lit) {
    size_t i;
    for (i = 0; i < n; i++) {
        char a = p[i];
        if (a >= 'A' && a <= 'Z') a += 32;
        if (a != lit[i]) return 0;
    }
    return lit[n] == '\0';
}

static void weed_trim(const char **p, size_t *n) {
    while (*n > 0 && ((*p)[0] == ' ' || (*p)[0] == '\t')) { (*p)++; (*n)--; }
    while (*n > 0 && ((*p)[*n - 1] == ' ' || (*p)[*n - 1] == '\t')) (*n)--;
}

/* Parse one request head (head_len bytes including the blank line).
 * Returns 1 = fast-path candidate (req filled, keep_alive set),
 *         0 = hand off (anything this loop does not fully model).   */
static int weed_parse_head(const uint8_t *head, size_t head_len,
                           weed_req *req, int *keep_alive) {
    const char *p = (const char *)head;
    const char *end = p + head_len - 2;  /* final CRLF of blank line */
    const char *eol = memchr(p, '\r', (size_t)(end - p));
    if (eol == NULL || eol[1] != '\n') return 0;

    /* request line: METHOD SP PATH SP HTTP/1.x */
    const char *sp1 = memchr(p, ' ', (size_t)(eol - p));
    if (sp1 == NULL) return 0;
    const char *sp2 = memchr(sp1 + 1, ' ', (size_t)(eol - sp1 - 1));
    if (sp2 == NULL) return 0;
    size_t mlen = (size_t)(sp1 - p);
    size_t vlen = (size_t)(eol - sp2 - 1);
    if (memchr(sp1 + 1, ' ', (size_t)(sp2 - sp1 - 1)) != NULL) return 0;
    int head_only;
    if (mlen == 3 && memcmp(p, "GET", 3) == 0) head_only = 0;
    else if (mlen == 4 && memcmp(p, "HEAD", 4) == 0) head_only = 1;
    else return 0;
    int http11;
    if (vlen == 8 && memcmp(sp2 + 1, "HTTP/1.1", 8) == 0) http11 = 1;
    else if (vlen == 8 && memcmp(sp2 + 1, "HTTP/1.0", 8) == 0) http11 = 0;
    else return 0;  /* 0.9 / exotic versions: the Python parser decides */

    memset(req, 0, sizeof(*req));
    req->method = p;
    req->method_len = mlen;
    req->path = sp1 + 1;
    req->path_len = (size_t)(sp2 - sp1 - 1);
    req->head_only = head_only;
    if (req->path_len == 0) return 0;

    int ka = http11;
    const char *line = eol + 2;
    while (line < end) {
        const char *le = memchr(line, '\r', (size_t)(end - line));
        if (le == NULL) le = end;
        const char *colon = memchr(line, ':', (size_t)(le - line));
        if (colon != NULL) {
            const char *k = line;
            size_t kn = (size_t)(colon - line);
            const char *v = colon + 1;
            size_t vn = (size_t)(le - colon - 1);
            weed_trim(&k, &kn);
            weed_trim(&v, &vn);
            if (weed_token_eq_ci(k, kn, "connection")) {
                if (weed_token_eq_ci(v, vn, "close")) ka = 0;
                else if (weed_token_eq_ci(v, vn, "keep-alive")) ka = 1;
            } else if (weed_token_eq_ci(k, kn, "content-length")) {
                /* a GET with a body: let Python frame and drain it */
                if (!(vn == 1 && v[0] == '0')) return 0;
            } else if (weed_token_eq_ci(k, kn, "transfer-encoding") ||
                       weed_token_eq_ci(k, kn, "expect") ||
                       weed_token_eq_ci(k, kn, "if-none-match") ||
                       weed_token_eq_ci(k, kn, "if-modified-since") ||
                       weed_token_eq_ci(k, kn, "etag-md5") ||
                       weed_token_eq_ci(k, kn, "x-weed-deadline")) {
                /* conditional / framing / deadline semantics live in
                 * Python (the mini loop parses the budget, 504-fast-
                 * rejects expired ones, and scopes the ambient
                 * deadline around dispatch — docs/CHAOS.md) */
                return 0;
            } else if (weed_token_eq_ci(k, kn, "range")) {
                if (req->range != NULL) return 0;  /* duplicate Range */
                req->range = v;
                req->range_len = vn;
            } else if (weed_token_eq_ci(k, kn, "x-weed-trace")) {
                req->trace = v;
                req->trace_len = vn;
            }
        }
        line = le + 2;
    }
    *keep_alive = ka;
    return 1;
}

/* ---- response writing ---------------------------------------------- */

/* 1 = fully written, 0 = would block (EPOLLOUT pending), -1 = dead */
static int weed_conn_flush(weed_conn *c) {
    while (c->wpos < c->wlen) {
        ssize_t n = send(c->fd, c->wbuf + c->wpos, c->wlen - c->wpos,
                         MSG_NOSIGNAL);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
            if (errno == EINTR) continue;
            return -1;
        }
        c->wpos += (size_t)n;
    }
    while (c->body_left > 0) {
        off_t off = (off_t)c->body_off;
        size_t chunk = c->body_left < WEED_SERVE_SENDFILE_CHUNK
                           ? c->body_left
                           : WEED_SERVE_SENDFILE_CHUNK;
        ssize_t n = sendfile(c->fd, c->body_fd, &off, chunk);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
            if (errno == EINTR) continue;
            return -1;
        }
        if (n == 0) return -1;  /* source truncated under us: the
                                   promised Content-Length cannot be
                                   met — kill the connection so the
                                   client sees a short read, never
                                   silent corruption */
        c->body_off = (int64_t)off;
        c->body_left -= (size_t)n;
    }
    return 1;
}

/* process buffered requests until blocked.  Returns 0 to keep the
 * connection in the loop, -1 when it left (destroyed or handed off). */
static int weed_conn_process(weed_loop *lp, weed_conn *c) {
    while (!c->writing) {
        size_t avail = c->rlen - c->rpos;
        if (avail < 4) break;
        if (c->scan < c->rpos) c->scan = c->rpos;
        ssize_t he = weed_find_head_end(c->rbuf, c->rlen, c->scan);
        if (he < 0) {
            c->scan = c->rlen >= 3 ? c->rlen - 3 : 0;
            if (avail > WEED_SERVE_HEAD_LIMIT) {
                weed_conn_handoff(lp, c);  /* Python replies 431 */
                return -1;
            }
            break;
        }
        size_t head_len = (size_t)he + 4 - c->rpos;
        if (head_len > WEED_SERVE_HEAD_LIMIT) {
            /* a COMPLETE head past the cap: the incomplete-head check
             * above never fires when the whole head coalesced into one
             * buffered read — hand off so Python's read_head replies
             * 431 instead of serving the oversized request as 200 */
            weed_conn_handoff(lp, c);
            return -1;
        }

        double tp0 = weed_now_s();
        weed_req req;
        int keep_alive = 1;
        if (!weed_parse_head(c->rbuf + c->rpos, head_len, &req, &keep_alive) ||
            lp->cbs->resolve == NULL) {
            weed_conn_handoff(lp, c);
            return -1;
        }
        c->t_parse = weed_now_s() - tp0;

        weed_resp resp;
        memset(&resp, 0, sizeof(resp));
        resp.fd = -1;
        void *token = NULL;
        double tr0 = weed_now_s();
        int rc = lp->cbs->resolve(lp->cbs->ctx, &req, &resp, &token);
        c->t_resolve = weed_now_s() - tr0;
        if (rc == 0) {
            weed_conn_handoff(lp, c);
            return -1;
        }
        if (rc < 0) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }

        c->rpos += head_len;
        c->scan = c->rpos;
        c->nreqs++;
        int close_now =
            !keep_alive || (lp->max_reqs > 0 && c->nreqs >= lp->max_reqs);
        c->closing = close_now;

        /* assemble head exactly as fast_reply does: resolver prefix
         * (status line + headers), optional Connection: close, then
         * Content-Length last */
        size_t body_total = resp.fd >= 0 ? resp.count : resp.body_len;
        char tail[64];
        int tn = snprintf(tail, sizeof(tail), "Content-Length: %zu\r\n\r\n",
                          body_total);
        c->wlen = c->wpos = 0;
        int oom = weed_wbuf_append(c, resp.prefix, resp.prefix_len);
        if (!oom && close_now)
            oom = weed_wbuf_append(c, "Connection: close\r\n", 19);
        if (!oom) oom = weed_wbuf_append(c, tail, (size_t)tn);
        if (!oom && !req.head_only && resp.fd < 0 && resp.body_len > 0)
            oom = weed_wbuf_append(c, resp.body, resp.body_len);
        c->token = token;
        c->status = resp.status;
        c->resp_bytes = c->wlen + (req.head_only ? 0 : (resp.fd >= 0 ? resp.count : 0));
        if (oom) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
        if (!req.head_only && resp.fd >= 0 && resp.count > 0) {
            c->body_fd = resp.fd;
            c->body_off = resp.off;
            c->body_left = resp.count;
            c->close_body_fd = resp.close_fd;
        } else if (resp.fd >= 0 && resp.close_fd) {
            close(resp.fd);  /* HEAD / empty body: nothing to send */
        }
        c->writing = 1;
        c->t_send0 = weed_now_s();
        int wr = weed_conn_flush(c);
        if (wr < 0) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
        if (wr == 0) {
            if (weed_conn_interest(lp, c, EPOLLOUT) < 0) {
                weed_conn_destroy(lp, c, 1);
                return -1;
            }
            return 0;
        }
        weed_conn_release_resp(lp, c, 1);
        c->writing = 0;
        c->wlen = c->wpos = 0;
        if (c->closing) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
        if (c->rpos == c->rlen) {
            c->rpos = c->rlen = c->scan = 0;  /* cheap full reset */
        }
    }
    if (c->eof && !c->writing) {
        /* pipeline drained (or never complete) after FIN: done */
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    return 0;
}

/* One write attempt on an in-flight response, shared by the EPOLLOUT
 * handler and the idle-reaper's drain probe.  Returns -1 when the
 * connection left the loop (destroyed or handed off), else 0; partial
 * progress touches the idle LRU (a slow-but-draining client is active,
 * not idle), completion finishes the response and resumes the
 * pipeline. */
static int weed_conn_flush_step(weed_loop *lp, weed_conn *c) {
    size_t wpos0 = c->wpos, left0 = c->body_left;
    int wr = weed_conn_flush(c);
    if (wr < 0) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    if (wr == 0) {
        if (c->wpos != wpos0 || c->body_left != left0)
            weed_lru_touch(lp, c);
        return 0;
    }
    weed_conn_release_resp(lp, c, 1);
    c->writing = 0;
    c->wlen = c->wpos = 0;
    weed_lru_touch(lp, c);
    if (c->closing) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    if (weed_conn_interest(lp, c, EPOLLIN) < 0) {
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    return weed_conn_process(lp, c);
}

static int weed_conn_read(weed_loop *lp, weed_conn *c) {
    for (;;) {
        if (weed_rbuf_reserve(c, 4096) < 0) {
            weed_conn_destroy(lp, c, 1);
            return -1;
        }
        ssize_t n = recv(c->fd, c->rbuf + c->rlen, c->rcap - c->rlen, 0);
        if (n > 0) {
            c->rlen += (size_t)n;
            if (c->rlen < c->rcap) break;  /* short read: drained */
            continue;
        }
        if (n == 0) {  /* FIN: serve what is buffered, then close */
            c->eof = 1;
            break;
        }
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        if (errno == EINTR) continue;
        weed_conn_destroy(lp, c, 1);
        return -1;
    }
    weed_lru_touch(lp, c);
    return weed_conn_process(lp, c);
}

static void weed_accept_drain(weed_loop *lp) {
    for (;;) {
        struct sockaddr_storage ss;
        socklen_t slen = sizeof(ss);
        int fd = accept4(lp->listen_fd, (struct sockaddr *)&ss, &slen,
                         SOCK_NONBLOCK | SOCK_CLOEXEC);
        if (fd < 0) {
            if (errno == EINTR) continue;
            if (errno == EMFILE || errno == ENFILE) {
                /* fd exhaustion: the backlog stays non-empty, so the
                 * level-triggered listen event would re-fire every
                 * epoll round in a hot spin — park the listen fd and
                 * re-arm after a beat */
                epoll_ctl(lp->epfd, EPOLL_CTL_DEL, lp->listen_fd, NULL);
                lp->listen_paused_until_ms = weed_now_ms() + 100;
            }
            return;  /* EAGAIN / ECONNABORTED: next listen event retries */
        }
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        weed_conn *c = calloc(1, sizeof(weed_conn));
        if (c == NULL) {
            close(fd);
            continue;
        }
        c->fd = fd;
        c->body_fd = -1;
        c->ip[0] = '\0';
        if (ss.ss_family == AF_INET) {
            const struct sockaddr_in *a = (const struct sockaddr_in *)&ss;
            const uint8_t *b = (const uint8_t *)&a->sin_addr;
            snprintf(c->ip, sizeof(c->ip), "%u.%u.%u.%u", b[0], b[1], b[2],
                     b[3]);
            c->port = (int)ntohs(a->sin_port);
        } else if (ss.ss_family == AF_INET6) {
            const struct sockaddr_in6 *a6 = (const struct sockaddr_in6 *)&ss;
            const uint8_t *b = (const uint8_t *)&a6->sin6_addr;
            /* enough fidelity for logs/ACL checks on the data plane */
            snprintf(c->ip, sizeof(c->ip),
                     "%x:%x:%x:%x:%x:%x:%x:%x",
                     (b[0] << 8) | b[1], (b[2] << 8) | b[3],
                     (b[4] << 8) | b[5], (b[6] << 8) | b[7],
                     (b[8] << 8) | b[9], (b[10] << 8) | b[11],
                     (b[12] << 8) | b[13], (b[14] << 8) | b[15]);
            c->port = (int)ntohs(a6->sin6_port);
        }
        /* link into LRU tail */
        c->prev = lp->lru.prev;
        c->next = &lp->lru;
        lp->lru.prev->next = c;
        lp->lru.prev = c;
        c->last_ms = weed_now_ms();
        struct epoll_event ev;
        memset(&ev, 0, sizeof(ev));
        ev.events = EPOLLIN | EPOLLRDHUP;
        ev.data.ptr = c;
        if (epoll_ctl(lp->epfd, EPOLL_CTL_ADD, fd, &ev) < 0) {
            weed_lru_unlink(c);
            close(fd);
            free(c);
        }
    }
}

static void weed_expire_idle(weed_loop *lp) {
    if (lp->idle_ms <= 0) return;
    int64_t cutoff = weed_now_ms() - lp->idle_ms;
    while (lp->lru.next != &lp->lru && lp->lru.next->last_ms < cutoff) {
        weed_conn *c = lp->lru.next;
        if (c->writing) {
            /* EPOLLOUT cadence cannot prove drain progress: TCP only
             * reports writable once the send queue falls below HALF
             * full, so a client sipping a multi-MB buffered body sees
             * zero events for whole idle windows.  send()/sendfile()
             * have no such threshold — they accept bytes whenever ANY
             * space exists — so probe by flushing: moved bytes = a
             * live, draining client (flush_step touches the LRU);
             * zero bytes across a full idle window = a true stall.
             * A stalled writer therefore dies within two idle
             * windows, mirroring the threaded arm's stall-retry
             * sendall. */
            if (weed_conn_flush_step(lp, c) < 0)
                continue;  /* left the loop (done+closing, or dead) */
            if (c->last_ms >= cutoff)
                continue;  /* progressed (or completed): re-read next */
        }
        weed_conn_destroy(lp, c, 1);
    }
}

/* tags for the two non-connection epoll registrations */
static int weed_tag_listen;
static int weed_tag_wake;

/* Run the loop until a byte arrives on wake_fd.  Returns 0 on clean
 * shutdown, -errno when setup fails.  listen_fd and wake_fd are NOT
 * closed (the embedder owns them); every connection fd is. */
static int weed_serve_loop(int listen_fd, int wake_fd, weed_serve_cbs *cbs,
                           long idle_ms, long max_reqs) {
    weed_loop lp;
    memset(&lp, 0, sizeof(lp));
    lp.listen_fd = listen_fd;
    lp.wake_fd = wake_fd;
    lp.cbs = cbs;
    lp.idle_ms = idle_ms;
    lp.max_reqs = max_reqs;
    lp.lru.next = lp.lru.prev = &lp.lru;
    lp.epfd = epoll_create1(EPOLL_CLOEXEC);
    if (lp.epfd < 0) return -errno;

    int fl = fcntl(listen_fd, F_GETFL, 0);
    if (fl >= 0) fcntl(listen_fd, F_SETFL, fl | O_NONBLOCK);

    struct epoll_event ev;
    memset(&ev, 0, sizeof(ev));
    ev.events = EPOLLIN;
    ev.data.ptr = &weed_tag_listen;
    if (epoll_ctl(lp.epfd, EPOLL_CTL_ADD, listen_fd, &ev) < 0) {
        int e = errno;
        close(lp.epfd);
        return -e;
    }
    memset(&ev, 0, sizeof(ev));
    ev.events = EPOLLIN;
    ev.data.ptr = &weed_tag_wake;
    if (epoll_ctl(lp.epfd, EPOLL_CTL_ADD, wake_fd, &ev) < 0) {
        int e = errno;
        close(lp.epfd);
        return -e;
    }

    struct epoll_event events[WEED_SERVE_EVENTS];
    while (!lp.stop) {
        int timeout = -1;
        if (lp.idle_ms > 0 && lp.lru.next != &lp.lru) {
            int64_t dl = lp.lru.next->last_ms + lp.idle_ms - weed_now_ms();
            timeout = dl < 0 ? 0 : (dl > 1000 ? 1000 : (int)dl);
        }
        if (lp.listen_paused_until_ms) {
            int64_t dl = lp.listen_paused_until_ms - weed_now_ms();
            if (dl <= 0) {
                memset(&ev, 0, sizeof(ev));
                ev.events = EPOLLIN;
                ev.data.ptr = &weed_tag_listen;
                epoll_ctl(lp.epfd, EPOLL_CTL_ADD, listen_fd, &ev);
                lp.listen_paused_until_ms = 0;
            } else if (timeout < 0 || dl < timeout) {
                timeout = (int)dl;
            }
        }
        int n = epoll_wait(lp.epfd, events, WEED_SERVE_EVENTS, timeout);
        if (n < 0) {
            if (errno == EINTR) continue;
            break;
        }
        for (int i = 0; i < n && !lp.stop; i++) {
            void *tag = events[i].data.ptr;
            if (tag == &weed_tag_wake) {
                char drain[64];
                while (read(wake_fd, drain, sizeof(drain)) > 0) {}
                lp.stop = 1;
                break;
            }
            if (tag == &weed_tag_listen) {
                weed_accept_drain(&lp);
                continue;
            }
            weed_conn *c = (weed_conn *)tag;
            uint32_t evs = events[i].events;
            if (evs & (EPOLLERR | EPOLLHUP)) {
                weed_conn_destroy(&lp, c, 1);
                continue;
            }
            if (c->writing) {
                if (evs & EPOLLOUT) weed_conn_flush_step(&lp, c);
                continue;
            }
            if (evs & (EPOLLIN | EPOLLRDHUP)) weed_conn_read(&lp, c);
        }
        weed_expire_idle(&lp);
    }

    while (lp.lru.next != &lp.lru) weed_conn_destroy(&lp, lp.lru.next, 1);
    close(lp.epfd);
    return 0;
}

#endif /* WEED_SERVE_C */
