"""Native runtime shims (the reference's vendored-assembly tier).

The reference's performance-critical native code is vendored Go
assembly: klauspost/crc32 (SSE4.2 Castagnoli, needle/crc.go:8) and
klauspost/reedsolomon (AVX2 GF(2^8), ec_encoder.go:13). This package
supplies both counterparts as small C libraries compiled lazily with
the system compiler and loaded via ctypes — no pybind11/pip needed:

  crc32c.c  hardware CRC-32C           → `from seaweedfs_tpu.native import crc32c`
  gf256.c   SIMD GF(2^8) matrix apply  → `seaweedfs_tpu.native.gf`
            (the "native" EC codec backend; the TPU SWAR kernel in
            ec/codec_tpu.py serves accelerator hosts instead)

When no compiler is available the pure-Python/numpy fallbacks serve:
util/crc.py slicing-by-8 and the "cpu" numpy LUT codec backend.
Importing a missing shim raises ImportError, which the callers catch.
"""

from __future__ import annotations

import ctypes

from seaweedfs_tpu.native import _build

_lib = _build.load("crc32c.c", "_crc32c.so")
if _lib is not None:
    try:
        _lib.weed_crc32c.restype = ctypes.c_uint32
        _lib.weed_crc32c.argtypes = (
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_size_t,
        )
    except OSError:
        _lib = None

if _lib is not None:

    def crc32c(data, crc: int = 0) -> int:
        """Hardware-accelerated CRC-32C (SSE4.2 when the CPU has it).
        Accepts any bytes-like object, matching the Python fallback.
        Writable buffers (bytearray, memoryview of one) are addressed
        zero-copy — at native CRC speed a bytes() round-trip of the
        input is a measurable fraction of the whole call."""
        if isinstance(data, bytes):
            return _lib.weed_crc32c(crc & 0xFFFFFFFF, data, len(data))
        mv = memoryview(data)
        if not mv.contiguous:
            b = bytes(mv)
            return _lib.weed_crc32c(crc & 0xFFFFFFFF, b, len(b))
        n = mv.nbytes
        if mv.readonly:
            b = bytes(mv)
            return _lib.weed_crc32c(crc & 0xFFFFFFFF, b, n)
        arr = (ctypes.c_char * n).from_buffer(mv)
        try:
            return _lib.weed_crc32c(crc & 0xFFFFFFFF, arr, n)
        finally:
            del arr  # release the buffer export before mv goes away


# needle record serializer + one-pass POST hot loop: a CPython
# extension, not ctypes — the many-field signatures would cost more in
# ctypes conversion than the serialization itself (native/needle_ext.c;
# _build scans the #include graph, so staleness tracks needle.c,
# crc32c.c, and post.c without a hand-maintained deps tuple)
needle_ext = _build.load_ext("needle_ext.c", "_needle_ext")

# event-driven serving core (native/serve.c behind serve_ext.c): the
# epoll accept/read/dispatch loop with the zero-copy sendfile GET fast
# path (docs/SERVING.md). Linux-only by design — on hosts where the
# epoll/sendfile includes don't exist the build fails and every daemon
# keeps the threaded mini request loop.
serve_ext = _build.load_ext("serve_ext.c", "_serve_ext")
