"""Native runtime shims (the reference's vendored-assembly tier).

The reference's only native code is vendored Go assembly:
klauspost/crc32 (SSE4.2 Castagnoli, needle/crc.go:8) and
klauspost/reedsolomon AVX2 (replaced here by the TPU SWAR kernel,
ec/codec_tpu.py). This package supplies the CRC counterpart as a small
C library compiled lazily with the system compiler and loaded via
ctypes — no pybind11/pip needed. When no compiler is available the
pure-Python slicing-by-8 fallback in util/crc.py serves instead.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_crc32c.so")
_SRC_PATH = os.path.join(_HERE, "crc32c.c")


def _build() -> str | None:
    """Compile crc32c.c → _crc32c.so (cached; rebuilt when stale)."""
    try:
        if os.path.exists(_SO_PATH) and os.path.getmtime(
            _SO_PATH
        ) >= os.path.getmtime(_SRC_PATH):
            return _SO_PATH
        for cc in ("cc", "gcc", "g++", "clang"):
            # build to a temp file then rename: concurrent importers
            # must never dlopen a half-written .so
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
            os.close(fd)
            try:
                proc = subprocess.run(
                    [cc, "-O2", "-shared", "-fPIC", "-o", tmp, _SRC_PATH],
                    capture_output=True,
                    timeout=60,
                )
                if proc.returncode == 0:
                    os.replace(tmp, _SO_PATH)
                    return _SO_PATH
            except (OSError, subprocess.TimeoutExpired):
                pass
            finally:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    except OSError:
        pass
    return None


_lib = None
_so = _build()
if _so is not None:
    try:
        _lib = ctypes.CDLL(_so)
        _lib.weed_crc32c.restype = ctypes.c_uint32
        _lib.weed_crc32c.argtypes = (
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_size_t,
        )
    except OSError:
        _lib = None

if _lib is None:  # surface as ImportError so util/crc.py falls back
    raise ImportError("native crc32c unavailable (no compiler or load failed)")


def crc32c(data, crc: int = 0) -> int:
    """Hardware-accelerated CRC-32C (SSE4.2 when the CPU has it).
    Accepts any bytes-like object, matching the Python fallback."""
    if not isinstance(data, bytes):
        data = bytes(data)
    return _lib.weed_crc32c(crc & 0xFFFFFFFF, data, len(data))
