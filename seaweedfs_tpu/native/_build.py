"""Lazy compile-and-load for the native shims.

Each shim is one C file next to this module, compiled with whatever
system compiler is present and loaded via ctypes — no pybind11/pip.
Callers treat a None return as "no native path" and fall back to their
pure-Python/numpy implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))


def load(src_name: str, so_name: str) -> ctypes.CDLL | None:
    """Compile src_name → so_name (cached; rebuilt when stale) and dlopen it."""
    src = os.path.join(_HERE, src_name)
    so = os.path.join(_HERE, so_name)
    built = None
    try:
        if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
            built = so
        else:
            for cc in ("cc", "gcc", "g++", "clang"):
                # build to a temp file then rename: concurrent importers
                # must never dlopen a half-written .so
                fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
                os.close(fd)
                try:
                    proc = subprocess.run(
                        [cc, "-O2", "-shared", "-fPIC", "-o", tmp, src],
                        capture_output=True,
                        timeout=60,
                    )
                    if proc.returncode == 0:
                        os.replace(tmp, so)
                        built = so
                        break
                except (OSError, subprocess.TimeoutExpired):
                    pass
                finally:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
    except OSError:
        pass
    if built is None:
        return None
    try:
        return ctypes.CDLL(built)
    except OSError:
        return None
