"""Lazy compile-and-load for the native shims.

Each shim is one C file next to this module, compiled with whatever
system compiler is present — no pybind11/pip. Two loaders share one
compile cache: `load` dlopens a plain shared object via ctypes;
`load_ext` imports a CPython extension module (for bindings too hot
for ctypes argument conversion, like the needle serializer). Callers
treat a None return as "no native path" and fall back to their
pure-Python/numpy implementations.

Staleness: a cached .so is rebuilt whenever the source — or anything
it (transitively) `#include "..."`s — is newer than the artifact. The
include graph is scanned from the sources themselves, so adding an
include never silently ships old code because a caller forgot to
update a deps tuple (that bit during PR 2's needle_ext GIL change:
the .so predated the edited needle.c and kept loading). When the
artifact is stale and no compiler works, the loader WARNS and returns
None (pure-Python fallback) rather than dlopening the old code.
"""

from __future__ import annotations

import ctypes
import os
import re
import subprocess
import tempfile
import warnings

_HERE = os.path.dirname(os.path.abspath(__file__))

_COMPILERS = ("cc", "gcc", "g++", "clang")

_INCLUDE_RE = re.compile(rb'^[ \t]*#[ \t]*include[ \t]*"([^"]+)"', re.M)


def _local_includes(src: str, seen: set[str] | None = None) -> set[str]:
    """Transitive `#include "..."` closure of `src`, resolved relative
    to this directory (all shims live flat here). Missing files are
    ignored — the compiler will say so louder."""
    if seen is None:
        seen = set()
    try:
        with open(src, "rb") as f:
            text = f.read()
    except OSError:
        return seen
    for m in _INCLUDE_RE.finditer(text):
        name = m.group(1).decode("utf-8", "replace")
        path = os.path.join(_HERE, os.path.basename(name))
        if path in seen or not os.path.exists(path):
            continue
        seen.add(path)
        _local_includes(path, seen)
    return seen


def _compile(src: str, so: str, deps: tuple[str, ...], includes: tuple[str, ...]) -> str | None:
    """Compile src → so unless the cached .so is newer than src AND
    every #included dep (scanned from the sources + any caller-passed
    extras). Returns the .so path, or None when no compiler worked.
    Builds to a temp file then renames: concurrent importers must
    never dlopen a half-written .so."""
    try:
        dep_paths = {src}
        dep_paths.update(os.path.join(_HERE, d) for d in deps)
        dep_paths.update(_local_includes(src))
        newest_src = max(
            os.path.getmtime(p) for p in dep_paths if os.path.exists(p)
        )
        if os.path.exists(so) and os.path.getmtime(so) >= newest_src:
            return so
        stale = os.path.exists(so)
        for cc in _COMPILERS:
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
            os.close(fd)
            try:
                proc = subprocess.run(
                    [cc, "-O2", "-shared", "-fPIC"]
                    + [f"-I{i}" for i in includes]
                    + ["-o", tmp, src],
                    capture_output=True,
                    timeout=60,
                )
                if proc.returncode == 0:
                    os.replace(tmp, so)
                    return so
            except (OSError, subprocess.TimeoutExpired):
                pass
            finally:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        if stale:
            # an out-of-date artifact exists but cannot be rebuilt on
            # this host: never load it silently — the pure-Python
            # fallback is slower but correct
            warnings.warn(
                f"{os.path.basename(so)} is stale (source newer than the "
                "built artifact) and no working C compiler was found; "
                "falling back to the pure-Python path",
                RuntimeWarning,
                stacklevel=2,
            )
    except OSError:
        pass
    return None


def load(src_name: str, so_name: str, deps: tuple[str, ...] = ()) -> ctypes.CDLL | None:
    """Compile src_name → so_name (cached; rebuilt when stale) and dlopen it."""
    built = _compile(os.path.join(_HERE, src_name), os.path.join(_HERE, so_name), deps, ())
    if built is None:
        return None
    try:
        return ctypes.CDLL(built)
    except OSError:
        return None


def load_ext(src_name: str, mod_name: str, deps: tuple[str, ...] = ()):
    """Compile a CPython extension source → <mod_name>.so and import it.
    Returns the module, or None (callers fall back to pure Python)."""
    import importlib.util
    import sysconfig

    paths = sysconfig.get_paths()
    includes = tuple(dict.fromkeys((paths["include"], paths["platinclude"])))
    built = _compile(
        os.path.join(_HERE, src_name),
        os.path.join(_HERE, mod_name + ".so"),
        deps,
        includes,
    )
    if built is None:
        return None
    try:
        spec = importlib.util.spec_from_file_location(mod_name, built)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except (ImportError, OSError):
        return None
