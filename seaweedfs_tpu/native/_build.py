"""Lazy compile-and-load for the native shims.

Each shim is one C file next to this module, compiled with whatever
system compiler is present — no pybind11/pip. Two loaders share one
compile cache: `load` dlopens a plain shared object via ctypes;
`load_ext` imports a CPython extension module (for bindings too hot
for ctypes argument conversion, like the needle serializer). Callers
treat a None return as "no native path" and fall back to their
pure-Python/numpy implementations.

Staleness: a cached .so is rebuilt whenever the source — or anything
it (transitively) `#include "..."`s — is newer than the artifact. The
include graph is scanned from the sources themselves, so adding an
include never silently ships old code because a caller forgot to
update a deps tuple (that bit during PR 2's needle_ext GIL change:
the .so predated the edited needle.c and kept loading). When the
artifact is stale and no compiler works, the loader WARNS and returns
None (pure-Python fallback) rather than dlopening the old code.

Hardening (weedlint C tier, docs/ANALYSIS.md): every build runs with
-Wall -Wextra -Werror — the shims are the one part of the tree no
interpreter-level tooling can see into, so the compiler's analysis is
the lint tier and a warning is a build failure, never a note lost in a
subprocess pipe. `WEED_NATIVE_SAN=asan|ubsan|tsan` switches the whole
shim tier to a sanitizer build (separate artifact names, so sanitized
and production caches never collide). A sanitizer .so only dlopens
when its runtime is preloaded; `san_preload_env()` hands callers the
LD_PRELOAD recipe per mode — for TSan with
`ignore_noninstrumented_modules=1`, because the interpreter itself is
not instrumented and only races with an instrumented shim frame (the
epoll loop, the shm GCRA bucket) are this tier's business.
"""

from __future__ import annotations

import ctypes
import os
import re
import subprocess
import tempfile
import warnings

_HERE = os.path.dirname(os.path.abspath(__file__))

_COMPILERS = ("cc", "gcc", "g++", "clang")

# the compiler IS the C tier's linter: keep every shim warning-clean
# (blanket suppressions are a weedlint finding, not a fix)
_WARN_FLAGS = ("-Wall", "-Wextra", "-Werror")

_SAN_FLAGS = {
    "asan": (
        "-O1", "-g", "-fsanitize=address", "-fno-omit-frame-pointer",
    ),
    "ubsan": (
        "-O1", "-g", "-fsanitize=undefined",
        "-fno-sanitize-recover=undefined", "-fno-omit-frame-pointer",
    ),
    "tsan": (
        "-O1", "-g", "-fsanitize=thread", "-fno-omit-frame-pointer",
    ),
}

# the runtime each sanitizer mode must have preloaded before a stock
# (uninstrumented) python can dlopen a shim built in that mode
_SAN_RUNTIMES = {"asan": "libasan.so", "tsan": "libtsan.so"}

_INCLUDE_RE = re.compile(rb'^[ \t]*#[ \t]*include[ \t]*"([^"]+)"', re.M)


def san_mode() -> str:
    """'' (production), 'asan', 'ubsan', or 'tsan' — WEED_NATIVE_SAN."""
    mode = os.environ.get("WEED_NATIVE_SAN", "").strip().lower()
    return mode if mode in _SAN_FLAGS else ""


def _san_so_name(so_name: str, mode: str) -> str:
    """Sanitized artifacts get their own cache names (_crc32c.asan.so):
    a sanitizer .so silently replacing the production cache would make
    every later plain run dlopen-fail into the slow Python fallback."""
    if not mode:
        return so_name
    base, ext = os.path.splitext(so_name)
    return f"{base}.{mode}{ext}"


def san_preload_env(mode: str | None = None) -> dict[str, str] | None:
    """Env additions that let a stock (uninstrumented) python dlopen a
    shim built in `mode` (default: the active san_mode()): LD_PRELOAD
    the compiler's matching runtime. None when no compiler can name
    one, or the mode needs no preload (ubsan links its runtime in).

    asan: detect_leaks=0 because CPython itself "leaks" interned/static
    allocations at exit; the point is heap-corruption coverage of the C
    parsers, not CPython leak audits. tsan:
    ignore_noninstrumented_modules=1 because every interpreter-internal
    access would otherwise report — only races touching an instrumented
    shim frame are signal; halt_on_error=1 so a detected data race
    fails the test run instead of scrolling past in stderr."""
    mode = san_mode() if mode is None else mode
    runtime = _SAN_RUNTIMES.get(mode)
    if runtime is None:
        return None
    for cc in _COMPILERS:
        try:
            proc = subprocess.run(
                [cc, f"-print-file-name={runtime}"],
                capture_output=True,
                timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        path = proc.stdout.decode().strip()
        if proc.returncode == 0 and os.path.isabs(path) and os.path.exists(path):
            env = {"LD_PRELOAD": path}
            if mode == "asan":
                env["ASAN_OPTIONS"] = (
                    "detect_leaks=0:verify_asan_link_order=0"
                )
            elif mode == "tsan":
                env["TSAN_OPTIONS"] = (
                    "ignore_noninstrumented_modules=1:halt_on_error=1"
                )
            return env
    return None


def asan_preload_env() -> dict[str, str] | None:
    """The ASan-specific recipe (pre-tsan-tier name, kept for its
    existing call sites)."""
    return san_preload_env("asan")


def _local_includes(src: str, seen: set[str] | None = None) -> set[str]:
    """Transitive `#include "..."` closure of `src`, resolved relative
    to this directory (all shims live flat here). Missing files are
    ignored — the compiler will say so louder."""
    if seen is None:
        seen = set()
    try:
        with open(src, "rb") as f:
            text = f.read()
    except OSError:
        return seen
    for m in _INCLUDE_RE.finditer(text):
        name = m.group(1).decode("utf-8", "replace")
        path = os.path.join(_HERE, os.path.basename(name))
        if path in seen or not os.path.exists(path):
            continue
        seen.add(path)
        _local_includes(path, seen)
    return seen


def compile_cmd(
    cc: str,
    src: str,
    out: str,
    includes: tuple[str, ...] = (),
    warn_flags: tuple[str, ...] = _WARN_FLAGS,
) -> list[str]:
    """The ONE cc command line for a native shim: production builds
    (`_compile`) and the weedlint c-warnings tier both use exactly
    this, so the lint tier can never drift from what actually ships."""
    mode = san_mode()
    opt = _SAN_FLAGS[mode] if mode else ("-O2",)
    return (
        [cc, *opt, "-shared", "-fPIC", *warn_flags]
        + [f"-I{i}" for i in includes]
        + ["-o", out, src]
    )


def _compile(src: str, so: str, deps: tuple[str, ...], includes: tuple[str, ...]) -> str | None:
    """Compile src → so unless the cached .so is newer than src AND
    every #included dep (scanned from the sources + any caller-passed
    extras). Returns the .so path, or None when no compiler worked.
    Builds to a temp file then renames: concurrent importers must
    never dlopen a half-written .so."""
    try:
        dep_paths = {src}
        dep_paths.update(os.path.join(_HERE, d) for d in deps)
        dep_paths.update(_local_includes(src))
        newest_src = max(
            os.path.getmtime(p) for p in dep_paths if os.path.exists(p)
        )
        if os.path.exists(so) and os.path.getmtime(so) >= newest_src:
            return so
        stale = os.path.exists(so)
        for cc in _COMPILERS:
            # -Werror first (the lint contract), but a FUTURE compiler
            # inventing a new -Wextra diagnostic must not silently
            # demote the whole native tier to the Python fallback:
            # when the -Werror failure was warning-promoted (and only
            # then — a hard error retried is just doubled latency),
            # retry warnings-non-fatal and make the debt loud. The
            # weedlint c-warnings check still fails the tree until the
            # warning is fixed.
            for warn_flags in (_WARN_FLAGS, _WARN_FLAGS[:-1]):
                fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
                os.close(fd)
                try:
                    proc = subprocess.run(
                        compile_cmd(
                            cc, src, tmp, includes, warn_flags
                        ),
                        capture_output=True,
                        timeout=60,
                    )
                    if proc.returncode == 0:
                        if "-Werror" not in warn_flags:
                            warnings.warn(
                                f"{os.path.basename(src)} only compiles "
                                f"with warnings on this host ({cc}); "
                                f"loading it anyway — run `python -m "
                                f"seaweedfs_tpu.analysis --rules c` and "
                                f"fix the diagnostics",
                                RuntimeWarning,
                                stacklevel=2,
                            )
                        # weedlint: ignore[crash-rename-no-dirsync] — rebuildable .so cache artifact; a lost publish recompiles on next import
                        os.replace(tmp, so)
                        return so
                    if b"-Werror" not in proc.stderr:
                        break  # hard error: the retry cannot help
                except (OSError, subprocess.TimeoutExpired):
                    break  # no such compiler / wedged: next compiler
                finally:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
        if stale:
            # an out-of-date artifact exists but cannot be rebuilt on
            # this host: never load it silently — the pure-Python
            # fallback is slower but correct
            warnings.warn(
                f"{os.path.basename(so)} is stale (source newer than the "
                "built artifact) and no working C compiler was found; "
                "falling back to the pure-Python path",
                RuntimeWarning,
                stacklevel=2,
            )
    except OSError:
        pass
    return None


def load(src_name: str, so_name: str, deps: tuple[str, ...] = ()) -> ctypes.CDLL | None:
    """Compile src_name → so_name (cached; rebuilt when stale) and dlopen it."""
    so_name = _san_so_name(so_name, san_mode())
    built = _compile(os.path.join(_HERE, src_name), os.path.join(_HERE, so_name), deps, ())
    if built is None:
        return None
    try:
        return ctypes.CDLL(built)
    except OSError:
        return None


def load_ext(src_name: str, mod_name: str, deps: tuple[str, ...] = ()):
    """Compile a CPython extension source → <mod_name>.so and import it.
    Returns the module, or None (callers fall back to pure Python)."""
    import importlib.util
    import sysconfig

    paths = sysconfig.get_paths()
    includes = tuple(dict.fromkeys((paths["include"], paths["platinclude"])))
    built = _compile(
        os.path.join(_HERE, src_name),
        os.path.join(_HERE, _san_so_name(mod_name + ".so", san_mode())),
        deps,
        includes,
    )
    if built is None:
        return None
    try:
        spec = importlib.util.spec_from_file_location(mod_name, built)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except (ImportError, OSError):
        return None
