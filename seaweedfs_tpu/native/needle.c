/* One-call needle record serialization: header + body + CRC + padding.
 *
 * Role match: the reference's prepareWriteBuffer
 * (weed/storage/needle/needle_read_write.go:31-120) builds the full
 * on-disk record in one buffer pass in Go; the Python to_bytes mirrors
 * it field-by-field but pays interpreter cost per field on the hottest
 * write path.  This shim does the same single pass in C, including the
 * Castagnoli checksum (shared implementation: crc32c.c is #included so
 * one dlopen carries both entry points).
 *
 * Layout written (big-endian, v2/v3 — needle.py module docstring):
 *   cookie u32 | id u64 | size u32
 *   [data_size u32 | data | flags u8 | optional fields...]   when data
 *   checksum u32 (masked)
 *   [append_at_ns u64]                                        v3
 *   padding 1..8 bytes to 8B alignment (reference quirk: never 0)
 */

#include <time.h>

#include "crc32c.c"

#define V3_TIMESTAMP 8
#define HEADER 16
#define CHECKSUM 4
#define PAD 8

/* Monotonic seconds for the tracing plane's stage timings. One
 * clock_gettime is ~20 ns — cheap enough to leave on unconditionally
 * in the hot loop (docs/TRACING.md budgets the whole span at <2%). */
static inline double w_monotonic(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

static inline void put_u32(uint8_t *p, uint32_t v) {
    p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
static inline void put_u64(uint8_t *p, uint64_t v) {
    p[0] = v >> 56; p[1] = v >> 48; p[2] = v >> 40; p[3] = v >> 32;
    p[4] = v >> 24; p[5] = v >> 16; p[6] = v >> 8; p[7] = v;
}

/* CRC2.0 mask (crc.go value()): tells recovered-from-disk checksums
 * apart from in-memory ones. */
static inline uint32_t masked(uint32_t crc) {
    return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

/* Worst-case record length for buffer sizing (name/mime capped at 255,
 * pairs < 64KiB enforced by the Python caller). name_len/mime_len stay
 * in the signature for call-site symmetry with weed_needle_encode, but
 * the bound uses their 255-byte caps, not the actual lengths. */
long weed_needle_max_size(uint32_t data_len, uint32_t name_len,
                          uint32_t mime_len, uint32_t pairs_len) {
    (void)name_len;
    (void)mime_len;
    return (long)HEADER + 4 + (long)data_len + 1 + 1 + 255 + 1 + 255 + 5 + 2 +
           2 + (long)pairs_len + CHECKSUM + V3_TIMESTAMP + PAD;
}

/* Serialize one record into out; returns total length (>0) or -1 on a
 * constraint violation.  size_out gets the stored `size` field,
 * crc_out the RAW (unmasked) CRC32-C of data.  crc_seconds (nullable)
 * receives the CRC pass's wall seconds so the tracing plane can report
 * the crc stage separately from record assembly. */
long weed_needle_encode(uint8_t *out, uint32_t cookie, uint64_t id,
                        const uint8_t *data, uint32_t data_len, uint32_t flags,
                        const uint8_t *name, uint32_t name_len,
                        const uint8_t *mime, uint32_t mime_len,
                        uint64_t last_modified, const uint8_t *ttl2,
                        const uint8_t *pairs, uint32_t pairs_len, int version,
                        uint64_t append_at_ns, uint32_t *size_out,
                        uint32_t *crc_out, double *crc_seconds) {
    if (mime_len > 255 || pairs_len > 65535 || (version != 1 && version != 2 && version != 3))
        return -1;
    if (name_len > 255) name_len = 255; /* NameSize u8 cap, as to_bytes */

    double tcrc = w_monotonic();
    uint32_t crc = weed_crc32c(0, data, data_len);
    if (crc_seconds) *crc_seconds = w_monotonic() - tcrc;
    *crc_out = crc;
    uint8_t *p = out + HEADER;
    uint32_t size;

    if (version == 1) {
        size = data_len;
        __builtin_memcpy(p, data, data_len);
        p += data_len;
    } else if (data_len > 0) {
        put_u32(p, data_len);
        p += 4;
        __builtin_memcpy(p, data, data_len);
        p += data_len;
        *p++ = (uint8_t)(flags & 0xFF);
        if (flags & 0x02) { /* FLAG_HAS_NAME */
            *p++ = (uint8_t)name_len;
            __builtin_memcpy(p, name, name_len);
            p += name_len;
        }
        if (flags & 0x04) { /* FLAG_HAS_MIME */
            *p++ = (uint8_t)mime_len;
            __builtin_memcpy(p, mime, mime_len);
            p += mime_len;
        }
        if (flags & 0x08) { /* FLAG_HAS_LAST_MODIFIED_DATE: low 5 bytes BE */
            *p++ = (uint8_t)(last_modified >> 32);
            *p++ = (uint8_t)(last_modified >> 24);
            *p++ = (uint8_t)(last_modified >> 16);
            *p++ = (uint8_t)(last_modified >> 8);
            *p++ = (uint8_t)last_modified;
        }
        if (flags & 0x10) { /* FLAG_HAS_TTL */
            *p++ = ttl2 ? ttl2[0] : 0;
            *p++ = ttl2 ? ttl2[1] : 0;
        }
        if (flags & 0x20) { /* FLAG_HAS_PAIRS */
            *p++ = (uint8_t)(pairs_len >> 8);
            *p++ = (uint8_t)pairs_len;
            __builtin_memcpy(p, pairs, pairs_len);
            p += pairs_len;
        }
        size = (uint32_t)(p - out - HEADER);
    } else {
        size = 0; /* empty body: tombstones / deletes */
    }

    put_u32(out, cookie);
    put_u64(out + 4, id);
    put_u32(out + 12, size);

    put_u32(p, masked(crc));
    p += 4;
    if (version == 3) {
        put_u64(p, append_at_ns);
        p += 8;
    }
    /* padding: ALWAYS 1..8 (needle_read_write.go:287 quirk) */
    long unpadded = (long)(p - out);
    long pad = PAD - (unpadded % PAD);
    for (long i = 0; i < pad; i++) *p++ = 0;

    *size_out = size;
    return (long)(p - out);
}
