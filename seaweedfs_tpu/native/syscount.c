/* LD_PRELOAD syscall-wrapper counter for the serving-edge bench
 * (bench.py serve-floor, docs/SERVING.md).
 *
 * The container ships no strace/perf, so the syscall-floor breakdown
 * is measured by interposing the libc wrappers the C serving loop
 * (native/serve.c) goes through: every call bumps a per-symbol
 * counter, and SIGUSR2 dumps the cumulative table to the file named
 * by $WEED_SYSCOUNT_OUT.  The bench snapshots before and after a
 * closed-loop GET window and divides the delta by the request count —
 * an external measurement of syscalls-per-request, not the loop's own
 * bookkeeping.
 *
 * Only wrappers are counted: raw syscall(2) users (futex from the
 * GIL, clock_nanosleep from time.sleep) never enter these PLT stubs,
 * which is exactly right — they are not part of the serving edge.
 *
 *   cc -O2 -shared -fPIC -o syscount.so syscount.c
 *   LD_PRELOAD=./syscount.so WEED_SYSCOUNT_OUT=/tmp/c.txt python ...
 */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

/* every wrapper symbol native/serve.c can reach */
#define WEED_COUNTED(X)                                                  \
    X(accept4) X(epoll_wait) X(epoll_ctl) X(recv) X(recvfrom) X(send)    \
    X(sendto) X(sendmsg) X(writev) X(write) X(read) X(sendfile)          \
    X(close) X(fcntl) X(setsockopt) X(dup) X(dup3)

enum {
#define WEED_ENUM(n) CNT_##n,
    WEED_COUNTED(WEED_ENUM)
#undef WEED_ENUM
        CNT_MAX
};

static const char *const weed_names[CNT_MAX] = {
#define WEED_NAME(n) #n,
    WEED_COUNTED(WEED_NAME)
#undef WEED_NAME
};

static unsigned long long weed_counts[CNT_MAX];
static unsigned long long weed_dump_gen;
static const char *weed_out_path;

static int (*real_close)(int);

static void *weed_real(const char *name) {
    void *fn = dlsym(RTLD_NEXT, name);
    if (fn == NULL) abort(); /* libc without the symbol: unusable rig */
    return fn;
}

#define BUMP(n) \
    __atomic_fetch_add(&weed_counts[CNT_##n], 1, __ATOMIC_RELAXED)

/* SIGUSR2: rewrite the dump file with the cumulative table. Only
 * async-signal-safe calls (open/write/close via the saved real
 * pointer so the dump's own close is not counted). */
static void weed_dump(int sig) {
    (void)sig;
    int saved = errno;
    char buf[2048];
    size_t off = 0;
    unsigned long long gen =
        __atomic_add_fetch(&weed_dump_gen, 1, __ATOMIC_RELAXED);
    off += (size_t)snprintf(buf + off, sizeof(buf) - off,
                            "gen %llu\n", gen);
    for (int i = 0; i < CNT_MAX; i++)
        off += (size_t)snprintf(
            buf + off, sizeof(buf) - off, "%s %llu\n", weed_names[i],
            __atomic_load_n(&weed_counts[i], __ATOMIC_RELAXED));
    int fd = open(weed_out_path ? weed_out_path : "/dev/null",
                  O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd >= 0) {
        ssize_t n = write(fd, buf, off);
        (void)n;
        if (real_close != NULL)
            real_close(fd);
    }
    errno = saved;
}

__attribute__((constructor)) static void weed_syscount_init(void) {
    weed_out_path = getenv("WEED_SYSCOUNT_OUT");
    real_close = (int (*)(int))weed_real("close");
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = weed_dump;
    sa.sa_flags = SA_RESTART;
    sigaction(SIGUSR2, &sa, NULL);
}

int accept4(int fd, struct sockaddr *addr, socklen_t *len, int flags) {
    static int (*real)(int, struct sockaddr *, socklen_t *, int);
    if (real == NULL) real = weed_real("accept4");
    BUMP(accept4);
    return real(fd, addr, len, flags);
}

int epoll_wait(int epfd, struct epoll_event *ev, int max, int timeout) {
    static int (*real)(int, struct epoll_event *, int, int);
    if (real == NULL) real = weed_real("epoll_wait");
    BUMP(epoll_wait);
    return real(epfd, ev, max, timeout);
}

int epoll_ctl(int epfd, int op, int fd, struct epoll_event *ev) {
    static int (*real)(int, int, int, struct epoll_event *);
    if (real == NULL) real = weed_real("epoll_ctl");
    BUMP(epoll_ctl);
    return real(epfd, op, fd, ev);
}

ssize_t recv(int fd, void *buf, size_t len, int flags) {
    static ssize_t (*real)(int, void *, size_t, int);
    if (real == NULL) real = weed_real("recv");
    BUMP(recv);
    return real(fd, buf, len, flags);
}

ssize_t recvfrom(int fd, void *buf, size_t len, int flags,
                 struct sockaddr *src, socklen_t *slen) {
    static ssize_t (*real)(int, void *, size_t, int, struct sockaddr *,
                           socklen_t *);
    if (real == NULL) real = weed_real("recvfrom");
    BUMP(recvfrom);
    return real(fd, buf, len, flags, src, slen);
}

ssize_t send(int fd, const void *buf, size_t len, int flags) {
    static ssize_t (*real)(int, const void *, size_t, int);
    if (real == NULL) real = weed_real("send");
    BUMP(send);
    return real(fd, buf, len, flags);
}

ssize_t sendto(int fd, const void *buf, size_t len, int flags,
               const struct sockaddr *dst, socklen_t dlen) {
    static ssize_t (*real)(int, const void *, size_t, int,
                           const struct sockaddr *, socklen_t);
    if (real == NULL) real = weed_real("sendto");
    BUMP(sendto);
    return real(fd, buf, len, flags, dst, dlen);
}

ssize_t sendmsg(int fd, const struct msghdr *msg, int flags) {
    static ssize_t (*real)(int, const struct msghdr *, int);
    if (real == NULL) real = weed_real("sendmsg");
    BUMP(sendmsg);
    return real(fd, msg, flags);
}

ssize_t writev(int fd, const struct iovec *iov, int iovcnt) {
    static ssize_t (*real)(int, const struct iovec *, int);
    if (real == NULL) real = weed_real("writev");
    BUMP(writev);
    return real(fd, iov, iovcnt);
}

ssize_t write(int fd, const void *buf, size_t len) {
    static ssize_t (*real)(int, const void *, size_t);
    if (real == NULL) real = weed_real("write");
    BUMP(write);
    return real(fd, buf, len);
}

ssize_t read(int fd, void *buf, size_t len) {
    static ssize_t (*real)(int, void *, size_t);
    if (real == NULL) real = weed_real("read");
    BUMP(read);
    return real(fd, buf, len);
}

ssize_t sendfile(int out_fd, int in_fd, off_t *off, size_t count) {
    static ssize_t (*real)(int, int, off_t *, size_t);
    if (real == NULL) real = weed_real("sendfile");
    BUMP(sendfile);
    return real(out_fd, in_fd, off, count);
}

int close(int fd) {
    if (real_close == NULL)
        real_close = (int (*)(int))weed_real("close");
    BUMP(close);
    return real_close(fd);
}

int fcntl(int fd, int cmd, ...) {
    static int (*real)(int, int, ...);
    if (real == NULL)
        real = (int (*)(int, int, ...))weed_real("fcntl");
    BUMP(fcntl);
    va_list ap;
    va_start(ap, cmd);
    void *arg = va_arg(ap, void *);
    va_end(ap);
    return real(fd, cmd, arg);
}

int setsockopt(int fd, int level, int opt, const void *val, socklen_t len) {
    static int (*real)(int, int, int, const void *, socklen_t);
    if (real == NULL) real = weed_real("setsockopt");
    BUMP(setsockopt);
    return real(fd, level, opt, val, len);
}

int dup(int fd) {
    static int (*real)(int);
    if (real == NULL) real = weed_real("dup");
    BUMP(dup);
    return real(fd);
}

int dup3(int oldfd, int newfd, int flags) {
    static int (*real)(int, int, int);
    if (real == NULL) real = weed_real("dup3");
    BUMP(dup3);
    return real(oldfd, newfd, flags);
}
