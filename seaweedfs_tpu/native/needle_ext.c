/* CPython extension binding for the one-call needle serializer.
 *
 * ctypes costs ~5us of argument conversion per call with this many
 * fields — more than the serialization itself.  A METH_FASTCALL
 * extension keeps the binding under ~1us, which is what the volume
 * write hot path needs (needle_read_write.go:31 prepareWriteBuffer is
 * a single buffer pass in the reference too; see needle.c for the
 * record layout).
 *
 * encode(cookie, id, data, flags, name, mime, last_modified,
 *        ttl2_or_None, pairs, version, append_at_ns)
 *   -> (record_bytes, size, raw_crc)
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "needle.c"
#include "post.c"

static PyObject *py_encode(PyObject *Py_UNUSED(self), PyObject *const *args,
                           Py_ssize_t nargs) {
    if (nargs != 11) {
        PyErr_SetString(PyExc_TypeError, "encode() takes 11 arguments");
        return NULL;
    }
    uint32_t cookie = (uint32_t)PyLong_AsUnsignedLongMask(args[0]);
    uint64_t id = PyLong_AsUnsignedLongLongMask(args[1]);
    uint32_t flags = (uint32_t)PyLong_AsUnsignedLongMask(args[3]);
    uint64_t last_modified = PyLong_AsUnsignedLongLongMask(args[6]);
    long version = PyLong_AsLong(args[9]);
    uint64_t append_at_ns = PyLong_AsUnsignedLongLongMask(args[10]);
    if (PyErr_Occurred()) return NULL;

    Py_buffer data, name, mime, pairs, ttl;
    ttl.buf = NULL;
    if (PyObject_GetBuffer(args[2], &data, PyBUF_SIMPLE) < 0) return NULL;
    if (PyObject_GetBuffer(args[4], &name, PyBUF_SIMPLE) < 0) goto err_data;
    if (PyObject_GetBuffer(args[5], &mime, PyBUF_SIMPLE) < 0) goto err_name;
    if (PyObject_GetBuffer(args[8], &pairs, PyBUF_SIMPLE) < 0) goto err_mime;
    if (args[7] != Py_None) {
        if (PyObject_GetBuffer(args[7], &ttl, PyBUF_SIMPLE) < 0) goto err_pairs;
        if (ttl.len < 2) {
            PyErr_SetString(PyExc_ValueError, "ttl must be 2 bytes");
            goto err_all;
        }
    }
    if (mime.len > 255) {
        PyErr_SetString(PyExc_ValueError, "mime longer than 255 bytes");
        goto err_all;
    }
    if (pairs.len >= 65536) {
        PyErr_SetString(PyExc_ValueError, "pairs longer than 64KB");
        goto err_all;
    }

    long maxlen = weed_needle_max_size((uint32_t)data.len, (uint32_t)name.len,
                                       (uint32_t)mime.len, (uint32_t)pairs.len);
    PyObject *out = PyBytes_FromStringAndSize(NULL, maxlen);
    if (out == NULL) goto err_all;

    uint32_t size, crc;
    long total;
    if (data.len >= 65536) {
        /* big payloads: the memcpy + CRC32-C dominates — run it
         * without the GIL so concurrent handler threads (and the
         * background scrubber) aren't serialized behind it. All
         * buffers are pinned by the Py_buffer views and `out` is not
         * yet visible to any other thread. */
        Py_BEGIN_ALLOW_THREADS
        total = weed_needle_encode(
            (uint8_t *)PyBytes_AS_STRING(out), cookie, id,
            (const uint8_t *)data.buf, (uint32_t)data.len, flags,
            (const uint8_t *)name.buf, (uint32_t)name.len,
            (const uint8_t *)mime.buf, (uint32_t)mime.len, last_modified,
            (const uint8_t *)ttl.buf, (const uint8_t *)pairs.buf,
            (uint32_t)pairs.len, (int)version, append_at_ns, &size, &crc,
            NULL);
        Py_END_ALLOW_THREADS
    } else {
        total = weed_needle_encode(
            (uint8_t *)PyBytes_AS_STRING(out), cookie, id,
            (const uint8_t *)data.buf, (uint32_t)data.len, flags,
            (const uint8_t *)name.buf, (uint32_t)name.len,
            (const uint8_t *)mime.buf, (uint32_t)mime.len, last_modified,
            (const uint8_t *)ttl.buf, (const uint8_t *)pairs.buf,
            (uint32_t)pairs.len, (int)version, append_at_ns, &size, &crc,
            NULL);
    }
    if (ttl.buf) PyBuffer_Release(&ttl);
    PyBuffer_Release(&pairs);
    PyBuffer_Release(&mime);
    PyBuffer_Release(&name);
    PyBuffer_Release(&data);
    if (total < 0) {
        Py_DECREF(out);
        PyErr_SetString(PyExc_ValueError, "unsupported needle version");
        return NULL;
    }
    if (_PyBytes_Resize(&out, total) < 0) return NULL;
    return Py_BuildValue("(NIk)", out, size, (unsigned long)crc);

err_all:
    if (ttl.buf) PyBuffer_Release(&ttl);
err_pairs:
    PyBuffer_Release(&pairs);
err_mime:
    PyBuffer_Release(&mime);
err_name:
    PyBuffer_Release(&name);
err_data:
    PyBuffer_Release(&data);
    return NULL;
}

/* decode(blob, version, expected_size) -> (cookie, id, size, data,
 *     flags, name, mime, last_modified, ttl2|None, pairs, append_at_ns,
 *     raw_crc)
 * expected_size < 0 skips the index-size cross-check.  Raises
 * ValueError with the same messages Needle.from_bytes uses (the Python
 * wrapper re-raises them as CorruptNeedle). */
static PyObject *py_decode(PyObject *Py_UNUSED(self), PyObject *const *args,
                           Py_ssize_t nargs) {
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError, "decode() takes 3 arguments");
        return NULL;
    }
    Py_buffer blob;
    if (PyObject_GetBuffer(args[0], &blob, PyBUF_SIMPLE) < 0) return NULL;
    long version = PyLong_AsLong(args[1]);
    long long expected = PyLong_AsLongLong(args[2]);
    if (PyErr_Occurred()) {
        PyBuffer_Release(&blob);
        return NULL;
    }
    const uint8_t *b = (const uint8_t *)blob.buf;
    Py_ssize_t len = blob.len;
    const char *err = NULL;
    PyObject *result = NULL;

    if (len < HEADER) {
        err = "needle header truncated";
        goto out;
    }
    uint32_t cookie = (uint32_t)b[0] << 24 | b[1] << 16 | b[2] << 8 | b[3];
    uint64_t id = 0;
    for (int i = 0; i < 8; i++) id = id << 8 | b[4 + i];
    uint32_t size = (uint32_t)b[12] << 24 | b[13] << 16 | b[14] << 8 | b[15];
    if (expected >= 0 && size != (uint64_t)expected) {
        err = "entry not found: size mismatch";
        goto out;
    }
    Py_ssize_t need = HEADER + (Py_ssize_t)size + CHECKSUM;
    if (version == 3) need += V3_TIMESTAMP;
    if (len < need) {
        err = "needle record truncated";
        goto out;
    }

    const uint8_t *body = b + HEADER;
    const uint8_t *data_p = NULL, *name_p = NULL, *mime_p = NULL,
                  *pairs_p = NULL, *ttl_p = NULL;
    uint32_t data_len = 0, name_len = 0, mime_len = 0, pairs_len = 0;
    uint64_t last_modified = 0;
    uint32_t flags = 0;

    if (version == 1) {
        data_p = body;
        data_len = size;
    } else if (version == 2 || version == 3) {
        uint32_t idx = 0, end = size;
        if (idx < end) {
            if (idx + 4 > end) {
                err = "data_size out of range";
                goto out;
            }
            data_len = (uint32_t)body[idx] << 24 | body[idx + 1] << 16 |
                       body[idx + 2] << 8 | body[idx + 3];
            idx += 4;
            if ((uint64_t)data_len + idx > end) {
                err = "data_size out of range";
                goto out;
            }
            data_p = body + idx;
            idx += data_len;
            if (idx >= end) {
                err = "flags byte out of range";
                goto out;
            }
            flags = body[idx++];
        }
        if (idx < end && (flags & 0x02)) { /* name */
            name_len = body[idx++];
            if ((uint64_t)name_len + idx > end) {
                err = "name out of range";
                goto out;
            }
            name_p = body + idx;
            idx += name_len;
        }
        if (idx < end && (flags & 0x04)) { /* mime */
            mime_len = body[idx++];
            if ((uint64_t)mime_len + idx > end) {
                err = "mime out of range";
                goto out;
            }
            mime_p = body + idx;
            idx += mime_len;
        }
        if (idx < end && (flags & 0x08)) { /* last_modified, 5B BE */
            if (idx + 5 > end) {
                err = "last_modified out of range";
                goto out;
            }
            for (int i = 0; i < 5; i++)
                last_modified = last_modified << 8 | body[idx + i];
            idx += 5;
        }
        if (idx < end && (flags & 0x10)) { /* ttl 2B */
            if (idx + 2 > end) {
                err = "ttl out of range";
                goto out;
            }
            ttl_p = body + idx;
            idx += 2;
        }
        if (idx < end && (flags & 0x20)) { /* pairs */
            if (idx + 2 > end) {
                err = "pairs_size out of range";
                goto out;
            }
            pairs_len = (uint32_t)body[idx] << 8 | body[idx + 1];
            idx += 2;
            if ((uint64_t)pairs_len + idx > end) {
                err = "pairs out of range";
                goto out;
            }
            pairs_p = body + idx;
            idx += pairs_len;
        }
    } else {
        err = "unsupported needle version";
        goto out;
    }

    uint32_t crc = 0;
    if (size > 0) {
        uint32_t stored = (uint32_t)b[HEADER + size] << 24 |
                          b[HEADER + size + 1] << 16 |
                          b[HEADER + size + 2] << 8 | b[HEADER + size + 3];
        if (data_len >= 65536) {
            /* GIL released for the big-payload CRC: the verify of a
             * multi-MiB needle is milliseconds of pure C that would
             * otherwise stall every other handler thread (and inflate
             * foreground p99 whenever the scrubber is re-reading). The
             * source buffer is pinned by the caller's Py_buffer. */
            Py_BEGIN_ALLOW_THREADS
            crc = weed_crc32c(0, data_p, data_len);
            Py_END_ALLOW_THREADS
        } else {
            crc = weed_crc32c(0, data_p, data_len);
        }
        if (stored != masked(crc)) {
            err = "CRC error! Data On Disk Corrupted";
            goto out;
        }
    }
    uint64_t append_at_ns = 0;
    if (version == 3) {
        const uint8_t *ts = b + HEADER + size + CHECKSUM;
        for (int i = 0; i < 8; i++) append_at_ns = append_at_ns << 8 | ts[i];
    }

    result = Py_BuildValue(
        "(IKIy#Iy#y#KOy#KI)", (unsigned int)cookie,
        (unsigned long long)id, (unsigned int)size,
        (const char *)(data_p ? (const char *)data_p : ""),
        (Py_ssize_t)data_len, (unsigned int)flags,
        (const char *)(name_p ? (const char *)name_p : ""),
        (Py_ssize_t)name_len,
        (const char *)(mime_p ? (const char *)mime_p : ""),
        (Py_ssize_t)mime_len, (unsigned long long)last_modified, Py_None,
        (const char *)(pairs_p ? (const char *)pairs_p : ""),
        (Py_ssize_t)pairs_len, (unsigned long long)append_at_ns,
        (unsigned int)crc);
    if (result && ttl_p) {
        PyObject *ttl_bytes = PyBytes_FromStringAndSize((const char *)ttl_p, 2);
        if (ttl_bytes == NULL) {
            Py_CLEAR(result);
        } else {
            PyTuple_SetItem(result, 8, ttl_bytes); /* steals ref */
        }
    }
out:
    PyBuffer_Release(&blob);
    if (err) {
        PyErr_SetString(PyExc_ValueError, err);
        return NULL;
    }
    return result;
}

/* post(body, content_type, raw_gzipped, q_filename, url_filename,
 *      pairs, base_flags, cookie, id, version, last_modified,
 *      append_at_ns, fd, offset, fix_jpg)
 *   -> None                         needs the Python slow path
 *    | (reply_bytes, total, size, (parse_s, assemble_s, crc_s,
 *       pwrite_s, reply_s))         record pwritten at `offset`;
 *      the 5-double tuple is the tracing plane's per-stage wall time
 *   raises OSError when the pwrite itself fails (errno preserved).
 *
 * The whole hot span — multipart/raw extraction, needle assembly, CRC,
 * pwrite, reply formatting — runs with the GIL RELEASED (post.c); the
 * caller holds the volume lock, which a GIL release does not drop, so
 * the single-writer-per-volume invariant is untouched. */
static PyObject *py_post(PyObject *Py_UNUSED(self), PyObject *const *args,
                         Py_ssize_t nargs) {
    if (nargs != 15) {
        PyErr_SetString(PyExc_TypeError, "post() takes 15 arguments");
        return NULL;
    }
    weed_post_req r;
    memset(&r, 0, sizeof(r));
    r.raw_gzipped = (int)PyLong_AsLong(args[2]);
    r.base_flags = (uint32_t)PyLong_AsUnsignedLongMask(args[6]);
    r.cookie = (uint32_t)PyLong_AsUnsignedLongMask(args[7]);
    r.id = PyLong_AsUnsignedLongLongMask(args[8]);
    r.version = (int)PyLong_AsLong(args[9]);
    r.last_modified = PyLong_AsUnsignedLongLongMask(args[10]);
    r.append_at_ns = PyLong_AsUnsignedLongLongMask(args[11]);
    r.fd = (int)PyLong_AsLong(args[12]);
    r.offset = (int64_t)PyLong_AsLongLong(args[13]);
    r.fix_jpg = (int)PyLong_AsLong(args[14]);
    if (PyErr_Occurred()) return NULL;

    Py_buffer body, ctype, qname, uname, pairs;
    if (PyObject_GetBuffer(args[0], &body, PyBUF_SIMPLE) < 0) return NULL;
    if (PyObject_GetBuffer(args[1], &ctype, PyBUF_SIMPLE) < 0) goto err_body;
    if (PyObject_GetBuffer(args[3], &qname, PyBUF_SIMPLE) < 0) goto err_ctype;
    if (PyObject_GetBuffer(args[4], &uname, PyBUF_SIMPLE) < 0) goto err_qname;
    if (PyObject_GetBuffer(args[5], &pairs, PyBUF_SIMPLE) < 0) goto err_uname;

    r.body = (const uint8_t *)body.buf;
    r.body_len = (size_t)body.len;
    r.ctype = (const uint8_t *)ctype.buf;
    r.ctype_len = (size_t)ctype.len;
    r.q_name = (const uint8_t *)qname.buf;
    r.q_name_len = (size_t)qname.len;
    r.url_name = (const uint8_t *)uname.buf;
    r.url_name_len = (size_t)uname.len;
    r.pairs = (const uint8_t *)pairs.buf;
    r.pairs_len = (size_t)pairs.len;

    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = weed_post(&r);
    Py_END_ALLOW_THREADS

    PyBuffer_Release(&pairs);
    PyBuffer_Release(&uname);
    PyBuffer_Release(&qname);
    PyBuffer_Release(&ctype);
    PyBuffer_Release(&body);

    if (rc == WEED_POST_DECLINE) Py_RETURN_NONE;
    if (rc == WEED_POST_IOERR) {
        errno = r.io_errno;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    return Py_BuildValue("(y#lI(ddddd))", r.reply, (Py_ssize_t)r.reply_len,
                         r.total, (unsigned int)r.size, r.st_parse,
                         r.st_assemble, r.st_crc, r.st_pwrite, r.st_reply);

    /* unwind: each label releases ITS OWN buffer then falls through,
     * so a GetBuffer failure on arg N releases exactly args 0..N-1 */
err_uname:
    PyBuffer_Release(&uname);
err_qname:
    PyBuffer_Release(&qname);
err_ctype:
    PyBuffer_Release(&ctype);
err_body:
    PyBuffer_Release(&body);
    return NULL;
}

/* METH_FASTCALL entries are _PyCFunctionFast, not PyCFunction; the
 * double cast through a generic function pointer is the CPython-
 * sanctioned spelling (what 3.11's _PyCFunction_CAST expands to) and
 * keeps -Wcast-function-type quiet under -Werror. */
#define FASTCALL_CAST(f) ((PyCFunction)(void (*)(void))(f))

static PyMethodDef methods[] = {
    {"encode", FASTCALL_CAST(py_encode), METH_FASTCALL,
     "serialize one needle record"},
    {"decode", FASTCALL_CAST(py_decode), METH_FASTCALL,
     "parse + CRC-verify one needle record"},
    {"post", FASTCALL_CAST(py_post), METH_FASTCALL,
     "one-pass volume POST: extract + assemble + CRC + pwrite + reply"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_needle_ext", NULL, -1, methods,
    NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit__needle_ext(void) { return PyModule_Create(&moduledef); }
