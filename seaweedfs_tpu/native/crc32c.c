/* CRC32-Castagnoli: the needle-checksum hot path.
 *
 * Role match: the reference vendors github.com/klauspost/crc32 for its
 * SSE4.2 Castagnoli kernel (weed/storage/needle/crc.go:8); this is the
 * same component as a small C library loaded via ctypes.
 *
 * Two paths, chosen once at load time:
 *   - hardware: SSE4.2 crc32 instruction, three independent 1 KiB
 *     lanes in flight per loop (crc32di has ~3-cycle latency but
 *     1/cycle throughput, so a single chain runs at a third of the
 *     machine's rate; lane CRCs recombine through precomputed
 *     zero-extension tables, the klauspost/crc32 structure)
 *   - portable: slicing-by-8 tables
 * Both compute the standard reflected CRC-32C (poly 0x1EDC6F41).
 */

#include <stddef.h>
#include <stdint.h>

#if defined(__x86_64__) /* crc32di needs 64-bit mode */
#include <cpuid.h>
#define HAVE_X86 1
#endif

#define LANE 1024 /* bytes per lane in the 3-way hardware loop */

static uint32_t table8[8][256];
/* zero-extension operators: shiftNk(c) = CRC register after appending
 * N KiB of zero bytes to a stream whose register is c. Extension is
 * linear over GF(2), so each is four byte-indexed tables — the lane
 * recombination of the 3-way loop. */
static uint32_t shift1k[4][256];
static uint32_t shift2k[4][256];
static int use_hw = 0;

static uint32_t zext_bytewise(uint32_t c, size_t n) {
    while (n--) c = table8[0][c & 0xFF] ^ (c >> 8);
    return c;
}

static uint32_t shift_apply(const uint32_t t[4][256], uint32_t c) {
    return t[3][c >> 24] ^ t[2][(c >> 16) & 0xFF] ^
           t[1][(c >> 8) & 0xFF] ^ t[0][c & 0xFF];
}

/* constructor: runs once at dlopen, before any caller thread exists —
 * lazy init under ctypes would race (the GIL is released during calls) */
__attribute__((constructor)) static void init_tables(void) {
    const uint32_t poly = 0x82F63B78u; /* reflected Castagnoli */
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
        table8[0][i] = c;
    }
    for (int t = 1; t < 8; t++)
        for (int i = 0; i < 256; i++)
            table8[t][i] =
                table8[0][table8[t - 1][i] & 0xFF] ^ (table8[t - 1][i] >> 8);
    for (int t = 0; t < 4; t++)
        for (int i = 0; i < 256; i++)
            shift1k[t][i] = zext_bytewise((uint32_t)i << (8 * t), LANE);
    /* by linearity: shift2k = shift1k applied twice (shift1k must be
     * complete first — shift_apply reads all four of its rows) */
    for (int t = 0; t < 4; t++)
        for (int i = 0; i < 256; i++)
            shift2k[t][i] = shift_apply(shift1k, shift1k[t][i]);
#ifdef HAVE_X86
    {
        unsigned int eax, ebx, ecx, edx;
        if (__get_cpuid(1, &eax, &ebx, &ecx, &edx))
            use_hw = (ecx & (1u << 20)) != 0; /* SSE4.2 */
    }
#endif
}

#ifdef HAVE_X86
__attribute__((target("sse4.2"))) static uint32_t crc_hw(uint32_t crc,
                                                         const uint8_t *p,
                                                         size_t n) {
    uint64_t c = crc;
    /* 3 independent crc32di chains hide the instruction's latency;
     * reg(A||B||D, c) = zext(reg(A,c), 2K) ^ zext(reg(B,0), 1K)
     *                   ^ reg(D,0) recombines the lanes */
    while (n >= 3 * LANE) {
        uint64_t a = c, b = 0, d = 0;
        for (int i = 0; i < LANE; i += 8) {
            uint64_t va, vb, vd;
            __builtin_memcpy(&va, p + i, 8);
            __builtin_memcpy(&vb, p + LANE + i, 8);
            __builtin_memcpy(&vd, p + 2 * LANE + i, 8);
            a = __builtin_ia32_crc32di(a, va);
            b = __builtin_ia32_crc32di(b, vb);
            d = __builtin_ia32_crc32di(d, vd);
        }
        c = shift_apply(shift2k, (uint32_t)a) ^
            shift_apply(shift1k, (uint32_t)b) ^ (uint32_t)d;
        p += 3 * LANE;
        n -= 3 * LANE;
    }
    while (n >= 8) {
        uint64_t v;
        __builtin_memcpy(&v, p, 8);
        c = __builtin_ia32_crc32di(c, v);
        p += 8;
        n -= 8;
    }
    uint32_t c32 = (uint32_t)c;
    while (n--) c32 = __builtin_ia32_crc32qi(c32, *p++);
    return c32;
}
#endif

static uint32_t crc_sw(uint32_t c, const uint8_t *p, size_t n) {
    while (n >= 8) {
        uint32_t lo, hi;
        __builtin_memcpy(&lo, p, 4);
        __builtin_memcpy(&hi, p + 4, 4);
        lo ^= c;
        c = table8[7][lo & 0xFF] ^ table8[6][(lo >> 8) & 0xFF] ^
            table8[5][(lo >> 16) & 0xFF] ^ table8[4][lo >> 24] ^
            table8[3][hi & 0xFF] ^ table8[2][(hi >> 8) & 0xFF] ^
            table8[1][(hi >> 16) & 0xFF] ^ table8[0][hi >> 24];
        p += 8;
        n -= 8;
    }
    while (n--) c = table8[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
    return c;
}

/* Standard CRC-32C continuing from `crc` (pre-inversion handled here).
 * `data` is const void *: callers hold char/uint8_t buffers alike and
 * must not need signedness casts (-Wpointer-sign under -Werror). */
uint32_t weed_crc32c(uint32_t crc, const void *data, size_t n) {
    const uint8_t *p = (const uint8_t *)data;
    uint32_t c = crc ^ 0xFFFFFFFFu;
#ifdef HAVE_X86
    if (use_hw) return crc_hw(c, p, n) ^ 0xFFFFFFFFu;
#endif
    return crc_sw(c, p, n) ^ 0xFFFFFFFFu;
}
