/* CPython binding for the epoll serving core (serve.c), the way
 * needle_ext.c binds post.c.
 *
 * loop(listen_fd, wake_fd, resolver, handoff, complete,
 *      idle_ms, max_reqs) -> None
 *
 * Runs the event loop with the GIL RELEASED; each callback re-takes it
 * via PyGILState_Ensure for exactly as long as the Python call lasts:
 *
 *   resolver(path, range, head_only, trace, if_none_match)
 *       -> None                        decline: hand the connection off
 *        | (status, prefix_bytes, body_bytes|None,
 *           fd, offset, count, close_fd, ctx)
 *        | (..., etag_bytes|None, prefix304_bytes|None, gen, cacheable)
 *                                      fast path: the loop writes
 *                                      prefix + Connection/Content-
 *                                      Length tail + body (bytes, or
 *                                      sendfile of count@offset from
 *                                      fd); ctx rides to complete().
 *                                      The widened 12-tuple lets the C
 *                                      loop answer If-None-Match 304s
 *                                      against etag via prefix304 and
 *                                      cache the plan (keyed by path,
 *                                      invalidated when the generation
 *                                      counter moves past gen)
 *   handoff(fd, pending_bytes, ip, port)
 *                                      ownership of fd transfers; the
 *                                      embedder re-parses `pending`
 *                                      (the current head onward) in
 *                                      the Python mini loop
 *   complete(ctx, status, resp_bytes, t_parse, t_resolve, t_send, ok)
 *                                      response finished (ok=False:
 *                                      the connection died mid-write)
 *
 * The resolver's returned tuple is held alive (one reference) until
 * complete() runs, which is what keeps the prefix/body buffers valid
 * while the loop drains them; complete() is guaranteed exactly once
 * per fast-path response, including on connection teardown and loop
 * exit.  A resolver/complete/handoff exception is reported via
 * sys.unraisablehook and degrades to decline/continue — a Python bug
 * must never wedge the accept path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "serve.c"

typedef struct {
    PyObject *resolver;
    PyObject *handoff;
    PyObject *complete;
} weed_glue;

static PyObject *glue_str_or_none(const char *p, size_t n) {
    if (p == NULL) Py_RETURN_NONE;
    return PyUnicode_DecodeLatin1(p, (Py_ssize_t)n, "replace");
}

static int glue_resolve(void *vctx, const weed_req *req, weed_resp *resp,
                        void **token) {
    weed_glue *g = (weed_glue *)vctx;
    PyGILState_STATE st = PyGILState_Ensure();
    int rc = 0;
    PyObject *path = PyUnicode_DecodeLatin1(req->path, (Py_ssize_t)req->path_len,
                                            "replace");
    PyObject *range = glue_str_or_none(req->range, req->range_len);
    PyObject *trace = glue_str_or_none(req->trace, req->trace_len);
    PyObject *inm = glue_str_or_none(req->inm, req->inm_len);
    PyObject *r = NULL;
    if (path != NULL && range != NULL && trace != NULL && inm != NULL) {
        r = PyObject_CallFunctionObjArgs(
            g->resolver, path, range, req->head_only ? Py_True : Py_False,
            trace, inm, NULL);
    }
    Py_XDECREF(path);
    Py_XDECREF(range);
    Py_XDECREF(trace);
    Py_XDECREF(inm);
    if (r == NULL) {
        PyErr_WriteUnraisable(g->resolver);
    } else if (r == Py_None) {
        Py_DECREF(r);
    } else {
        /* the plan is an 8-tuple, or a 12-tuple carrying the
         * conditional-GET / plan-cache extras; manual unpack because
         * PyArg_ParseTuple insists on an exact length */
        Py_ssize_t n = PyTuple_Check(r) ? PyTuple_GET_SIZE(r) : -1;
        int ok = (n == 8 || n == 12);
        int status = 0, fd = -1, close_fd = 0, cacheable = 0;
        long long off = 0, count = 0;
        unsigned long long gen = 0;
        PyObject *prefix = NULL, *body = NULL, *etag = NULL, *p304 = NULL;
        if (ok) {
            status = (int)PyLong_AsLong(PyTuple_GET_ITEM(r, 0));
            prefix = PyTuple_GET_ITEM(r, 1);
            body = PyTuple_GET_ITEM(r, 2);
            fd = (int)PyLong_AsLong(PyTuple_GET_ITEM(r, 3));
            off = PyLong_AsLongLong(PyTuple_GET_ITEM(r, 4));
            count = PyLong_AsLongLong(PyTuple_GET_ITEM(r, 5));
            close_fd = PyObject_IsTrue(PyTuple_GET_ITEM(r, 6));
            ok = !PyErr_Occurred() && close_fd >= 0 &&
                 PyBytes_Check(prefix) &&
                 (body == Py_None || PyBytes_Check(body));
        }
        if (ok && n == 12) {
            etag = PyTuple_GET_ITEM(r, 8);
            p304 = PyTuple_GET_ITEM(r, 9);
            gen = PyLong_AsUnsignedLongLong(PyTuple_GET_ITEM(r, 10));
            cacheable = PyObject_IsTrue(PyTuple_GET_ITEM(r, 11));
            ok = !PyErr_Occurred() && cacheable >= 0 &&
                 (etag == Py_None || PyBytes_Check(etag)) &&
                 (p304 == Py_None || PyBytes_Check(p304));
        }
        if (ok) {
            resp->status = status;
            resp->prefix = (const uint8_t *)PyBytes_AS_STRING(prefix);
            resp->prefix_len = (size_t)PyBytes_GET_SIZE(prefix);
            if (body != Py_None) {
                resp->body = (const uint8_t *)PyBytes_AS_STRING(body);
                resp->body_len = (size_t)PyBytes_GET_SIZE(body);
            }
            resp->fd = fd;
            resp->off = (int64_t)off;
            resp->count = count < 0 ? 0 : (size_t)count;
            resp->close_fd = close_fd;
            if (etag != NULL && etag != Py_None) {
                resp->etag = (const uint8_t *)PyBytes_AS_STRING(etag);
                resp->etag_len = (size_t)PyBytes_GET_SIZE(etag);
            }
            if (p304 != NULL && p304 != Py_None) {
                resp->prefix304 = (const uint8_t *)PyBytes_AS_STRING(p304);
                resp->prefix304_len = (size_t)PyBytes_GET_SIZE(p304);
            }
            resp->gen = (uint64_t)gen;
            resp->cacheable = cacheable;
            *token = r;  /* keeps prefix/body/etag alive until complete() */
            rc = 1;
        } else {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_TypeError,
                                "resolver plan must be an 8- or 12-tuple");
            PyErr_WriteUnraisable(g->resolver);
            if (fd >= 0 && close_fd > 0) close(fd);
            Py_DECREF(r);
        }
    }
    PyGILState_Release(st);
    return rc;
}

static void glue_handoff(void *vctx, int fd, const uint8_t *pending,
                         size_t len, const char *ip, int port, long nreqs) {
    weed_glue *g = (weed_glue *)vctx;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallFunction(g->handoff, "iy#sil", fd,
                                        (const char *)pending,
                                        (Py_ssize_t)len, ip, port, nreqs);
    if (r == NULL) {
        /* the embedder never took ownership: close here or leak */
        PyErr_WriteUnraisable(g->handoff);
        close(fd);
    } else {
        Py_DECREF(r);
    }
    PyGILState_Release(st);
}

static void glue_complete(void *vctx, void *token, int status,
                          size_t resp_bytes, double t_parse, double t_resolve,
                          double t_send, int ok) {
    weed_glue *g = (weed_glue *)vctx;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *tup = (PyObject *)token;
    PyObject *ctx = PyTuple_GET_ITEM(tup, 7); /* borrowed */
    PyObject *r = PyObject_CallFunction(
        g->complete, "OindddO", ctx, status, (Py_ssize_t)resp_bytes, t_parse,
        t_resolve, t_send, ok ? Py_True : Py_False);
    if (r == NULL) PyErr_WriteUnraisable(g->complete);
    else Py_DECREF(r);
    Py_DECREF(tup);
    PyGILState_Release(st);
}

static PyObject *py_loop(PyObject *Py_UNUSED(self), PyObject *args) {
    int listen_fd, wake_fd, use_adm = 0;
    PyObject *resolver, *handoff, *complete;
    long idle_ms = 0, max_reqs = 0;
    if (!PyArg_ParseTuple(args, "iiOOO|lli:loop", &listen_fd, &wake_fd,
                          &resolver, &handoff, &complete, &idle_ms,
                          &max_reqs, &use_adm))
        return NULL;
    if (!PyCallable_Check(resolver) || !PyCallable_Check(handoff) ||
        !PyCallable_Check(complete)) {
        PyErr_SetString(PyExc_TypeError, "callbacks must be callable");
        return NULL;
    }
    weed_glue g = {resolver, handoff, complete};
    weed_serve_cbs cbs;
    memset(&cbs, 0, sizeof(cbs));
    cbs.ctx = &g;
    cbs.resolve = glue_resolve;
    cbs.handoff = glue_handoff;
    cbs.complete = glue_complete;
    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = weed_serve_loop(listen_fd, wake_fd, &cbs, idle_ms, max_reqs,
                         use_adm);
    Py_END_ALLOW_THREADS
    if (rc < 0) {
        errno = -rc;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    Py_RETURN_NONE;
}

static PyObject *py_gen_bump(PyObject *Py_UNUSED(self),
                             PyObject *Py_UNUSED(args)) {
    return PyLong_FromUnsignedLongLong(
        (unsigned long long)weed_gen_bump());
}

static PyObject *py_gen_get(PyObject *Py_UNUSED(self),
                            PyObject *Py_UNUSED(args)) {
    return PyLong_FromUnsignedLongLong((unsigned long long)weed_gen_get());
}

static PyObject *py_serve_stats(PyObject *Py_UNUSED(self),
                                PyObject *Py_UNUSED(args)) {
    return Py_BuildValue(
        "{s:K,s:K,s:K,s:K,s:K,s:K,s:K}",
        "served",
        (unsigned long long)__atomic_load_n(&weed_stat_served,
                                            __ATOMIC_RELAXED),
        "handoffs",
        (unsigned long long)__atomic_load_n(&weed_stat_handoffs,
                                            __ATOMIC_RELAXED),
        "not_modified",
        (unsigned long long)__atomic_load_n(&weed_stat_304,
                                            __ATOMIC_RELAXED),
        "cache_hits",
        (unsigned long long)__atomic_load_n(&weed_stat_cache_hits,
                                            __ATOMIC_RELAXED),
        "cache_inserts",
        (unsigned long long)__atomic_load_n(&weed_stat_cache_inserts,
                                            __ATOMIC_RELAXED),
        "shed",
        (unsigned long long)__atomic_load_n(&weed_stat_shed,
                                            __ATOMIC_RELAXED),
        "generation", (unsigned long long)weed_gen_get());
}

static PyObject *py_shm_attach(PyObject *Py_UNUSED(self), PyObject *args) {
    const char *path;
    double rate, burst, retry_floor = 0.0;
    unsigned int nslots = 1024;
    if (!PyArg_ParseTuple(args, "sdd|dI:shm_attach", &path, &rate, &burst,
                          &retry_floor, &nslots))
        return NULL;
    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = weed_shm_attach(path, rate, burst, retry_floor, nslots);
    Py_END_ALLOW_THREADS
    if (rc < 0) {
        errno = -rc;
        return PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
    }
    Py_RETURN_NONE;
}

static PyObject *py_shm_admit(PyObject *Py_UNUSED(self), PyObject *args) {
    const char *key;
    Py_ssize_t klen;
    if (!PyArg_ParseTuple(args, "s#:shm_admit", &key, &klen)) return NULL;
    if (!weed_shm_active()) {
        PyErr_SetString(PyExc_RuntimeError, "admission shm not attached");
        return NULL;
    }
    return PyFloat_FromDouble(weed_shm_admit(key, (size_t)klen));
}

static PyObject *py_shm_detach(PyObject *Py_UNUSED(self),
                               PyObject *Py_UNUSED(args)) {
    weed_shm_detach();
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"loop", py_loop, METH_VARARGS,
     "run the epoll serving loop until wake_fd is written"},
    {"gen_bump", py_gen_bump, METH_NOARGS,
     "advance the plan-cache generation counter (invalidates all entries)"},
    {"gen_get", py_gen_get, METH_NOARGS,
     "read the plan-cache generation counter"},
    {"serve_stats", py_serve_stats, METH_NOARGS,
     "process-wide C fast-path counters"},
    {"shm_attach", py_shm_attach, METH_VARARGS,
     "shm_attach(path, rate, burst, retry_floor=0.0, nslots=1024): map the "
     "shared admission token-bucket file (first writer's params win)"},
    {"shm_admit", py_shm_admit, METH_VARARGS,
     "shm_admit(key) -> 0.0 if admitted else suggested Retry-After seconds"},
    {"shm_detach", py_shm_detach, METH_NOARGS,
     "unmap the shared admission bucket"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_serve_ext", NULL, -1, methods,
    NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit__serve_ext(void) { return PyModule_Create(&moduledef); }
