/* CPython binding for the epoll serving core (serve.c), the way
 * needle_ext.c binds post.c.
 *
 * loop(listen_fd, wake_fd, resolver, handoff, complete,
 *      idle_ms, max_reqs) -> None
 *
 * Runs the event loop with the GIL RELEASED; each callback re-takes it
 * via PyGILState_Ensure for exactly as long as the Python call lasts:
 *
 *   resolver(path, range, head_only, trace)
 *       -> None                        decline: hand the connection off
 *        | (status, prefix_bytes, body_bytes|None,
 *           fd, offset, count, close_fd, ctx)
 *                                      fast path: the loop writes
 *                                      prefix + Connection/Content-
 *                                      Length tail + body (bytes, or
 *                                      sendfile of count@offset from
 *                                      fd); ctx rides to complete()
 *   handoff(fd, pending_bytes, ip, port)
 *                                      ownership of fd transfers; the
 *                                      embedder re-parses `pending`
 *                                      (the current head onward) in
 *                                      the Python mini loop
 *   complete(ctx, status, resp_bytes, t_parse, t_resolve, t_send, ok)
 *                                      response finished (ok=False:
 *                                      the connection died mid-write)
 *
 * The resolver's returned tuple is held alive (one reference) until
 * complete() runs, which is what keeps the prefix/body buffers valid
 * while the loop drains them; complete() is guaranteed exactly once
 * per fast-path response, including on connection teardown and loop
 * exit.  A resolver/complete/handoff exception is reported via
 * sys.unraisablehook and degrades to decline/continue — a Python bug
 * must never wedge the accept path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "serve.c"

typedef struct {
    PyObject *resolver;
    PyObject *handoff;
    PyObject *complete;
} weed_glue;

static PyObject *glue_str_or_none(const char *p, size_t n) {
    if (p == NULL) Py_RETURN_NONE;
    return PyUnicode_DecodeLatin1(p, (Py_ssize_t)n, "replace");
}

static int glue_resolve(void *vctx, const weed_req *req, weed_resp *resp,
                        void **token) {
    weed_glue *g = (weed_glue *)vctx;
    PyGILState_STATE st = PyGILState_Ensure();
    int rc = 0;
    PyObject *path = PyUnicode_DecodeLatin1(req->path, (Py_ssize_t)req->path_len,
                                            "replace");
    PyObject *range = glue_str_or_none(req->range, req->range_len);
    PyObject *trace = glue_str_or_none(req->trace, req->trace_len);
    PyObject *r = NULL;
    if (path != NULL && range != NULL && trace != NULL) {
        r = PyObject_CallFunctionObjArgs(
            g->resolver, path, range, req->head_only ? Py_True : Py_False,
            trace, NULL);
    }
    Py_XDECREF(path);
    Py_XDECREF(range);
    Py_XDECREF(trace);
    if (r == NULL) {
        PyErr_WriteUnraisable(g->resolver);
    } else if (r == Py_None) {
        Py_DECREF(r);
    } else {
        int status = 0, fd = -1, close_fd = 0;
        long long off = 0;
        Py_ssize_t count = 0;
        PyObject *prefix = NULL, *body = NULL, *ctx = NULL;
        if (PyTuple_Check(r) &&
            PyArg_ParseTuple(r, "iSOiLnpO:resolver", &status, &prefix, &body,
                             &fd, &off, &count, &close_fd, &ctx) &&
            (body == Py_None || PyBytes_Check(body))) {
            resp->status = status;
            resp->prefix = (const uint8_t *)PyBytes_AS_STRING(prefix);
            resp->prefix_len = (size_t)PyBytes_GET_SIZE(prefix);
            if (body != Py_None) {
                resp->body = (const uint8_t *)PyBytes_AS_STRING(body);
                resp->body_len = (size_t)PyBytes_GET_SIZE(body);
            }
            resp->fd = fd;
            resp->off = (int64_t)off;
            resp->count = count < 0 ? 0 : (size_t)count;
            resp->close_fd = close_fd;
            *token = r;  /* keeps prefix/body alive until complete() */
            rc = 1;
        } else {
            PyErr_WriteUnraisable(g->resolver);
            if (fd >= 0 && close_fd) close(fd);
            Py_DECREF(r);
        }
    }
    PyGILState_Release(st);
    return rc;
}

static void glue_handoff(void *vctx, int fd, const uint8_t *pending,
                         size_t len, const char *ip, int port, long nreqs) {
    weed_glue *g = (weed_glue *)vctx;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallFunction(g->handoff, "iy#sil", fd,
                                        (const char *)pending,
                                        (Py_ssize_t)len, ip, port, nreqs);
    if (r == NULL) {
        /* the embedder never took ownership: close here or leak */
        PyErr_WriteUnraisable(g->handoff);
        close(fd);
    } else {
        Py_DECREF(r);
    }
    PyGILState_Release(st);
}

static void glue_complete(void *vctx, void *token, int status,
                          size_t resp_bytes, double t_parse, double t_resolve,
                          double t_send, int ok) {
    weed_glue *g = (weed_glue *)vctx;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *tup = (PyObject *)token;
    PyObject *ctx = PyTuple_GET_ITEM(tup, 7); /* borrowed */
    PyObject *r = PyObject_CallFunction(
        g->complete, "OindddO", ctx, status, (Py_ssize_t)resp_bytes, t_parse,
        t_resolve, t_send, ok ? Py_True : Py_False);
    if (r == NULL) PyErr_WriteUnraisable(g->complete);
    else Py_DECREF(r);
    Py_DECREF(tup);
    PyGILState_Release(st);
}

static PyObject *py_loop(PyObject *Py_UNUSED(self), PyObject *args) {
    int listen_fd, wake_fd;
    PyObject *resolver, *handoff, *complete;
    long idle_ms = 0, max_reqs = 0;
    if (!PyArg_ParseTuple(args, "iiOOO|ll:loop", &listen_fd, &wake_fd,
                          &resolver, &handoff, &complete, &idle_ms,
                          &max_reqs))
        return NULL;
    if (!PyCallable_Check(resolver) || !PyCallable_Check(handoff) ||
        !PyCallable_Check(complete)) {
        PyErr_SetString(PyExc_TypeError, "callbacks must be callable");
        return NULL;
    }
    weed_glue g = {resolver, handoff, complete};
    weed_serve_cbs cbs;
    memset(&cbs, 0, sizeof(cbs));
    cbs.ctx = &g;
    cbs.resolve = glue_resolve;
    cbs.handoff = glue_handoff;
    cbs.complete = glue_complete;
    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = weed_serve_loop(listen_fd, wake_fd, &cbs, idle_ms, max_reqs);
    Py_END_ALLOW_THREADS
    if (rc < 0) {
        errno = -rc;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"loop", py_loop, METH_VARARGS,
     "run the epoll serving loop until wake_fd is written"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_serve_ext", NULL, -1, methods,
    NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit__serve_ext(void) { return PyModule_Create(&moduledef); }
