"""ctypes wrapper for the SIMD GF(2^8) matrix-apply shim (gf256.c).

Importing this module raises ImportError when no compiler is available;
ec/codec_native.py catches that and the numpy "cpu" backend serves.
"""

from __future__ import annotations

import ctypes

import numpy as np

from seaweedfs_tpu.native import _build

_lib = _build.load("gf256.c", "_gf256.so")
if _lib is None:
    raise ImportError("native gf256 unavailable (no compiler or load failed)")

_u8p = ctypes.POINTER(ctypes.c_uint8)
try:
    _lib.weed_gf_apply.restype = None
    _lib.weed_gf_apply.argtypes = (
        _u8p,  # matrix [r*k]
        ctypes.c_int32,  # r
        ctypes.c_int32,  # k
        ctypes.POINTER(_u8p),  # inputs  [k] row pointers
        ctypes.POINTER(_u8p),  # outputs [r] row pointers
        ctypes.c_size_t,  # n
    )
except AttributeError as e:  # stale/foreign .so without our export
    raise ImportError(f"native gf256 lacks weed_gf_apply: {e}") from e


def apply_matrix(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """out[r] = XOR_c gfmul(matrix[r,c], inputs[c]) over the 0x11D field.

    matrix [R, C] u8, inputs [C, N] u8 → [R, N] u8. Same contract as
    codec.cpu_apply_matrix (rows of the C-contiguous arrays are passed
    as raw pointers; no copies beyond contiguity normalization).
    """
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    inputs = np.ascontiguousarray(inputs, dtype=np.uint8)
    r, k = matrix.shape
    if inputs.shape[0] != k:
        raise ValueError(f"matrix has {k} columns but inputs has {inputs.shape[0]} rows")
    n = inputs.shape[1]
    out = np.empty((r, n), dtype=np.uint8)
    in_ptrs = (_u8p * k)(*(inputs[i].ctypes.data_as(_u8p) for i in range(k)))
    out_ptrs = (_u8p * r)(*(out[i].ctypes.data_as(_u8p) for i in range(r)))
    _lib.weed_gf_apply(
        matrix.ctypes.data_as(_u8p), r, k, in_ptrs, out_ptrs, n
    )
    return out
