/* One-pass volume POST hot loop: body → needle record → pwrite → reply.
 *
 * Role match: the reference's upload path is one Go pass —
 * needle.CreateNeedleFromRequest (needle.go:85 ParseUpload) feeding
 * prepareWriteBuffer (needle_read_write.go:31) — with no interpreter
 * between the socket buffer and the disk write. The Python port pays
 * ~87 us of volume-server CPU per write even after the round-4/5 fast
 * paths (OPERATIONS.md same-method A/B); this file is that whole span
 * as one C call: multipart/raw payload extraction, needle assembly
 * (via weed_needle_encode from needle.c), CRC32-C, pwrite at the
 * caller's append offset, and the 201 reply body formatting.
 *
 * Contract with the Python fallback (server/write_path.py
 * build_upload_needle + storage/volume.py write_needle): byte-identical
 * or DECLINE. Anything whose bytes depend on Python-only machinery —
 * transparent gzip compression, JPEG orientation fixing, base64/qp
 * transfer decoding, non-ASCII names (Python round-trips them
 * latin-1 → str → utf-8), overwrite/dedup of an existing id — returns
 * WEED_POST_DECLINE and the caller re-runs the pure-Python path on the
 * same buffer. The fallback also owns every error reply, so a declined
 * malformed body raises the exact MalformedUpload message it always
 * did. tests/test_native_post.py sweeps the identity.
 */

#include <errno.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#define WEED_POST_OK 0
#define WEED_POST_DECLINE (-1)
#define WEED_POST_IOERR (-2)

/* --- tiny byte-string helpers (no locale, no NUL assumptions) ------- */

static int w_lower(int c) { return (c >= 'A' && c <= 'Z') ? c + 32 : c; }

static int ci_prefix(const uint8_t *s, size_t n, const char *prefix) {
    size_t m = strlen(prefix);
    if (n < m) return 0;
    for (size_t i = 0; i < m; i++)
        if (w_lower(s[i]) != w_lower((uint8_t)prefix[i])) return 0;
    return 1;
}

static int ci_equals(const uint8_t *s, size_t n, const char *t) {
    return strlen(t) == n && ci_prefix(s, n, t);
}

static const uint8_t *w_memmem(const uint8_t *hay, size_t hn,
                               const uint8_t *needle, size_t nn) {
    if (nn == 0 || hn < nn) return NULL;
    const uint8_t *end = hay + hn - nn;
    for (const uint8_t *p = hay; p <= end; p++) {
        p = memchr(p, needle[0], (size_t)(end - p) + 1);
        if (p == NULL) return NULL;
        if (memcmp(p, needle, nn) == 0) return p;
    }
    return NULL;
}

static void w_strip(const uint8_t **s, size_t *n) {
    while (*n && (**s == ' ' || **s == '\t')) { (*s)++; (*n)--; }
    while (*n && ((*s)[*n - 1] == ' ' || (*s)[*n - 1] == '\t')) (*n)--;
}

/* Python's regex \s class over bytes: [ \t\n\r\f\v] — the boundary and
 * filename scans must terminate tokens on exactly this set or the C
 * and Python parsers frame different parts from the same body */
static int w_isspace(uint8_t c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
           c == '\v';
}

/* --- os.path.splitext + util/compression.is_gzippable ports --------- */

static void w_splitext(const uint8_t *name, size_t n, const uint8_t **ext,
                       size_t *ext_len) {
    *ext = NULL;
    *ext_len = 0;
    size_t base = 0;
    for (size_t i = 0; i < n; i++)
        if (name[i] == '/') base = i + 1;
    /* leading dots of the basename are not an extension (splitext) */
    size_t first = base;
    while (first < n && name[first] == '.') first++;
    for (size_t i = n; i > first; i--) {
        if (name[i - 1] == '.') {
            *ext = name + i - 1;
            *ext_len = n - (i - 1);
            return;
        }
    }
}

static const char *const GZ_ALWAYS[] = {
    ".svg", ".bmp", ".pdf", ".txt", ".html", ".htm", ".css", ".js",
    ".json", ".php", ".java", ".go", ".rb", ".c", ".cpp", ".h", ".hpp",
    NULL};
static const char *const GZ_NEVER[] = {
    ".zip", ".rar", ".gz", ".bz2", ".xz", ".png", ".jpg", ".jpeg", NULL};

static int ext_in(const uint8_t *ext, size_t n, const char *const *list) {
    for (int i = 0; list[i]; i++) {
        size_t m = strlen(list[i]);
        if (m != n) continue;
        int hit = 1;
        for (size_t j = 0; j < n; j++)
            if (w_lower(ext[j]) != (uint8_t)list[i][j]) { hit = 0; break; }
        if (hit) return 1;
    }
    return 0;
}

static int mime_suffix(const uint8_t *m, size_t n, const char *suf) {
    size_t s = strlen(suf);
    return n >= s && memcmp(m + n - s, suf, s) == 0;
}

/* CASE-SENSITIVE prefix: Python's str.startswith — the mime-type rules
 * in util/compression.py deliberately do not lower-case */
static int cs_prefix(const uint8_t *s, size_t n, const char *prefix) {
    size_t m = strlen(prefix);
    return n >= m && memcmp(s, prefix, m) == 0;
}

/* compression.is_gzippable: type rules first, mostly-text sniff as the
 * tiebreak — MUST match util/compression.py bit for bit, or the C and
 * Python paths store different (compressed vs raw) bytes. The mime
 * prefix/suffix compares are case-SENSITIVE, exactly like the Python
 * startswith/endswith they mirror (an 'Image/svg' body sniffs as text
 * there, so it must here too). */
static int w_is_gzippable(const uint8_t *ext, size_t ext_len,
                          const uint8_t *mime, size_t mime_len,
                          const uint8_t *data, size_t data_len) {
    if (cs_prefix(mime, mime_len, "text/")) return 1;
    if (ext_in(ext, ext_len, (const char *const[]){".svg", ".bmp", NULL}))
        return 1;
    if (cs_prefix(mime, mime_len, "image/")) return 0;
    if (ext_in(ext, ext_len, GZ_NEVER)) return 0;
    if (ext_in(ext, ext_len, GZ_ALWAYS)) return 1;
    if (cs_prefix(mime, mime_len, "application/")) {
        if (mime_suffix(mime, mime_len, "xml") ||
            mime_suffix(mime, mime_len, "json") ||
            mime_suffix(mime, mime_len, "script"))
            return 1;
    }
    /* _is_mostly_text: sample 1024, NUL disqualifies, non-text ratio */
    size_t sn = data_len < 1024 ? data_len : 1024;
    if (sn == 0) return 0;
    size_t non_text = 0;
    for (size_t i = 0; i < sn; i++) {
        uint8_t c = data[i];
        if (c == 0) return 0;
        if (!((c >= 32 && c <= 126) || c == '\t' || c == '\n' || c == '\r' ||
              c == '\f' || c == '\b' || c == 0x1b))
            non_text++;
    }
    return (double)non_text / (double)sn < 0.15;
}

/* --- multipart/form-data first-file-part scan ----------------------- */

typedef struct {
    const uint8_t *data;
    size_t data_len;
    const uint8_t *filename;
    size_t filename_len;
    const uint8_t *mime;
    size_t mime_len;
    int is_gzipped;
} weed_part;

/* boundary\s*=\s*("..."|token) out of the Content-Type value
 * (util/multipart._BOUNDARY_RE port). Returns 0 ok, -1 decline. */
static int parse_boundary(const uint8_t *ct, size_t n, const uint8_t **b,
                          size_t *bn) {
    for (size_t i = 0; i + 8 <= n; i++) {
        if (!ci_prefix(ct + i, n - i, "boundary")) continue;
        size_t j = i + 8;
        while (j < n && w_isspace(ct[j])) j++;
        if (j >= n || ct[j] != '=') continue;
        j++;
        while (j < n && w_isspace(ct[j])) j++;
        if (j < n && ct[j] == '"') {
            size_t k = j + 1;
            while (k < n && ct[k] != '"') k++;
            if (k >= n || k == j + 1) return -1; /* [^"]+ needs >=1 char */
            *b = ct + j + 1;
            *bn = k - (j + 1);
            return 0;
        }
        size_t k = j;
        while (k < n && ct[k] != ';' && ct[k] != ',' && !w_isspace(ct[k]))
            k++;
        if (k == j) return -1;
        *b = ct + j;
        *bn = k - j;
        return 0;
    }
    return -1;
}

/* util/multipart._find_delim over V (= CRLF + body, materialized by the
 * caller): next *valid* delimiter line at/after `start`.
 * Sets *line (match index), *after (just past boundary), *closing.
 * Returns 0 found, -1 not found. */
static int find_delim(const uint8_t *v, size_t vn, const uint8_t *delim,
                      size_t dn, size_t start, size_t *line, size_t *after,
                      int *closing) {
    size_t pos = start;
    while (pos + dn <= vn) {
        const uint8_t *hit = w_memmem(v + pos, vn - pos, delim, dn);
        if (hit == NULL) return -1;
        size_t idx = (size_t)(hit - v);
        size_t aft = idx + dn;
        int cl = (aft + 2 <= vn && v[aft] == '-' && v[aft + 1] == '-');
        size_t rest = cl ? aft + 2 : aft;
        /* transport padding (SP/HT) then CRLF or end-of-data only */
        size_t eol = rest;
        while (eol + 1 < vn && !(v[eol] == '\r' && v[eol + 1] == '\n')) eol++;
        size_t tail_end = (eol + 1 < vn) ? eol : vn;
        int ok = 1;
        for (size_t i = rest; i < tail_end; i++)
            if (v[i] != ' ' && v[i] != '\t') { ok = 0; break; }
        if (ok) {
            *line = idx;
            *after = aft;
            *closing = cl;
            return 0;
        }
        pos = idx + 1;
    }
    return -1;
}

/* First file part of a multipart body (util/multipart.parse_upload
 * port over V = CRLF+body). Returns WEED_POST_OK with *out filled, or
 * WEED_POST_DECLINE for anything the Python parser must rule on
 * (malformed framing, transfer encodings, escaped filenames). */
static int scan_multipart(const uint8_t *v, size_t vn, const uint8_t *boundary,
                          size_t bn, weed_part *out) {
    size_t dn = 4 + bn; /* "\r\n--" + boundary */
    uint8_t *delim = malloc(dn);
    if (delim == NULL) return WEED_POST_DECLINE;
    memcpy(delim, "\r\n--", 4);
    memcpy(delim + 4, boundary, bn);

    weed_part first;
    int have_first = 0;
    int rc = WEED_POST_DECLINE;
    size_t line, pos;
    int closing;
    if (find_delim(v, vn, delim, dn, 0, &line, &pos, &closing) != 0)
        goto done;
    while (!closing) {
        const uint8_t *eolp = w_memmem(v + pos, vn - pos, (const uint8_t *)"\r\n", 2);
        if (eolp == NULL) break;
        size_t eol = (size_t)(eolp - v);
        size_t nidx = vn, npos = (size_t)-1;
        int ncl = 0;
        if (find_delim(v, vn, delim, dn, eol, &nidx, &npos, &ncl) != 0) {
            nidx = vn;
            npos = (size_t)-1;
        }
        const uint8_t *part = v + eol + 2;
        size_t part_len = (nidx > eol + 2) ? nidx - (eol + 2) : 0;
        closing = ncl;
        int last = (npos == (size_t)-1);

        /* head/payload split on the first CRLFCRLF */
        const uint8_t *head = part;
        size_t head_len;
        const uint8_t *payload;
        size_t payload_len;
        const uint8_t *sep = w_memmem(part, part_len, (const uint8_t *)"\r\n\r\n", 4);
        if (sep != NULL) {
            head_len = (size_t)(sep - part);
            payload = sep + 4;
            payload_len = part_len - head_len - 4;
        } else if (part_len >= 2 && part[0] == '\r' && part[1] == '\n') {
            head_len = 0;
            payload = part + 2;
            payload_len = part_len - 2;
        } else {
            if (last) break;
            pos = npos;
            continue;
        }

        /* part headers: the four keys the Python parser rules on */
        const uint8_t *disp = NULL, *ptype = NULL, *penc = NULL, *pte = NULL;
        size_t disp_len = 0, ptype_len = 0, penc_len = 0, pte_len = 0;
        size_t hp = 0;
        while (hp < head_len) {
            const uint8_t *nl =
                w_memmem(head + hp, head_len - hp, (const uint8_t *)"\r\n", 2);
            size_t le = nl ? (size_t)(nl - head) : head_len;
            const uint8_t *colon = memchr(head + hp, ':', le - hp);
            if (colon != NULL) {
                const uint8_t *k = head + hp;
                size_t kn = (size_t)(colon - k);
                const uint8_t *val = colon + 1;
                size_t valn = le - hp - kn - 1;
                w_strip(&k, &kn);
                w_strip(&val, &valn);
                if (ci_equals(k, kn, "content-disposition")) {
                    disp = val; disp_len = valn;
                } else if (ci_equals(k, kn, "content-type")) {
                    ptype = val; ptype_len = valn;
                } else if (ci_equals(k, kn, "content-encoding")) {
                    penc = val; penc_len = valn;
                } else if (ci_equals(k, kn, "content-transfer-encoding")) {
                    pte = val; pte_len = valn;
                }
            }
            hp = nl ? le + 2 : head_len;
        }
        if (pte_len && !ci_equals(pte, pte_len, "binary") &&
            !ci_equals(pte, pte_len, "7bit") && !ci_equals(pte, pte_len, "8bit"))
            goto done; /* base64/quoted-printable: Python decodes */

        /* filename\s*=\s*("..."|token) in the disposition */
        const uint8_t *fname = NULL;
        size_t fname_len = 0;
        for (size_t i = 0; disp != NULL && i + 8 <= disp_len; i++) {
            if (!ci_prefix(disp + i, disp_len - i, "filename")) continue;
            size_t j = i + 8;
            while (j < disp_len && w_isspace(disp[j])) j++;
            if (j >= disp_len || disp[j] != '=') continue;
            j++;
            while (j < disp_len && w_isspace(disp[j])) j++;
            if (j < disp_len && disp[j] == '"') {
                size_t k = j + 1;
                while (k < disp_len && disp[k] != '"') {
                    if (disp[k] == '\\') goto done; /* escaped: Python */
                    k++;
                }
                if (k >= disp_len) goto done; /* unterminated quote:
                    Python's regex falls back to its token branch and
                    KEEPS the opening quote in the name — decline so
                    the fallback rules on it */
                fname = disp + j + 1;
                fname_len = k - (j + 1);
            } else {
                size_t k = j;
                while (k < disp_len && disp[k] != ';' && !w_isspace(disp[k]))
                    k++;
                if (k == j) continue;
                fname = disp + j;
                fname_len = k - j;
            }
            break;
        }

        weed_part cand = {
            .data = payload,
            .data_len = payload_len,
            .filename = fname,
            .filename_len = fname_len,
            .mime = ptype,
            .mime_len = ptype_len,
            .is_gzipped = penc_len && ci_equals(penc, penc_len, "gzip"),
        };
        if (fname_len) {
            *out = cand;
            rc = WEED_POST_OK;
            goto done;
        }
        if (!have_first) {
            first = cand;
            have_first = 1;
        }
        if (last) break;
        pos = npos;
    }
    if (have_first) {
        *out = first;
        rc = WEED_POST_OK;
    }
done:
    free(delim);
    return rc;
}

/* bytes valid for both the needle fields and the JSON reply without
 * escaping: printable ASCII minus quote and backslash. Anything else
 * declines (Python's latin-1 → str → utf-8 round-trip and json.dumps
 * escapes would diverge from raw bytes). */
static int ascii_clean(const uint8_t *s, size_t n) {
    for (size_t i = 0; i < n; i++)
        if (s[i] < 0x20 || s[i] > 0x7e || s[i] == '"' || s[i] == '\\') return 0;
    return 1;
}

static int ends_jpg(const uint8_t *s, size_t n) {
    if (n >= 4) {
        const uint8_t *e = s + n - 4;
        if (e[0] == '.' && w_lower(e[1]) == 'j' && w_lower(e[2]) == 'p' &&
            w_lower(e[3]) == 'g')
            return 1;
    }
    if (n >= 5) {
        const uint8_t *e = s + n - 5;
        if (e[0] == '.' && w_lower(e[1]) == 'j' && w_lower(e[2]) == 'p' &&
            w_lower(e[3]) == 'e' && w_lower(e[4]) == 'g')
            return 1;
    }
    return 0;
}

/* --- the one-pass POST ---------------------------------------------- */

typedef struct {
    /* in */
    const uint8_t *body;
    size_t body_len;
    const uint8_t *ctype;
    size_t ctype_len;
    int raw_gzipped;
    const uint8_t *q_name;   /* ?filename= (wins) */
    size_t q_name_len;
    const uint8_t *url_name; /* path filename (last resort) */
    size_t url_name_len;
    const uint8_t *pairs;
    size_t pairs_len;
    uint32_t base_flags;
    uint32_t cookie;
    uint64_t id;
    int version;
    uint64_t last_modified;
    uint64_t append_at_ns;
    int fd;
    int64_t offset;
    int fix_jpg;
    /* out */
    char reply[384];
    size_t reply_len;
    long total;
    uint32_t size;
    int io_errno;
    /* per-stage wall seconds for the tracing plane (docs/TRACING.md):
     * the Python fallback emits the SAME five stage names, so a bench
     * `--trace` breakdown reads identically whichever path served */
    double st_parse, st_assemble, st_crc, st_pwrite, st_reply;
} weed_post_req;

static int weed_post(weed_post_req *r) {
    if (r->version != 2 && r->version != 3) return WEED_POST_DECLINE;
    if (r->pairs_len >= 65536) return WEED_POST_DECLINE;
    r->st_parse = r->st_assemble = r->st_crc = r->st_pwrite = r->st_reply = 0.0;
    double t_stage = w_monotonic();

    const uint8_t *data = r->body;
    size_t data_len = r->body_len;
    const uint8_t *mime = r->ctype;
    size_t mime_len = r->ctype_len;
    const uint8_t *part_name = NULL;
    size_t part_name_len = 0;
    int is_gz = r->raw_gzipped;
    uint8_t *v = NULL;

    int multipart = ci_prefix(r->ctype, r->ctype_len, "multipart/form-data");
    if (multipart) {
        const uint8_t *b;
        size_t bn;
        if (parse_boundary(r->ctype, r->ctype_len, &b, &bn) != 0)
            return WEED_POST_DECLINE;
        /* V = CRLF + body: the virtual leading CRLF makes the first
         * boundary parse like every other delimiter line (same
         * materialization the Python parser performs) */
        v = malloc(r->body_len + 2);
        if (v == NULL) return WEED_POST_DECLINE;
        v[0] = '\r';
        v[1] = '\n';
        memcpy(v + 2, r->body, r->body_len);
        weed_part part;
        if (scan_multipart(v, r->body_len + 2, b, bn, &part) != WEED_POST_OK) {
            free(v);
            return WEED_POST_DECLINE;
        }
        data = part.data;
        data_len = part.data_len;
        mime = part.mime;
        mime_len = part.mime_len;
        part_name = part.filename;
        part_name_len = part.filename_len;
        is_gz = part.is_gzipped;
    }
    r->st_parse = w_monotonic() - t_stage;

    int rc = WEED_POST_DECLINE;
    if (data_len == 0) goto out; /* empty body: tombstone-shaped, Python */

    /* fname = q.filename or part.filename or url filename */
    const uint8_t *name = r->q_name;
    size_t name_len = r->q_name_len;
    if (name_len == 0) { name = part_name; name_len = part_name_len; }
    if (name_len == 0) { name = r->url_name; name_len = r->url_name_len; }
    if (name_len > 255) goto out;       /* reply carries it unescaped-long */
    if (!ascii_clean(name, name_len)) goto out;
    if (!ascii_clean(mime, mime_len)) goto out;
    if (r->fix_jpg && name_len && ends_jpg(name, name_len)) goto out;
    if (!is_gz && data_len > 128) {
        const uint8_t *ext;
        size_t ext_len;
        w_splitext(name, name_len, &ext, &ext_len);
        if (w_is_gzippable(ext, ext_len, mime, mime_len, data, data_len))
            goto out; /* Python compresses; bytes would diverge */
    }

    uint32_t flags = r->base_flags;
    if (is_gz) flags |= 0x01;                          /* FLAG_GZIP */
    if (name_len) flags |= 0x02;                       /* FLAG_HAS_NAME */
    /* Python: `if ctype and len(ctype) < 256 and ctype !=
     * "application/octet-stream"` — an exact case-sensitive compare */
    int mime_ok =
        mime_len > 0 && mime_len < 256 &&
        !(mime_len == 24 &&
          memcmp(mime, "application/octet-stream", 24) == 0);
    if (mime_ok) flags |= 0x04;                        /* FLAG_HAS_MIME */
    if (r->pairs_len) flags |= 0x20;                   /* FLAG_HAS_PAIRS */

    long cap = weed_needle_max_size((uint32_t)data_len, (uint32_t)name_len,
                                    (uint32_t)(mime_ok ? mime_len : 0),
                                    (uint32_t)r->pairs_len);
    t_stage = w_monotonic();
    uint8_t *rec = malloc((size_t)cap);
    if (rec == NULL) goto out;
    uint32_t size, crc;
    long total = weed_needle_encode(
        rec, r->cookie, r->id, data, (uint32_t)data_len, flags, name,
        (uint32_t)name_len, mime_ok ? mime : (const uint8_t *)"",
        (uint32_t)(mime_ok ? mime_len : 0), r->last_modified, NULL, r->pairs,
        (uint32_t)r->pairs_len, r->version, r->append_at_ns, &size, &crc,
        &r->st_crc);
    if (total < 0) {
        free(rec);
        goto out;
    }
    r->st_assemble = w_monotonic() - t_stage - r->st_crc;

    t_stage = w_monotonic();
    size_t done = 0;
    while (done < (size_t)total) {
        ssize_t w = pwrite(r->fd, rec + done, (size_t)total - done,
                           (off_t)(r->offset + (int64_t)done));
        if (w < 0) {
            if (errno == EINTR) continue;
            r->io_errno = errno;
            free(rec);
            rc = WEED_POST_IOERR;
            goto out;
        }
        if (w == 0) {
            r->io_errno = EIO;
            free(rec);
            rc = WEED_POST_IOERR;
            goto out;
        }
        done += (size_t)w;
    }
    free(rec);
    r->st_pwrite = w_monotonic() - t_stage;

    /* b'{"name": %s, "size": %d, "eTag": "%s"}' with %s = json.dumps
     * (trivial for the ascii_clean-gated name) and the etag the raw
     * CRC32-C as 8 lowercase hex digits (bytesutil.put_u32().hex()) */
    t_stage = w_monotonic();
    r->reply_len = (size_t)snprintf(
        r->reply, sizeof(r->reply),
        "{\"name\": \"%.*s\", \"size\": %u, \"eTag\": \"%08x\"}",
        (int)name_len, name ? (const char *)name : "", size, crc);
    r->st_reply = w_monotonic() - t_stage;
    r->total = total;
    r->size = size;
    rc = WEED_POST_OK;
out:
    free(v);
    return rc;
}
