from seaweedfs_tpu.shell.command_env import CommandEnv
from seaweedfs_tpu.shell.commands import COMMANDS, run_command

__all__ = ["CommandEnv", "COMMANDS", "run_command"]
