from seaweedfs_tpu.shell.command_env import CommandEnv
from seaweedfs_tpu.shell.commands import COMMANDS, run_command
from seaweedfs_tpu.shell import fs_commands  # noqa: F401  (registers fs.*)

__all__ = ["CommandEnv", "COMMANDS", "run_command"]
