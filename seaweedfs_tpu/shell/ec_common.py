"""EC admin planners — pure functions over in-memory EcNode state.

Behavioral match of weed/shell/command_ec_common.go and
command_ec_balance.go. Every planner takes `apply` (the reference's
applyBalancing flag, threaded through command_ec_common.go:18) so tests
can run the full plan without a cluster; when apply=True the plan step
issues the copy/mount/unmount/delete gRPC verbs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from seaweedfs_tpu.ec.ec_files import DATA_SHARDS, PARITY_SHARDS

TOTAL_SHARDS_COUNT = DATA_SHARDS + PARITY_SHARDS

from seaweedfs_tpu.pb import rpc, volume_pb2
from seaweedfs_tpu.shell.command_env import CommandEnv, TopologyNodeInfo


def shard_bits_to_ids(bits: int) -> list[int]:
    return [i for i in range(TOTAL_SHARDS_COUNT) if bits & (1 << i)]


def ids_to_shard_bits(ids) -> int:
    bits = 0
    for i in ids:
        bits |= 1 << i
    return bits


@dataclass
class EcNode:
    """Planner view of one volume server (command_ec_common.go EcNode)."""

    url: str
    dc: str
    rack: str
    free_ec_slot: int
    # vid -> (collection, shard-bit mask)
    ec_shards: dict[int, tuple[str, int]] = field(default_factory=dict)

    def shard_count(self) -> int:
        return sum(bin(bits).count("1") for _, bits in self.ec_shards.values())

    def local_shard_ids(self, vid: int) -> list[int]:
        entry = self.ec_shards.get(vid)
        return shard_bits_to_ids(entry[1]) if entry else []

    def add_shards(self, vid: int, collection: str, shard_ids) -> None:
        col, bits = self.ec_shards.get(vid, (collection, 0))
        self.ec_shards[vid] = (col, bits | ids_to_shard_bits(shard_ids))
        self.free_ec_slot -= len(list(shard_ids))

    def delete_shards(self, vid: int, shard_ids) -> None:
        entry = self.ec_shards.get(vid)
        if not entry:
            return
        col, bits = entry
        bits &= ~ids_to_shard_bits(shard_ids)
        if bits:
            self.ec_shards[vid] = (col, bits)
        else:
            del self.ec_shards[vid]
        self.free_ec_slot += len(list(shard_ids))


def collect_ec_nodes(env: CommandEnv, selected_dc: str = "") -> list[EcNode]:
    """Build planner state from one VolumeList call
    (command_ec_common.go collectEcNodes)."""
    dump = env.collect_topology()
    return ec_nodes_from_topology(dump.nodes, selected_dc)


def ec_nodes_from_topology(
    nodes: list[TopologyNodeInfo], selected_dc: str = ""
) -> list[EcNode]:
    out = []
    for n in nodes:
        if selected_dc and n.dc != selected_dc:
            continue
        # free slots in shard units: each volume slot holds a full
        # 14-shard set (command_ec_common.go countFreeShardSlots)
        used = len(n.volumes)
        free = max(0, (n.max_volumes - used)) * TOTAL_SHARDS_COUNT
        en = EcNode(url=n.url, dc=n.dc, rack=n.rack, free_ec_slot=free)
        for s in n.ec_shards:
            en.ec_shards[s["Id"]] = (s.get("Collection", ""), s["EcIndexBits"])
            en.free_ec_slot -= bin(s["EcIndexBits"]).count("1")
        out.append(en)
    return out


def balanced_ec_distribution(nodes: list[EcNode], shard_count: int = TOTAL_SHARDS_COUNT) -> list[EcNode]:
    """Assign `shard_count` shards round-robin over nodes sorted by
    free slots, skipping full nodes (command_ec_encode.go:240
    balancedEcDistribution after sortEcNodesByFreeslotsDecending)."""
    if not nodes:
        return []
    order = sorted(nodes, key=lambda n: -n.free_ec_slot)
    # spreadEcShards errors upfront when totalFreeEcSlots < TotalShardsCount;
    # same here — callers treat [] as "no capacity"
    if sum(max(n.free_ec_slot, 0) for n in order) < shard_count:
        return []
    assigned = {n.url: 0 for n in order}
    picked: list[EcNode] = []
    i = 0
    while len(picked) < shard_count:
        n = order[i % len(order)]
        if n.free_ec_slot - assigned[n.url] > 0:
            picked.append(n)
            assigned[n.url] += 1
        i += 1
    return picked


# ----------------------------------------------------------------------
# gRPC move primitives (no-ops when apply=False)


def copy_and_mount_shards(
    env: CommandEnv,
    target: EcNode,
    vid: int,
    collection: str,
    shard_ids: list[int],
    source_url: str,
    apply: bool = True,
) -> None:
    """Copy shards from source to target then mount them
    (oneServerCopyAndMountEcShardsFromSource)."""
    if apply:
        with env.volume_channel(target.url) as ch:
            stub = rpc.volume_stub(ch)
            if target.url != source_url:
                stub.VolumeEcShardsCopy(
                    volume_pb2.VolumeEcShardsCopyRequest(
                        volume_id=vid,
                        collection=collection,
                        shard_ids=shard_ids,
                        copy_ecx_file=True,
                        source_data_node=source_url,
                    )
                )
            stub.VolumeEcShardsMount(
                volume_pb2.VolumeEcShardsMountRequest(
                    volume_id=vid, collection=collection, shard_ids=shard_ids
                )
            )


def unmount_and_delete_shards(
    env: CommandEnv,
    source_url: str,
    vid: int,
    collection: str,
    shard_ids: list[int],
    apply: bool = True,
) -> None:
    if apply:
        with env.volume_channel(source_url) as ch:
            stub = rpc.volume_stub(ch)
            stub.VolumeEcShardsUnmount(
                volume_pb2.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=shard_ids)
            )
            stub.VolumeEcShardsDelete(
                volume_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection, shard_ids=shard_ids
                )
            )


def move_mounted_shard(
    env: CommandEnv,
    source: EcNode,
    dest: EcNode,
    vid: int,
    shard_id: int,
    apply: bool = True,
) -> None:
    """Move one mounted shard source→dest, updating planner state
    (moveMountedShardToEcNode)."""
    collection = source.ec_shards.get(vid, ("", 0))[0]
    copy_and_mount_shards(env, dest, vid, collection, [shard_id], source.url, apply)
    unmount_and_delete_shards(env, source.url, vid, collection, [shard_id], apply)
    dest.add_shards(vid, collection, [shard_id])
    source.delete_shards(vid, [shard_id])


# ----------------------------------------------------------------------
# balance planners (command_ec_balance.go)


def dedup_ec_shards(env: CommandEnv, nodes: list[EcNode], vid: int, apply: bool = True) -> int:
    """Drop duplicate copies of a shard, keeping the copy on the node
    with the fewest shards removed last (doDeduplicateEcShards)."""
    holders: dict[int, list[EcNode]] = {}
    for n in nodes:
        for sid in n.local_shard_ids(vid):
            holders.setdefault(sid, []).append(n)
    removed = 0
    for sid, owners in holders.items():
        if len(owners) <= 1:
            continue
        owners.sort(key=lambda n: n.shard_count(), reverse=True)
        for extra in owners[:-1]:  # keep the least-loaded owner
            collection = extra.ec_shards.get(vid, ("", 0))[0]
            unmount_and_delete_shards(env, extra.url, vid, collection, [sid], apply)
            extra.delete_shards(vid, [sid])
            removed += 1
    return removed


def balance_across_racks(env: CommandEnv, nodes: list[EcNode], vid: int, apply: bool = True) -> int:
    """Spread one volume's shards so no rack holds more than
    ceil(total/racks) (doBalanceEcShardsAcrossRacks)."""
    racks: dict[str, list[EcNode]] = {}
    for n in nodes:
        racks.setdefault(n.rack, []).append(n)
    shards_per_rack: dict[str, list[tuple[EcNode, int]]] = {r: [] for r in racks}
    total = 0
    for n in nodes:
        for sid in n.local_shard_ids(vid):
            shards_per_rack[n.rack].append((n, sid))
            total += 1
    if total == 0 or len(racks) <= 1:
        return 0
    average = -(-total // len(racks))  # ceil
    moves = 0
    overflow: list[tuple[EcNode, int]] = []
    for rack, entries in shards_per_rack.items():
        while len(entries) > average:
            overflow.append(entries.pop())
    for source, sid in overflow:
        # pick the rack with the fewest shards of this vid, then the
        # freest node on it
        dest_rack = min(shards_per_rack, key=lambda r: len(shards_per_rack[r]))
        candidates = [n for n in racks[dest_rack] if n.free_ec_slot > 0 and n is not source]
        if not candidates:
            continue
        dest = max(candidates, key=lambda n: n.free_ec_slot)
        move_mounted_shard(env, source, dest, vid, sid, apply)
        shards_per_rack[dest_rack].append((dest, sid))
        moves += 1
    return moves


def balance_within_racks(env: CommandEnv, nodes: list[EcNode], vid: int, apply: bool = True) -> int:
    """Within each rack, spread one volume's shards evenly over its
    nodes (doBalanceEcShardsWithinRacks)."""
    racks: dict[str, list[EcNode]] = {}
    for n in nodes:
        racks.setdefault(n.rack, []).append(n)
    moves = 0
    for rack_nodes in racks.values():
        owned: list[tuple[EcNode, int]] = []
        for n in rack_nodes:
            for sid in n.local_shard_ids(vid):
                owned.append((n, sid))
        if not owned or len(rack_nodes) <= 1:
            continue
        average = -(-len(owned) // len(rack_nodes))
        counts = {n.url: len(n.local_shard_ids(vid)) for n in rack_nodes}
        for source, sid in owned:
            if counts[source.url] <= average:
                continue
            candidates = [
                n
                for n in rack_nodes
                if counts[n.url] < average and n.free_ec_slot > 0 and n is not source
            ]
            if not candidates:
                continue
            dest = max(candidates, key=lambda n: n.free_ec_slot)
            move_mounted_shard(env, source, dest, vid, sid, apply)
            counts[source.url] -= 1
            counts[dest.url] += 1
            moves += 1
    return moves


def balance_ec_rack(env: CommandEnv, rack_nodes: list[EcNode], apply: bool = True) -> int:
    """Even out *total* shard counts inside one rack without stacking
    the same volume (balanceEcRack)."""
    if len(rack_nodes) <= 1:
        return 0
    total = sum(n.shard_count() for n in rack_nodes)
    average = total / len(rack_nodes)
    moves = 0
    moved = True
    while moved:
        moved = False
        nodes = sorted(rack_nodes, key=lambda n: n.shard_count())
        low, high = nodes[0], nodes[-1]
        if high.shard_count() > average and low.shard_count() + 1 <= average:
            for vid in list(high.ec_shards):
                if vid in low.ec_shards:
                    continue
                sids = high.local_shard_ids(vid)
                if not sids:
                    continue
                move_mounted_shard(env, high, low, vid, sids[0], apply)
                moves += 1
                moved = True
                break
    return moves


def balance_ec_volumes(
    env: CommandEnv,
    nodes: list[EcNode],
    collection: str | None = None,
    apply: bool = True,
) -> dict:
    """Full ec.balance pass: dedup → across racks → within racks →
    per-rack totals (balanceEcVolumes + balanceEcRack)."""
    vids = sorted(
        {
            vid
            for n in nodes
            for vid, (col, _) in n.ec_shards.items()
            if collection is None or col == collection
        }
    )
    stats = {"dedup": 0, "across_racks": 0, "within_racks": 0, "rack_total": 0}
    for vid in vids:
        stats["dedup"] += dedup_ec_shards(env, nodes, vid, apply)
    for vid in vids:
        stats["across_racks"] += balance_across_racks(env, nodes, vid, apply)
    for vid in vids:
        stats["within_racks"] += balance_within_racks(env, nodes, vid, apply)
    racks: dict[str, list[EcNode]] = {}
    for n in nodes:
        racks.setdefault(n.rack, []).append(n)
    for rack_nodes in racks.values():
        stats["rack_total"] += balance_ec_rack(env, rack_nodes, apply)
    return stats
