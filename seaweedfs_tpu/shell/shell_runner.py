"""Interactive admin REPL (weed/shell/shell_liner.go) and the master's
maintenance cron runner (weed/server/master_server.go:183
startAdminScripts: when leader, run the configured admin script lines
on a fixed period)."""

from __future__ import annotations

import sys
import threading

from seaweedfs_tpu.shell.command_env import CommandEnv
from seaweedfs_tpu.shell.commands import COMMANDS, run_command


def _arm_readline():
    """Line editing + history + tab completion on a real terminal —
    the operator experience the reference gets from peterh/liner
    (shell_liner.go: history file, prompt editing, command completion).
    No-op when stdin is piped/scripted or readline is unavailable."""
    import atexit
    import os

    try:
        import readline
    except ImportError:  # pragma: no cover - always present on linux
        return None

    def complete(text, state):
        names = sorted(n for n in COMMANDS if n.startswith(text))
        return names[state] if state < len(names) else None

    readline.set_completer(complete)
    readline.set_completer_delims(" \t")
    readline.parse_and_bind("tab: complete")
    hist = os.path.expanduser("~/.seaweedfs_tpu_shell_history")
    try:
        readline.read_history_file(hist)
    except OSError:
        pass
    readline.set_history_length(1000)
    atexit.register(lambda: _save_history(readline, hist))
    return readline


def _save_history(readline, hist: str) -> None:
    try:
        readline.write_history_file(hist)
    except OSError:
        pass


def run_shell(masters: list[str], stdin=None, stdout=None) -> None:
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    env = CommandEnv(masters)
    # readline only drives the REAL tty path: input() reads through it
    # when stdin/stdout are the process's own terminal
    interactive = (
        stdin is sys.stdin
        and stdout is sys.stdout
        and hasattr(stdin, "isatty")
        and stdin.isatty()
    )
    if interactive:
        _arm_readline()
    print("seaweedfs-tpu shell; `help` lists commands, `exit` quits", file=stdout)
    while True:
        if interactive:
            try:
                line = input("> ")
            except EOFError:
                return
            except KeyboardInterrupt:
                print(file=stdout)
                continue
        else:
            print("> ", end="", file=stdout, flush=True)
            line = stdin.readline()
            if not line:
                return
        if line.strip() in ("exit", "quit"):
            return
        line = line.strip()
        if not line:
            continue
        try:
            out = run_command(env, line)
            if out:
                print(out, end="", file=stdout)
        except Exception as e:  # noqa: BLE001 — REPL keeps running
            print(f"error: {e}", file=stdout)


DEFAULT_MAINTENANCE_SCRIPTS = [
    # what the reference master cron runs every 17 min when leader
    # (master_server.go:183-249)
    "ec.encode -fullPercent=95",
    "ec.rebuild -force",
    "ec.balance -force",
    "volume.balance -force",
    "volume.fix.replication",
]


class MaintenanceRunner:
    """Background admin-script loop (startAdminScripts). Attach to a
    master with `start()`; each period it runs the script lines through
    the same command table the shell uses."""

    def __init__(
        self,
        masters: list[str],
        scripts: list[str] | None = None,
        period_s: float = 17 * 60,
        is_leader=lambda: True,
    ):
        self.env = CommandEnv(masters)
        self.scripts = DEFAULT_MAINTENANCE_SCRIPTS if scripts is None else scripts
        self.period_s = period_s
        self.is_leader = is_leader
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_output: list[str] = []

    def run_once(self) -> list[str]:
        outputs = []
        for line in self.scripts:
            parts = line.split()
            if not parts:
                continue
            if parts[0] not in COMMANDS:
                outputs.append(f"{line}: unknown command")
                continue
            try:
                outputs.append(run_command(self.env, line))
            except Exception as e:  # noqa: BLE001 — cron keeps going
                outputs.append(f"{line}: {e}")
        self.last_output = outputs
        return outputs

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                if self.is_leader():
                    self.run_once()
            except Exception as e:  # noqa: BLE001 — the cron thread must survive
                self.last_output = [f"maintenance pass failed: {e}"]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
