"""Shell command environment.

Behavioral match of weed/shell/commands.go CommandEnv: holds the master
address, fetches the topology (one VolumeList call feeds every
planner), and opens volume-server gRPC channels on demand.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import grpc

from seaweedfs_tpu.pb import master_pb2, rpc
from seaweedfs_tpu.pb.rpc import grpc_address


@dataclass
class TopologyNodeInfo:
    """One data node as seen in the master's VolumeList dump."""

    url: str
    public_url: str
    dc: str
    rack: str
    max_volumes: int
    volumes: list[dict] = field(default_factory=list)
    ec_shards: list[dict] = field(default_factory=list)  # {Id, Collection, EcIndexBits}


@dataclass
class TopologyDump:
    volume_size_limit_mb: int
    nodes: list[TopologyNodeInfo] = field(default_factory=list)


class CommandEnv:
    def __init__(self, masters: list[str]):
        self.masters = list(masters)
        # fs.* context (commands.go CommandEnv option.FilerHost/directory):
        # set by `fs.cd http://<filer>:<port>/path`; subsequent relative
        # fs paths resolve against (filer, cwd)
        self.filer: str = ""
        self.cwd: str = "/"

    @property
    def master(self) -> str:
        return self.masters[0]

    # ------------------------------------------------------------------
    # fs path resolution (commandEnv.parseUrl, commands.go:54-113)
    def parse_fs_path(self, input_path: str) -> tuple[str, str]:
        """'http://filer:8888/a/b' | '/a/b' | 'b' → (filer, abs path)."""
        import posixpath
        import urllib.parse

        if input_path.startswith(("http://", "https://")):
            u = urllib.parse.urlparse(input_path)
            return u.netloc, posixpath.normpath(u.path or "/")
        if not self.filer:
            raise ValueError(
                "no filer selected; use fs.cd http://<filer>:<port>/path first"
            )
        if input_path.startswith("/"):
            return self.filer, posixpath.normpath(input_path)
        return self.filer, posixpath.normpath(
            posixpath.join(self.cwd, input_path)
        )

    def filer_channel(self, filer: str) -> grpc.Channel:
        return rpc.dial(grpc_address(filer))

    # ------------------------------------------------------------------
    def master_stub(self, ch: grpc.Channel) -> rpc.Stub:
        return rpc.master_stub(ch)

    def master_channel(self) -> grpc.Channel:
        return rpc.dial(grpc_address(self.master))

    def volume_channel(self, url: str) -> grpc.Channel:
        return rpc.dial(grpc_address(url))

    # ------------------------------------------------------------------
    def collect_topology(self) -> TopologyDump:
        """VolumeList → parsed per-node volume/EC info (the one call
        every planner starts from, command_ec_common.go collectEcNodes)."""
        with self.master_channel() as ch:
            resp = rpc.master_stub(ch).VolumeList(master_pb2.VolumeListRequest())
        topo = json.loads(resp.topology_json)
        dump = TopologyDump(volume_size_limit_mb=resp.volume_size_limit_mb)
        for dc in topo.get("DataCenters", []):
            for rack in dc.get("Racks", []):
                for dn in rack.get("DataNodes", []):
                    dump.nodes.append(
                        TopologyNodeInfo(
                            url=dn["Url"],
                            public_url=dn.get("PublicUrl", dn["Url"]),
                            dc=dc["Id"],
                            rack=rack["Id"],
                            max_volumes=dn.get("Max", 0),
                            volumes=dn.get("VolumeInfos", []),
                            ec_shards=dn.get("EcShardInfos", []),
                        )
                    )
        return dump
