"""Admin shell commands.

Behavioral match of weed/shell/ (the reference's full REPL command set).
Implemented here:
  ec.encode  ec.batch  ec.decode  ec.rebuild  ec.balance
  volume.balance  volume.fix.replication  volume.vacuum  volume.list
  volume.delete  volume.mount  volume.unmount  volume.move  volume.copy
  volume.tier.upload  volume.tier.download
  collection.list  collection.delete
The 11 fs.* commands (cd/pwd/ls/du/cat/tree/mv/meta.cat/meta.save/
meta.load/meta.notify) live in shell/fs_commands.py, registered on
import by shell/__init__.py.

Each command is `run(env, args, out) -> None`, printing human output to
`out` (an io.TextIOBase). Planners accept -force/-apply the same way the
reference threads applyBalancing (command_ec_common.go:18).
"""

from __future__ import annotations

import io
import shlex
import time

import grpc

from seaweedfs_tpu.pb import master_pb2, rpc, volume_pb2
from seaweedfs_tpu.shell import ec_common
from seaweedfs_tpu.shell.command_env import CommandEnv, TopologyDump

COMMANDS: dict[str, "Command"] = {}


class Command:
    name = ""
    help = ""

    def run(self, env: CommandEnv, args: list[str], out: io.TextIOBase) -> None:
        raise NotImplementedError


def register(cls):
    COMMANDS[cls.name] = cls()
    return cls


def run_command(env: CommandEnv, line: str, out: io.TextIOBase | None = None) -> str:
    """Parse + run one command line; returns captured output."""
    buf = io.StringIO()
    parts = shlex.split(line)
    if not parts:
        return ""
    cmd = COMMANDS.get(parts[0])
    if cmd is None:
        raise ValueError(f"unknown command {parts[0]!r}; try `help`")
    cmd.run(env, parts[1:], out or buf)
    return buf.getvalue()


def _flag(args: list[str], name: str, default: str = "") -> str:
    """-name=value or -name value."""
    for i, a in enumerate(args):
        if a == f"-{name}" and i + 1 < len(args):
            return args[i + 1]
        if a.startswith(f"-{name}="):
            return a.split("=", 1)[1]
    return default


def _has_flag(args: list[str], name: str) -> bool:
    return any(a == f"-{name}" or a.startswith(f"-{name}=") for a in args)



def _lookup_collection(env: CommandEnv, vid: int) -> str:
    for n in env.collect_topology().nodes:
        for v in n.volumes:
            if v["Id"] == vid:
                return v["Collection"]
    return ""


def _copy_volume(env: CommandEnv, vid: int, collection: str, src: str, dst: str) -> None:
    with env.volume_channel(dst) as ch:
        rpc.volume_stub(ch).VolumeCopy(
            volume_pb2.VolumeCopyRequest(
                volume_id=vid, collection=collection, source_data_node=src
            )
        )


def _move_volume(env: CommandEnv, vid: int, collection: str, src: str, dst: str) -> None:
    """copy + delete with a readonly guard on the source so no write
    lands between the copy and the delete (the reference tails instead,
    command_volume_move.go; readonly-then-move trades brief write
    unavailability of this volume for the same safety)."""
    with env.volume_channel(src) as ch:
        rpc.volume_stub(ch).VolumeMarkReadonly(
            volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
        )
    try:
        _copy_volume(env, vid, collection, src, dst)
    except Exception:
        # copy failed: revert the readonly mark so the source volume
        # keeps serving writes instead of staying wedged
        with env.volume_channel(src) as ch:
            rpc.volume_stub(ch).VolumeMarkWritable(
                volume_pb2.VolumeMarkWritableRequest(volume_id=vid)
            )
        raise
    with env.volume_channel(src) as ch:
        rpc.volume_stub(ch).VolumeDelete(volume_pb2.VolumeDeleteRequest(volume_id=vid))


# ----------------------------------------------------------------------
# collection / volume info


@register
class CollectionList(Command):
    name = "collection.list"
    help = "list all collections"

    def run(self, env, args, out):
        with env.master_channel() as ch:
            resp = rpc.master_stub(ch).CollectionList(
                master_pb2.CollectionListRequest(
                    include_normal_volumes=True, include_ec_volumes=True
                )
            )
        for c in resp.collections:
            print(f"collection:{c}", file=out)


@register
class CollectionDelete(Command):
    name = "collection.delete"
    help = "collection.delete <collection>"

    def run(self, env, args, out):
        if not args:
            raise ValueError("usage: collection.delete <collection>")
        with env.master_channel() as ch:
            rpc.master_stub(ch).CollectionDelete(
                master_pb2.CollectionDeleteRequest(name=args[0])
            )
        print(f"collection {args[0]} is deleted", file=out)


@register
class VolumeList(Command):
    name = "volume.list"
    help = "list all volumes"

    def run(self, env, args, out):
        dump = env.collect_topology()
        for n in dump.nodes:
            print(f"node {n.url} dc:{n.dc} rack:{n.rack}", file=out)
            for v in sorted(n.volumes, key=lambda v: v["Id"]):
                print(
                    f"  volume id:{v['Id']} size:{v['Size']} "
                    f"collection:{v['Collection']!r} file_count:{v['FileCount']} "
                    f"delete_count:{v['DeleteCount']} read_only:{v['ReadOnly']}",
                    file=out,
                )
            for s in sorted(n.ec_shards, key=lambda s: s["Id"]):
                sids = ec_common.shard_bits_to_ids(s["EcIndexBits"])
                print(f"  ec volume id:{s['Id']} shards:{sids}", file=out)


# ----------------------------------------------------------------------
# volume admin


@register
class VolumeDelete(Command):
    name = "volume.delete"
    help = "volume.delete -node <host:port> -volumeId <vid>"

    def run(self, env, args, out):
        node = _flag(args, "node")
        vid = int(_flag(args, "volumeId"))
        with env.volume_channel(node) as ch:
            rpc.volume_stub(ch).VolumeDelete(
                volume_pb2.VolumeDeleteRequest(volume_id=vid)
            )
        print(f"volume {vid} deleted from {node}", file=out)


@register
class VolumeMount(Command):
    name = "volume.mount"
    help = "volume.mount -node <host:port> -volumeId <vid>"

    def run(self, env, args, out):
        node = _flag(args, "node")
        vid = int(_flag(args, "volumeId"))
        with env.volume_channel(node) as ch:
            rpc.volume_stub(ch).VolumeMount(volume_pb2.VolumeMountRequest(volume_id=vid))
        print(f"volume {vid} mounted on {node}", file=out)


@register
class VolumeUnmount(Command):
    name = "volume.unmount"
    help = "volume.unmount -node <host:port> -volumeId <vid>"

    def run(self, env, args, out):
        node = _flag(args, "node")
        vid = int(_flag(args, "volumeId"))
        with env.volume_channel(node) as ch:
            rpc.volume_stub(ch).VolumeUnmount(
                volume_pb2.VolumeUnmountRequest(volume_id=vid)
            )
        print(f"volume {vid} unmounted on {node}", file=out)


@register
class VolumeCopy(Command):
    name = "volume.copy"
    help = "volume.copy -from <host:port> -to <host:port> -volumeId <vid>"

    def run(self, env, args, out):
        src = _flag(args, "from")
        dst = _flag(args, "to")
        vid = int(_flag(args, "volumeId"))
        _copy_volume(env, vid, _lookup_collection(env, vid), src, dst)
        print(f"volume {vid} copied {src} => {dst}", file=out)


@register
class VolumeMove(Command):
    name = "volume.move"
    help = "volume.move -from <host:port> -to <host:port> -volumeId <vid>"

    def run(self, env, args, out):
        src = _flag(args, "from")
        dst = _flag(args, "to")
        vid = int(_flag(args, "volumeId"))
        _move_volume(env, vid, _lookup_collection(env, vid), src, dst)
        print(f"volume {vid} moved {src} => {dst}", file=out)


@register
class VolumeVacuum(Command):
    name = "volume.vacuum"
    help = "volume.vacuum [-garbageThreshold 0.3] — run the 4-phase vacuum across the cluster"

    def run(self, env, args, out):
        threshold = float(_flag(args, "garbageThreshold", "0.3"))
        dump = env.collect_topology()
        compacted = 0
        for n in dump.nodes:
            for v in n.volumes:
                if v["ReadOnly"]:
                    continue
                with env.volume_channel(n.url) as ch:
                    stub = rpc.volume_stub(ch)
                    check = stub.VacuumVolumeCheck(
                        volume_pb2.VacuumVolumeCheckRequest(volume_id=v["Id"])
                    )
                    if check.garbage_ratio <= threshold:
                        continue
                    stub.VacuumVolumeCompact(
                        volume_pb2.VacuumVolumeCompactRequest(volume_id=v["Id"])
                    )
                    stub.VacuumVolumeCommit(
                        volume_pb2.VacuumVolumeCommitRequest(volume_id=v["Id"])
                    )
                    stub.VacuumVolumeCleanup(
                        volume_pb2.VacuumVolumeCleanupRequest(volume_id=v["Id"])
                    )
                compacted += 1
                print(f"vacuumed volume {v['Id']} on {n.url}", file=out)
        print(f"vacuumed {compacted} volumes", file=out)


# ----------------------------------------------------------------------
# volume.balance (command_volume_balance.go)


def plan_volume_balance(dump: TopologyDump, collection: str | None = None) -> list[dict]:
    """Plan moves so every node holds ≈ its share of volumes. Returns
    [{vid, from, to}] without applying."""
    nodes = dump.nodes
    if not nodes:
        return []
    counts = {
        n.url: len([v for v in n.volumes if collection is None or v["Collection"] == collection])
        for n in nodes
    }
    caps = {n.url: max(n.max_volumes, 1) for n in nodes}
    total = sum(counts.values())
    cap_total = sum(caps.values())
    moves = []
    vols_by_node = {
        n.url: [v for v in n.volumes if collection is None or v["Collection"] == collection]
        for n in nodes
    }
    # target share per node proportional to capacity (reference balances
    # by ratio of volume count to max count)
    def ratio(url):
        return counts[url] / caps[url]

    urls = [n.url for n in nodes]
    for _ in range(total):  # each volume moves at most once
        urls.sort(key=ratio)
        low, high = urls[0], urls[-1]
        # move only while the donor's ratio stays above the receiver's
        # even after giving one away (integer cross-multiply, no float)
        if (counts[high] - 1) * caps[low] <= counts[low] * caps[high]:
            break
        candidates = [
            v
            for v in vols_by_node[high]
            if v["Id"] not in {x["Id"] for x in vols_by_node[low]}
        ]
        if not candidates:
            break
        v = candidates[0]
        moves.append({"vid": v["Id"], "collection": v["Collection"], "from": high, "to": low})
        vols_by_node[high].remove(v)
        vols_by_node[low].append(v)
        counts[high] -= 1
        counts[low] += 1
    return moves


@register
class VolumeBalance(Command):
    name = "volume.balance"
    help = "volume.balance [-collection name] [-force]"

    def run(self, env, args, out):
        apply = _has_flag(args, "force")
        collection = _flag(args, "collection") or None
        dump = env.collect_topology()
        moves = plan_volume_balance(dump, collection)
        for m in moves:
            print(f"moving volume {m['vid']} {m['from']} => {m['to']}", file=out)
            if apply:
                _move_volume(env, m["vid"], m["collection"], m["from"], m["to"])
        print(f"planned {len(moves)} moves, applied={apply}", file=out)


# ----------------------------------------------------------------------
# volume.fix.replication (command_volume_fix_replication.go)


def plan_fix_replication(dump: TopologyDump) -> list[dict]:
    """Find under-replicated volumes; plan [{vid, from, to}] copies.
    Placement-aware: prefers a different rack when the placement's
    diff_rack_count calls for it."""
    from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement

    locations: dict[int, list] = {}
    info: dict[int, dict] = {}
    for n in dump.nodes:
        for v in n.volumes:
            locations.setdefault(v["Id"], []).append(n)
            info[v["Id"]] = v
    plans = []
    for vid, nodes_with in locations.items():
        v = info[vid]
        rp = ReplicaPlacement.from_byte(v["ReplicaPlacement"])
        want = rp.copy_count
        have = len(nodes_with)
        if have >= want:
            continue
        present = {n.url for n in nodes_with}
        present_racks = {(n.dc, n.rack) for n in nodes_with}
        candidates = [n for n in dump.nodes if n.url not in present]
        # prefer rack diversity when required
        if rp.diff_rack_count > 0:
            preferred = [n for n in candidates if (n.dc, n.rack) not in present_racks]
            candidates = preferred or candidates
        candidates.sort(key=lambda n: len(n.volumes))
        for target in candidates[: want - have]:
            plans.append(
                {
                    "vid": vid,
                    "collection": v["Collection"],
                    "from": nodes_with[0].url,
                    "to": target.url,
                }
            )
    return plans


@register
class VolumeFixReplication(Command):
    name = "volume.fix.replication"
    help = "volume.fix.replication [-n dry-run]"

    def run(self, env, args, out):
        dry = _has_flag(args, "n")
        dump = env.collect_topology()
        plans = plan_fix_replication(dump)
        for p in plans:
            print(f"replicating volume {p['vid']} {p['from']} => {p['to']}", file=out)
            if not dry:
                _copy_volume(env, p["vid"], p["collection"], p["from"], p["to"])
        print(f"fixed {0 if dry else len(plans)} volumes (planned {len(plans)})", file=out)


# ----------------------------------------------------------------------
# ec.* (command_ec_encode.go / _rebuild.go / _balance.go / _decode.go)


def collect_volume_ids_for_ec_encode(
    dump: TopologyDump, collection: str, quiet_period_s: float, full_percent: float
) -> list[int]:
    """Quiet + full volumes (collectVolumeIdsForEcEncode:258): volumes
    of the collection whose size exceeds full_percent% of the limit.
    (Our heartbeat rows don't carry modified-at; quiet filtering happens
    server-side at generate time.)"""
    limit = dump.volume_size_limit_mb * 1024 * 1024
    vids = []
    for n in dump.nodes:
        for v in n.volumes:
            if v["Collection"] != collection:
                continue
            if v["Size"] >= limit * full_percent / 100.0:
                vids.append(v["Id"])
    return sorted(set(vids))


def do_ec_encode(env: CommandEnv, vid: int, collection: str, out) -> None:
    """The 6-step encode pipeline (volume_grpc_erasure_coding.go:25-36 +
    command_ec_encode.go doEcEncode): mark readonly on all replicas →
    generate on one → spread by balanced distribution → mount → delete
    source shards it no longer owns → confirm all 14 shards registered
    at the master → delete the original volume."""
    with env.master_channel() as ch:
        resp = rpc.master_stub(ch).LookupVolume(
            master_pb2.LookupVolumeRequest(vids=[str(vid)])
        )
    locs = [l.url for e in resp.vid_locations for l in e.locations]
    if not locs:
        raise ValueError(f"volume {vid} not found")
    source = locs[0]

    # 1. mark readonly everywhere (markVolumeReadonly :119)
    for url in locs:
        with env.volume_channel(url) as ch:
            rpc.volume_stub(ch).VolumeMarkReadonly(
                volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
            )
    # 2. generate EC shards on the source
    with env.volume_channel(source) as ch:
        rpc.volume_stub(ch).VolumeEcShardsGenerate(
            volume_pb2.VolumeEcShardsGenerateRequest(volume_id=vid, collection=collection)
        )
    print(f"generated ec shards for volume {vid} on {source}", file=out)

    # 3. spread (spreadEcShards :153 + balancedEcDistribution :240)
    nodes = ec_common.collect_ec_nodes(env)
    allocation = ec_common.balanced_ec_distribution(nodes)
    if len(allocation) < ec_common.TOTAL_SHARDS_COUNT:
        raise RuntimeError(
            f"not enough free ec shard slots to spread volume {vid}; "
            "the generated shards remain on the source, volume untouched"
        )
    per_node: dict[str, list[int]] = {}
    node_by_url = {n.url: n for n in nodes}
    for sid, node in enumerate(allocation):
        per_node.setdefault(node.url, []).append(sid)
    for url, shard_ids in per_node.items():
        ec_common.copy_and_mount_shards(
            env, node_by_url[url], vid, collection, shard_ids, source, apply=True
        )
        print(f"spread ec shards {vid}.{shard_ids} => {url}", file=out)
    # 4. delete shards from the source that moved elsewhere
    moved = [sid for url, sids in per_node.items() if url != source for sid in sids]
    if moved:
        with env.volume_channel(source) as ch:
            rpc.volume_stub(ch).VolumeEcShardsDelete(
                volume_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection, shard_ids=moved
                )
            )
    # 5. confirm the master has REGISTERED every mounted shard before
    # any replica drops the volume. The mount beats ride each holder's
    # own heartbeat stream (immediate on mount via Store.notify_change,
    # but a stream mid-reconnect can delay one), so timing alone is not
    # ordering — this poll is what turns mount-before-delete into
    # registered-before-delete, the property that keeps reads available
    # through the cutover (BASELINE config 5;
    # volume_grpc_erasure_coding.go:25-36 ordering).
    deadline = time.time() + 30
    with env.master_channel() as ch:
        stub = rpc.master_stub(ch)
        while True:
            try:
                ec_resp = stub.LookupEcVolume(
                    master_pb2.LookupEcVolumeRequest(volume_id=vid), timeout=5
                )
                seen = {e.shard_id for e in ec_resp.shard_id_locations if e.locations}
            except grpc.RpcError:
                seen = set()
            if len(seen) >= ec_common.TOTAL_SHARDS_COUNT:
                break
            if time.time() > deadline:
                raise RuntimeError(
                    f"volume {vid}: only shards {sorted(seen)} registered with "
                    "the master after 30s; refusing to delete the source volume "
                    "(reads would go dark for the missing shards)"
                )
            time.sleep(0.05)
    # 6. delete the original volume on every replica
    for url in locs:
        with env.volume_channel(url) as ch:
            rpc.volume_stub(ch).VolumeDelete(volume_pb2.VolumeDeleteRequest(volume_id=vid))
    print(f"ec encoded volume {vid}", file=out)


@register
class EcEncode(Command):
    name = "ec.encode"
    help = "ec.encode [-collection name] [-volumeId vid] [-fullPercent 95]"

    def run(self, env, args, out):
        collection = _flag(args, "collection")
        vid_flag = _flag(args, "volumeId")
        dump = env.collect_topology()
        if vid_flag:
            vids = [int(vid_flag)]
            if not _has_flag(args, "collection"):
                # resolve the volume's real collection so copy/mount
                # address the right base name
                for n in dump.nodes:
                    for v in n.volumes:
                        if v["Id"] == vids[0]:
                            collection = v["Collection"]
        else:
            vids = collect_volume_ids_for_ec_encode(
                dump, collection, 60.0, float(_flag(args, "fullPercent", "95"))
            )
        for vid in vids:
            do_ec_encode(env, vid, collection, out)


@register
class EcBatch(Command):
    name = "ec.batch"
    help = (
        "ec.batch -volumeIds 1,2,3 — encode N sealed volumes per server "
        "in ONE mesh program (volume-parallel SPMD batch over the device "
        "mesh), then mount their shards in place (collections resolved "
        "from topology)"
    )

    def run(self, env, args, out):
        vid_flag = _flag(args, "volumeIds")
        if not vid_flag:
            raise ValueError("ec.batch needs -volumeIds vid,vid,...")
        # dedupe: a repeated id would open two write handles onto the
        # same shard files and interleave-corrupt them before the
        # originals get deleted
        vids = sorted({int(x) for x in vid_flag.split(",") if x})
        # each volume's real collection names its base files; resolve
        # from topology (same as ec.encode's -volumeId path)
        dump = env.collect_topology()
        collections = {
            v["Id"]: v["Collection"] for n in dump.nodes for v in n.volumes
        }

        # group by the server holding each volume: batching is local to
        # a node's device mesh (each node encodes its own batch)
        with env.master_channel() as ch:
            resp = rpc.master_stub(ch).LookupVolume(
                master_pb2.LookupVolumeRequest(vids=[str(v) for v in vids])
            )
        by_server: dict[str, list[int]] = {}
        replicas: dict[int, list[str]] = {}
        for entry in resp.vid_locations:
            if not entry.locations:
                raise ValueError(f"volume {entry.vid} not found")
            vid = int(entry.vid)
            replicas[vid] = [l.url for l in entry.locations]
            by_server.setdefault(entry.locations[0].url, []).append(vid)

        for url, server_vids in sorted(by_server.items()):
            # readonly on EVERY replica (markVolumeReadonly, like
            # do_ec_encode): a replica left writable would diverge from
            # the EC set the moment a write lands on it
            for vid in server_vids:
                for rurl in replicas[vid]:
                    with env.volume_channel(rurl) as ch:
                        rpc.volume_stub(ch).VolumeMarkReadonly(
                            volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
                        )
            with env.volume_channel(url) as ch:
                rpc.volume_stub(ch).VolumeEcShardsBatchGenerate(
                    volume_pb2.VolumeEcShardsBatchGenerateRequest(
                        volume_ids=server_vids
                    ),
                    timeout=600,
                )
            print(
                f"batch-generated ec shards for volumes {server_vids} "
                f"on {url} (one mesh program)",
                file=out,
            )
            # serve from EC in place: mount all 14 shards, drop the
            # originals (spreading stays ec.encode/ec.balance's job)
            for vid in server_vids:
                with env.volume_channel(url) as ch:
                    stub = rpc.volume_stub(ch)
                    stub.VolumeEcShardsMount(
                        volume_pb2.VolumeEcShardsMountRequest(
                            volume_id=vid,
                            collection=collections.get(vid, ""),
                            shard_ids=list(range(ec_common.TOTAL_SHARDS_COUNT)),
                        )
                    )
                # drop EVERY replica of the original volume, not just
                # the encoding server's copy
                for rurl in replicas[vid]:
                    with env.volume_channel(rurl) as ch:
                        rpc.volume_stub(ch).VolumeDelete(
                            volume_pb2.VolumeDeleteRequest(volume_id=vid)
                        )
                print(f"volume {vid} now serves from ec shards", file=out)


def find_missing_shards(nodes: list[ec_common.EcNode], vid: int) -> list[int]:
    present = 0
    for n in nodes:
        entry = n.ec_shards.get(vid)
        if entry:
            present |= entry[1]
    return [i for i in range(ec_common.TOTAL_SHARDS_COUNT) if not present & (1 << i)]


def do_ec_rebuild(env: CommandEnv, vid: int, out, apply: bool = True) -> list[int]:
    """Rebuild missing shards on one rebuilder node
    (command_ec_rebuild.go rebuildOneEcVolume), rack-gather style:
    survivors STAY on their holders — VolumeEcShardsRebuild's pipelined
    driver streams their tiles off the holders in parallel with the
    reconstruction, so the rebuild is not serialized behind a full
    cluster copy. Only the .ecx index (plus one seed survivor when the
    rebuilder holds no shard of the volume — a local file fixes the
    shard size for the tile walk) is copied up front. If the streaming
    verb fails (holder unreachable, no master route) the classic
    copy-every-survivor flow runs as the fallback. Rebuilt shards are
    mounted on the rebuilder; the master learns via heartbeat."""
    import grpc as _grpc

    nodes = ec_common.collect_ec_nodes(env)
    missing = find_missing_shards(nodes, vid)
    if not missing:
        print(f"volume {vid}: no missing shards", file=out)
        return []
    holders = [n for n in nodes if vid in n.ec_shards]
    if not holders:
        raise ValueError(f"no ec shards for volume {vid}")
    collection = holders[0].ec_shards[vid][0]
    # rebuilder = node with most free slots
    rebuilder = max(nodes, key=lambda n: n.free_ec_slot)
    if not apply:
        return missing
    original_local = set(rebuilder.local_shard_ids(vid))
    local = set(original_local)
    if not local:
        donor = next(n for n in holders if n.url != rebuilder.url)
        seed = donor.local_shard_ids(vid)[0]
        with env.volume_channel(rebuilder.url) as ch:
            rpc.volume_stub(ch).VolumeEcShardsCopy(
                volume_pb2.VolumeEcShardsCopyRequest(
                    volume_id=vid,
                    collection=collection,
                    shard_ids=[seed],
                    copy_ecx_file=True,
                    source_data_node=donor.url,
                )
            )
        local.add(seed)

    def rebuild_now() -> list[int]:
        with env.volume_channel(rebuilder.url) as ch:
            resp = rpc.volume_stub(ch).VolumeEcShardsRebuild(
                volume_pb2.VolumeEcShardsRebuildRequest(
                    volume_id=vid, collection=collection
                ),
                timeout=600,
            )
        return list(resp.rebuilt_shard_ids)

    _FALLBACK_CODES = (
        _grpc.StatusCode.FAILED_PRECONDITION,  # verb lacked survivors
        _grpc.StatusCode.UNAVAILABLE,  # holder/master unreachable
        _grpc.StatusCode.UNKNOWN,  # server-side exception surfaced
    )
    try:
        rebuilt = rebuild_now()
    except _grpc.RpcError as e:
        if e.code() not in _FALLBACK_CODES:
            # DEADLINE_EXCEEDED etc: the server-side streaming rebuild
            # may still be RUNNING — a blind retry would race its
            # preallocated target files and misread them as present
            raise
        # fallback: pull every surviving shard the rebuilder lacks,
        # then rebuild from purely local files
        for n in holders:
            if n.url == rebuilder.url:
                continue
            need = [s for s in n.local_shard_ids(vid) if s not in local]
            if not need:
                continue
            with env.volume_channel(rebuilder.url) as ch:
                rpc.volume_stub(ch).VolumeEcShardsCopy(
                    volume_pb2.VolumeEcShardsCopyRequest(
                        volume_id=vid,
                        collection=collection,
                        shard_ids=need,
                        copy_ecx_file=True,
                        source_data_node=n.url,
                    )
                )
            local.update(need)
        rebuilt = rebuild_now()
    with env.volume_channel(rebuilder.url) as ch:
        rpc.volume_stub(ch).VolumeEcShardsMount(
            volume_pb2.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection, shard_ids=rebuilt
            )
        )
        # drop the borrowed survivor copies (they stay mounted on their
        # original owners); keep only what this node now contributes
        borrowed = [s for s in local if s not in original_local and s not in rebuilt]
        if borrowed:
            rpc.volume_stub(ch).VolumeEcShardsDelete(
                volume_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection, shard_ids=borrowed
                )
            )
    print(f"rebuilt shards {rebuilt} for volume {vid} on {rebuilder.url}", file=out)
    return rebuilt


def do_ec_rebuild_batch(
    env: CommandEnv, vids: list[int], out, apply: bool = True
) -> dict[int, list[int]]:
    """Rebuild missing shards for many volumes, batching volumes that
    can rebuild from purely local survivors on the same node through
    ONE VolumeEcShardsBatchRebuild verb (the mesh-batched decode —
    the RepairScheduler's node-loss fan-in). Volumes that need a
    rack-gather, have no >=10-local-shard holder, or whose batch verb
    fails take the single-volume do_ec_rebuild path, so the result is
    never worse than calling it in a loop. Returns {vid: rebuilt ids}."""
    import grpc as _grpc

    nodes = ec_common.collect_ec_nodes(env)
    results: dict[int, list[int]] = {}
    by_server: dict[str, list[tuple[int, str, list[int]]]] = {}
    leftovers: list[int] = []
    for vid in sorted({int(v) for v in vids}):
        missing = find_missing_shards(nodes, vid)
        if not missing:
            results[vid] = []
            continue
        if not apply:
            results[vid] = missing
            continue
        # the batch arm needs one node already holding >= 10 shards of
        # the volume (all survivors local, no seed copy)
        cands = [
            n
            for n in nodes
            if vid in n.ec_shards
            and len(n.local_shard_ids(vid)) >= ec_common.DATA_SHARDS
        ]
        if not cands:
            leftovers.append(vid)
            continue
        rebuilder = max(cands, key=lambda n: n.free_ec_slot)
        collection = rebuilder.ec_shards[vid][0]
        by_server.setdefault(rebuilder.url, []).append(
            (vid, collection, missing)
        )
    if not apply:
        return results

    for url, entries in sorted(by_server.items()):
        if len(entries) < 2:
            # nothing to amortize: the single-volume verb's remote-
            # survivor handling and fallbacks are strictly richer
            leftovers.extend(vid for vid, _, _ in entries)
            continue
        server_vids = [vid for vid, _, _ in entries]
        try:
            with env.volume_channel(url) as ch:
                rpc.volume_stub(ch).VolumeEcShardsBatchRebuild(
                    volume_pb2.VolumeEcShardsBatchGenerateRequest(
                        volume_ids=server_vids
                    ),
                    timeout=600,
                )
        except _grpc.RpcError as e:
            print(
                f"batch rebuild of volumes {server_vids} on {url} "
                f"failed ({e.code()}); falling back per volume",
                file=out,
            )
            leftovers.extend(server_vids)
            continue
        print(
            f"batch-rebuilt ec shards for volumes {server_vids} on "
            f"{url} (one mesh program per damage signature)",
            file=out,
        )
        for vid, collection, missing in entries:
            with env.volume_channel(url) as ch:
                rpc.volume_stub(ch).VolumeEcShardsMount(
                    volume_pb2.VolumeEcShardsMountRequest(
                        volume_id=vid,
                        collection=collection,
                        shard_ids=missing,
                    )
                )
            results[vid] = missing
    for vid in leftovers:
        results[vid] = do_ec_rebuild(env, vid, out, apply)
    return results


@register
class EcRebuildBatch(Command):
    name = "ec.rebuild.batch"
    help = (
        "ec.rebuild.batch [-volumeIds 1,2,3] [-force] — rebuild many "
        "EC volumes, batching same-node local-survivor rebuilds "
        "through one mesh decode program per damage signature"
    )

    def run(self, env, args, out):
        vid_flag = _flag(args, "volumeIds")
        apply = _has_flag(args, "force")
        nodes = ec_common.collect_ec_nodes(env)
        vids = (
            [int(x) for x in vid_flag.split(",") if x]
            if vid_flag
            else sorted({vid for n in nodes for vid in n.ec_shards})
        )
        results = do_ec_rebuild_batch(env, vids, out, apply)
        if not apply:
            for vid, missing in sorted(results.items()):
                if missing:
                    print(
                        f"volume {vid}: missing shards {missing} "
                        f"(dry run; -force to rebuild)",
                        file=out,
                    )


@register
class EcRebuild(Command):
    name = "ec.rebuild"
    help = "ec.rebuild [-volumeId vid] [-force]"

    def run(self, env, args, out):
        vid_flag = _flag(args, "volumeId")
        apply = _has_flag(args, "force")
        nodes = ec_common.collect_ec_nodes(env)
        vids = (
            [int(vid_flag)]
            if vid_flag
            else sorted({vid for n in nodes for vid in n.ec_shards})
        )
        for vid in vids:
            missing = do_ec_rebuild(env, vid, out, apply)
            if not apply and missing:
                print(
                    f"volume {vid}: missing shards {missing} (dry run; -force to rebuild)",
                    file=out,
                )


def do_ec_verify(
    env: CommandEnv,
    vid: int,
    out,
    tile_bytes: int = 4 * 1024 * 1024,
    rate_mb_s: float = 0.0,
    as_json: bool = False,
) -> list[int]:
    """Scrub one EC volume: stream all 14 shards from their holders,
    recompute the parity from the data shards with the local codec
    backend (auto: the TPU kernels on a TPU host, the native SIMD shim
    otherwise — same selection as the serving path), and compare.
    Returns the per-parity-row mismatched-byte counts [4].

    Runs through the scrub engine's verify core
    (scrub/verify.verify_parity_stream — the same code path the
    background sweeper and the TPU mesh verify tier exercise), which
    adds `-rate` token-bucket limiting (MB/s; 0 = full speed) so an
    operator can scrub a live volume without flattening foreground
    p99, plus corrupt-shard localization and `-json` machine-readable
    output. A corrupt DATA shard shows as mismatches in ALL four
    parity rows; a corrupt PARITY shard only in its own row."""
    import json as _json

    from seaweedfs_tpu.scrub.ratelimit import TokenBucket
    from seaweedfs_tpu.scrub.verify import verify_parity_stream

    with env.master_channel() as ch:
        resp = rpc.master_stub(ch).LookupEcVolume(
            master_pb2.LookupEcVolumeRequest(volume_id=vid), timeout=10
        )
    holders: dict[int, list[str]] = {
        e.shard_id: [l.url for l in e.locations]
        for e in resp.shard_id_locations
        if e.locations
    }
    missing = [i for i in range(ec_common.TOTAL_SHARDS_COUNT) if i not in holders]
    if missing:
        raise RuntimeError(
            f"volume {vid}: shards {missing} have no registered holder; "
            "run ec.rebuild first"
        )

    def make_reader(sid: int):
        def read_span(offset: int, size: int) -> bytes:
            last_err = None
            for url in holders[sid]:
                try:
                    with env.volume_channel(url) as ch:
                        chunks = [
                            r.data
                            for r in rpc.volume_stub(ch).VolumeEcShardRead(
                                volume_pb2.VolumeEcShardReadRequest(
                                    volume_id=vid,
                                    shard_id=sid,
                                    offset=offset,
                                    size=size,
                                ),
                                timeout=30,
                            )
                        ]
                    return b"".join(chunks)
                except Exception as e:  # noqa: BLE001 - try the next holder
                    last_err = e
            raise RuntimeError(f"shard {vid}.{sid} unreadable: {last_err}")

        return read_span

    limiter = (
        TokenBucket(rate_mb_s * 1024 * 1024) if rate_mb_s > 0 else None
    )
    try:
        res = verify_parity_stream(
            [make_reader(sid) for sid in range(ec_common.TOTAL_SHARDS_COUNT)],
            tile_bytes=tile_bytes,
            limiter=limiter,
        )
    except RuntimeError as e:
        raise RuntimeError(f"volume {vid}: {e}") from None
    mismatch, total = res.mismatch, res.bytes_per_shard
    if as_json:
        print(
            _json.dumps(
                {
                    "volumeId": vid,
                    "corrupt": res.corrupt,
                    "mismatchPerParityRow": mismatch,
                    "bytesPerShard": total,
                    "badTiles": res.bad_tiles,
                    "culpritShards": sorted(res.culprits),
                    "unlocalizedTiles": res.unlocalized,
                    "rateMBs": rate_mb_s,
                }
            ),
            file=out,
        )
        return mismatch
    if any(mismatch):
        rows = [p for p, m in enumerate(mismatch) if m]
        kind = (
            "parity shard(s) corrupt"
            if len(rows) < ec_common.PARITY_SHARDS
            else "data shard corruption (all parity rows disagree)"
        )
        print(
            f"volume {vid}: CORRUPT — mismatched bytes per parity row "
            f"{mismatch} over {total} B/shard: {kind}"
            + (
                f"; culprit shard(s) {sorted(res.culprits)}"
                if res.culprits
                else ""
            ),
            file=out,
        )
    else:
        print(
            f"volume {vid}: verified clean ({total} bytes/shard x 14 shards)",
            file=out,
        )
    return mismatch


@register
class EcVerify(Command):
    name = "ec.verify"
    help = (
        "ec.verify [-volumeId vid] [-rate MB/s] [-json] — scrub: stream "
        "shards, recompute + compare parity (rate-limited via the scrub "
        "engine's token bucket)"
    )

    def run(self, env, args, out):
        vid_flag = _flag(args, "volumeId")
        rate = float(_flag(args, "rate") or 0)
        as_json = _has_flag(args, "json")
        nodes = ec_common.collect_ec_nodes(env)
        vids = (
            [int(vid_flag)]
            if vid_flag
            else sorted({vid for n in nodes for vid in n.ec_shards})
        )
        if not vids:
            print("no ec volumes found", file=out)
            return
        for vid in vids:
            do_ec_verify(env, vid, out, rate_mb_s=rate, as_json=as_json)


@register
class EcBalance(Command):
    name = "ec.balance"
    help = "ec.balance [-collection name] [-force]"

    def run(self, env, args, out):
        apply = _has_flag(args, "force")
        collection = _flag(args, "collection") or None
        nodes = ec_common.collect_ec_nodes(env)
        stats = ec_common.balance_ec_volumes(env, nodes, collection, apply)
        print(
            f"ec.balance dedup:{stats['dedup']} across_racks:{stats['across_racks']} "
            f"within_racks:{stats['within_racks']} rack_total:{stats['rack_total']} "
            f"applied={apply}",
            file=out,
        )


@register
class EcDecode(Command):
    name = "ec.decode"
    help = "ec.decode -volumeId vid [-collection name] — EC shards back to a normal volume"

    def run(self, env, args, out):
        vid = int(_flag(args, "volumeId"))
        collection = _flag(args, "collection")
        nodes = ec_common.collect_ec_nodes(env)
        holders = [n for n in nodes if vid in n.ec_shards]
        if not holders:
            raise ValueError(f"no ec shards for volume {vid}")
        if not collection:
            collection = holders[0].ec_shards[vid][0]
        # collect every shard onto one node, then decode there
        # (command_ec_decode.go collectEcShards + generateNormalVolume)
        target = max(holders, key=lambda n: len(n.local_shard_ids(vid)))
        have = set(target.local_shard_ids(vid))
        for n in holders:
            if n.url == target.url:
                continue
            need = [s for s in n.local_shard_ids(vid) if s not in have]
            if not need:
                continue
            with env.volume_channel(target.url) as ch:
                rpc.volume_stub(ch).VolumeEcShardsCopy(
                    volume_pb2.VolumeEcShardsCopyRequest(
                        volume_id=vid,
                        collection=collection,
                        shard_ids=need,
                        copy_ecx_file=True,
                        source_data_node=n.url,
                    )
                )
            have.update(need)
        with env.volume_channel(target.url) as ch:
            rpc.volume_stub(ch).VolumeEcShardsToVolume(
                volume_pb2.VolumeEcShardsToVolumeRequest(
                    volume_id=vid, collection=collection
                )
            )
        # drop the ec shards everywhere now that the volume is back
        for n in holders:
            sids = n.local_shard_ids(vid)
            with env.volume_channel(n.url) as ch:
                stub = rpc.volume_stub(ch)
                stub.VolumeEcShardsUnmount(
                    volume_pb2.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=sids)
                )
                stub.VolumeEcShardsDelete(
                    volume_pb2.VolumeEcShardsDeleteRequest(
                        volume_id=vid, collection=collection, shard_ids=sids
                    )
                )
        print(f"decoded ec volume {vid} back to a normal volume on {target.url}", file=out)


@register
class Help(Command):
    name = "help"
    help = "list commands"

    def run(self, env, args, out):
        for name in sorted(COMMANDS):
            print(f"{name:28s} {COMMANDS[name].help}", file=out)


# ----------------------------------------------------------------------
# tiered storage (command_volume_tier_upload.go / _download.go)


def _find_volume_node(env: CommandEnv, vid: int) -> str:
    for n in env.collect_topology().nodes:
        for v in n.volumes:
            if v["Id"] == vid:
                return n.url
    raise ValueError(f"volume {vid} not found on any node")


@register
class VolumeTierUpload(Command):
    name = "volume.tier.upload"
    help = (
        "volume.tier.upload -volumeId <vid> -dest <backendName> "
        "[-keepLocalDatFile] — move a sealed volume's .dat to a remote tier"
    )

    def run(self, env, args, out):
        vid = int(_flag(args, "volumeId"))
        dest = _flag(args, "dest")
        if not dest:
            raise ValueError("-dest <backendName> required (e.g. s3.default)")
        node = _flag(args, "node") or _find_volume_node(env, vid)
        collection = _flag(args, "collection") or _lookup_collection(env, vid)
        with env.volume_channel(node) as ch:
            for resp in rpc.volume_stub(ch).VolumeTierMoveDatToRemote(
                volume_pb2.VolumeTierMoveDatToRemoteRequest(
                    volume_id=vid,
                    collection=collection,
                    destination_backend_name=dest,
                    keep_local_dat_file=_has_flag(args, "keepLocalDatFile"),
                )
            ):
                print(
                    f"uploaded {resp.processed} bytes "
                    f"({resp.processed_percentage:.0f}%)",
                    file=out,
                )
        print(f"volume {vid} dat moved to {dest}", file=out)


@register
class VolumeTierDownload(Command):
    name = "volume.tier.download"
    help = (
        "volume.tier.download -volumeId <vid> [-keepRemoteDatFile] — "
        "bring a tiered volume's .dat back to local disk"
    )

    def run(self, env, args, out):
        vid = int(_flag(args, "volumeId"))
        node = _flag(args, "node") or _find_volume_node(env, vid)
        collection = _flag(args, "collection") or _lookup_collection(env, vid)
        with env.volume_channel(node) as ch:
            for resp in rpc.volume_stub(ch).VolumeTierMoveDatFromRemote(
                volume_pb2.VolumeTierMoveDatFromRemoteRequest(
                    volume_id=vid,
                    collection=collection,
                    keep_remote_dat_file=_has_flag(args, "keepRemoteDatFile"),
                )
            ):
                print(
                    f"downloaded {resp.processed} bytes "
                    f"({resp.processed_percentage:.0f}%)",
                    file=out,
                )
        print(f"volume {vid} dat restored locally", file=out)


# ----------------------------------------------------------------------
# scrub plane operator surface (docs/SCRUB.md — beyond-reference: the
# 2019 reference has no integrity commands at all)


def _http_json(url: str, timeout: float = 10.0) -> dict:
    import json as _json
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return _json.loads(r.read())


@register
class ScrubStatus(Command):
    name = "scrub.status"
    help = (
        "scrub.status [-json] — per-node background-scrub health: sweep "
        "progress, corruption counts, quarantined shards"
    )

    def run(self, env, args, out):
        import json as _json

        dump = env.collect_topology()
        report = {}
        for n in dump.nodes:
            try:
                report[n.url] = _http_json(f"http://{n.url}/status")
            except OSError as e:
                report[n.url] = {"error": str(e)}
        if _has_flag(args, "json"):
            print(
                _json.dumps(
                    {
                        url: {
                            "Scrub": st.get("Scrub"),
                            "QuarantinedShards": st.get("QuarantinedShards"),
                            "error": st.get("error"),
                        }
                        for url, st in report.items()
                    }
                ),
                file=out,
            )
            return
        for url, st in sorted(report.items()):
            if "error" in st and "Scrub" not in st:
                print(f"{url}: unreachable ({st['error']})", file=out)
                continue
            scrub = st.get("Scrub") or {}
            quarantined = st.get("QuarantinedShards") or {}
            if scrub.get("Disabled"):
                print(f"{url}: scrub disabled", file=out)
            else:
                vols = scrub.get("Volumes") or []
                corrupt = sum(v.get("corruptions_found", 0) for v in vols)
                scanned = sum(v.get("scanned_bytes", 0) for v in vols)
                print(
                    f"{url}: sweeps {scrub.get('SweepsCompleted', 0)}"
                    f"{' (running)' if scrub.get('SweepRunning') else ''}, "
                    f"{len(vols)} volume(s) tracked, "
                    f"{scanned >> 20} MiB verified, "
                    f"{corrupt} corruption(s)",
                    file=out,
                )
                for v in vols:
                    if v.get("last_error"):
                        print(
                            f"  vid {v['volume_id']}"
                            f"{' (ec)' if v.get('is_ec') else ''}: "
                            f"{v['last_error']}",
                            file=out,
                        )
            for vid, sids in sorted(quarantined.items()):
                print(f"  vid {vid}: quarantined shards {sids}", file=out)


@register
class ScrubTrigger(Command):
    name = "scrub.trigger"
    help = (
        "scrub.trigger [-volumeId vid] [-node host:port] — start a sweep "
        "now (all nodes, or one node; with -volumeId that volume first)"
    )

    def run(self, env, args, out):
        vid = _flag(args, "volumeId")
        node = _flag(args, "node")
        dump = env.collect_topology()
        urls = [node] if node else [n.url for n in dump.nodes]
        qs = f"?volumeId={int(vid)}" if vid else ""
        for url in urls:
            try:
                _http_json(f"http://{url}/scrub/trigger{qs}")
                print(f"{url}: sweep triggered", file=out)
            except OSError as e:
                print(f"{url}: trigger failed: {e}", file=out)


@register
class RepairQueue(Command):
    name = "repair.queue"
    help = (
        "repair.queue [-json] — the master repair scheduler's tracked "
        "damage, backoff state, and recent repair history"
    )

    def run(self, env, args, out):
        import json as _json

        snap = _http_json(f"http://{env.master}/repair/queue")
        if _has_flag(args, "json"):
            print(_json.dumps(snap), file=out)
            return
        if snap.get("Disabled"):
            print(
                "repair scheduler disabled on this master "
                "(-repairInterval 0); repair is manual "
                "(ec.rebuild / volume.fix.replication)",
                file=out,
            )
        else:
            cfg = snap.get("Config", {})
            print(
                f"scheduler: every {cfg.get('Interval')}s, "
                f"concurrency {cfg.get('Concurrency')}, "
                f"grace {cfg.get('GraceSeconds')}s, "
                f"active {snap.get('Active', 0)}",
                file=out,
            )
            tasks = snap.get("Tasks", [])
            if not tasks:
                print("no damage tracked", file=out)
            for task in tasks:
                state = (
                    "running"
                    if task["InFlight"]
                    else f"attempt {task['Attempts']}, next try "
                    f"{max(0, task['NextTry'] - time.time()):.0f}s"
                )
                print(
                    f"  {task['Kind']} vid {task['VolumeId']}: "
                    f"{task['Detail']} [{state}]"
                    + (
                        f" last error: {task['LastError']}"
                        if task["LastError"]
                        else ""
                    ),
                    file=out,
                )
            for h in snap.get("History", [])[-10:]:
                print(
                    f"  done: {h['Kind']} vid {h['VolumeId']} "
                    f"in {h['RepairSeconds']}s "
                    f"(time-to-repair {h['TimeToRepairSeconds']}s)",
                    file=out,
                )
        scrub = snap.get("Scrub") or {}
        for url, s in sorted(scrub.items()):
            print(
                f"  scrub@{url}: {s['Volumes']} vol(s), "
                f"{s['Corruptions']} corruption(s), "
                f"{s['QuarantinedShards']} quarantined shard(s)",
                file=out,
            )


@register
class NodeDrain(Command):
    name = "node.drain"
    help = (
        "node.drain -node host:port [-wait seconds] [-stop] [-json] — "
        "weedguard decommission (docs/HEALTH.md): mark the node "
        "draining (excluded from write assignment at once) and have "
        "the master RepairScheduler move its volumes and EC shards "
        "off; -wait polls until the node is empty, printing repair-"
        "queue evidence. -stop cancels a drain."
    )

    def run(self, env, args, out):
        import json as _json

        node = _flag(args, "node")
        if not node:
            raise ValueError("node.drain needs -node host:port")
        stop = _has_flag(args, "stop")
        try:
            wait_s = float(_flag(args, "wait", "0") or "0")
        except ValueError:
            wait_s = 0.0
        url = f"http://{env.master}/node/drain?node={node}"
        if stop:
            url += "&stop=1"
        snap = _http_json(url)
        if _has_flag(args, "json"):
            print(_json.dumps(snap), file=out)
            return
        if snap.get("error"):
            raise ValueError(snap["error"])
        if stop:
            print(f"drain of {node} cancelled", file=out)
            return
        if not snap.get("registered"):
            # an unregistered address drains vacuously — most likely a
            # typo; claiming "empty, safe to stop" here would invite
            # SIGTERMing the wrong (undrained) process
            print(
                f"WARNING: {node} is not registered with this master — "
                "check the address (the drain mark was recorded; "
                "-stop clears it)",
                file=out,
            )
            return
        if not snap.get("repairScheduler"):
            print(
                "WARNING: repair scheduler disabled on this master "
                "(-repairInterval 0) — the drain mark excludes the "
                "node from assignment but nothing will move its data",
                file=out,
            )
        print(
            f"draining {node}: {snap.get('volumes', 0)} volume(s), "
            f"{snap.get('ecShards', 0)} ec shard(s) to move",
            file=out,
        )
        deadline = time.time() + wait_s
        moved_evidence: list[str] = []
        while wait_s > 0:
            snap = _http_json(url + "&status=1")  # read-only poll form
            if snap.get("volumes", 0) == 0 and snap.get("ecShards", 0) == 0:
                break
            if time.time() >= deadline:
                print(
                    f"  still holding {snap.get('volumes', 0)} volume(s) "
                    f"/ {snap.get('ecShards', 0)} shard(s) after "
                    f"{wait_s:.0f}s — drain continues in the background",
                    file=out,
                )
                # name WHY it is stuck (a blocked drain usually means
                # no eligible target: add capacity)
                rq = _http_json(f"http://{env.master}/repair/queue")
                for t in rq.get("Tasks", []):
                    if t["Kind"].startswith("drain") and t.get("LastError"):
                        print(
                            f"  blocked: {t['Kind']} vid {t['VolumeId']}: "
                            f"{t['LastError']}",
                            file=out,
                        )
                return
            time.sleep(0.5)
        # repair-queue evidence: the drain tasks that moved the data
        rq = _http_json(f"http://{env.master}/repair/queue")
        for h in rq.get("History", []):
            if h["Kind"].startswith("drain"):
                moved_evidence.append(
                    f"  moved: {h['Kind']} vid {h['VolumeId']} "
                    f"in {h['RepairSeconds']}s"
                )
        for line in moved_evidence[-20:]:
            print(line, file=out)
        if wait_s > 0:
            print(
                f"{node} is empty — safe to stop the process "
                "(SIGTERM finishes in-flight work and deregisters)",
                file=out,
            )


# ----------------------------------------------------------------------
# tracing plane (docs/TRACING.md)


def _trace_nodes(env: CommandEnv) -> list[str]:
    """master + every volume server — the daemons the shell can reach
    from topology alone (gateways aren't registered there; query their
    /debug/traces directly)."""
    urls = [env.master]
    for n in env.collect_topology().nodes:
        urls.append(n.url)
    return urls


@register
class TraceStatus(Command):
    name = "trace.status"
    help = (
        "trace.status [-json] — per-node tracer health: enabled flag, "
        "ring occupancy, slow-trace threshold, in-flight requests"
    )

    def run(self, env, args, out):
        import json as _json

        report = {}
        for url in _trace_nodes(env):
            try:
                report[url] = _http_json(f"http://{url}/debug/traces?n=0")
            except (OSError, ValueError) as e:
                report[url] = {"error": str(e)}
        if _has_flag(args, "json"):
            print(_json.dumps(report), file=out)
            return
        for url, st in sorted(report.items()):
            if "error" in st:
                print(f"{url}: unreachable ({st['error']})", file=out)
                continue
            print(
                f"{url}: tracing {'on' if st.get('enabled') else 'OFF'}, "
                f"{st.get('recorded', 0)} span(s) recorded "
                f"(ring {st.get('ring_size')}, dropped {st.get('dropped', 0)}), "
                f"{st.get('inflight', 0)} in flight, "
                f"slow threshold {st.get('slow_ms', 0)}ms",
                file=out,
            )


@register
class TraceDump(Command):
    name = "trace.dump"
    help = (
        "trace.dump [-traceId <id>] [-n <spans-per-node>] [-slow] — "
        "merge /debug/traces from every node and print span trees "
        "(-slow prints each node's slowest-N instead of recent)"
    )

    def run(self, env, args, out):
        n = int(_flag(args, "n", "64") or 64)
        want = _flag(args, "traceId", "")
        use_slow = _has_flag(args, "slow")
        spans: list[dict] = []
        for url in _trace_nodes(env):
            try:
                payload = _http_json(f"http://{url}/debug/traces?n={n}")
            except (OSError, ValueError) as e:
                print(f"{url}: unreachable ({e})", file=out)
                continue
            spans.extend(payload.get("slowest" if use_slow else "recent", []))
        if want:
            spans = [s for s in spans if s.get("trace") == want]
        if not spans:
            print("no spans", file=out)
            return
        # group by trace, dedupe by (node, span) — a span can appear in
        # both a node's recent and slowest lists, but span ids from
        # DIFFERENT daemons must never overwrite each other — and order
        # trees by start time
        by_trace: dict[str, dict[tuple, dict]] = {}
        for s in spans:
            key = (s.get("node", ""), s["span"])
            by_trace.setdefault(s["trace"], {})[key] = s
        for trace_id in sorted(
            by_trace, key=lambda t: min(s["start"] for s in by_trace[t].values())
        ):
            tree = by_trace[trace_id]
            print(f"trace {trace_id}:", file=out)
            ids = {s["span"] for s in tree.values()}
            children: dict[str, list[dict]] = {}
            roots = []
            for s in sorted(tree.values(), key=lambda s: s["start"]):
                parent = s.get("parent") or ""
                # parent == own id only on a (residual) cross-process
                # id collision; treat as a root instead of a cycle
                if parent and parent != s["span"] and parent in ids:
                    children.setdefault(parent, []).append(s)
                else:
                    roots.append(s)

            def walk(s, depth):
                stages = s.get("stages_ms")
                stage_txt = (
                    " stages(ms)=" + ",".join(
                        f"{k}:{v}" for k, v in stages.items()
                    )
                    if stages
                    else ""
                )
                print(
                    "  " * (depth + 1)
                    + f"{s['name']} [{s['node']}] {s['dur_ms']}ms "
                    f"status={s['status']} bytes={s['bytes']} "
                    f"plane={s['plane']}{stage_txt}",
                    file=out,
                )
                for c in children.get(s["span"], []):
                    walk(c, depth + 1)

            for r in roots:
                walk(r, 0)


# ----------------------------------------------------------------------
# cluster telemetry plane (docs/TELEMETRY.md)


@register
class ClusterHealth(Command):
    name = "cluster.health"
    help = (
        "cluster.health [-json] — per-node weedguard health scores/"
        "states (docs/HEALTH.md) plus the leader collector's view: "
        "per-target scrape health (staleness, last error), alert "
        "counts, push-loop status"
    )

    def run(self, env, args, out):
        import json as _json

        snap = _http_json(f"http://{env.master}/cluster/health")
        if _has_flag(args, "json"):
            print(_json.dumps(snap), file=out)
            return
        nh = snap.get("NodeHealth") or {}
        if nh:
            if not nh.get("Enabled", True):
                print("health plane disabled (WEED_HEALTH=0)", file=out)
            for url, row in sorted((nh.get("Nodes") or {}).items()):
                flags = [
                    f
                    for f, on in (
                        ("lame-duck", row.get("LameDuck")),
                        ("draining", row.get("Draining")),
                        ("scrub-flagged", row.get("ScrubFlagged")),
                    )
                    if on
                ]
                line = (
                    f"  {url}: {row.get('State')} "
                    f"(score {row.get('Score')}, phi {row.get('Phi')}, "
                    f"err_ewma {row.get('ErrEwma')})"
                )
                if flags:
                    line += " [" + ", ".join(flags) + "]"
                if row.get("Reasons"):
                    line += " — " + ", ".join(row["Reasons"])
                print(line, file=out)
        if snap.get("Disabled"):
            print(
                "telemetry collector disabled on this master "
                "(-telemetryInterval 0)",
                file=out,
            )
            return
        print(
            f"collector: every {snap.get('IntervalSeconds')}s, "
            f"{snap.get('Cycles', 0)} cycle(s), window "
            f"{snap.get('WindowSeconds')}s, "
            f"{snap.get('FiringAlerts', 0)} firing / "
            f"{snap.get('PendingAlerts', 0)} pending alert(s)",
            file=out,
        )
        for url, row in sorted((snap.get("Targets") or {}).items()):
            state = "up" if row.get("Up") else "DOWN"
            line = (
                f"  {url} [{row.get('Kind')}]: {state}, "
                f"stale {row.get('StalenessSeconds', 0):.1f}s, "
                f"{row.get('Series', 0)} series, "
                f"{row.get('Scrapes', 0)} scrape(s)"
            )
            if row.get("LastError"):
                line += f" last error: {row['LastError']}"
            print(line, file=out)
        for job, push in sorted((snap.get("Push") or {}).items()):
            line = f"  push@{job}: last success {push.get('last_success_unix', 0)}"
            if push.get("last_error"):
                line += f" last error: {push['last_error']}"
            print(line, file=out)


@register
class ClusterAlerts(Command):
    name = "cluster.alerts"
    help = (
        "cluster.alerts [-json] — firing/pending alerts and recent "
        "resolved history from the master rule engine"
    )

    def run(self, env, args, out):
        import json as _json

        snap = _http_json(f"http://{env.master}/cluster/alerts")
        if _has_flag(args, "json"):
            print(_json.dumps(snap), file=out)
            return
        if snap.get("Disabled"):
            print(
                "telemetry collector disabled on this master "
                "(-telemetryInterval 0)",
                file=out,
            )
            return
        firing = snap.get("Firing") or []
        pending = snap.get("Pending") or []
        if not firing and not pending:
            print("no active alerts", file=out)
        for a in firing:
            print(
                f"FIRING [{a['Severity']}] {a['Alert']} @ {a['Target']}: "
                f"{a['Detail']}",
                file=out,
            )
        for a in pending:
            print(
                f"pending [{a['Severity']}] {a['Alert']} @ {a['Target']}: "
                f"{a['Detail']}",
                file=out,
            )
        for a in (snap.get("History") or [])[-10:]:
            print(
                f"  resolved {a['Alert']} @ {a['Target']} "
                f"(fired {a.get('FiredAtUnix', 0)}, "
                f"resolved {a.get('ResolvedAtUnix', 0)})",
                file=out,
            )


@register
class ClusterTop(Command):
    name = "cluster.top"
    help = (
        "cluster.top [-n 10] [-json] — busiest nodes by req/s (with "
        "5xx rate, http p99, and heartbeat-reported in-flight/write-"
        "queue depth) and biggest volumes by size"
    )

    def run(self, env, args, out):
        import json as _json

        n = int(_flag(args, "n", "10") or 10)
        snap = _http_json(f"http://{env.master}/cluster/top?n={n}")
        if _has_flag(args, "json"):
            print(_json.dumps(snap), file=out)
            return
        if snap.get("Disabled"):
            print(
                "telemetry collector disabled on this master "
                "(-telemetryInterval 0)",
                file=out,
            )
            return
        print("busiest nodes:", file=out)
        for row in snap.get("Nodes") or []:
            p99 = row.get("P99Ms")
            load = ""
            if row.get("InFlight") is not None:
                # QoS columns (volume servers only): the heartbeat load
                # signal queue-depth-aware assignment weighs
                load = (
                    f", inflight {row['InFlight']}, "
                    f"wqueue {row['WriteQueueDepth']}"
                )
            print(
                f"  {row['Url']} [{row['Kind']}]: "
                f"{row['ReqPerSec']:.2f} req/s, "
                f"{row['ErrPerSec']:.2f} err/s, "
                f"p99 {'-' if p99 is None else f'{p99:.1f}ms'}"
                + load,
                file=out,
            )
        print("biggest volumes:", file=out)
        for row in snap.get("Volumes") or []:
            print(
                f"  vid {row['VolumeId']} @ {row['Node']}: "
                f"{row['SizeBytes'] >> 20} MiB, "
                f"{row['FileCount']} file(s)"
                + (
                    f" [{row['Collection']}]" if row.get("Collection") else ""
                ),
                file=out,
            )


@register
class ClusterSlo(Command):
    name = "cluster.slo"
    help = (
        "cluster.slo [-json] — weedscope SLO engine: per-objective "
        "burn rates over the fast/slow windows, error-budget "
        "remaining, and the soak scorecard (availability, accepted "
        "p99.9, retry amplification, MTTR)"
    )

    def run(self, env, args, out):
        import json as _json

        snap = _http_json(f"http://{env.master}/cluster/slo")
        if _has_flag(args, "json"):
            print(_json.dumps(snap), file=out)
            return
        if snap.get("Disabled"):
            print(
                "telemetry collector disabled on this master "
                "(-telemetryInterval 0)",
                file=out,
            )
            return
        if not snap.get("Enabled", True):
            print("SLO engine disabled (WEED_SLO=0)", file=out)
            return
        print(
            f"windows: fast {snap.get('FastWindowSeconds')}s / "
            f"slow {snap.get('SlowWindowSeconds')}s, "
            f"burn threshold {snap.get('BurnThreshold')}x"
            + (
                f", BREACHING: {', '.join(snap['Breaching'])}"
                if snap.get("Breaching")
                else ""
            ),
            file=out,
        )
        for row in snap.get("Objectives") or []:
            thr = row.get("ThresholdSeconds")
            goal = (
                f"{row['Target']:.4%} non-5xx"
                if row.get("Kind") == "availability"
                else f"{row['Target']:.2%} of {row.get('Plane')} "
                f"under {thr * 1000.0:.0f}ms"
            )
            print(
                f"  {row['Verdict'].upper():8s} {row['Objective']}: {goal} "
                f"— burn fast {row['BurnFast']:.2f}x / "
                f"slow {row['BurnSlow']:.2f}x, "
                f"budget {row['BudgetRemaining']:.2%}",
                file=out,
            )
        card = snap.get("Scorecard") or {}
        if card:
            p999 = card.get("AcceptedP999Ms")
            mttr = card.get("MTTRSeconds")
            print(
                f"scorecard ({card.get('WindowSeconds')}s): "
                f"{card.get('Requests', 0):.0f} request(s), "
                f"availability {card.get('AvailabilityPct', 100.0):.4f}%, "
                f"p99.9 {'-' if p999 is None else f'{p999:.1f}ms'}, "
                f"retry x{card.get('RetryAmplification', 1.0):.3f}, "
                f"MTTR {'-' if mttr is None else f'{mttr:.1f}s'}",
                file=out,
            )


@register
class CapsuleCapture(Command):
    name = "capsule.capture"
    help = (
        "capsule.capture [-node host:port] [-reason R] [-json] — "
        "snapshot an incident capsule (blackbox ring, traces, folded "
        "stacks, metrics; TSDB window + verdicts on the master) NOW "
        "on every reachable node (or just -node)"
    )

    def run(self, env, args, out):
        import json as _json
        from urllib.parse import quote

        node = _flag(args, "node")
        reason = _flag(args, "reason", "shell")
        urls = [node] if node else _trace_nodes(env)
        rows = []
        for url in urls:
            try:
                manifest = _http_json(
                    f"http://{url}/capsule/capture?reason={quote(reason)}",
                    timeout=30.0,
                )
            except (OSError, ValueError) as e:
                rows.append({"Node": url, "Error": str(e)})
                continue
            manifest["Node"] = manifest.get("Node") or url
            rows.append(manifest)
        if _has_flag(args, "json"):
            print(_json.dumps({"Capsules": rows}), file=out)
            return
        for row in rows:
            if row.get("Error"):
                print(f"{row['Node']}: unreachable ({row['Error']})", file=out)
                continue
            ok = [f["Name"] for f in row.get("Files") or [] if f.get("Ok")]
            failed = [
                f["Name"] for f in row.get("Files") or [] if not f.get("Ok")
            ]
            line = f"{row['Node']}: captured {row['Id']} ({', '.join(ok)})"
            if failed:
                line += f" FAILED: {', '.join(failed)}"
            print(line, file=out)


@register
class CapsuleCollect(Command):
    name = "capsule.collect"
    help = (
        "capsule.collect [-reason R] [-n 5] [-json] — gather each "
        "node's newest capsule (optionally matching -reason) and merge "
        "their blackbox wide-events by trace id into one cross-node "
        "incident view"
    )

    def run(self, env, args, out):
        import json as _json

        reason = _flag(args, "reason")
        n = int(_flag(args, "n", "5") or 5)
        summary: list[dict] = []
        merged: dict[str, list[dict]] = {}
        for url in _trace_nodes(env):
            try:
                caps = (
                    _http_json(f"http://{url}/capsule/list").get("Capsules")
                    or []
                )
            except (OSError, ValueError) as e:
                summary.append({"Node": url, "Error": str(e)})
                continue
            if reason:
                caps = [c for c in caps if reason in c.get("Reason", "")]
            if not caps:
                summary.append({"Node": url, "Capsule": None})
                continue
            cap = caps[-1]  # list_capsules returns oldest first
            summary.append({
                "Node": url,
                "Capsule": cap.get("Id"),
                "Reason": cap.get("Reason"),
                "Trigger": cap.get("Trigger"),
                "CapturedAtUnix": cap.get("CapturedAtUnix"),
            })
            try:
                bb = _http_json(
                    f"http://{url}/capsule/get"
                    f"?id={cap['Id']}&file=blackbox.json"
                )
            except (OSError, ValueError):
                continue
            for rec in (bb.get("tail") or []) + (bb.get("ok") or []):
                tid = rec.get("trace") or ""
                if not tid:
                    continue
                rec = dict(rec)
                rec["node"] = url
                merged.setdefault(tid, []).append(rec)
        for evs in merged.values():
            evs.sort(key=lambda r: r.get("t", 0))
        if _has_flag(args, "json"):
            print(
                _json.dumps({"Nodes": summary, "Traces": merged}), file=out
            )
            return
        for row in summary:
            if row.get("Error"):
                print(f"{row['Node']}: unreachable ({row['Error']})", file=out)
            elif row.get("Capsule") is None:
                print(f"{row['Node']}: no matching capsule", file=out)
            else:
                print(
                    f"{row['Node']}: {row['Capsule']} "
                    f"({row['Trigger']}: {row['Reason']})",
                    file=out,
                )
        # widest traces first: the cross-node stories are the point
        ranked = sorted(
            merged.items(),
            key=lambda kv: (-len({e['node'] for e in kv[1]}), -len(kv[1])),
        )
        print(f"{len(merged)} trace(s) across capsules", file=out)
        for tid, evs in ranked[:n]:
            nodes = len({e["node"] for e in evs})
            print(f"  trace {tid} ({len(evs)} event(s), {nodes} node(s)):",
                  file=out)
            for e in evs:
                flags = f" [{','.join(e['flags'])}]" if e.get("flags") else ""
                print(
                    f"    {e['node']} {e['name']} {e['status']} "
                    f"{e['dur_ms']:.1f}ms{flags}",
                    file=out,
                )


@register
class ProfileCapture(Command):
    name = "profile.capture"
    help = (
        "profile.capture [-node host:port] [-seconds 2] [-top 15] "
        "[-folded] — capture folded stacks from a node's continuous "
        "sampling profiler (default: every node, ranked)"
    )

    def run(self, env, args, out):
        node = _flag(args, "node")
        seconds = float(_flag(args, "seconds", "2") or 2)
        top = int(_flag(args, "top", "15") or 15)
        urls = [node] if node else _trace_nodes(env)
        for url in urls:
            try:
                payload = _http_json(
                    f"http://{url}/debug/profile?seconds={seconds}",
                    timeout=seconds + 15.0,
                )
            except (OSError, ValueError) as e:
                print(f"{url}: unreachable ({e})", file=out)
                continue
            stacks = payload.get("stacks") or {}
            print(
                f"{url}: {payload.get('samples', 0)} sample(s) over "
                f"{payload.get('seconds')}s "
                f"(interval {payload.get('interval_ms')}ms, "
                f"{'running' if payload.get('running') else 'PAUSED'})",
                file=out,
            )
            ranked = sorted(stacks.items(), key=lambda kv: -kv[1])
            if _has_flag(args, "folded"):
                for stack, count in ranked:
                    print(f"{stack} {count}", file=out)
                continue
            for stack, count in ranked[:top]:
                # print the innermost frames; full stacks via -folded
                leaf = ";".join(stack.split(";")[-3:])
                print(f"  {count:6d}  {leaf}", file=out)


# ----------------------------------------------------------------------
# tiering + replication plane operator surface (docs/TIERING.md)


def _http_json_post(url: str, timeout: float = 10.0) -> dict:
    import json as _json
    import urllib.request

    req = urllib.request.Request(url, method="POST", data=b"")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return _json.loads(r.read())


def _http_text(url: str, timeout: float = 10.0) -> str:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", "replace")


@register
class TierStatus(Command):
    name = "tier.status"
    help = (
        "tier.status [-json] — scheduler rules + recent moves from the "
        "master, and per-node tiered-volume state from every holder"
    )

    def run(self, env, args, out):
        import json as _json

        try:
            sched = _http_json(f"http://{env.master}/cluster/tier")
        except (OSError, ValueError) as e:
            sched = {"error": str(e)}
        nodes = {}
        dump = env.collect_topology()
        for n in dump.nodes:
            try:
                nodes[n.url] = _http_json(f"http://{n.url}/tier/status")
            except (OSError, ValueError) as e:
                nodes[n.url] = {"error": str(e)}
        if _has_flag(args, "json"):
            print(_json.dumps({"Scheduler": sched, "Nodes": nodes}), file=out)
            return
        if sched.get("Disabled"):
            print(
                "tier scheduler disabled on this master (-tierInterval 0); "
                "tiering is manual (tier.move)",
                file=out,
            )
        elif "error" in sched:
            print(f"master unreachable: {sched['error']}", file=out)
        else:
            rules = sched.get("Rules") or {}
            print(
                f"scheduler: every {sched.get('IntervalSeconds')}s, "
                f"backend '{rules.get('Backend', '')}', "
                f"min age {rules.get('MinAgeSeconds')}s, "
                f"cold <= {rules.get('ColdReadsPerSec')}/s, "
                f"hot > {rules.get('HotReadsPerSec')}/s, "
                f"active {sched.get('Active', 0)}, "
                f"started {sched.get('MovesStarted', 0)}, "
                f"failed {sched.get('MovesFailed', 0)}",
                file=out,
            )
            for h in (sched.get("History") or [])[-10:]:
                print(
                    f"  {h['Direction']} vid {h['VolumeId']} @ {h['Holder']} "
                    f"in {h['Seconds']}s"
                    + (f" ERROR: {h['Error']}" if h.get("Error") else ""),
                    file=out,
                )
        for url, st in sorted(nodes.items()):
            if "error" in st:
                print(f"{url}: unreachable ({st['error']})", file=out)
                continue
            rows = [
                (int(vid), row) for vid, row in st.items()
                if isinstance(row, dict)
            ]
            if not rows:
                continue
            print(f"{url}:", file=out)
            for vid, row in sorted(rows):
                if row.get("Tiered"):
                    print(
                        f"  vid {vid}: TIERED -> {row.get('Backend')} "
                        f"(remote {row.get('RemoteShards')}, "
                        f"local {row.get('LocalShards')})",
                        file=out,
                    )
                else:
                    print(
                        f"  vid {vid}: local shards {row.get('LocalShards')}",
                        file=out,
                    )


@register
class TierMove(Command):
    name = "tier.move"
    help = (
        "tier.move -volumeId vid -dest backend.name [-in] "
        "[-node host:port] — move an EC volume's shards out to the "
        "backend (or back in with -in) on every holder"
    )

    def run(self, env, args, out):
        import json as _json

        vid = _flag(args, "volumeId")
        if not vid:
            print("tier.move: -volumeId required", file=out)
            return
        direction = "in" if _has_flag(args, "in") else "out"
        dest = _flag(args, "dest")
        if direction == "out" and not dest:
            print("tier.move: -dest backend.name required for tier-out", file=out)
            return
        node = _flag(args, "node")
        if node:
            urls = [node]
        else:
            # every node that holds shards of this volume (tier-out is
            # per-holder: each node streams its OWN shards out)
            urls = []
            dump = env.collect_topology()
            for n in dump.nodes:
                try:
                    st = _http_json(f"http://{n.url}/tier/status")
                except (OSError, ValueError):
                    continue
                if vid in st:
                    urls.append(n.url)
        if not urls:
            print(f"tier.move: no holder found for vid {vid}", file=out)
            return
        qs = f"volumeId={vid}&direction={direction}"
        if direction == "out":
            qs += f"&destination={dest}"
        for url in urls:
            try:
                result = _http_json_post(
                    f"http://{url}/tier/move?{qs}", timeout=600.0
                )
            except (OSError, ValueError) as e:
                print(f"{url}: FAILED ({e})", file=out)
                continue
            print(f"{url}: {_json.dumps(result)}", file=out)


@register
class ReplicationLag(Command):
    name = "replication.lag"
    help = (
        "replication.lag [-json] — cross-cluster replication consumer "
        "lag as seen by the leader's telemetry rings (filer-exposed "
        "weed_replication_lag_events), plus any firing lag alerts"
    )

    def run(self, env, args, out):
        import json as _json

        try:
            alerts = _http_json(f"http://{env.master}/cluster/alerts")
        except (OSError, ValueError) as e:
            alerts = {"error": str(e)}
        rows = {}
        # scrape the registered filer gateways directly: the producer
        # side's view of queue depth is authoritative for lag
        try:
            health = _http_json(f"http://{env.master}/cluster/health")
        except (OSError, ValueError):
            health = {}
        for url, row in (health.get("Targets") or {}).items():
            if row.get("Kind") != "filer":
                continue
            try:
                text = _http_text(f"http://{url}/metrics")
            except (OSError, ValueError) as e:
                rows[url] = {"error": str(e)}
                continue
            lag = None
            for line in text.splitlines():
                if line.startswith("weed_replication_lag_events"):
                    try:
                        lag = float(line.rsplit(None, 1)[1])
                    except (IndexError, ValueError):
                        pass
            rows[url] = {"LagEvents": lag}
        firing = [
            a for a in (alerts.get("Firing") or [])
            if a.get("Alert") == "replication_lag"
        ]
        if _has_flag(args, "json"):
            print(_json.dumps({"Filers": rows, "Alerts": firing}), file=out)
            return
        if not rows:
            print(
                "no filer gateways registered with the master "
                "(is telemetry on, and did the filer announce?)",
                file=out,
            )
        for url, row in sorted(rows.items()):
            if "error" in row:
                print(f"{url}: unreachable ({row['error']})", file=out)
            elif row["LagEvents"] is None:
                print(
                    f"{url}: no lag metric (no notification queue "
                    f"configured on this filer)",
                    file=out,
                )
            else:
                print(f"{url}: {row['LagEvents']:.0f} event(s) behind", file=out)
        for a in firing:
            print(
                f"ALERT {a.get('Severity')}: {a.get('Target')} "
                f"{a.get('Detail')}",
                file=out,
            )
