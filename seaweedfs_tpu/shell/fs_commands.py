"""fs.* shell commands — filer namespace operations from the admin
shell (reference weed/shell/command_fs_*.go, 11 commands).

Context model matches commands.go CommandEnv: `fs.cd
http://<filer>:<port>/dir` selects the filer + working directory;
later relative paths resolve against it. Absolute http:// paths work
on any command without a prior cd.
"""

from __future__ import annotations


import posixpath
import struct

from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.shell.commands import Command, CommandEnv, _flag, _has_flag, register


def _stub(env: CommandEnv, filer: str):
    return env.filer_channel(filer)


def _lookup(stub, directory: str, name: str) -> fpb.Entry | None:
    import grpc

    try:
        resp = stub.LookupDirectoryEntry(
            fpb.LookupDirectoryEntryRequest(directory=directory, name=name)
        )
    except grpc.RpcError as e:
        # only "no such entry" maps to None; a down/unreachable filer
        # must surface as the infrastructure error it is
        if e.code() == grpc.StatusCode.NOT_FOUND:
            return None
        raise
    return resp.entry if resp.entry.name else None


_PAGE = 1024


def _list(stub, directory: str) -> list[fpb.Entry]:
    """Full listing with pagination — the filer caps one ListEntries
    page (fs.meta.save is a backup tool; silent truncation of big
    directories would be data loss)."""
    out: list[fpb.Entry] = []
    start = ""
    while True:
        page = [
            r.entry
            for r in stub.ListEntries(
                fpb.ListEntriesRequest(
                    directory=directory,
                    start_from_file_name=start,
                    inclusive_start_from=False,
                    limit=_PAGE,
                )
            )
        ]
        out.extend(page)
        if len(page) < _PAGE:
            return out
        start = page[-1].name


def _is_dir(stub, path: str) -> bool:
    if path == "/":
        return True
    d, name = posixpath.split(path)
    e = _lookup(stub, d or "/", name)
    return e is not None and e.is_directory


def _entry_size(e: fpb.Entry) -> int:
    return e.attributes.file_size or sum(c.size for c in e.chunks)


def _walk(stub, directory: str):
    """Yield (directory, entry) depth-first (filer_pb TraverseBfs role)."""
    for e in _list(stub, directory):
        yield directory, e
        if e.is_directory:
            child = f"{directory.rstrip('/')}/{e.name}"
            yield from _walk(stub, child)


def _walk_path(stub, path: str):
    """_walk that also accepts a single-file path (a backup tool must
    not silently save 0 entries for an existing file)."""
    if _is_dir(stub, path):
        yield from _walk(stub, path)
        return
    d, name = posixpath.split(path)
    e = _lookup(stub, d or "/", name)
    if e is None:
        raise ValueError(f"{path} not found")
    yield d or "/", e


@register
class FsCd(Command):
    name = "fs.cd"
    help = "fs.cd http://<filer>:<port>/dir | fs.cd <dir> — change working directory"

    def run(self, env, args, out):
        if not args:
            env.cwd = "/"
            return
        filer, path = env.parse_fs_path(args[0])
        with _stub(env, filer) as ch:
            if not _is_dir(rpc.filer_stub(ch), path):
                raise ValueError(f"{path} is not a directory")
        env.filer = filer
        env.cwd = path
        print(f"{filer}{path}", file=out)


@register
class FsPwd(Command):
    name = "fs.pwd"
    help = "fs.pwd — print the current filer working directory"

    def run(self, env, args, out):
        if not env.filer:
            print("(no filer selected; fs.cd http://<filer>:<port>/)", file=out)
            return
        print(f"http://{env.filer}{env.cwd}", file=out)


@register
class FsLs(Command):
    name = "fs.ls"
    help = "fs.ls [-l] [-a] [path] — list directory entries"

    def run(self, env, args, out):
        paths = [a for a in args if not a.startswith("-")]
        filer, path = env.parse_fs_path(paths[0] if paths else ".")
        long_fmt = _has_flag(args, "l")
        show_all = _has_flag(args, "a")
        with _stub(env, filer) as ch:
            stub = rpc.filer_stub(ch)
            entries = _list(stub, path)
        shown = 0
        for e in sorted(entries, key=lambda x: x.name):
            if not show_all and e.name.startswith("."):
                continue
            shown += 1
            if long_fmt:
                a = e.attributes
                kind = "d" if e.is_directory else "-"
                print(
                    f"{kind}{a.file_mode & 0o777:03o} {a.uid:>4} {a.gid:>4} "
                    f"{_entry_size(e):>12} {e.name}{'/' if e.is_directory else ''}",
                    file=out,
                )
            else:
                print(f"{e.name}{'/' if e.is_directory else ''}", file=out)
        if long_fmt:
            print(f"total {shown}", file=out)


@register
class FsDu(Command):
    name = "fs.du"
    help = "fs.du [path] — recursive disk usage (bytes, files, dirs)"

    def run(self, env, args, out):
        filer, path = env.parse_fs_path(args[0] if args else ".")
        with _stub(env, filer) as ch:
            stub = rpc.filer_stub(ch)
            size = files = dirs = 0
            if not _is_dir(stub, path):
                d, name = posixpath.split(path)
                e = _lookup(stub, d or "/", name)
                if e is None:
                    raise ValueError(f"{path} not found")
                size, files = _entry_size(e), 1
            else:
                for _, e in _walk(stub, path):
                    if e.is_directory:
                        dirs += 1
                    else:
                        files += 1
                        size += _entry_size(e)
        print(f"{size}\t{files} files\t{dirs} dirs\t{path}", file=out)


@register
class FsCat(Command):
    name = "fs.cat"
    help = "fs.cat <path> — print a file's content"

    def run(self, env, args, out):
        if not args:
            raise ValueError("fs.cat <path>")
        filer, path = env.parse_fs_path(args[0])
        import urllib.parse
        import urllib.request

        with urllib.request.urlopen(
            f"http://{filer}{urllib.parse.quote(path)}", timeout=30
        ) as r:
            data = r.read()
        try:
            print(data.decode(), end="", file=out)
        except UnicodeDecodeError:
            print(f"<binary: {len(data)} bytes>", file=out)


@register
class FsTree(Command):
    name = "fs.tree"
    help = "fs.tree [path] — tree view of the namespace"

    def run(self, env, args, out):
        filer, path = env.parse_fs_path(args[0] if args else ".")
        with _stub(env, filer) as ch:
            stub = rpc.filer_stub(ch)
            print(path, file=out)
            files, dirs = self._tree(stub, path, "", out)
        print(f"\n{dirs} directories, {files} files", file=out)

    def _tree(self, stub, directory: str, prefix: str, out) -> tuple[int, int]:
        entries = sorted(_list(stub, directory), key=lambda e: e.name)
        files = dirs = 0
        for i, e in enumerate(entries):
            last = i == len(entries) - 1
            tee = "└── " if last else "├── "
            print(f"{prefix}{tee}{e.name}", file=out)
            if e.is_directory:
                dirs += 1
                ext = "    " if last else "│   "
                f2, d2 = self._tree(
                    stub, f"{directory.rstrip('/')}/{e.name}", prefix + ext, out
                )
                files += f2
                dirs += d2
            else:
                files += 1
        return files, dirs


@register
class FsMv(Command):
    name = "fs.mv"
    help = "fs.mv <src> <dst> — move/rename (atomic; into dst if dst is a dir)"

    def run(self, env, args, out):
        if len(args) != 2:
            raise ValueError("fs.mv <src> <dst>")
        filer, src = env.parse_fs_path(args[0])
        filer2_, dst = env.parse_fs_path(args[1])
        if filer2_ != filer:
            raise ValueError("cannot move across filers")
        sd, sn = posixpath.split(src)
        with _stub(env, filer) as ch:
            stub = rpc.filer_stub(ch)
            if _is_dir(stub, dst):
                dd, dn = dst, sn
            else:
                dd, dn = posixpath.split(dst)
            stub.AtomicRenameEntry(
                fpb.AtomicRenameEntryRequest(
                    old_directory=sd or "/",
                    old_name=sn,
                    new_directory=dd or "/",
                    new_name=dn,
                )
            )
        print(f"moved {src} -> {dd.rstrip('/')}/{dn}", file=out)


@register
class FsMetaCat(Command):
    name = "fs.meta.cat"
    help = "fs.meta.cat <path> — print an entry's metadata"

    def run(self, env, args, out):
        if not args:
            raise ValueError("fs.meta.cat <path>")
        filer, path = env.parse_fs_path(args[0])
        d, name = posixpath.split(path)
        with _stub(env, filer) as ch:
            e = _lookup(rpc.filer_stub(ch), d or "/", name)
        if e is None:
            raise ValueError(f"{path} not found")
        print(str(e), file=out)


_META_MAGIC = b"SWMETA01"


@register
class FsMetaSave(Command):
    name = "fs.meta.save"
    help = "fs.meta.save [-o <file>] [path] — save metadata tree to a local file"

    def run(self, env, args, out):
        paths = [
            a
            for i, a in enumerate(args)
            if not a.startswith("-") and (i == 0 or args[i - 1] != "-o")
        ]
        filer, path = env.parse_fs_path(paths[0] if paths else ".")
        out_file = _flag(args, "o") or f"meta{path.replace('/', '-')}.meta"
        count = 0
        with _stub(env, filer) as ch, open(out_file, "wb") as f:
            stub = rpc.filer_stub(ch)
            f.write(_META_MAGIC)
            for directory, e in _walk_path(stub, path):
                blob = fpb.FullEntry(dir=directory, entry=e).SerializeToString()
                f.write(struct.pack(">I", len(blob)))
                f.write(blob)
                count += 1
        print(f"saved {count} entries to {out_file}", file=out)


@register
class FsMetaLoad(Command):
    name = "fs.meta.load"
    help = "fs.meta.load <file> — restore metadata saved by fs.meta.save"

    def run(self, env, args, out):
        if not args:
            raise ValueError("fs.meta.load <file>")
        if not env.filer:
            raise ValueError("fs.cd to the destination filer first")
        count = 0
        with open(args[0], "rb") as f, _stub(env, env.filer) as ch:
            stub = rpc.filer_stub(ch)
            if f.read(len(_META_MAGIC)) != _META_MAGIC:
                raise ValueError(f"{args[0]} is not an fs.meta.save file")
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    break
                (n,) = struct.unpack(">I", hdr)
                fe = fpb.FullEntry()
                fe.ParseFromString(f.read(n))
                stub.CreateEntry(
                    fpb.CreateEntryRequest(directory=fe.dir, entry=fe.entry)
                )
                count += 1
        print(f"loaded {count} entries", file=out)


@register
class FsMetaNotify(Command):
    name = "fs.meta.notify"
    help = "fs.meta.notify [path] — publish create events for the tree to the notification queue"

    def run(self, env, args, out):
        from seaweedfs_tpu import notification

        filer, path = env.parse_fs_path(args[0] if args else ".")
        queue = notification.queue
        if queue is None:
            raise ValueError(
                "no notification queue configured (notification.toml)"
            )
        count = 0
        with _stub(env, filer) as ch:
            stub = rpc.filer_stub(ch)
            for directory, e in _walk_path(stub, path):
                queue.send_message(
                    f"{directory.rstrip('/')}/{e.name}",
                    fpb.EventNotification(new_entry=e),
                )
                count += 1
        print(f"notified {count} entries", file=out)
