"""Read-side streaming: chunk views → bytes from volume servers
(weed/filer2/stream.go StreamContent).

Each view's fid is resolved through the master (operation.lookup cache)
and fetched from a volume server; sub-chunk views slice the fetched
needle. Reference parity: a sparse hole ends the stream — views stop at
the first gap and nothing is zero-filled (filechunks.go semantics,
pinned by the ported view tests).

QoS plane (docs/QOS.md): when the volume has more than one replica,
chunk fetches ride the hedged-read driver — a read that outlives the
volume's adaptive latency quantile fires a second attempt at the next
replica and cancels the loser. This is the path the filer's own GET
handler, the S3 gateway, and the WebDAV gateway all read through, so
one seam hedges every gateway at once. Replica order passes through
the client circuit breaker (vid_map), so a recently-dead replica is
tried last. `WEED_QOS=0`/`WEED_QOS_HEDGE=0` restores the plain
single-attempt read wholesale.
"""

from __future__ import annotations

from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.client import vid_map as _vm
from seaweedfs_tpu.filer import filechunks


def _replica_urls(master: str, fid: str) -> tuple[list[str], set[str]]:
    """("host:port/fid" candidates healthiest-first, suspect netlocs).

    The master orders suspects last and flags them (health plane,
    docs/HEALTH.md); the client breaker re-partitions on top for
    failures only THIS process has seen. Single-replica volumes return
    one url."""
    vid = fid.split(",")[0]
    result = op.lookup(master, vid)
    if result.error:
        raise RuntimeError(result.error)
    if not result.locations:
        raise RuntimeError(f"volume {vid} has no locations")
    suspects = {
        loc["url"] for loc in result.locations if loc.get("suspect")
    }
    return (
        _vm.order_by_health(
            [f"{loc['url']}/{fid}" for loc in result.locations]
        ),
        suspects,
    )


def fetch_chunk(master: str, fid: str) -> bytes:
    """One chunk fid → bytes, hedged across replicas when possible.

    When the best remaining candidate is a master-flagged SUSPECT (a
    gray node: reachable, probably slow-or-dead), the hedge fires
    EAGERLY — both replicas race from the start instead of waiting out
    the adaptive delay against a node the cluster already distrusts."""
    urls, suspects = _replica_urls(master, fid)
    if len(urls) < 2:
        data, _ = op.download(urls[0])
        return data
    from seaweedfs_tpu.qos import hedge

    data, _ = hedge.download(
        urls,
        key=fid.split(",")[0],
        eager=urls[0].partition("/")[0] in suspects,
    )
    return data


def stream_content(master: str, chunks, offset: int = 0, size: int | None = None):
    """Yield the file's bytes for [offset, offset+size)."""
    if size is None:
        size = filechunks.total_size(chunks) - offset
    for view in filechunks.view_from_chunks(chunks, offset, size):
        data = fetch_chunk(master, view.fid)
        yield data[view.offset : view.offset + view.size]


def read_all(master: str, chunks) -> bytes:
    return b"".join(stream_content(master, chunks))
