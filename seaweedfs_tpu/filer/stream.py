"""Read-side streaming: chunk views → bytes from volume servers
(weed/filer2/stream.go StreamContent).

Each view's fid is resolved through the master (operation.lookup cache)
and fetched from a volume server; sub-chunk views slice the fetched
needle. Reference parity: a sparse hole ends the stream — views stop at
the first gap and nothing is zero-filled (filechunks.go semantics,
pinned by the ported view tests).
"""

from __future__ import annotations

from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.filer import filechunks


def stream_content(master: str, chunks, offset: int = 0, size: int | None = None):
    """Yield the file's bytes for [offset, offset+size)."""
    if size is None:
        size = filechunks.total_size(chunks) - offset
    for view in filechunks.view_from_chunks(chunks, offset, size):
        url = op.lookup_file_id(master, view.fid)
        data, _ = op.download(url)
        yield data[view.offset : view.offset + view.size]


def read_all(master: str, chunks) -> bytes:
    return b"".join(stream_content(master, chunks))
