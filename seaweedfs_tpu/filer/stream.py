"""Read-side streaming: chunk views → bytes from volume servers
(weed/filer2/stream.go StreamContent).

Each view's fid is resolved through the master (operation.lookup cache)
and fetched from a volume server; sub-chunk views slice the fetched
needle. Missing intervals (sparse files) read as zeros, matching the
reference's zero-padded view walk.
"""

from __future__ import annotations

from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.filer import filechunks


def stream_content(master: str, chunks, offset: int = 0, size: int | None = None):
    """Yield the file's bytes for [offset, offset+size)."""
    if size is None:
        size = filechunks.total_size(chunks) - offset
    views = filechunks.view_from_chunks(chunks, offset, size)
    pos = offset
    for view in views:
        if view.logic_offset > pos:
            yield b"\x00" * (view.logic_offset - pos)
            pos = view.logic_offset
        url = op.lookup_file_id(master, view.fid)
        data, _ = op.download(url)
        yield data[view.offset : view.offset + view.size]
        pos += view.size
    if pos < offset + size:
        # trailing hole inside the requested range, but never past EOF
        eof = filechunks.total_size(chunks)
        tail = min(offset + size, eof) - pos
        if tail > 0:
            yield b"\x00" * tail


def read_all(master: str, chunks) -> bytes:
    return b"".join(stream_content(master, chunks))
