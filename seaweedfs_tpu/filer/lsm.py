"""Embedded LSM-tree KV store: the filer's leveldb-role backend.

The reference ships leveldb/leveldb2 as its default embedded filer
stores (weed/filer2/leveldb2/leveldb2_store.go); this is the same
component built from scratch rather than bound to a C library:

  WAL  append-only write-ahead log, replayed into the memtable on open
  memtable  in-memory map, flushed to an SSTable past a size threshold
  SSTable   immutable sorted file: records + sparse index + bloom
            filter + footer; point reads binary-search the sparse
            index then scan at most `_INDEX_EVERY` records
  manifest  JSON list of live tables, swapped atomically (tmp+rename)
  compaction  when L0 grows past `_COMPACT_AT` tables, all tables merge
            into one (newest record wins, tombstones dropped — safe
            because the merge always covers the full key range)

Keys order by (directory, name) via `dir + NUL + name` encoding, the
same trick leveldb2 plays with its directory-hash prefixes: a
directory listing is one contiguous range scan in every table.

Compaction runs synchronously inside the flush that crosses the
threshold (a deliberate deviation from leveldb's background thread:
single-writer filers gain nothing from the race, and deterministic
compaction is testable).

Crash story: WAL records are length-prefixed and torn tails are
truncated on replay; SSTables are immutable and only referenced after
their manifest swap; a crash between flush and WAL reset replays
already-flushed records into the memtable, which is idempotent
(newest-wins by table order, and the memtable outranks all tables).
"""

from __future__ import annotations

import heapq
import json
import os
import struct
import threading
import zlib

from seaweedfs_tpu.filer.entry import Entry, normalize_path, split_path
from seaweedfs_tpu.util import durable
from seaweedfs_tpu.filer.filerstore import EntryNotFound, FilerStore

_PUT, _DEL = 1, 2
_INDEX_EVERY = 16
_FOOTER = struct.Struct("<QQIQ")  # index_off, bloom_off, count, magic
_MAGIC = 0x5357_4C53_4D31_0001  # "SWLSM1"
_REC_HDR = struct.Struct("<IIB")  # klen, vlen, op
# WAL records carry a crc32 of key+value: a flipped byte mid-file would
# otherwise desync the length framing and replay garbage entries (only
# the torn *tail* is detectable by length alone)
_WAL_HDR = struct.Struct("<IIBI")  # klen, vlen, op, crc32


def _key(dir_path: str, name: str) -> bytes:
    return dir_path.encode() + b"\x00" + name.encode()


class _Bloom:
    """Fixed double-hash bloom filter (k=4, ~10 bits/key).

    Hashes must be process-independent (the bits are persisted and
    reread by later processes; Python's builtin hash() is seeded per
    process and would turn into false negatives = lost keys), so they
    come from one blake2b digest split in half."""

    def __init__(self, bits: bytearray):
        self.bits = bits

    @classmethod
    def build(cls, keys: list[bytes]) -> "_Bloom":
        nbits = max(64, len(keys) * 10)
        bits = bytearray((nbits + 7) // 8)
        b = cls(bits)
        for k in keys:
            for h in b._hashes(k):
                bits[h // 8] |= 1 << (h % 8)
        return b

    def _hashes(self, key: bytes):
        import hashlib

        nbits = len(self.bits) * 8
        d = hashlib.blake2b(key, digest_size=8).digest()
        h1 = int.from_bytes(d[:4], "little")
        h2 = int.from_bytes(d[4:], "little") or 1
        for i in range(4):
            yield (h1 + i * h2) % nbits

    def __contains__(self, key: bytes) -> bool:
        return all(self.bits[h // 8] >> (h % 8) & 1 for h in self._hashes(key))


class _SSTable:
    """One immutable sorted table; sparse index + bloom held in memory."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        self._f.seek(-_FOOTER.size, os.SEEK_END)
        index_off, bloom_off, self.count, magic = _FOOTER.unpack(
            self._f.read(_FOOTER.size)
        )
        if magic != _MAGIC:
            raise ValueError(f"bad sstable magic in {path}")
        self._f.seek(index_off)
        raw_index = self._f.read(bloom_off - index_off)
        self.index: list[tuple[bytes, int]] = []  # (key, record offset)
        pos = 0
        while pos < len(raw_index):
            klen, off = struct.unpack_from("<IQ", raw_index, pos)
            pos += 12
            self.index.append((raw_index[pos : pos + klen], off))
            pos += klen
        self._f.seek(bloom_off)
        bloom_raw = self._f.read(
            os.path.getsize(path) - bloom_off - _FOOTER.size
        )
        self.bloom = _Bloom(bytearray(bloom_raw))
        self._data_end = index_off
        self._lock = threading.Lock()

    @staticmethod
    def write(path: str, records: list[tuple[bytes, int, bytes]]) -> None:
        """records: sorted (key, op, value). Atomic via tmp+rename."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            index = []
            for i, (k, op, v) in enumerate(records):
                if i % _INDEX_EVERY == 0:
                    index.append((k, f.tell()))
                f.write(_REC_HDR.pack(len(k), len(v), op) + k + v)
            index_off = f.tell()
            for k, off in index:
                f.write(struct.pack("<IQ", len(k), off) + k)
            bloom_off = f.tell()
            f.write(bytes(_Bloom.build([k for k, _, _ in records]).bits))
            f.write(_FOOTER.pack(index_off, bloom_off, len(records), _MAGIC))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        durable.fsync_dir(os.path.dirname(path) or ".")

    def _scan_from(self, offset: int):
        """Yield (key, op, value) records starting at a record offset.
        Caller holds self._lock."""
        self._f.seek(offset)
        pos = offset
        while pos < self._data_end:
            hdr = self._f.read(_REC_HDR.size)
            klen, vlen, op = _REC_HDR.unpack(hdr)
            k = self._f.read(klen)
            v = self._f.read(vlen)
            pos += _REC_HDR.size + klen + vlen
            yield k, op, v

    def _seek_offset(self, key: bytes) -> int:
        """Record offset of the sparse-index slot at or before `key`."""
        lo, hi = 0, len(self.index) - 1
        best = 0
        while lo <= hi:
            mid = (lo + hi) // 2
            if self.index[mid][0] <= key:
                best = self.index[mid][1]
                lo = mid + 1
            else:
                hi = mid - 1
        return best

    def get(self, key: bytes) -> tuple[int, bytes] | None:
        """(op, value) for key, or None. Bloom-gated point read."""
        if not self.index or key not in self.bloom:
            return None
        with self._lock:
            for k, op, v in self._scan_from(self._seek_offset(key)):
                if k == key:
                    return op, v
                if k > key:
                    return None
        return None

    def iter_range(self, start_key: bytes, end_key: bytes | None = None):
        """Stream records with start_key <= key (< end_key when given).

        Opens a private file handle EAGERLY (before returning the
        generator): callers invoke this under the store lock, so a
        concurrent compaction cannot unlink the path before the open —
        and once open, the fd keeps the unlinked inode readable for the
        rest of the (lockless, lazy) iteration. The table bytes are
        immutable, so no further locking is needed; a paginated listing
        stops after its page instead of materializing the directory's
        tail."""
        if not self.index:
            return iter(())
        f = open(self.path, "rb")

        def gen():
            with f:
                pos = self._seek_offset(start_key)
                f.seek(pos)
                while pos < self._data_end:
                    klen, vlen, op = _REC_HDR.unpack(f.read(_REC_HDR.size))
                    k = f.read(klen)
                    v = f.read(vlen)
                    pos += _REC_HDR.size + klen + vlen
                    if end_key is not None and k >= end_key:
                        return
                    if k >= start_key:
                        yield k, op, v

        return gen()

    def range_from(
        self, start_key: bytes, end_key: bytes | None = None
    ) -> list[tuple[bytes, int, bytes]]:
        """Materialized iter_range (compaction wants the whole table)."""
        return list(self.iter_range(start_key, end_key))

    def close(self) -> None:
        self._f.close()


class LsmStore(FilerStore):
    """FilerStore over the LSM engine. `path` is a directory."""

    name = "lsm"

    def __init__(
        self,
        path: str,
        memtable_bytes: int = 4 * 1024 * 1024,
        compact_at: int = 4,
    ):
        self._dir = path
        self._memtable_bytes = memtable_bytes
        self._compact_at = compact_at
        os.makedirs(path, exist_ok=True)
        self._lock = threading.RLock()
        self._mem: dict[bytes, tuple[int, bytes]] = {}  # key -> (op, value)
        self._mem_size = 0
        self._next_table = 1
        self._tables: list[_SSTable] = []  # oldest → newest
        self._load_manifest()
        self._wal_path = os.path.join(path, "wal.log")
        self._replay_wal()
        self._wal = open(self._wal_path, "ab")

    # --- persistence plumbing ---

    def _manifest_path(self) -> str:
        return os.path.join(self._dir, "MANIFEST")

    def _load_manifest(self) -> None:
        try:
            with open(self._manifest_path()) as f:
                names = json.load(f)
        except (OSError, ValueError):
            names = []
        for n in names:
            p = os.path.join(self._dir, n)
            if os.path.exists(p):
                self._tables.append(_SSTable(p))
                num = int(n.split(".")[0])
                self._next_table = max(self._next_table, num + 1)

    def _write_manifest(self) -> None:
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump([os.path.basename(t.path) for t in self._tables], f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path())
        durable.fsync_dir(self._dir)

    def _replay_wal(self) -> None:
        if not os.path.exists(self._wal_path):
            return
        good = 0
        with open(self._wal_path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + _WAL_HDR.size <= len(raw):
            klen, vlen, op, crc = _WAL_HDR.unpack_from(raw, pos)
            end = pos + _WAL_HDR.size + klen + vlen
            if end > len(raw):
                break  # torn tail
            k = raw[pos + _WAL_HDR.size : pos + _WAL_HDR.size + klen]
            v = raw[pos + _WAL_HDR.size + klen : end]
            if zlib.crc32(v, zlib.crc32(k)) != crc:
                break  # corrupt record: cut here, like a torn tail
            self._mem[k] = (op, v)
            self._mem_size += len(k) + len(v) + 16
            good = end
            pos = end
        if good < len(raw):  # truncate the torn tail for the next append
            with open(self._wal_path, "r+b") as f:
                f.truncate(good)

    def _wal_append(self, key: bytes, op: int, value: bytes) -> None:
        crc = zlib.crc32(value, zlib.crc32(key))
        self._wal.write(
            _WAL_HDR.pack(len(key), len(value), op, crc) + key + value
        )
        self._wal.flush()

    def _flush_memtable(self) -> None:
        """Memtable → new L0 SSTable; maybe compact; reset WAL.
        Caller holds self._lock."""
        if not self._mem:
            return
        records = [(k, op, v) for k, (op, v) in sorted(self._mem.items())]
        name = f"{self._next_table:06d}.sst"
        self._next_table += 1
        path = os.path.join(self._dir, name)
        _SSTable.write(path, records)
        self._tables.append(_SSTable(path))
        if len(self._tables) >= self._compact_at:
            self._compact()
        else:
            self._write_manifest()
        self._mem.clear()
        self._mem_size = 0
        # reset the WAL only after the manifest references the table
        self._wal.close()
        self._wal = open(self._wal_path, "wb")

    def _compact(self) -> None:
        """Merge all tables, newest wins, tombstones dropped.
        Caller holds self._lock."""
        merged: dict[bytes, tuple[int, bytes]] = {}
        for t in self._tables:  # oldest → newest: later writes win
            for k, op, v in t.range_from(b""):
                merged[k] = (op, v)
        records = [
            (k, op, v)
            for k, (op, v) in sorted(merged.items())
            if op != _DEL
        ]
        name = f"{self._next_table:06d}.sst"
        self._next_table += 1
        path = os.path.join(self._dir, name)
        _SSTable.write(path, records)
        old = self._tables
        self._tables = [_SSTable(path)]
        self._write_manifest()
        for t in old:
            t.close()
            try:
                os.unlink(t.path)
            except OSError:
                pass

    def _put(self, key: bytes, op: int, value: bytes) -> None:
        with self._lock:
            self._wal_append(key, op, value)
            self._mem[key] = (op, value)
            self._mem_size += len(key) + len(value) + 16
            if self._mem_size >= self._memtable_bytes:
                self._flush_memtable()

    def _get(self, key: bytes) -> bytes | None:
        with self._lock:
            hit = self._mem.get(key)
            if hit is None:
                for t in reversed(self._tables):  # newest first
                    got = t.get(key)
                    if got is not None:
                        hit = got
                        break
        if hit is None or hit[0] == _DEL:
            return None
        return hit[1]

    # --- FilerStore SPI ---

    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        self._put(_key(d, name), _PUT, entry.encode())

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry:
        d, name = split_path(full_path)
        data = self._get(_key(d, name))
        if data is None:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, data)

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        self._put(_key(d, name), _DEL, b"")

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, include_start: bool, limit: int
    ) -> list[Entry]:
        dir_path = normalize_path(dir_path)
        prefix = dir_path.encode() + b"\x00"
        start = prefix + start_file_name.encode()
        # NUL separates dir from name, so dir+0x01 upper-bounds the
        # directory's whole key range
        end = dir_path.encode() + b"\x01"
        # limit-aware k-way merge, newest-wins per key: each source is
        # already sorted; priority = source recency (memtable > newer
        # table > older). Stops as soon as the page is full instead of
        # materializing the directory's tail (tables stream lazily via
        # iter_range; only the memtable — bounded by memtable_bytes —
        # is snapshotted here). Sources are BUILT under the lock:
        # iter_range opens its file handle eagerly, so a concurrent
        # flush-triggered compaction can't unlink a snapshotted table
        # out from under the merge.
        def _table_source(t: _SSTable, pri: int):
            # explicit binding: a genexp inside the list comprehension
            # would close over the loop variable and give every source
            # the LAST priority, letting ties fall to op where
            # PUT < DEL — i.e. deletes resurrected across tables
            it = t.iter_range(start, end)  # opens the fd now, under the lock
            return ((k, -pri, op, v) for k, op, v in it)

        with self._lock:
            n_tables = len(self._tables)
            sources = [
                _table_source(t, pri) for pri, t in enumerate(self._tables)
            ]
            sources.append(
                iter(
                    sorted(
                        (k, -n_tables, op, v)
                        for k, (op, v) in self._mem.items()
                        if start <= k < end
                    )
                )
            )
        out = []
        current: bytes | None = None
        for k, neg_pri, op, v in heapq.merge(*sources):
            if k == current:
                continue  # a newer source already decided this key
            current = k
            if op == _DEL:
                continue
            name = k[len(prefix) :].decode()
            if start_file_name:
                if include_start and name < start_file_name:
                    continue
                if not include_start and name <= start_file_name:
                    continue
            out.append(Entry.decode(f"{dir_path}/{name}", v))
            if len(out) >= limit:
                break
        return out

    def flush(self) -> None:
        """Force the memtable to disk (test/shutdown hook)."""
        with self._lock:
            self._flush_memtable()

    def close(self) -> None:
        with self._lock:
            self._flush_memtable()
            self._wal.close()
            for t in self._tables:
                t.close()
