"""Cassandra-backed filer store speaking the CQL v4 binary protocol.

Behavioral match of weed/filer2/cassandra/cassandra_store.go: the
`filemeta (directory, name, meta)` table with `PRIMARY KEY (directory,
name)` clustering ASC, and its five statements verbatim —

  INSERT INTO filemeta (directory,name,meta) VALUES(?,?,?) USING TTL ?
  SELECT meta FROM filemeta WHERE directory=? AND name=?
  DELETE FROM filemeta WHERE directory=? AND name=?
  DELETE FROM filemeta WHERE directory=?
  SELECT name, meta FROM filemeta WHERE directory=? AND name>[=]?
      ORDER BY name ASC LIMIT ?

The reference rides gocql; this store implements the wire protocol
over one socket (native_protocol_v4: STARTUP/READY handshake, QUERY
with bound values at consistency ONE, RESULT void/rows decoding,
ERROR surfacing). The gate is connectivity — constructing dials the
node and raises with guidance; tests/cloud_fakes.FakeCassandra speaks
the same frames offline.
"""

from __future__ import annotations

import socket
import struct
import threading

from seaweedfs_tpu.filer.entry import Entry, child_path, normalize_path, split_path
from seaweedfs_tpu.filer.filerstore import EntryNotFound, FilerStore

# opcodes (native_protocol_v4.spec §2.4)
OP_ERROR, OP_STARTUP, OP_READY, OP_QUERY, OP_RESULT = 0x00, 0x01, 0x02, 0x07, 0x08
RESULT_VOID, RESULT_ROWS, RESULT_SET_KEYSPACE = 0x0001, 0x0002, 0x0003
CONSISTENCY_ONE = 0x0001
FLAG_VALUES = 0x01
GLOBAL_TABLES_SPEC = 0x0001


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">i", len(b)) + b


def _value(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _FrameReader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def take(self, n: int) -> bytes:
        d = self.data[self.off : self.off + n]
        if len(d) < n:
            raise ValueError("cql: short frame")
        self.off += n
        return d

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def string(self) -> str:
        return self.take(struct.unpack(">H", self.take(2))[0]).decode()

    def value(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self.take(n)

    def type_option(self) -> None:
        """Consume one column type option (simple ids only; our schema
        is varchar/blob)."""
        tid = self.i16()
        if tid == 0x0000:  # custom: class string
            self.string()
        elif tid in (0x0020, 0x0022):  # list/set: one sub-option
            self.type_option()
        elif tid == 0x0021:  # map: two sub-options
            self.type_option()
            self.type_option()


class CqlConnection:
    """One node connection: framed request/response, stream id 0."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        self.rfile = self.sock.makefile("rb")
        self._lock = threading.Lock()
        # STARTUP → READY (v4 handshake)
        body = struct.pack(">H", 1) + _string("CQL_VERSION") + _string("3.0.0")
        opcode, resp = self.request(OP_STARTUP, body)
        if opcode != OP_READY:
            raise ConnectionError(f"cql: handshake failed (opcode {opcode})")

    def close(self) -> None:
        for c in (self.rfile.close, self.sock.close):
            try:
                c()
            except OSError:
                pass

    def request(self, opcode: int, body: bytes) -> tuple[int, bytes]:
        with self._lock:
            frame = struct.pack(">BBhBi", 0x04, 0, 0, opcode, len(body)) + body
            self.sock.sendall(frame)
            hdr = self.rfile.read(9)
            if len(hdr) < 9:
                raise ConnectionError("cql: connection closed")
            _ver, _flags, _stream, r_opcode, length = struct.unpack(">BBhBi", hdr)
            payload = self.rfile.read(length)
            if len(payload) < length:
                raise ConnectionError("cql: short frame body")
        return r_opcode, payload

    def query(self, cql: str, values: list[bytes | None] = ()):  # type: ignore[assignment]
        """Run one QUERY; returns list[list[bytes|None]] for rows
        results, [] for void."""
        body = _long_string(cql) + struct.pack(">H", CONSISTENCY_ONE)
        if values:
            body += struct.pack(">BH", FLAG_VALUES, len(values))
            for v in values:
                body += _value(v)
        else:
            body += struct.pack(">B", 0)
        opcode, payload = self.request(OP_QUERY, body)
        if opcode == OP_ERROR:
            r = _FrameReader(payload)
            code = r.i32()
            raise RuntimeError(f"cql error {code:#06x}: {r.string()}")
        if opcode != OP_RESULT:
            raise ValueError(f"cql: unexpected opcode {opcode}")
        r = _FrameReader(payload)
        kind = r.i32()
        if kind in (RESULT_VOID, RESULT_SET_KEYSPACE):
            return []
        if kind != RESULT_ROWS:
            return []
        flags = r.i32()
        columns = r.i32()
        if flags & GLOBAL_TABLES_SPEC:
            r.string(), r.string()  # keyspace, table
        for _ in range(columns):
            if not flags & GLOBAL_TABLES_SPEC:
                r.string(), r.string()
            r.string()  # column name
            r.type_option()
        rows = []
        for _ in range(r.i32()):
            rows.append([r.value() for _ in range(columns)])
        return rows


class CassandraStore(FilerStore):
    name = "cassandra"

    def __init__(self, hosts: str, keyspace: str = "seaweedfs"):
        host, _, port = hosts.split(",")[0].strip().partition(":")
        try:
            self._conn = CqlConnection(host, int(port or 9042))
        except OSError as e:
            raise RuntimeError(
                f"filer store 'cassandra' cannot reach a node at {hosts!r} "
                f"({e}); start one, or use an embedded kind: memory | "
                "sqlite | sql | sortedlog | lsm"
            ) from e
        try:
            self._conn.query(f"USE {keyspace}")
        except (RuntimeError, OSError) as e:
            self._conn.close()  # don't leak the TCP connection
            raise RuntimeError(
                f"filer store 'cassandra': keyspace {keyspace!r} not usable "
                f"on {hosts!r} ({e}); create it with the filemeta table "
                "(CREATE TABLE filemeta (directory varchar, name varchar, "
                "meta blob, PRIMARY KEY (directory, name)) WITH CLUSTERING "
                "ORDER BY (name ASC)), or use an embedded kind"
            ) from e

    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        # Bind the entry's TTL (reference cassandra_store.go:63 binds
        # entry.TtlSec) so TTL'd entries expire server-side.
        ttl = entry.attr.ttl_sec
        self._conn.query(
            "INSERT INTO filemeta (directory,name,meta) VALUES(?,?,?) "
            "USING TTL ? ",
            [d.encode(), name.encode(), entry.encode(), struct.pack(">i", ttl)],
        )

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry:
        d, name = split_path(full_path)
        rows = self._conn.query(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            [d.encode(), name.encode()],
        )
        if not rows or rows[0][0] is None:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, rows[0][0])

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        self._conn.query(
            "DELETE FROM filemeta WHERE directory=? AND name=?",
            [d.encode(), name.encode()],
        )

    def delete_folder_children(self, full_path: str) -> None:
        d = normalize_path(full_path)
        self._conn.query(
            "DELETE FROM filemeta WHERE directory=?", [d.encode()]
        )

    def list_directory_entries(
        self, dir_path, start_file_name, include_start, limit
    ):
        d = normalize_path(dir_path)
        op = ">=" if include_start else ">"
        rows = self._conn.query(
            f"SELECT name, meta FROM filemeta WHERE directory=? AND name{op}? "
            "ORDER BY name ASC LIMIT ?",
            [
                d.encode(),
                start_file_name.encode(),
                struct.pack(">i", limit),
            ],
        )
        out = []
        for name_b, meta in rows:
            if name_b is None or meta is None:
                continue
            name = name_b.decode()
            out.append(Entry.decode(child_path(d, name), meta))
        return out

    def close(self) -> None:
        self._conn.close()
