"""Minimal PostgreSQL driver: frontend/backend protocol v3, no library.

Just enough DB-API surface for filer/abstract_sql.AbstractSqlStore —
connection.cursor()/commit()/rollback()/close(), cursor.execute() with
$N parameters, fetchone/fetchall — speaking the wire protocol directly:

  * StartupMessage (protocol 3.0) with cleartext or md5 password auth
  * the EXTENDED query protocol for parameterized statements
    (Parse → Bind with binary parameter/result formats → Describe →
    Execute → Sync), so values never pass through SQL literals
  * simple Query for BEGIN/COMMIT/ROLLBACK (DB-API transaction shape:
    implicit BEGIN before the first statement, explicit commit/rollback)

Parameter and result values use the binary format: int → int8
big-endian, str → utf8, bytes → raw. That covers the filemeta schema
(dirhash BIGINT, name/directory VARCHAR, meta bytea). Unique-violation
errors (SQLSTATE 23505) raise IntegrityError so the store's
duplicate-key detection works per PEP 249. The offline peer is
tests/cloud_fakes.FakePostgres, which speaks the same frames.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading


class PgError(RuntimeError):
    def __init__(self, fields: dict):
        self.sqlstate = fields.get("C", "")
        super().__init__(
            f"postgres error {self.sqlstate}: {fields.get('M', '')}"
        )


class IntegrityError(PgError):
    """SQLSTATE class 23 (integrity constraint violation)."""


def _cstr(s: str) -> bytes:
    return s.encode() + b"\0"


class PgConnection:
    def __init__(
        self,
        host: str,
        port: int = 5432,
        user: str = "seaweedfs",
        password: str = "",
        database: str = "seaweedfs",
        timeout: float = 10.0,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        self.rfile = self.sock.makefile("rb")
        self._lock = threading.RLock()
        self._in_txn = False
        try:
            body = struct.pack(">i", 196608)  # protocol 3.0
            body += _cstr("user") + _cstr(user)
            body += _cstr("database") + _cstr(database)
            body += b"\0"
            self.sock.sendall(struct.pack(">i", len(body) + 4) + body)
            self._auth(user, password)
        except BaseException:
            self.close()  # don't leak the fd on a failed handshake/auth
            raise

    # --- frames ---------------------------------------------------------
    def _send(self, kind: bytes, body: bytes) -> None:
        self.sock.sendall(kind + struct.pack(">i", len(body) + 4) + body)

    def _recv(self) -> tuple[bytes, bytes]:
        kind = self.rfile.read(1)
        if not kind:
            raise ConnectionError("postgres: connection closed")
        (length,) = struct.unpack(">i", self.rfile.read(4))
        return kind, self.rfile.read(length - 4)

    @staticmethod
    def _error_fields(body: bytes) -> dict:
        fields = {}
        for part in body.split(b"\0"):
            if part:
                fields[chr(part[0])] = part[1:].decode("utf-8", "replace")
        return fields

    def _raise(self, body: bytes) -> None:
        fields = self._error_fields(body)
        cls = (
            IntegrityError
            if fields.get("C", "").startswith("23")
            else PgError
        )
        raise cls(fields)

    def _auth(self, user: str, password: str) -> None:
        while True:
            kind, body = self._recv()
            if kind == b"E":
                self._raise(body)
            if kind == b"R":
                (code,) = struct.unpack(">i", body[:4])
                if code == 0:
                    continue  # AuthenticationOk
                if code == 3:  # cleartext
                    self._send(b"p", _cstr(password))
                elif code == 5:  # md5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        password.encode() + user.encode()
                    ).hexdigest()
                    digest = hashlib.md5(inner.encode() + salt).hexdigest()
                    self._send(b"p", _cstr("md5" + digest))
                else:
                    raise ConnectionError(
                        f"postgres: unsupported auth method {code}"
                    )
                continue
            if kind == b"Z":  # ReadyForQuery
                return
            # ParameterStatus / BackendKeyData / NoticeResponse: skip

    # --- queries --------------------------------------------------------
    @staticmethod
    def _encode_param(v) -> bytes | None:
        if v is None:
            return None
        if isinstance(v, bytes):
            return v
        if isinstance(v, int):
            return struct.pack(">q", v)
        return str(v).encode()

    def _simple(self, sql: str) -> None:
        with self._lock:
            self._send(b"Q", _cstr(sql))
            err = None
            while True:
                kind, body = self._recv()
                if kind == b"E":
                    err = body
                elif kind == b"Z":
                    break
            if err is not None:
                self._raise(err)

    @staticmethod
    def _frame(kind: bytes, body: bytes) -> bytes:
        return kind + struct.pack(">i", len(body) + 4) + body

    def execute(self, sql: str, args: tuple = ()):  # -> list[list]
        """Extended-protocol statement; returns data rows (raw bytes
        per column, None for NULL).

        Outside an explicit transaction the statement runs standalone
        (already atomic in PostgreSQL — no BEGIN/COMMIT round trips).
        Inside one, a same-named SAVEPOINT precedes it so a failed
        statement (e.g. a duplicate-key INSERT the store degrades to
        UPDATE) rolls back to the savepoint instead of aborting the
        whole transaction and wedging the connection. All frames for
        the statement go out in ONE write."""
        with self._lock:
            def bare(stmt_sql: str) -> bytes:
                # Parse/Bind/Execute for a no-param, no-result utility
                # statement; Bind = unnamed portal + stmt + 3 zero
                # int16 counts (formats, params, result formats)
                out = self._frame(
                    b"P", b"\0" + _cstr(stmt_sql) + struct.pack(">h", 0)
                )
                out += self._frame(
                    b"B", b"\0\0" + struct.pack(">hhh", 0, 0, 0)
                )
                out += self._frame(b"E", b"\0" + struct.pack(">i", 0))
                return out

            buf = bytearray()
            if self._in_txn:
                buf += bare("SAVEPOINT _sw")
            buf += self._frame(
                b"P", b"\0" + _cstr(sql) + struct.pack(">h", 0)
            )
            bind = b"\0\0"  # unnamed portal, unnamed statement
            bind += struct.pack(">hh", 1, 1)  # all params binary
            bind += struct.pack(">h", len(args))
            for a in args:
                enc = self._encode_param(a)
                if enc is None:
                    bind += struct.pack(">i", -1)
                else:
                    bind += struct.pack(">i", len(enc)) + enc
            bind += struct.pack(">hh", 1, 1)  # all results binary
            buf += self._frame(b"B", bind)
            buf += self._frame(b"E", b"\0" + struct.pack(">i", 0))
            if self._in_txn:
                # pg skips messages after an error until Sync, so this
                # RELEASE runs only when the statement succeeded —
                # savepoints never pile up on the happy path
                buf += bare("RELEASE SAVEPOINT _sw")
            buf += self._frame(b"S", b"")
            self.sock.sendall(bytes(buf))
            rows: list[list] = []
            err = None
            while True:
                kind, body = self._recv()
                if kind == b"E":
                    err = body
                elif kind == b"D":
                    (ncols,) = struct.unpack(">h", body[:2])
                    off = 2
                    row = []
                    for _ in range(ncols):
                        (n,) = struct.unpack(">i", body[off : off + 4])
                        off += 4
                        if n < 0:
                            row.append(None)
                        else:
                            row.append(body[off : off + n])
                            off += n
                    rows.append(row)
                elif kind == b"Z":
                    break
            if err is not None:
                if self._in_txn:
                    # restore the transaction to the savepoint so the
                    # caller can continue (insert→update degrade), then
                    # drop the savepoint so error paths don't pile them
                    # up either
                    self._simple("ROLLBACK TO SAVEPOINT _sw")
                    self._simple("RELEASE SAVEPOINT _sw")
                self._raise(err)
            return rows

    # --- DB-API-ish surface ---------------------------------------------
    def cursor(self) -> "PgCursor":
        return PgCursor(self)

    def begin(self) -> None:
        """Open an explicit transaction (AbstractSqlStore calls this
        from begin_transaction when the driver offers it)."""
        with self._lock:
            if not self._in_txn:
                self._simple("BEGIN")
                self._in_txn = True

    def commit(self) -> None:
        with self._lock:
            if self._in_txn:
                self._simple("COMMIT")
                self._in_txn = False

    def rollback(self) -> None:
        with self._lock:
            if self._in_txn:
                self._simple("ROLLBACK")
                self._in_txn = False

    def close(self) -> None:
        for c in (self.rfile.close, self.sock.close):
            try:
                c()
            except OSError:
                pass


class PgCursor:
    def __init__(self, conn: PgConnection):
        self._conn = conn
        self._rows: list[list] = []

    def execute(self, sql: str, args: tuple = ()) -> None:
        self._rows = self._conn.execute(sql, tuple(args))

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self):
        return self._rows

    def close(self) -> None:
        self._rows = []
