from seaweedfs_tpu.filer.entry import Attr, Entry, new_directory_entry
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filerstore import (
    EntryNotFound,
    FilerStore,
    MemoryStore,
    SortedLogStore,
    SqliteStore,
    new_store,
)
from seaweedfs_tpu.filer.lsm import LsmStore

__all__ = [
    "Attr",
    "Entry",
    "EntryNotFound",
    "Filer",
    "FilerStore",
    "LsmStore",
    "MemoryStore",
    "SortedLogStore",
    "SqliteStore",
    "new_directory_entry",
    "new_store",
]
