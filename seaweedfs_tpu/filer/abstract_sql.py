"""Dialect-driven SQL filer store — the abstract_sql layer.

Behavioral match of weed/filer2/abstract_sql/abstract_sql_store.go:13-47:
one store implementation holds the seven SQL statements as data; each
dialect (mysql_store.go:45-52, postgres_store.go:47-54, and sqlite as
the in-image driver) contributes only its SQL text and a DB-API
connection factory. The schema is the reference's `filemeta` table —
(dirhash, name, directory, meta) with dirhash the md5-folded int64 of
the directory string (util/bytes.go:53 HashStringToLong) so the
B-tree clusters siblings and list queries stay range scans.

mysql / postgres construct with their reference SQL but gate on their
client libraries, which are not in this image — new_store("mysql"|
"postgres") raises with guidance; the `sql` kind runs the SAME dialect machinery over
stdlib sqlite3 and is what the conformance matrix exercises.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass

from seaweedfs_tpu.filer.entry import Entry, child_path, normalize_path, split_path
from seaweedfs_tpu.filer.filerstore import EntryNotFound, FilerStore


def hash_string_to_long(directory: str) -> int:
    """Reference-compatible dirhash (util/bytes.go:53): the first 8 md5
    bytes folded big-endian into a signed int64."""
    digest = hashlib.md5(directory.encode()).digest()
    return int.from_bytes(digest[:8], "big", signed=True)


@dataclass(frozen=True)
class SqlDialect:
    """The seven statements of abstract_sql_store.go:15-21 plus DDL.

    Parameter order is the reference's:
      insert  (dirhash, name, directory, meta)
      update  (meta, dirhash, name, directory)
      find    (dirhash, name, directory)
      delete  (dirhash, name, directory)
      delete_folder_children (dirhash, directory)
      list_*  (dirhash, start_name, directory, limit)
    """

    name: str
    create_table: str
    insert: str
    update: str
    find: str
    delete: str
    delete_folder_children: str
    list_exclusive: str
    list_inclusive: str


SQLITE_DIALECT = SqlDialect(
    name="sqlite",
    create_table=(
        "CREATE TABLE IF NOT EXISTS filemeta ("
        " dirhash INTEGER,"
        " name TEXT,"
        " directory TEXT,"
        " meta BLOB,"
        " PRIMARY KEY (dirhash, name))"
    ),
    insert="INSERT INTO filemeta (dirhash,name,directory,meta) VALUES(?,?,?,?)",
    update="UPDATE filemeta SET meta=? WHERE dirhash=? AND name=? AND directory=?",
    find="SELECT meta FROM filemeta WHERE dirhash=? AND name=? AND directory=?",
    delete="DELETE FROM filemeta WHERE dirhash=? AND name=? AND directory=?",
    delete_folder_children="DELETE FROM filemeta WHERE dirhash=? AND directory=?",
    list_exclusive=(
        "SELECT name, meta FROM filemeta WHERE dirhash=? AND name>? AND"
        " directory=? ORDER BY name ASC LIMIT ?"
    ),
    list_inclusive=(
        "SELECT name, meta FROM filemeta WHERE dirhash=? AND name>=? AND"
        " directory=? ORDER BY name ASC LIMIT ?"
    ),
)

# mysql_store.go:45-52 verbatim SQL shapes (%s paramstyle)
MYSQL_DIALECT = SqlDialect(
    name="mysql",
    create_table=(
        "CREATE TABLE IF NOT EXISTS filemeta ("
        " dirhash BIGINT,"
        " name VARCHAR(1000),"
        " directory TEXT,"
        " meta LONGBLOB,"
        " PRIMARY KEY (dirhash, name))"
    ),
    insert="INSERT INTO filemeta (dirhash,name,directory,meta) VALUES(%s,%s,%s,%s)",
    update="UPDATE filemeta SET meta=%s WHERE dirhash=%s AND name=%s AND directory=%s",
    find="SELECT meta FROM filemeta WHERE dirhash=%s AND name=%s AND directory=%s",
    delete="DELETE FROM filemeta WHERE dirhash=%s AND name=%s AND directory=%s",
    delete_folder_children="DELETE FROM filemeta WHERE dirhash=%s AND directory=%s",
    list_exclusive=(
        "SELECT name, meta FROM filemeta WHERE dirhash=%s AND name>%s AND"
        " directory=%s ORDER BY name ASC LIMIT %s"
    ),
    list_inclusive=(
        "SELECT name, meta FROM filemeta WHERE dirhash=%s AND name>=%s AND"
        " directory=%s ORDER BY name ASC LIMIT %s"
    ),
)

# postgres_store.go:47-54 verbatim SQL shapes ($N paramstyle)
POSTGRES_DIALECT = SqlDialect(
    name="postgres",
    create_table=(
        "CREATE TABLE IF NOT EXISTS filemeta ("
        " dirhash BIGINT,"
        " name VARCHAR(1000),"
        " directory VARCHAR(4096),"
        " meta bytea,"
        " PRIMARY KEY (dirhash, name))"
    ),
    insert="INSERT INTO filemeta (dirhash,name,directory,meta) VALUES($1,$2,$3,$4)",
    update="UPDATE filemeta SET meta=$1 WHERE dirhash=$2 AND name=$3 AND directory=$4",
    find="SELECT meta FROM filemeta WHERE dirhash=$1 AND name=$2 AND directory=$3",
    delete="DELETE FROM filemeta WHERE dirhash=$1 AND name=$2 AND directory=$3",
    delete_folder_children="DELETE FROM filemeta WHERE dirhash=$1 AND directory=$2",
    list_exclusive=(
        "SELECT name, meta FROM filemeta WHERE dirhash=$1 AND name>$2 AND"
        " directory=$3 ORDER BY name ASC LIMIT $4"
    ),
    list_inclusive=(
        "SELECT name, meta FROM filemeta WHERE dirhash=$1 AND name>=$2 AND"
        " directory=$3 ORDER BY name ASC LIMIT $4"
    ),
)


class AbstractSqlStore(FilerStore):
    """FilerStore over any DB-API connection + SqlDialect
    (abstract_sql_store.go:61-185 method-for-method)."""

    name = "sql"

    def __init__(self, conn, dialect: SqlDialect):
        self._conn = conn
        self._dialect = dialect
        self._lock = threading.RLock()
        self._tx_depth = 0
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(dialect.create_table)
            cur.close()
            self._conn.commit()

    def _exec(self, sql: str, args: tuple) -> None:
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(sql, args)
            cur.close()
            if self._tx_depth == 0:
                self._conn.commit()

    @staticmethod
    def _is_duplicate_key(exc: BaseException) -> bool:
        """DB-API drivers all subclass their duplicate-key error from a
        class named IntegrityError (PEP 249); anything else (disk full,
        connection lost) must propagate, not degrade to UPDATE."""
        return any(
            k.__name__ == "IntegrityError" for k in type(exc).__mro__
        )

    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        meta = entry.encode()
        try:
            self._exec(
                self._dialect.insert, (hash_string_to_long(d), name, d, meta)
            )
        except Exception as e:
            if not self._is_duplicate_key(e):
                raise
            # the reference's filer calls UpdateEntry when the entry
            # exists; our Filer reuses insert for overwrite, so a
            # duplicate-key insert degrades to the dialect's UPDATE
            self.update_entry(entry)

    def update_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        self._exec(
            self._dialect.update,
            (entry.encode(), hash_string_to_long(d), name, d),
        )

    def find_entry(self, full_path: str) -> Entry:
        d, name = split_path(full_path)
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(self._dialect.find, (hash_string_to_long(d), name, d))
            row = cur.fetchone()
            cur.close()
        if row is None:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, row[0])

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        self._exec(self._dialect.delete, (hash_string_to_long(d), name, d))

    def delete_folder_children(self, full_path: str) -> None:
        d = normalize_path(full_path)
        self._exec(
            self._dialect.delete_folder_children, (hash_string_to_long(d), d)
        )

    def list_directory_entries(
        self, dir_path, start_file_name, include_start, limit
    ):
        d = normalize_path(dir_path)
        sql = (
            self._dialect.list_inclusive
            if include_start
            else self._dialect.list_exclusive
        )
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(sql, (hash_string_to_long(d), start_file_name, d, limit))
            rows = cur.fetchall()
            cur.close()
        out = []
        for name, meta in rows:
            # binary-protocol drivers (pg_driver) return text columns
            # as raw bytes; sqlite returns str
            if isinstance(name, bytes):
                name = name.decode()
            out.append(Entry.decode(child_path(d, name), meta))
        return out

    # tx: same deferred-commit protocol as the embedded SqliteStore;
    # drivers that expose begin() (pg_driver) open a server-side
    # transaction here — sqlite3 begins implicitly on first statement
    def begin_transaction(self) -> None:
        self._lock.acquire()
        self._tx_depth += 1
        begin = getattr(self._conn, "begin", None)
        if begin is not None and self._tx_depth == 1:
            begin()

    def commit_transaction(self) -> None:
        self._tx_depth -= 1
        if self._tx_depth == 0:
            self._conn.commit()
        self._lock.release()

    def rollback_transaction(self) -> None:
        self._tx_depth -= 1
        self._conn.rollback()
        self._lock.release()

    def close(self) -> None:
        self._conn.close()


def new_sqlite_sql_store(path: str = ":memory:") -> AbstractSqlStore:
    """The `sql` store kind: the abstract layer over stdlib sqlite3 —
    the tested driver for the dialect machinery."""
    import sqlite3

    conn = sqlite3.connect(path, check_same_thread=False)
    return AbstractSqlStore(conn, SQLITE_DIALECT)


def new_postgres_store(path: str = "") -> AbstractSqlStore:
    """The postgres kind over the in-repo wire-protocol driver
    (filer/pg_driver.py) — no psycopg2; gated on connectivity.

    `path` is "host:port" or "host:port/database?user=U&password=P"
    (defaults: 5432 / seaweedfs / seaweedfs / empty password)."""
    from seaweedfs_tpu.filer.pg_driver import PgConnection

    raw = path or "localhost:5432"
    host, port, user, password, database = _parse_db_path(
        raw, 5432, "postgres"
    )
    try:
        conn = PgConnection(
            host, port, user=user, password=password, database=database
        )
    except OSError as e:
        raise RuntimeError(
            f"filer store 'postgres' cannot reach a server at {raw!r} "
            f"({e}); start one (with the filemeta table — the dialect "
            "DDL is POSTGRES_DIALECT.create_table), or use an embedded "
            "kind: memory | sqlite | sql | sortedlog | lsm"
        ) from e
    return AbstractSqlStore(conn, POSTGRES_DIALECT)


def _parse_db_path(raw: str, default_port: int, kind: str):
    """host:port[/database?user=U&password=P] → connection params."""
    import urllib.parse

    hostport, _, rest = raw.partition("/")
    host, _, port = hostport.partition(":")
    try:
        port_num = int(port or default_port)
    except ValueError:
        raise RuntimeError(
            f"filer store {kind!r}: bad port in {raw!r}; expected "
            "host:port[/database?user=U&password=P]"
        ) from None
    database, user, password = "seaweedfs", "seaweedfs", ""
    if rest:
        dbpart, _, query = rest.partition("?")
        if dbpart:
            database = dbpart
        params = dict(urllib.parse.parse_qsl(query))
        user = params.get("user", user)
        password = params.get("password", password)
    return host or "localhost", port_num, user, password, database


def new_mysql_store(path: str = "") -> AbstractSqlStore:
    """The mysql kind over the in-repo wire-protocol driver
    (filer/mysql_driver.py) — no MySQLdb/pymysql; gated on
    connectivity. Same `path` shape as postgres."""
    from seaweedfs_tpu.filer.mysql_driver import MysqlConnection

    raw = path or "localhost:3306"
    host, port, user, password, database = _parse_db_path(raw, 3306, "mysql")
    try:
        conn = MysqlConnection(
            host, port, user=user, password=password, database=database
        )
    except OSError as e:
        raise RuntimeError(
            f"filer store 'mysql' cannot reach a server at {raw!r} ({e}); "
            "start one (with the filemeta table — the dialect DDL is "
            "MYSQL_DIALECT.create_table), or use an embedded kind: "
            "memory | sqlite | sql | sortedlog | lsm"
        ) from e
    return AbstractSqlStore(conn, MYSQL_DIALECT)


def new_gated_sql_store(kind: str, path: str = "") -> AbstractSqlStore:
    """Both SQL kinds now run on in-repo wire drivers, gated on
    connectivity rather than client libraries."""
    if kind == "postgres":
        return new_postgres_store(path)
    if kind == "mysql":
        return new_mysql_store(path)
    raise ValueError(f"not a SQL store kind: {kind!r}")
