"""Filer core: the namespace layer.

Behavioral match of weed/filer2/filer.go: path→Entry CRUD over a
pluggable store with

  * parent-directory auto-creation on CreateEntry (filer.go:76
    ensures every ancestor exists, cached),
  * overwrite semantics that hand replaced chunks to an async deletion
    channel (filer_deletion.go:11-66 loopProcessingDeletion),
  * recursive delete collecting every descendant's chunks
    (filer_delete_entry.go:11),
  * update-event notifications for the replication plane
    (filer_notify.go:9-39).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from seaweedfs_tpu.filer import filechunks
from seaweedfs_tpu.filer.entry import (
    Attr,
    Entry,
    new_directory_entry,
    normalize_path,
    split_path,
)
from seaweedfs_tpu.filer.filerstore import EntryNotFound, FilerStore


class Filer:
    def __init__(
        self,
        store: FilerStore,
        masters: list[str] | None = None,
        on_event: Callable[[Entry | None, Entry | None, bool], None] | None = None,
    ):
        self.store = store
        self.masters = masters or []
        # (old_entry, new_entry, delete_chunks) — the EventNotification
        # triple pushed to notification queues (filer_notify.go)
        self.on_event = on_event
        self._dir_cache: set[str] = set()  # ccache role (filer.go:33)
        self._deletion_lock = threading.Lock()
        self._pending_chunk_deletions: list[str] = []
        self._deletion_thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------------
    # deletion channel (filer_deletion.go)
    def start_deletion_loop(self, interval: float = 1.0) -> None:
        def loop():
            while not self._stop.wait(interval):
                self.flush_chunk_deletions()

        self._deletion_thread = threading.Thread(target=loop, daemon=True)
        self._deletion_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.flush_chunk_deletions()
        self.store.close()

    def delete_chunks_async(self, fids: list[str]) -> None:
        with self._deletion_lock:
            self._pending_chunk_deletions.extend(fids)

    def flush_chunk_deletions(self) -> None:
        with self._deletion_lock:
            fids, self._pending_chunk_deletions = self._pending_chunk_deletions, []
        if not fids or not self.masters:
            return
        from seaweedfs_tpu.client import operation as op

        try:
            # HA: any live master can resolve locations for the batch
            op.with_master_failover(
                self.masters, lambda m: op.delete_files(m, fids)
            )
        except Exception:  # noqa: BLE001 — deletion is best-effort GC
            pass

    # ------------------------------------------------------------------
    def _notify(self, old: Entry | None, new: Entry | None, delete_chunks: bool) -> None:
        if self.on_event:
            self.on_event(old, new, delete_chunks)

    def create_entry(self, entry: Entry) -> None:
        """Insert (or overwrite) an entry, auto-creating parents
        (filer.go:76 CreateEntry)."""
        dir_path = entry.directory
        self._ensure_dirs(dir_path)
        old = None
        try:
            old = self.store.find_entry(entry.full_path)
        except EntryNotFound:
            pass
        if old is not None and not old.is_directory and not entry.is_directory:
            # replaced chunks → deletion channel (deleteChunksIfNotNew)
            old_garbage = filechunks.minus_chunks(old.chunks, entry.chunks)
            if old_garbage:
                self.delete_chunks_async([c.fid for c in old_garbage])
        self.store.insert_entry(entry)
        self._notify(old, entry, delete_chunks=old is not None)

    def _ensure_dirs(self, dir_path: str) -> None:
        dir_path = normalize_path(dir_path)
        if dir_path == "/" or dir_path in self._dir_cache:
            return
        parent, _ = split_path(dir_path)
        self._ensure_dirs(parent)
        try:
            existing = self.store.find_entry(dir_path)
            if existing.is_directory:
                # weedlint: ignore[race-check-then-act] — idempotent cache fill: concurrent mkdirs both insert the same directory entry (store is last-writer-wins) and both add the same path; holding _lock across store I/O would serialize every write
                self._dir_cache.add(dir_path)
                return
        except EntryNotFound:
            pass
        d = new_directory_entry(dir_path)
        self.store.insert_entry(d)
        # weedlint: ignore[race-check-then-act] — idempotent cache fill: duplicate insert_entry of a fresh directory is last-writer-wins on identical bytes; set.add is atomic and the worst case is one redundant notify
        self._dir_cache.add(dir_path)
        self._notify(None, d, delete_chunks=False)

    def find_entry(self, full_path: str) -> Entry:
        full_path = normalize_path(full_path)
        if full_path == "/":
            return new_directory_entry("/")
        return self.store.find_entry(full_path)

    def update_entry(self, entry: Entry) -> None:
        old = None
        try:
            old = self.store.find_entry(entry.full_path)
        except EntryNotFound:
            pass
        self.store.update_entry(entry)
        self._notify(old, entry, delete_chunks=False)

    def list_entries(
        self,
        dir_path: str,
        start_file_name: str = "",
        include_start: bool = False,
        limit: int = 1024,
        prefix: str = "",
    ) -> list[Entry]:
        entries = self.store.list_directory_entries(
            dir_path, start_file_name, include_start, limit
        )
        if prefix:
            entries = [e for e in entries if e.name.startswith(prefix)]
        return entries

    def delete_entry(
        self,
        full_path: str,
        is_recursive: bool = False,
        delete_data: bool = True,
    ) -> list[str]:
        """Delete an entry; directories require is_recursive when
        non-empty. Returns the chunk fids queued for deletion
        (filer_delete_entry.go DeleteEntryMetaAndData)."""
        entry = self.find_entry(full_path)
        fids: list[str] = []
        if entry.is_directory:
            children = self.store.list_directory_entries(full_path, "", True, 2)
            if children and not is_recursive:
                raise ValueError(f"{full_path}: folder not empty")
            self._collect_and_delete_children(full_path, fids)
        else:
            fids.extend(c.fid for c in entry.chunks)
        self.store.delete_entry(full_path)
        self._dir_cache.discard(normalize_path(full_path))
        if delete_data and fids:
            self.delete_chunks_async(fids)
        self._notify(entry, None, delete_chunks=delete_data)
        return fids

    def _collect_and_delete_children(self, dir_path: str, fids: list[str]) -> None:
        while True:
            children = self.store.list_directory_entries(dir_path, "", True, 1024)
            if not children:
                return
            for child in children:
                if child.is_directory:
                    self._collect_and_delete_children(child.full_path, fids)
                else:
                    fids.extend(c.fid for c in child.chunks)
                self.store.delete_entry(child.full_path)
                self._dir_cache.discard(normalize_path(child.full_path))

    # ------------------------------------------------------------------
    def atomic_rename(self, old_path: str, new_path: str) -> None:
        """Move an entry (recursively for directories) inside one store
        transaction (filer_grpc_server_rename.go AtomicRenameEntry)."""
        self.store.begin_transaction()
        try:
            self._rename_recursive(normalize_path(old_path), normalize_path(new_path))
            self.store.commit_transaction()
        except BaseException:
            self.store.rollback_transaction()
            raise

    def _rename_recursive(self, old_path: str, new_path: str) -> None:
        entry = self.store.find_entry(old_path)
        if entry.is_directory:
            self._ensure_dirs(new_path)
            for child in self.store.list_directory_entries(old_path, "", True, 1 << 30):
                self._rename_recursive(
                    child.full_path, f"{new_path}/{child.name}"
                )
            self.store.delete_entry(old_path)
            self._dir_cache.discard(old_path)
        else:
            moved = Entry(
                full_path=new_path,
                attr=entry.attr,
                chunks=list(entry.chunks),
                extended=dict(entry.extended),
            )
            self._ensure_dirs(moved.directory)
            self.store.insert_entry(moved)
            self.store.delete_entry(old_path)
        self._notify(entry, None, delete_chunks=False)
