"""Minimal MySQL driver: client/server protocol, no library.

Just enough DB-API surface for filer/abstract_sql.AbstractSqlStore,
speaking the MySQL client/server protocol directly:

  * handshake v10 with mysql_native_password auth
    (token = SHA1(pw) XOR SHA1(scramble + SHA1(SHA1(pw))))
  * prepared statements (COM_STMT_PREPARE / COM_STMT_EXECUTE) with
    binary parameter and result rows, so values never ride SQL text;
    the dialect's %s placeholders are rewritten to the protocol's `?`
  * COM_QUERY for BEGIN / COMMIT / ROLLBACK / DDL

Parameters: int → LONGLONG, str → VAR_STRING, bytes → BLOB. Result
decoding follows each column's declared type (LONGLONG binary, else
length-encoded bytes). ER_DUP_ENTRY (1062) and friends raise
IntegrityError per PEP 249 so the store's duplicate-key detection
works. MySQL does not abort a transaction on a statement error, so no
savepoint dance is needed (unlike pg_driver). The offline peer is
tests/cloud_fakes.FakeMysql.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading

CLIENT_LONG_PASSWORD = 0x00000001
CLIENT_PROTOCOL_41 = 0x00000200
CLIENT_CONNECT_WITH_DB = 0x00000008
CLIENT_SECURE_CONNECTION = 0x00008000
CLIENT_PLUGIN_AUTH = 0x00080000

COM_QUERY, COM_STMT_PREPARE, COM_STMT_EXECUTE, COM_STMT_CLOSE = (
    0x03,
    0x16,
    0x17,
    0x19,
)

TYPE_LONGLONG, TYPE_BLOB, TYPE_VAR_STRING = 0x08, 0xFC, 0xFD

_DUP_ERRNOS = {1062, 1557, 1569, 1586}  # duplicate key/entry family


class MysqlError(RuntimeError):
    def __init__(self, errno: int, message: str):
        self.errno = errno
        super().__init__(f"mysql error {errno}: {message}")


class IntegrityError(MysqlError):
    pass


def _scramble_native(password: str, salt: bytes) -> bytes:
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _lenenc(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def take(self, n: int) -> bytes:
        d = self.data[self.off : self.off + n]
        self.off += n
        return d

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def lenenc_int(self) -> int:
        first = self.u8()
        if first < 0xFB:
            return first
        if first == 0xFC:
            return self.u16()
        if first == 0xFD:
            return int.from_bytes(self.take(3), "little")
        return struct.unpack("<Q", self.take(8))[0]

    def lenenc_bytes(self) -> bytes:
        return self.take(self.lenenc_int())

    def cstr(self) -> bytes:
        end = self.data.index(0, self.off)
        out = self.data[self.off : end]
        self.off = end + 1
        return out


class MysqlConnection:
    def __init__(
        self,
        host: str,
        port: int = 3306,
        user: str = "seaweedfs",
        password: str = "",
        database: str = "seaweedfs",
        timeout: float = 10.0,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        self.rfile = self.sock.makefile("rb")
        self._lock = threading.RLock()
        self._seq = 0
        self._in_txn = False
        self._stmt_cache: dict[str, int] = {}  # sql -> server stmt id
        try:
            self._handshake(user, password, database)
        except BaseException:
            self.close()
            raise

    # --- packet framing -------------------------------------------------
    # A payload length of 0xFFFFFF marks a continuation: the logical packet
    # carries on in the next frame (and a payload of exactly 16 MiB - 1 must
    # be followed by an empty terminator frame on send).
    _MAX_FRAME = 0xFFFFFF

    def _read_packet(self) -> bytes:
        chunks = []
        while True:
            hdr = self.rfile.read(4)
            if len(hdr) < 4:
                raise ConnectionError("mysql: connection closed")
            length = int.from_bytes(hdr[:3], "little")
            self._seq = hdr[3] + 1
            payload = self.rfile.read(length)
            if len(payload) < length:
                raise ConnectionError("mysql: short packet")
            chunks.append(payload)
            if length < self._MAX_FRAME:
                return b"".join(chunks) if len(chunks) > 1 else chunks[0]

    def _send_packet(self, payload: bytes, reset_seq: bool = False) -> None:
        if reset_seq:
            self._seq = 0
        if len(payload) < self._MAX_FRAME:  # common case: one frame, one send
            self.sock.sendall(
                len(payload).to_bytes(3, "little")
                + bytes([self._seq & 0xFF])
                + payload
            )
            self._seq += 1
            return
        view = memoryview(payload)
        off = 0
        while True:
            frame = view[off : off + self._MAX_FRAME]
            self.sock.sendall(
                len(frame).to_bytes(3, "little") + bytes([self._seq & 0xFF])
            )
            if frame:
                self.sock.sendall(frame)
            self._seq += 1
            off += len(frame)
            # A max-size frame always needs a follow-up (possibly empty).
            if len(frame) < self._MAX_FRAME:
                break

    def _raise_err(self, payload: bytes) -> None:
        r = _Reader(payload)
        r.u8()  # 0xff
        errno = r.u16()
        rest = r.data[r.off :]
        if rest.startswith(b"#"):
            rest = rest[6:]  # sql state marker
        msg = rest.decode("utf-8", "replace")
        cls = IntegrityError if errno in _DUP_ERRNOS else MysqlError
        raise cls(errno, msg)

    # --- handshake ------------------------------------------------------
    def _handshake(self, user: str, password: str, database: str) -> None:
        greeting = self._read_packet()
        r = _Reader(greeting)
        if r.u8() == 0xFF:
            self._raise_err(greeting)
        r.cstr()  # server version
        r.u32()  # thread id
        salt = r.take(8)
        r.u8()  # filler
        r.u16()  # cap low
        r.u8()  # charset
        r.u16()  # status
        r.u16()  # cap high
        auth_len = r.u8()
        r.take(10)  # reserved
        salt += r.take(max(13, auth_len - 8))[:12]
        caps = (
            CLIENT_LONG_PASSWORD
            | CLIENT_PROTOCOL_41
            | CLIENT_SECURE_CONNECTION
            | CLIENT_CONNECT_WITH_DB
            | CLIENT_PLUGIN_AUTH
        )
        token = _scramble_native(password, salt)
        resp = struct.pack("<IIB23x", caps, 1 << 24, 0x21)
        resp += user.encode() + b"\0"
        resp += bytes([len(token)]) + token
        resp += database.encode() + b"\0"
        resp += b"mysql_native_password\0"
        self._send_packet(resp)
        ok = self._read_packet()
        if ok and ok[0] == 0xFF:
            self._raise_err(ok)
        if ok and ok[0] == 0xFE:
            raise ConnectionError(
                "mysql: server requests an auth switch (caching_sha2?); "
                "create the user WITH mysql_native_password"
            )
        # 0x00 OK

    # --- queries --------------------------------------------------------
    def _query_ok(self, sql: str) -> None:
        with self._lock:
            self._send_packet(bytes([COM_QUERY]) + sql.encode(), reset_seq=True)
            resp = self._read_packet()
            if resp and resp[0] == 0xFF:
                self._raise_err(resp)

    @staticmethod
    def _param(v):
        if isinstance(v, bool):
            v = int(v)
        if v is None:
            return TYPE_VAR_STRING, None
        if isinstance(v, int):
            return TYPE_LONGLONG, struct.pack("<q", v)
        if isinstance(v, bytes):
            return TYPE_BLOB, _lenenc(len(v)) + v
        b = str(v).encode()
        return TYPE_VAR_STRING, _lenenc(len(b)) + b

    def _prepare(self, sql: str) -> int:
        """COM_STMT_PREPARE once per distinct SQL: the seven dialect
        statements are a fixed set, so every later execute skips the
        prepare round trip (and nothing leaks — cached handles close
        with the connection)."""
        cached = self._stmt_cache.get(sql)
        if cached is not None:
            return cached
        self._send_packet(
            bytes([COM_STMT_PREPARE]) + sql.encode(), reset_seq=True
        )
        resp = self._read_packet()
        if resp[0] == 0xFF:
            self._raise_err(resp)
        r = _Reader(resp)
        r.u8()  # 0x00
        stmt_id = r.u32()
        num_cols = r.u16()
        num_params = r.u16()
        for _ in range(num_params):
            self._read_packet()  # param definition
        if num_params:
            self._read_packet()  # EOF
        for _ in range(num_cols):
            self._read_packet()  # column definition (re-sent at execute)
        if num_cols:
            self._read_packet()  # EOF
        self._stmt_cache[sql] = stmt_id
        return stmt_id

    def execute(self, sql: str, args: tuple = ()):  # -> list[list]
        """Prepare (cached) + execute (binary protocol); returns rows."""
        sql = sql.replace("%s", "?")  # dialect paramstyle → protocol's
        with self._lock:
            stmt_id = self._prepare(sql)
            body = bytes([COM_STMT_EXECUTE]) + struct.pack(
                "<IBI", stmt_id, 0, 1
            )
            nbytes = (len(args) + 7) // 8
            null_bitmap = bytearray(nbytes)
            types = b""
            values = b""
            for i, a in enumerate(args):
                t, enc = self._param(a)
                types += struct.pack("<BB", t, 0)
                if enc is None:
                    null_bitmap[i // 8] |= 1 << (i % 8)
                else:
                    values += enc
            body += bytes(null_bitmap) + b"\x01" + types + values
            self._send_packet(body, reset_seq=True)
            return self._read_resultset()

    @staticmethod
    def _column_type(definition: bytes) -> int:
        r = _Reader(definition)
        for _ in range(6):  # catalog schema table org_table name org_name
            r.lenenc_bytes()
        r.lenenc_int()  # fixed-length fields marker (0x0c)
        r.u16()  # charset
        r.u32()  # column length
        return r.u8()

    def _read_resultset(self):
        first = self._read_packet()
        if first[0] == 0xFF:
            self._raise_err(first)
        if first[0] == 0x00:  # OK: no resultset
            return []
        r = _Reader(first)
        ncols = r.lenenc_int()
        col_types = []
        for _ in range(ncols):
            col_types.append(self._column_type(self._read_packet()))
        self._read_packet()  # EOF
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:  # EOF
                return rows
            if pkt[0] == 0xFF:
                self._raise_err(pkt)
            rr = _Reader(pkt)
            rr.u8()  # 0x00 row header
            null_bitmap = rr.take((ncols + 9) // 8)
            row = []
            for i, t in enumerate(col_types):
                if null_bitmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                    row.append(None)
                elif t == TYPE_LONGLONG:
                    row.append(struct.unpack("<q", rr.take(8))[0])
                else:
                    row.append(rr.lenenc_bytes())
            rows.append(row)

    # --- DB-API-ish surface ---------------------------------------------
    def cursor(self) -> "MysqlCursor":
        return MysqlCursor(self)

    def begin(self) -> None:
        if not self._in_txn:
            self._query_ok("BEGIN")
            self._in_txn = True

    def commit(self) -> None:
        # autocommit covers standalone statements; only a begin()'d
        # transaction needs an explicit COMMIT round trip
        if self._in_txn:
            self._query_ok("COMMIT")
            self._in_txn = False

    def rollback(self) -> None:
        if self._in_txn:
            self._query_ok("ROLLBACK")
            self._in_txn = False

    def close(self) -> None:
        # best-effort: release cached server-side statement handles
        try:
            with self._lock:
                for stmt_id in self._stmt_cache.values():
                    self._send_packet(
                        bytes([COM_STMT_CLOSE]) + struct.pack("<I", stmt_id),
                        reset_seq=True,
                    )
                self._stmt_cache.clear()
        except OSError:
            pass
        for c in (self.rfile.close, self.sock.close):
            try:
                c()
            except OSError:
                pass


class MysqlCursor:
    def __init__(self, conn: MysqlConnection):
        self._conn = conn
        self._rows: list[list] = []

    def execute(self, sql: str, args: tuple = ()) -> None:
        self._rows = self._conn.execute(sql, tuple(args))

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self):
        return self._rows

    def close(self) -> None:
        self._rows = []
