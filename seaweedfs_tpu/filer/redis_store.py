"""Redis-backed filer store speaking RESP directly — no client library.

Behavioral match of weed/filer2/redis/universal_redis_store.go:

  * every entry is one string key: SET <fullpath> <meta bytes>
  * each directory keeps a set of child names for listing:
    SADD "<dir>\\x00" <name>  (DIR_LIST_MARKER suffix, :15)
  * FindEntry = GET, DeleteEntry = DEL + SREM from the parent set,
    listing = SMEMBERS + sort + slice + per-name GET (:119-160)
  * transactions are no-ops (:22-30) — redis single-key ops suffice

The reference rides go-redis; this store implements the RESP wire
protocol over one socket (the commands the model needs: SET GET DEL
SADD SREM SMEMBERS PING). The gate is connectivity: constructing dials
the server and raises with guidance when nothing answers — the in-repo
RESP fake (tests/cloud_fakes.FakeRedis) serves offline tests.
"""

from __future__ import annotations

import socket
import threading

from seaweedfs_tpu.filer.entry import Entry, child_path, normalize_path, split_path
from seaweedfs_tpu.filer.filerstore import EntryNotFound, FilerStore

DIR_LIST_MARKER = "\x00"


class RespClient:
    """Minimal RESP2 client: one connection, inline pipelining-free."""

    def __init__(self, address: str, timeout: float = 10.0):
        host, _, port = address.partition(":")
        self.sock = socket.create_connection(
            (host, int(port or 6379)), timeout=timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        self.rfile = self.sock.makefile("rb")
        self._lock = threading.Lock()

    def close(self) -> None:
        for c in (self.rfile.close, self.sock.close):
            try:
                c()
            except OSError:
                pass

    def call(self, *args: bytes | str):
        """Send one command array, return the parsed reply
        (bytes | int | list | None; errors raise)."""
        out = bytearray(b"*%d\r\n" % len(args))
        for a in args:
            b = a.encode() if isinstance(a, str) else a
            out += b"$%d\r\n" % len(b) + b + b"\r\n"
        with self._lock:
            self.sock.sendall(out)
            return self._read_reply()

    def _read_reply(self):
        line = self.rfile.readline()
        if not line:
            raise ConnectionError("redis: connection closed")
        kind, rest = line[:1], line[1:].rstrip(b"\r\n")
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RuntimeError(f"redis error: {rest.decode()}")
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self.rfile.read(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise ValueError(f"redis: bad reply type {kind!r}")


class RedisStore(FilerStore):
    name = "redis"

    def __init__(self, address: str):
        try:
            self._client = RespClient(address)
            self._client.call("PING")
        except OSError as e:
            raise RuntimeError(
                f"filer store 'redis' cannot reach a server at {address!r} "
                f"({e}); start one (or use an embedded kind: memory | "
                "sqlite | sql | sortedlog | lsm)"
            ) from e

    @staticmethod
    def _dir_key(directory: str) -> str:
        return directory + DIR_LIST_MARKER

    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        self._client.call("SET", entry.full_path, entry.encode())
        if name:
            self._client.call("SADD", self._dir_key(d), name)

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry:
        data = self._client.call("GET", full_path)
        if data is None:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, data)

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        self._client.call("DEL", full_path)
        if name:
            self._client.call("SREM", self._dir_key(d), name)

    def list_directory_entries(
        self, dir_path, start_file_name, include_start, limit
    ):
        d = normalize_path(dir_path)
        members = self._client.call("SMEMBERS", self._dir_key(d)) or []
        names = sorted(m.decode() for m in members)
        out = []
        for n in names:
            if start_file_name:
                if include_start and n < start_file_name:
                    continue
                if not include_start and n <= start_file_name:
                    continue
            path = child_path(d, n)
            data = self._client.call("GET", path)
            if data is None:
                continue  # expired/dangling member (reference skips too)
            out.append(Entry.decode(path, data))
            if len(out) >= limit:
                break
        return out

    def close(self) -> None:
        self._client.close()
