"""Chunk algebra: overlapping writes → visible intervals → read views.

Behavioral match of weed/filer2/filechunks.go: a file is a list of
FileChunk writes; later writes (higher mtime) overwrite earlier ones.
`non_overlapping_visible_intervals` resolves the write history into
disjoint intervals, `view_from_chunks` turns a (offset,size) read into
per-chunk views, `compact_file_chunks` splits fully-hidden chunks out
as garbage. Semantics pinned by the ported table tests from
filer2/filechunks_test.go.
"""

from __future__ import annotations

from dataclasses import dataclass

from seaweedfs_tpu.pb import filer_pb2


@dataclass
class VisibleInterval:
    start: int
    stop: int
    fid: str
    mtime: int
    is_full_chunk: bool = False


@dataclass
class ChunkView:
    fid: str
    offset: int  # offset within the stored chunk
    size: int
    logic_offset: int  # offset within the file
    is_full_chunk: bool = False


def total_size(chunks) -> int:
    size = 0
    for c in chunks:
        size = max(size, c.offset + c.size)
    return size


def etag(chunks) -> str:
    if len(chunks) == 1:
        return chunks[0].e_tag
    # FNV-1a 32-bit over the concatenated chunk etags (filechunks.go ETag)
    h = 0x811C9DC5
    for c in chunks:
        for b in c.e_tag.encode():
            h ^= b
            h = (h * 0x01000193) & 0xFFFFFFFF
    return f"{h:x}"


def non_overlapping_visible_intervals(chunks) -> list[VisibleInterval]:
    """Fold the chunk list, oldest write first, into disjoint visible
    intervals (NonOverlappingVisibleIntervals)."""
    visibles: list[VisibleInterval] = []
    for chunk in sorted(chunks, key=lambda c: c.mtime):
        new = VisibleInterval(
            start=chunk.offset,
            stop=chunk.offset + chunk.size,
            fid=chunk.fid,
            mtime=chunk.mtime,
            is_full_chunk=True,
        )
        carved: list[VisibleInterval] = []
        for v in visibles:
            # keep the parts of v not covered by the new write
            if v.start < new.start < v.stop:
                carved.append(VisibleInterval(v.start, new.start, v.fid, v.mtime, False))
            if v.start < new.stop < v.stop:
                carved.append(VisibleInterval(new.stop, v.stop, v.fid, v.mtime, False))
            if new.stop <= v.start or v.stop <= new.start:
                carved.append(v)
        carved.append(new)
        carved.sort(key=lambda v: v.start)
        visibles = carved
    return visibles


def view_from_visible_intervals(
    visibles: list[VisibleInterval], offset: int, size: int
) -> list[ChunkView]:
    stop = offset + size
    views: list[ChunkView] = []
    for v in visibles:
        # reference parity (filer2/filechunks.go ViewFromVisibleIntervals):
        # views advance only while contiguous — a hole ends the read, it
        # is NOT zero-filled (pinned by the ported test table, case 4)
        if v.start <= offset < v.stop and offset < stop:
            is_full = v.is_full_chunk and v.start == offset and v.stop <= stop
            views.append(
                ChunkView(
                    fid=v.fid,
                    offset=offset - v.start,
                    size=min(v.stop, stop) - offset,
                    logic_offset=offset,
                    is_full_chunk=is_full,
                )
            )
            offset = min(v.stop, stop)
    return views


def view_from_chunks(chunks, offset: int, size: int) -> list[ChunkView]:
    return view_from_visible_intervals(
        non_overlapping_visible_intervals(chunks), offset, size
    )


def compact_file_chunks(chunks):
    """Split chunks into (still-visible, fully-hidden garbage)
    (CompactFileChunks)."""
    visible_fids = {v.fid for v in non_overlapping_visible_intervals(chunks)}
    compacted, garbage = [], []
    for c in chunks:
        (compacted if c.fid in visible_fids else garbage).append(c)
    return compacted, garbage


def minus_chunks(as_, bs):
    """Chunks in `as_` whose fid is not in `bs` (MinusChunks)."""
    b_fids = {c.fid for c in bs}
    return [c for c in as_ if c.fid not in b_fids]


def make_chunk(fid: str, offset: int, size: int, mtime: int, e_tag: str = "") -> filer_pb2.FileChunk:
    return filer_pb2.FileChunk(fid=fid, offset=offset, size=size, mtime=mtime, e_tag=e_tag)
