"""TiKV-backed filer store over the raw-KV gRPC wire protocol.

Behavioral match of weed/filer2/tikv/tikv_store.go:113-170: one KV pair
per entry with key = md5(dir) + name (genKey, tikv_store.go:223-247),
point get/put/delete, directory listing and recursive delete as prefix
scans that re-derive the file name from key[16:] (getNameFromKey).

The reference rides pingcap/tidb's transactional kv.Storage client.
This store speaks TiKV's raw-KV surface directly over the repo's own
gRPC stack (pb/rpc.py): PD `GetMembers`/`GetRegion`/`GetStore` for
routing, then `RawGet/RawPut/RawDelete/RawDeleteRange/RawScan` on the
region leader's store, carrying the kvrpcpb Context (region id, epoch,
peer). Raw-KV is sufficient for the store's usage pattern — every
filer operation above is a single-key op or a prefix scan, and the
reference runs each inside its own one-shot transaction anyway. Region
info is cached per key-range and refreshed on region errors.

Gated on connectivity: constructing dials PD and raises with guidance
when nothing answers (tests/cloud_fakes.FakeTikv serves offline CI).
"""

from __future__ import annotations

import hashlib
import threading

import grpc

from seaweedfs_tpu.filer.entry import Entry, child_path, normalize_path, split_path
from seaweedfs_tpu.filer.filerstore import EntryNotFound, FilerStore
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.pb import tikv_pb2 as t

MD5_SIZE = 16
SCAN_BATCH = 256

_stub_cache: dict[str, object] = {}
_stub_lock = threading.Lock()


def _kv_stub(address: str):
    """Per-address tikv stub cache: channels are process-pooled already
    (rpc.cached_channel); building 5 multi-callables per op is not."""
    with _stub_lock:
        stub = _stub_cache.get(address)
        if stub is None:
            stub = _stub_cache[address] = rpc.tikv_stub(
                rpc.cached_channel(address)
            )
        return stub


def _hash_to_bytes(directory: str) -> bytes:
    """hashToBytes (tikv_store.go:244): md5 of the directory path."""
    return hashlib.md5(directory.encode()).digest()


def _gen_key(directory: str, name: str) -> bytes:
    return _hash_to_bytes(directory) + name.encode()


def _prefix_end(prefix: bytes) -> bytes:
    p = bytearray(prefix)
    for i in reversed(range(len(p))):
        if p[i] < 0xFF:
            p[i] += 1
            return bytes(p[: i + 1])
    return b""  # all-0xff: scan to the end of the keyspace


class TikvError(RuntimeError):
    pass


class _Region:
    __slots__ = ("region", "leader", "address")

    def __init__(self, region: t.Region, leader: t.Peer, address: str):
        self.region = region
        self.leader = leader
        self.address = address


class TikvStore(FilerStore):
    name = "tikv"

    def __init__(self, pd_address: str):
        self._pd_address = pd_address
        self._lock = threading.Lock()
        self._regions: list[_Region] = []  # cached, sorted by start_key
        self._stores: dict[int, str] = {}  # store_id -> address
        try:
            self._pd = rpc.pd_stub(rpc.cached_channel(pd_address))
            resp = self._pd.GetMembers(t.GetMembersRequest(), timeout=10)
        except grpc.RpcError as e:
            raise RuntimeError(
                f"filer store 'tikv' cannot reach PD at {pd_address!r} "
                f"({e.code().name if hasattr(e, 'code') else e}); start a "
                "TiKV cluster (or tests/cloud_fakes.FakeTikv), or use an "
                "embedded kind: memory | sqlite | sql | sortedlog | lsm"
            ) from e
        self._cluster_id = resp.header.cluster_id

    # --- PD routing -------------------------------------------------------
    def _header(self) -> t.RequestHeader:
        return t.RequestHeader(cluster_id=self._cluster_id)

    def _region_for(self, key: bytes) -> _Region:
        with self._lock:
            for r in self._regions:
                reg = r.region
                if reg.start_key <= key and (not reg.end_key or key < reg.end_key):
                    return r
        resp = self._pd.GetRegion(
            t.GetRegionRequest(header=self._header(), region_key=key), timeout=10
        )
        if not resp.region.id:
            raise TikvError(f"PD returned no region for key {key!r}")
        leader = resp.leader if resp.leader.id else resp.region.peers[0]
        address = self._store_address(leader.store_id)
        r = _Region(resp.region, leader, address)
        with self._lock:
            # racing resolvers must not cache duplicates: a stale twin
            # would eat the retry budget after an epoch bump
            for cached in self._regions:
                if cached.region.id == r.region.id:
                    return cached
            self._regions.append(r)
        return r

    def _store_address(self, store_id: int) -> str:
        with self._lock:
            addr = self._stores.get(store_id)
        if addr is not None:
            return addr
        resp = self._pd.GetStore(
            t.GetStoreRequest(header=self._header(), store_id=store_id), timeout=10
        )
        addr = resp.store.address
        if not addr:
            raise TikvError(f"PD knows no address for store {store_id}")
        with self._lock:
            self._stores[store_id] = addr
        return addr

    def _invalidate(self, r: _Region) -> None:
        with self._lock:
            if r in self._regions:
                self._regions.remove(r)
            # the store may have moved (same id, new address): let PD
            # re-resolve it on the retry
            self._stores.pop(r.leader.store_id, None)

    def _kv_call(self, key: bytes, fn):
        """Route one raw op through the region owning `key`; one retry
        after refreshing routing on a region error or a dead node."""
        for attempt in (0, 1):
            r = self._region_for(key)
            ctx = t.Context(
                region_id=r.region.id,
                region_epoch=r.region.region_epoch,
                peer=r.leader,
            )
            try:
                resp = fn(_kv_stub(r.address), ctx)
            except grpc.RpcError as e:
                # node gone / moved: drop the cached route so PD gets
                # asked again, then retry once
                self._invalidate(r)
                if attempt == 0:
                    continue
                raise TikvError(f"tikv {r.address} unreachable: {e}") from e
            if resp.HasField("region_error"):
                self._invalidate(r)
                if attempt == 0:
                    continue
                raise TikvError(f"tikv region error: {resp.region_error.message}")
            err = getattr(resp, "error", "")
            if err:
                raise TikvError(f"tikv error: {err}")
            return resp
        raise AssertionError("unreachable")

    # --- FilerStore SPI (tikv_store.go:81-221) ----------------------------
    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        key = _gen_key(d, name)
        self._kv_call(
            key,
            lambda stub, ctx: stub.RawPut(
                t.RawPutRequest(context=ctx, key=key, value=entry.encode()),
                timeout=10,
            ),
        )

    update_entry = insert_entry  # UpdateEntry delegates (tikv_store.go:100)

    def find_entry(self, full_path: str) -> Entry:
        d, name = split_path(full_path)
        key = _gen_key(d, name)
        resp = self._kv_call(
            key,
            lambda stub, ctx: stub.RawGet(
                t.RawGetRequest(context=ctx, key=key), timeout=10
            ),
        )
        if resp.not_found or not resp.value:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, resp.value)

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        key = _gen_key(d, name)
        self._kv_call(
            key,
            lambda stub, ctx: stub.RawDelete(
                t.RawDeleteRequest(context=ctx, key=key), timeout=10
            ),
        )

    def _scan_prefix(self, prefix: bytes, start_key: bytes):
        """Yield (key, value) pairs with `prefix`, ascending from
        start_key, riding RawScan batches across region boundaries
        (the Iter loop of tikv_store.go:150-168/185-218)."""
        end = _prefix_end(prefix)
        key = start_key
        retries = 0
        while True:
            r = self._region_for(key)
            ctx = t.Context(
                region_id=r.region.id,
                region_epoch=r.region.region_epoch,
                peer=r.leader,
            )
            try:
                resp = _kv_stub(r.address).RawScan(
                    t.RawScanRequest(
                        context=ctx, start_key=key, end_key=end, limit=SCAN_BATCH
                    ),
                    timeout=10,
                )
            except grpc.RpcError as e:
                self._invalidate(r)
                retries += 1
                if retries > 2:
                    raise TikvError(f"tikv {r.address} unreachable: {e}") from e
                continue
            if resp.HasField("region_error"):
                self._invalidate(r)
                retries += 1
                if retries > 2:
                    raise TikvError(
                        f"tikv region error: {resp.region_error.message}"
                    )
                continue
            retries = 0  # progress resets the per-batch budget
            for kv in resp.kvs:
                if not kv.key.startswith(prefix):
                    return
                yield kv.key, kv.value
            if len(resp.kvs) < SCAN_BATCH:
                # region exhausted: continue into the next region, or stop
                # at the keyspace/prefix end
                nxt = r.region.end_key
                if not nxt or (end and nxt >= end):
                    return
                key = nxt
            else:
                key = resp.kvs[-1].key + b"\x00"

    def delete_folder_children(self, full_path: str) -> None:
        # the reference iterates the prefix and deletes per key
        # (tikv_store.go:143-172); the scan is prefix = md5(dir) and the
        # re-derived genKey(dir, name) equals the scanned key
        prefix = _hash_to_bytes(normalize_path(full_path))
        for key, _value in list(self._scan_prefix(prefix, prefix)):
            self._kv_call(
                key,
                lambda stub, ctx, key=key: stub.RawDelete(
                    t.RawDeleteRequest(context=ctx, key=key), timeout=10
                ),
            )

    def list_directory_entries(
        self, dir_path, start_file_name, include_start, limit
    ):
        d = normalize_path(dir_path)
        prefix = _hash_to_bytes(d)
        start_key = prefix + start_file_name.encode()
        out: list[Entry] = []
        for key, value in self._scan_prefix(prefix, start_key):
            name = key[MD5_SIZE:].decode("utf-8", "replace")
            if not name:
                continue
            if name == start_file_name and not include_start:
                continue
            out.append(Entry.decode(child_path(d, name), value))
            if len(out) >= limit:
                break
        return out

    def close(self) -> None:
        pass  # channels are process-pooled (rpc.cached_channel)
