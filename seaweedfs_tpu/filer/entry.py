"""Filer entry model (weed/filer2/entry.go + entry_codec.go).

An Entry is a full path plus attributes and the chunk list; stores
serialize the (attributes, chunks, extended) triple as the pb Entry
message, keyed by path — same codec role as entry_codec.go's
EncodeAttributesAndChunks.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from seaweedfs_tpu.pb import filer_pb2


def split_path(full_path: str) -> tuple[str, str]:
    """"/a/b/c" → ("/a/b", "c"); "/" → ("/", "")."""
    full_path = normalize_path(full_path)
    if full_path == "/":
        return "/", ""
    dir_part, name = full_path.rsplit("/", 1)
    return dir_part or "/", name


def normalize_path(p: str) -> str:
    p = "/" + p.strip("/")
    while "//" in p:
        p = p.replace("//", "/")
    return p


def child_path(directory: str, name: str) -> str:
    """Join a directory and child name; correct at the root
    ("/", "x") → "/x", not "//x"."""
    return f"{directory.rstrip('/')}/{name}"


@dataclass
class Attr:
    mtime: int = 0  # seconds
    crtime: int = 0
    mode: int = 0o770
    uid: int = 0
    gid: int = 0
    mime: str = ""
    replication: str = ""
    collection: str = ""
    ttl_sec: int = 0
    symlink_target: str = ""
    file_size: int = 0  # explicit size; 0 = derive from chunk total

    @property
    def is_directory(self) -> bool:
        return bool(self.mode & 0o40000)  # os.ModeDir analogue


@dataclass
class Entry:
    full_path: str
    attr: Attr = field(default_factory=Attr)
    chunks: list = field(default_factory=list)  # list[filer_pb2.FileChunk]
    extended: dict = field(default_factory=dict)  # str -> bytes

    @property
    def directory(self) -> str:
        return split_path(self.full_path)[0]

    @property
    def name(self) -> str:
        return split_path(self.full_path)[1]

    @property
    def is_directory(self) -> bool:
        return self.attr.is_directory

    def size(self) -> int:
        # an explicit file_size wins (truncate can clamp below the
        # chunk total, since a kept chunk may span past the new EOF);
        # otherwise derive from the chunk list
        from seaweedfs_tpu.filer.filechunks import total_size

        return self.attr.file_size or total_size(self.chunks)

    # --- pb codec (entry_codec.go) ---
    def to_pb(self) -> filer_pb2.Entry:
        e = filer_pb2.Entry(
            name=self.name,
            is_directory=self.is_directory,
            attributes=filer_pb2.Attributes(
                file_size=self.size(),
                mtime=self.attr.mtime,
                file_mode=self.attr.mode,
                uid=self.attr.uid,
                gid=self.attr.gid,
                crtime=self.attr.crtime,
                mime=self.attr.mime,
                replication=self.attr.replication,
                collection=self.attr.collection,
                ttl_sec=self.attr.ttl_sec,
                symlink_target=self.attr.symlink_target,
            ),
        )
        e.chunks.extend(self.chunks)
        for k, v in self.extended.items():
            e.extended[k] = v
        return e

    @staticmethod
    def from_pb(directory: str, pb_entry: filer_pb2.Entry) -> "Entry":
        a = pb_entry.attributes
        entry = Entry(
            full_path=normalize_path(f"{directory}/{pb_entry.name}"),
            attr=Attr(
                mtime=a.mtime,
                crtime=a.crtime,
                mode=a.file_mode | (0o40000 if pb_entry.is_directory else 0),
                uid=a.uid,
                gid=a.gid,
                mime=a.mime,
                replication=a.replication,
                collection=a.collection,
                ttl_sec=a.ttl_sec,
                symlink_target=a.symlink_target,
                file_size=a.file_size,
            ),
            chunks=list(pb_entry.chunks),
            extended=dict(pb_entry.extended),
        )
        return entry

    def encode(self) -> bytes:
        return self.to_pb().SerializeToString()

    @staticmethod
    def decode(full_path: str, data: bytes) -> "Entry":
        pb_entry = filer_pb2.Entry.FromString(data)
        directory, name = split_path(full_path)
        pb_entry.name = name
        return Entry.from_pb(directory, pb_entry)


def new_directory_entry(path: str, mode: int = 0o770) -> Entry:
    now = int(time.time())
    return Entry(
        full_path=normalize_path(path),
        attr=Attr(mtime=now, crtime=now, mode=mode | 0o40000),
    )
