"""etcd-backed filer store over the etcd v3 gateway REST protocol.

Behavioral match of weed/filer2/etcd/etcd_store.go: one KV pair per
entry with key = `<dir>\\x00<name>` (DIR_FILE_SEPARATOR, :16), plain
Put/Get/Delete, directory listing and recursive delete as prefix
ranges over `<dir>\\x00`. The reference rides clientv3; this store
speaks the grpc-gateway REST surface (/v3/kv/range, /v3/kv/put,
/v3/kv/deleterange) — the same wire the EtcdSequencer uses — so the
gate is connectivity (tests/cloud_fakes.FakeEtcd serves offline).
"""

from __future__ import annotations

import base64

from seaweedfs_tpu.filer.entry import Entry, child_path, normalize_path, split_path
from seaweedfs_tpu.filer.filerstore import EntryNotFound, FilerStore
from seaweedfs_tpu.util.etcd import EtcdHttpError, EtcdKv

DIR_FILE_SEPARATOR = b"\x00"


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _prefix_end(prefix: bytes) -> bytes:
    """etcd prefix-scan upper bound: prefix with its last byte + 1."""
    p = bytearray(prefix)
    for i in reversed(range(len(p))):
        if p[i] < 0xFF:
            p[i] += 1
            return bytes(p[: i + 1])
    return b"\x00"  # all-0xff prefix: scan to the end of the keyspace


class EtcdFilerStore(FilerStore):
    name = "etcd"

    def __init__(self, urls: str):
        self._kv = EtcdKv(urls)
        try:
            self._kv.call("range", {"key": _b64(b"\x00")})  # connectivity
        except EtcdHttpError as e:
            raise RuntimeError(
                f"filer store 'etcd': {urls!r} answered but not as an "
                f"etcd v3 gateway ({e}); check the endpoint/gateway "
                "config, or use an embedded kind"
            ) from e
        except OSError as e:
            raise RuntimeError(
                f"filer store 'etcd' cannot reach {urls!r} ({e}); start "
                "etcd, or use an embedded kind: memory | sqlite | sql | "
                "sortedlog | lsm"
            ) from e

    @staticmethod
    def _key(directory: str, name: str) -> bytes:
        return directory.encode() + DIR_FILE_SEPARATOR + name.encode()

    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        self._kv.call(
            "put",
            {"key": _b64(self._key(d, name)), "value": _b64(entry.encode())},
        )

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry:
        d, name = split_path(full_path)
        resp = self._kv.call("range", {"key": _b64(self._key(d, name))})
        kvs = resp.get("kvs", [])
        if not kvs:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, base64.b64decode(kvs[0]["value"]))

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        self._kv.call("deleterange", {"key": _b64(self._key(d, name))})

    def delete_folder_children(self, full_path: str) -> None:
        prefix = normalize_path(full_path).encode() + DIR_FILE_SEPARATOR
        self._kv.call(
            "deleterange",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
        )

    def list_directory_entries(
        self, dir_path, start_file_name, include_start, limit
    ):
        d = normalize_path(dir_path)
        prefix = d.encode() + DIR_FILE_SEPARATOR
        # server-side range start + limit: begin AT prefix+start (one
        # extra row covers the exclusive case) instead of shipping the
        # whole directory per page
        start_key = prefix + start_file_name.encode()
        resp = self._kv.call(
            "range",
            {
                "key": _b64(start_key),
                "range_end": _b64(_prefix_end(prefix)),
                "sort_target": "KEY",
                "sort_order": "ASCEND",
                "limit": str(limit + 1),
            },
        )
        out = []
        for kv in resp.get("kvs", []):
            key = base64.b64decode(kv["key"])
            name = key[len(prefix) :].decode()
            if start_file_name and not include_start and name <= start_file_name:
                continue
            out.append(
                Entry.decode(
                    child_path(d, name), base64.b64decode(kv["value"])
                )
            )
            if len(out) >= limit:
                break
        return out
