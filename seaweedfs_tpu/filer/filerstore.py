"""FilerStore SPI + embedded implementations.

Behavioral match of weed/filer2/filerstore.go (9-method CRUD+list+tx
interface) with three embedded stores standing in for the reference's
8 pluggable KV backends:

  * MemoryStore  — dict-backed, for tests (≈ the reference's memdb)
  * SqliteStore  — stdlib sqlite3, same schema shape as the
    abstract_sql mysql/postgres stores (dirhash+name primary key,
    filer2/abstract_sql/abstract_sql_store.go)
  * SortedLogStore — append-only log + in-memory sorted index,
    leveldb-analogue persistence without a leveldb dependency
    (filer2/leveldb2/)

All store keys are (directory, name); values are the Entry pb codec
bytes (entry_codec.go).
"""

from __future__ import annotations

import os
import sqlite3
import struct
import threading

from seaweedfs_tpu.filer.entry import Entry, child_path, normalize_path, split_path


class EntryNotFound(KeyError):
    pass


class FilerStore:
    """SPI (filerstore.go:13-29)."""

    name = "abstract"

    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, full_path: str) -> Entry:
        raise NotImplementedError

    def delete_entry(self, full_path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, full_path: str) -> None:
        for e in self.list_directory_entries(full_path, "", True, 1 << 30):
            self.delete_entry(e.full_path)

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, include_start: bool, limit: int
    ) -> list[Entry]:
        raise NotImplementedError

    # tx hooks; embedded stores are single-process so default no-ops
    def begin_transaction(self) -> None: ...

    def commit_transaction(self) -> None: ...

    def rollback_transaction(self) -> None: ...

    def close(self) -> None: ...


class MemoryStore(FilerStore):
    name = "memory"

    def __init__(self) -> None:
        self._lock = threading.RLock()
        # dir -> {name: encoded entry}
        self._dirs: dict[str, dict[str, bytes]] = {}

    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        with self._lock:
            self._dirs.setdefault(d, {})[name] = entry.encode()

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry:
        d, name = split_path(full_path)
        with self._lock:
            data = self._dirs.get(d, {}).get(name)
        if data is None:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, data)

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        with self._lock:
            self._dirs.get(d, {}).pop(name, None)

    def list_directory_entries(self, dir_path, start_file_name, include_start, limit):
        dir_path = normalize_path(dir_path)
        with self._lock:
            names = sorted(self._dirs.get(dir_path, {}))
            out = []
            for n in names:
                if start_file_name:
                    if include_start and n < start_file_name:
                        continue
                    if not include_start and n <= start_file_name:
                        continue
                out.append(
                    Entry.decode(child_path(dir_path, n), self._dirs[dir_path][n])
                )
                if len(out) >= limit:
                    break
        return out


class SqliteStore(FilerStore):
    """abstract_sql-equivalent store on stdlib sqlite3
    (filer2/abstract_sql/abstract_sql_store.go: INSERT/UPDATE/DELETE/
    SELECT ... WHERE dirhash=? AND name=?; list by dirhash+name>)."""

    name = "sqlite"

    def __init__(self, path: str = ":memory:") -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        self._tx_depth = 0  # >0: inside begin/commit_transaction, defer commits
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory TEXT NOT NULL,"
            " name TEXT NOT NULL,"
            " meta BLOB,"
            " PRIMARY KEY (directory, name))"
        )
        self._conn.commit()

    def insert_entry(self, entry: Entry) -> None:
        d, name = split_path(entry.full_path)
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO filemeta (directory, name, meta) VALUES (?,?,?)",
                (d, name, entry.encode()),
            )
            if self._tx_depth == 0:
                self._conn.commit()

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry:
        d, name = split_path(full_path)
        with self._lock:
            row = self._conn.execute(
                "SELECT meta FROM filemeta WHERE directory=? AND name=?", (d, name)
            ).fetchone()
        if row is None:
            raise EntryNotFound(full_path)
        return Entry.decode(full_path, row[0])

    def delete_entry(self, full_path: str) -> None:
        d, name = split_path(full_path)
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE directory=? AND name=?", (d, name)
            )
            if self._tx_depth == 0:
                self._conn.commit()

    def delete_folder_children(self, full_path: str) -> None:
        d = normalize_path(full_path)
        with self._lock:
            self._conn.execute("DELETE FROM filemeta WHERE directory=?", (d,))
            if self._tx_depth == 0:
                self._conn.commit()

    def list_directory_entries(self, dir_path, start_file_name, include_start, limit):
        d = normalize_path(dir_path)
        op = ">=" if include_start else ">"
        with self._lock:
            rows = self._conn.execute(
                f"SELECT name, meta FROM filemeta WHERE directory=? AND name {op} ?"
                " ORDER BY name LIMIT ?",
                (d, start_file_name, limit),
            ).fetchall()
        return [Entry.decode(child_path(d, name), meta) for name, meta in rows]

    def begin_transaction(self) -> None:
        # per-op commits are deferred while _tx_depth > 0 so a rollback
        # really undoes the whole transaction (atomic_rename contract)
        self._lock.acquire()
        self._tx_depth += 1

    def commit_transaction(self) -> None:
        self._tx_depth -= 1
        if self._tx_depth == 0:
            self._conn.commit()
        self._lock.release()

    def rollback_transaction(self) -> None:
        self._tx_depth -= 1
        self._conn.rollback()
        self._lock.release()

    def close(self) -> None:
        self._conn.close()


class SortedLogStore(FilerStore):
    """Append-only record log + in-memory sorted index; replayed on
    open. Persistence role of the reference's leveldb store without the
    dependency: every insert/delete appends (op, path, meta) records."""

    name = "sortedlog"

    _PUT, _DEL = 1, 2

    def __init__(self, path: str) -> None:
        self._path = path
        self._mem = MemoryStore()
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        self._f = open(path, "ab")

    def _replay(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            while True:
                hdr = f.read(9)
                if len(hdr) < 9:
                    break
                op, plen, mlen = struct.unpack("<BII", hdr)
                raw_path = f.read(plen)
                meta = f.read(mlen)
                if len(raw_path) < plen or len(meta) < mlen:
                    break  # torn tail record; recover what we have
                try:
                    path = raw_path.decode()
                except UnicodeDecodeError:
                    break  # torn mid-character: same recovery as short read
                if op == self._PUT:
                    self._mem.insert_entry(Entry.decode(path, meta))
                else:
                    self._mem.delete_entry(path)

    def _append(self, op: int, path: str, meta: bytes) -> None:
        p = path.encode()
        with self._lock:
            self._f.write(struct.pack("<BII", op, len(p), len(meta)) + p + meta)
            self._f.flush()

    def insert_entry(self, entry: Entry) -> None:
        self._mem.insert_entry(entry)
        self._append(self._PUT, entry.full_path, entry.encode())

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Entry:
        return self._mem.find_entry(full_path)

    def delete_entry(self, full_path: str) -> None:
        self._mem.delete_entry(full_path)
        self._append(self._DEL, full_path, b"")

    def list_directory_entries(self, *args, **kw):
        return self._mem.list_directory_entries(*args, **kw)

    def close(self) -> None:
        self._f.close()


def new_store(kind: str, path: str = "") -> FilerStore:
    if kind == "memory":
        return MemoryStore()
    if kind == "sqlite":
        return SqliteStore(path or ":memory:")
    if kind == "sql":
        # the abstract_sql dialect layer over stdlib sqlite3
        # (filer2/abstract_sql/abstract_sql_store.go role)
        from seaweedfs_tpu.filer.abstract_sql import new_sqlite_sql_store

        return new_sqlite_sql_store(path or ":memory:")
    if kind in ("mysql", "postgres"):
        from seaweedfs_tpu.filer.abstract_sql import new_gated_sql_store

        return new_gated_sql_store(kind, path)
    if kind == "redis":
        # real RESP-protocol store, gated on connectivity
        from seaweedfs_tpu.filer.redis_store import RedisStore

        return RedisStore(path or "localhost:6379")
    if kind == "cassandra":
        # real CQL-v4-protocol store, gated on connectivity
        from seaweedfs_tpu.filer.cassandra_store import CassandraStore

        return CassandraStore(path or "localhost:9042")
    if kind == "etcd":
        # etcd v3 gateway REST store, gated on connectivity
        from seaweedfs_tpu.filer.etcd_store import EtcdFilerStore

        return EtcdFilerStore(path or "localhost:2379")
    if kind == "tikv":
        # raw-KV gRPC store via PD routing, gated on connectivity
        from seaweedfs_tpu.filer.tikv_store import TikvStore

        return TikvStore(path or "localhost:2379")
    if kind == "sortedlog":
        if not path:
            raise ValueError("sortedlog store needs a path")
        return SortedLogStore(path)
    if kind == "lsm":
        if not path:
            raise ValueError("lsm store needs a directory path")
        from seaweedfs_tpu.filer.lsm import LsmStore

        return LsmStore(path)
    raise ValueError(
        f"unknown filer store {kind!r}: embedded kinds are memory | sqlite"
        " | sql | sortedlog | lsm; redis (RESP), cassandra (CQL v4), etcd (v3"
        " gateway REST), tikv (raw-KV gRPC via PD), mysql and postgres"
        " (their own wire protocols) all speak to a live server"
        " (path = 'host:port' / PD address / DSN)"
    )
