"""CRC32-Castagnoli with SeaweedFS value masking.

Matches reference weed/storage/needle/crc.go:
  - `NewCRC(b)` / `Update` — standard reflected CRC-32C
    (poly 0x1EDC6F41, reflected 0x82F63B78, init/final-xor 0xFFFFFFFF;
    Go's crc32.Update with the Castagnoli table).
  - `Value()` — LevelDB-style masking: rotate-left 17 then
    + 0xa282ead8 (crc.go:24: `uint32(c>>15|c<<17) + 0xa282ead8`).

The hot path (checksumming needle payloads) is served by the native C
extension when available (seaweedfs_tpu.native, slicing-by-8); the pure
Python table fallback keeps the package dependency-free.
"""

from __future__ import annotations

_POLY_REFLECTED = 0x82F63B78


def _make_table() -> list[int]:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY_REFLECTED if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _make_table()

# Slicing-by-8 tables for the Python fallback (and for generating the C
# tables): T[k][b] = crc of byte b advanced k+1 bytes.
_TABLES8 = [_TABLE]
for _k in range(7):
    _prev = _TABLES8[-1]
    _TABLES8.append([_TABLE[_prev[b] & 0xFF] ^ (_prev[b] >> 8) for b in range(256)])


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    c = crc ^ 0xFFFFFFFF
    n = len(data)
    i = 0
    t0, t1, t2, t3, t4, t5, t6, t7 = _TABLES8
    while i + 8 <= n:
        c ^= int.from_bytes(data[i : i + 4], "little")
        hi = int.from_bytes(data[i + 4 : i + 8], "little")
        c = (
            t7[c & 0xFF]
            ^ t6[(c >> 8) & 0xFF]
            ^ t5[(c >> 16) & 0xFF]
            ^ t4[(c >> 24) & 0xFF]
            ^ t3[hi & 0xFF]
            ^ t2[(hi >> 8) & 0xFF]
            ^ t1[(hi >> 16) & 0xFF]
            ^ t0[(hi >> 24) & 0xFF]
        )
        i += 8
    while i < n:
        c = _TABLE[(c ^ data[i]) & 0xFF] ^ (c >> 8)
        i += 1
    return c ^ 0xFFFFFFFF


_native_crc32c = None
try:  # pragma: no cover - exercised when the native lib is built
    from seaweedfs_tpu.native import crc32c as _native_crc32c  # type: ignore
except Exception:
    _native_crc32c = None


def crc32c(data: bytes, crc: int = 0) -> int:
    """Standard CRC-32C (Castagnoli) of `data`, continuing from `crc`."""
    if _native_crc32c is not None:
        return _native_crc32c(data, crc)
    return _crc32c_py(data, crc)


def masked_value(crc: int) -> int:
    """SeaweedFS needle checksum: rotl17(crc) + 0xa282ead8 (mod 2^32)."""
    crc &= 0xFFFFFFFF
    rot = ((crc << 17) | (crc >> 15)) & 0xFFFFFFFF
    return (rot + 0xA282EAD8) & 0xFFFFFFFF


def needle_checksum(data: bytes) -> int:
    """The 4-byte checksum stored after a needle's body on disk."""
    return masked_value(crc32c(data))
