"""CRC32-Castagnoli with SeaweedFS value masking.

Matches reference weed/storage/needle/crc.go:
  - `NewCRC(b)` / `Update` — standard reflected CRC-32C
    (poly 0x1EDC6F41, reflected 0x82F63B78, init/final-xor 0xFFFFFFFF;
    Go's crc32.Update with the Castagnoli table).
  - `Value()` — LevelDB-style masking: rotate-left 17 then
    + 0xa282ead8 (crc.go:24: `uint32(c>>15|c<<17) + 0xa282ead8`).

The hot path (checksumming needle payloads) is served by the native C
extension when available (seaweedfs_tpu.native, slicing-by-8); the pure
Python table fallback keeps the package dependency-free.
"""

from __future__ import annotations

import threading

_POLY_REFLECTED = 0x82F63B78


def _make_table() -> list[int]:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY_REFLECTED if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _make_table()

# Slicing-by-8 tables for the Python fallback (and for generating the C
# tables): T[k][b] = crc of byte b advanced k+1 bytes.
_TABLES8 = [_TABLE]
for _k in range(7):
    _prev = _TABLES8[-1]
    _TABLES8.append([_TABLE[_prev[b] & 0xFF] ^ (_prev[b] >> 8) for b in range(256)])


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    c = crc ^ 0xFFFFFFFF
    n = len(data)
    i = 0
    t0, t1, t2, t3, t4, t5, t6, t7 = _TABLES8
    while i + 8 <= n:
        c ^= int.from_bytes(data[i : i + 4], "little")
        hi = int.from_bytes(data[i + 4 : i + 8], "little")
        c = (
            t7[c & 0xFF]
            ^ t6[(c >> 8) & 0xFF]
            ^ t5[(c >> 16) & 0xFF]
            ^ t4[(c >> 24) & 0xFF]
            ^ t3[hi & 0xFF]
            ^ t2[(hi >> 8) & 0xFF]
            ^ t1[(hi >> 16) & 0xFF]
            ^ t0[(hi >> 24) & 0xFF]
        )
        i += 8
    while i < n:
        c = _TABLE[(c ^ data[i]) & 0xFF] ^ (c >> 8)
        i += 1
    return c ^ 0xFFFFFFFF


_native_crc32c = None
try:  # pragma: no cover - exercised when the native lib is built
    from seaweedfs_tpu.native import crc32c as _native_crc32c  # type: ignore
except Exception:
    _native_crc32c = None


def crc32c(data: bytes, crc: int = 0) -> int:
    """Standard CRC-32C (Castagnoli) of `data`, continuing from `crc`."""
    if _native_crc32c is not None:
        return _native_crc32c(data, crc)
    return _crc32c_py(data, crc)


# --- GF(2) operator algebra for CRC composition ----------------------------
#
# The CRC register transit over k zero bytes is a linear operator on
# GF(2)^32. Representing it as 32 columns (column b = the operator
# applied to 1<<b) makes "advance a CRC past k bytes" a 32-term XOR
# and lets operators compose by squaring — O(log k) instead of O(k).
# This is what zlib's crc32_combine does for CRC-32; here for
# Castagnoli, shared by crc32c_combine below and the device-side CRC
# kernel (ec/crc_kernel.py), which lifts the same columns into a
# bit-matrix matmul so shard CRCs fold into the encode pass.

def _gf2_apply(cols: list[int], x: int) -> int:
    """Apply a 32-column GF(2) operator to a 32-bit value."""
    r = 0
    b = 0
    while x:
        if x & 1:
            r ^= cols[b]
        x >>= 1
        b += 1
    return r


def _gf2_compose(outer: list[int], inner: list[int]) -> list[int]:
    """Column representation of outer∘inner."""
    return [_gf2_apply(outer, c) for c in inner]


# Z_1: the register transit of ONE zero byte, r' = T[r & 0xFF] ^ (r >> 8)
_Z1_COLS = [_TABLE[(1 << b) & 0xFF] ^ ((1 << b) >> 8) for b in range(32)]
_ZPOW = [_Z1_COLS]  # _ZPOW[k] = transit of 2^k zero bytes
# growth must be serialized: _gf2_compose is long pure-Python, so two
# threads (concurrent generate/rebuild verbs folding CRCs) racing the
# append could land a stale square at the wrong index — and the table
# would then yield wrong combines for the life of the process
_ZPOW_LOCK = threading.Lock()


def _zero_shift_cols(nbytes: int) -> list[int]:
    """Columns of the k-zero-byte transit operator Z_k (k = nbytes ≥ 1),
    built from squared powers in O(log k) 32x32 GF(2) composes."""
    cols = None
    k = 0
    n = nbytes
    while n:
        if k >= len(_ZPOW):
            with _ZPOW_LOCK:
                while k >= len(_ZPOW):
                    _ZPOW.append(_gf2_compose(_ZPOW[-1], _ZPOW[-1]))
        if n & 1:
            p = _ZPOW[k]
            cols = p if cols is None else _gf2_compose(p, cols)
        n >>= 1
        k += 1
    return cols if cols is not None else [1 << b for b in range(32)]


_COMBINE_CACHE: dict[int, list[int]] = {}


def crc32c_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC-32C of A||B from crc32c(A), crc32c(B) and len(B).

    Same contract as zlib's crc32_combine: both inputs are ordinary
    (init/final-xor applied) CRC values, and so is the result. The
    init/xorout constants cancel, leaving Z_len2(crc1) ^ crc2 — the
    identity the EC streaming drivers use to fold per-tile device CRCs
    into whole-shard-file CRCs without re-reading a byte."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    cols = _COMBINE_CACHE.get(len2)
    if cols is None:
        cols = _zero_shift_cols(len2)
        if len(_COMBINE_CACHE) > 256:
            _COMBINE_CACHE.clear()  # bound; tile lengths are few
        _COMBINE_CACHE[len2] = cols
    return _gf2_apply(cols, crc1 & 0xFFFFFFFF) ^ (crc2 & 0xFFFFFFFF)


def masked_value(crc: int) -> int:
    """SeaweedFS needle checksum: rotl17(crc) + 0xa282ead8 (mod 2^32)."""
    crc &= 0xFFFFFFFF
    rot = ((crc << 17) | (crc >> 15)) & 0xFFFFFFFF
    return (rot + 0xA282EAD8) & 0xFFFFFFFF


def needle_checksum(data: bytes) -> int:
    """The 4-byte checksum stored after a needle's body on disk."""
    return masked_value(crc32c(data))
