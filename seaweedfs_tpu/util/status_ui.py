"""Shared HTML scaffolding for the status UI pages
(server/master_ui + volume_server_ui templates role)."""

from __future__ import annotations

_STYLE = (
    "body{font-family:sans-serif;margin:2em}"
    "table{border-collapse:collapse}td,th{border:1px solid #999;"
    "padding:4px 10px}"
)


def status_page(
    title: str,
    heading: str,
    intro_html: str,
    table_header_cells: list[str],
    table_rows_html: str,
    footer_links: list[str],
    section_heading: str | None = None,
) -> str:
    header = "".join(f"<th>{c}</th>" for c in table_header_cells)
    links = " &middot; ".join(
        f"<a href='{href}'>{href}</a>" for href in footer_links
    )
    if section_heading is None:
        section_heading = "Topology" if "Master" in title else "Volumes"
    return (
        f"<!DOCTYPE html><html><head><title>{title}</title>"
        f"<style>{_STYLE}</style></head><body>"
        f"<h1>{heading}</h1><p>{intro_html}</p>"
        f"<h2>{section_heading}</h2>"
        f"<table><tr>{header}</tr>{table_rows_html}</table>"
        f"<p>{links}</p></body></html>"
    )
