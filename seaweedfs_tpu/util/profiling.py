"""-cpuprofile support (reference command/benchmark.go:64,
master.go:74, server.go:66 pprof.StartCPUProfile): profile the run and
dump pstats to the given path on shutdown; the file loads with
`python -m pstats <path>` (the pprof-viewer role).

Profilers attach per thread, so enabling one on the main thread alone
would miss all real work (gRPC executors, benchmark workers). A
threading.setprofile trampoline bootstraps a profiler in every thread
created inside the context; stats from threads that finished by dump
time are aggregated with the main thread's (threads still running at
exit are skipped — a profiler cannot be safely disabled cross-thread).
The main thread gets the fast C profiler; worker threads get the
pure-Python `profile.Profile`, because CPython 3.12 registers the C
profiler as a process-exclusive sys.monitoring tool — only one
instance may be active at a time."""

from __future__ import annotations

import threading


class CpuProfile:
    def __init__(self, path: str):
        self.path = path
        self._main = None
        self._thread_profiles: list = []
        self._lock = threading.Lock()
        self._prev_hook = None
        self._stopped = False

    def __enter__(self):
        if not self.path:
            return self
        import cProfile
        import sys

        outer = self

        import profile as pyprofile

        def bootstrap(frame, event, arg):
            # first profile event in a new thread: replace this
            # trampoline with a per-thread pure-Python profiler (the C
            # profiler is process-exclusive under 3.12 sys.monitoring)
            sys.setprofile(None)
            prof = pyprofile.Profile()
            with outer._lock:
                outer._thread_profiles.append(
                    (threading.current_thread(), prof)
                )

            def tolerant(fr, ev, a):
                # installed mid-stack: frames below the install point
                # unwind at thread exit without matching call events;
                # stop profiling this thread at that boundary — and as
                # soon as the context exits (long-lived threads must
                # not keep paying profiler overhead forever)
                if outer._stopped:
                    sys.setprofile(None)
                    return
                try:
                    return prof.dispatcher(fr, ev, a)
                except AssertionError:
                    sys.setprofile(None)

            sys.setprofile(tolerant)

        self._prev_hook = getattr(threading, "_profile_hook", None)
        threading.setprofile(bootstrap)
        self._main = cProfile.Profile()
        self._main.enable()
        return self

    def __exit__(self, *exc):
        if self._main is None:
            return
        import pstats

        self._stopped = True
        self._main.disable()
        threading.setprofile(self._prev_hook)
        stats = pstats.Stats(self._main)
        skipped = 0
        with self._lock:
            for thread, prof in self._thread_profiles:
                if thread.is_alive():
                    # cannot disable another thread's profiler — its
                    # samples never reach the dump
                    skipped += 1
                    continue
                try:
                    stats.add(prof)
                except Exception:  # noqa: BLE001 - partial stats are fine
                    pass
        if skipped:
            from seaweedfs_tpu.util import wlog

            wlog.warning(
                "cpuprofile %s: %d thread(s) still running at exit; "
                "their samples were skipped (the continuous sampler at "
                "/debug/profile covers long-lived threads)",
                self.path,
                skipped,
            )
        stats.dump_stats(self.path)
