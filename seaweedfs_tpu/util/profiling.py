"""-cpuprofile support (reference command/benchmark.go:64,
master.go:74, server.go:66 pprof.StartCPUProfile): run the process
under cProfile, dump pstats to the given path on shutdown; the file
loads with `python -m pstats <path>` (the pprof-viewer role)."""

from __future__ import annotations


class CpuProfile:
    def __init__(self, path: str):
        self.path = path
        self._profile = None

    def __enter__(self):
        if self.path:
            import cProfile

            self._profile = cProfile.Profile()
            self._profile.enable()
        return self

    def __exit__(self, *exc):
        if self._profile is not None:
            self._profile.disable()
            self._profile.dump_stats(self.path)
