"""Driver for the event-driven serving core (native/serve.c).

`WeedHTTPServer.serve_forever` lands here first: when the `_serve_ext`
extension is built and `WEED_NATIVE_SERVE` != 0, the server's accept/
read/dispatch edge runs as ONE C epoll loop instead of a thread per
connection —

  * fast-path GET/HEAD requests (the owning daemon installed a
    `server.fast_resolver`) are answered without leaving the loop:
    the resolver maps the request to a pre-formatted response prefix
    plus either small in-memory bytes or a (fd, offset, count)
    sendfile plan, and the loop writes it zero-copy;
  * every other request HANDS THE CONNECTION OFF: the loop transfers
    the fd and its unconsumed buffer here, and the connection finishes
    its life in a `serve_connection` thread — the same threaded mini
    loop the kill switch falls back to, driving the same do_* handler
    methods, so slow paths have exactly one implementation;
  * per-response completion callbacks keep the tracing plane and the
    /metrics counters identical to the threaded path: a span per
    traced request (named `<role>.get`/`<role>.head`), stage timings
    parse/resolve/send attached the way the C POST span attaches
    parse/assemble/crc/pwrite/reply, and the
    weed_http_request_* counter/histogram labeled as ever.

Kill switch: WEED_NATIVE_SERVE=0 (or an unbuilt extension, or a
non-Linux host) restores the pure-Python threaded path wholesale.
"""

from __future__ import annotations

import os
import socket
import threading

try:
    from seaweedfs_tpu.native import serve_ext as _serve_ext
except ImportError:  # pragma: no cover - no compiler on host
    _serve_ext = None
if _serve_ext is not None and not (
    hasattr(_serve_ext, "loop") and hasattr(_serve_ext, "shm_admit")
):
    _serve_ext = None  # stale artifact without the current entry points

NATIVE_SERVE_ENABLED = os.environ.get("WEED_NATIVE_SERVE", "1") != "0"
# C-side plan cache (fd/offset/prefix keyed by path). Independent kill
# switch: WEED_SERVE_CACHE=0 forces every plan non-cacheable so each
# request re-resolves, while the rest of the fast path stays native.
SERVE_CACHE_ENABLED = os.environ.get("WEED_SERVE_CACHE", "1") != "0"

# Stage names attached to a fast-path GET span — the serving-loop
# counterpart of write_path.WRITE_STAGES (docs/TRACING.md): parse is
# the C head parse, resolve the Python needle lookup, send the
# header write + sendfile drain.
SERVE_STAGES = ("parse", "resolve", "send")


def available() -> bool:
    """True when the epoll serving core can run in this process."""
    return _serve_ext is not None and NATIVE_SERVE_ENABLED


def bump_generation() -> int:
    """Advance the plan-cache generation counter (process-global).

    The storage layer calls this on ANY mutation that could invalidate
    a cached (fd, offset, size, headers) plan: needle write, delete,
    vacuum fd-swap, remount.  Cheap (one relaxed atomic add) and safe
    to call with the extension missing."""
    if _serve_ext is None:
        return 0
    return _serve_ext.gen_bump()


def generation() -> int:
    """Current plan-cache generation (0 when the extension is absent)."""
    if _serve_ext is None:
        return 0
    return _serve_ext.gen_get()


def serve_stats() -> dict:
    """Process-wide C fast-path counters (empty dict when absent)."""
    if _serve_ext is None:
        return {}
    return _serve_ext.serve_stats()


def admission_shm_attach(
    path: str,
    rate: float,
    burst: float,
    retry_floor: float = 0.0,
    nslots: int = 1024,
) -> bool:
    """Map the shared admission token-bucket file (creating it when
    first).  Process-global and idempotent; False when the extension is
    missing (caller keeps the per-process bucket)."""
    if _serve_ext is None:
        return False
    _serve_ext.shm_attach(path, float(rate), float(burst),
                          float(retry_floor), int(nslots))
    return True


def admission_shm_admit(key: str) -> float:
    """Charge one request against the shared bucket for `key`.

    0.0 = admitted; positive = rejected, value is the suggested
    Retry-After in seconds.  Raises RuntimeError when not attached."""
    if _serve_ext is None:
        raise RuntimeError("admission shm not attached")
    return _serve_ext.shm_admit(key)


def admission_shm_detach() -> None:
    if _serve_ext is not None:
        _serve_ext.shm_detach()


def try_serve_forever(server) -> bool:
    """Run `server`'s accept loop on the C epoll core. False = caller
    should use the threaded socketserver path (extension missing, kill
    switch set, or the loop failed to start)."""
    # per-server opt-out: embedders (and the serve fuzzer's threaded
    # control arm) can pin one server to the threaded path while the
    # process default stays native
    if not available() or not getattr(server, "native_serve", True):
        return False
    try:
        wake_r, wake_w = os.pipe()
    except OSError:
        # mark the fallback explicitly: shutdown()'s arming-wait loop
        # distinguishes "thread will run the stdlib loop" (False) from
        # "native loop not armed yet" (absent) — without this marker an
        # EMFILE fallback would spin that loop for its full deadline
        server._serve_native = False
        return False
    os.set_blocking(wake_r, False)
    done = threading.Event()
    # _serve_native stays True for the server's LIFETIME (not just
    # while the loop runs): a second shutdown() — double stop()s are
    # normal in teardown paths — must be a no-op here, never fall
    # through to socketserver.shutdown(), which would wait forever on
    # an __is_shut_down event the stdlib loop (which never ran) will
    # never set
    server._serve_native = True
    server._serve_wake_w = wake_w
    server._serve_done = done
    resolve, handoff, complete = _callbacks(server)
    # C-side shared-bucket admission: only when this listener is gated
    # by a SHARED controller (internal listeners have no admission and
    # must never be charged; a per-process bucket stays in Python)
    adm = getattr(server, "admission", None)
    use_adm = 0
    if adm is not None and getattr(adm, "shared", False):
        from seaweedfs_tpu import qos as _qos

        # kill-switch parity: WEED_QOS_ADMISSION=0 set at start keeps
        # the C loop from shedding, like the Python gate (the Python
        # side re-reads the env per request; the native loop latches
        # it here — flipping it mid-run needs a restart)
        use_adm = 1 if _qos.enabled("admission") else 0
    try:
        _serve_ext.loop(
            server.socket.fileno(),
            wake_r,
            resolve,
            handoff,
            complete,
            int(getattr(server, "serve_idle_ms", 0) or 0),
            int(getattr(server, "serve_max_reqs", 0) or 0),
            use_adm,
        )
    except (OSError, ValueError):
        # loop setup failed (epoll exhausted, listen fd gone): fall
        # back to the threaded path for the life of this server — and
        # route future shutdown() calls back to socketserver's
        server._serve_native = False
        server._serve_wake_w = None
        done.set()
        try:
            os.close(wake_w)
        finally:
            os.close(wake_r)
        return False
    done.set()
    os.close(wake_r)
    # wake_w stays open until shutdown() (a shutdown racing loop exit
    # must still have a valid fd to write); server_close is too late
    # only for the exotic never-shutdown case, which leaks one pipe fd
    # per server object — the lifecycle tier's accounting below keeps
    # the normal path clean.
    return True


def shutdown(server) -> bool:
    """Stop a native serve loop. False = this server never ran the
    native loop (caller should run the stdlib shutdown). Idempotent:
    a repeated shutdown of a native server returns True and does
    nothing."""
    if not getattr(server, "_serve_native", False):
        return False
    wake_w = getattr(server, "_serve_wake_w", None)
    if wake_w is None:
        return True  # already shut down (or the loop already exited)
    try:
        os.write(wake_w, b"x")
    except OSError:
        pass  # loop already gone
    server._serve_done.wait(5.0)
    server._serve_wake_w = None
    try:
        os.close(wake_w)
    except OSError:
        pass
    return True


def _callbacks(server):
    """Build the (resolve, handoff, complete) trio around `server`.
    Everything the per-request path touches is hoisted into closure
    locals — the loop thread should read its own warm frame, not
    chase module attributes (the docs/TRACING.md cold-line rule)."""
    from seaweedfs_tpu import trace as _trace
    from seaweedfs_tpu.stats.metrics import (
        HTTP_REQUEST_COUNTER,
        HTTP_REQUEST_HISTOGRAM,
    )
    from seaweedfs_tpu.trace import blackbox as _blackbox
    from seaweedfs_tpu.util.httpd import serve_connection

    handler_cls = server.RequestHandlerClass
    trace_label = getattr(server, "trace_name", "")
    trace_node = getattr(server, "trace_node", "")
    # QoS plane (docs/QOS.md): fast-path GETs never enter the Python
    # dispatch funnel, so without this the heartbeat in_flight signal
    # under-reports a node saturated by zero-copy reads. resolve()
    # enters, complete() exits — the C loop fires complete() exactly
    # once per resolved response, including connection-lost teardowns
    # (weed_conn_release_resp runs on every destroy path).
    load_tracker = getattr(server, "load_tracker", None)
    open_span, close_span, sample_hit = _trace.loop_tracer(trace_node)
    trace_enabled = _trace.enabled
    hist_observe = HTTP_REQUEST_HISTOGRAM.observe
    put_exemplar = HTTP_REQUEST_HISTOGRAM.put_exemplar
    counter_labels = HTTP_REQUEST_COUNTER.labels
    # weedscope flight recorder: fast-path completions record the SAME
    # wide-event the threaded funnel records — stage names and status
    # identity across arms is tested (tests/test_native_serve.py)
    bb_record = _blackbox.recorder(trace_label, trace_node)
    get_name = f"{trace_label or 'http'}.get"
    head_name = f"{trace_label or 'http'}.head"
    import time as _time

    clock = _time.perf_counter

    cache_on = SERVE_CACHE_ENABLED

    def resolve(path, rng, head_only, trace_hdr, inm):
        # `fast_resolver` is re-read per request: the volume server
        # installs it before serve_forever, but a daemon that never
        # does simply declines everything (gateways)
        fr = server.fast_resolver
        if fr is None:
            return None
        plan = fr(path, rng, head_only)
        if plan is None:
            return None
        if len(plan) == 6:
            # legacy plan: carries no validator, so a conditional GET
            # must fall through to the threaded arm for the 304 check
            if inm is not None:
                return None
            status, prefix, body, fd, off, count = plan
            etag = prefix304 = None
            gen = cacheable = 0
        else:
            (status, prefix, body, fd, off, count,
             etag, prefix304, gen, cacheable) = plan
            if not cache_on:
                cacheable = 0
        sp = None
        if trace_enabled() and (trace_hdr or sample_hit()):
            sp = open_span(
                head_name if head_only else get_name,
                trace_hdr or None,
                0,
                clock(),
            )
        if load_tracker is not None:
            load_tracker.enter()  # exited in complete(); nothing can
            # raise between here and the loop owning the token
        return (
            status,
            prefix,
            body,
            fd,
            off,
            count,
            fd >= 0,  # the loop closes the per-request dup'd fd
            (sp, "HEAD" if head_only else "GET"),
            etag,
            prefix304,
            gen,
            1 if cacheable else 0,
        )

    def handoff(fd, pending, ip, port, nreqs):
        # once socket() succeeds the fd has an owner whose destructor
        # closes it — from that point NOTHING may propagate to the C
        # glue, whose error path close(fd) would double-close a number
        # a concurrent thread may already have reused (a raise BEFORE
        # ownership is fine: the glue's close is then the only one)
        sock = socket.socket(fileno=fd)
        try:
            sock.setblocking(True)
            threading.Thread(
                target=_drive_handoff,
                args=(sock, (ip, port), server, handler_cls, pending, nreqs),
                daemon=True,
                name="weed-serve-handoff",
            ).start()
        except Exception as e:  # thread exhaustion under extreme load
            from seaweedfs_tpu.util import wlog

            wlog.warning("serve handoff dropped %s:%s: %s", ip, port, e)
            try:
                sock.close()
            except OSError:
                pass

    def _drive_handoff(sock, addr, srv, cls, pending, nreqs):
        try:
            # initial_reqs: responses the C loop already served on this
            # connection — -serveMaxReqs keeps counting, not restarts
            serve_connection(
                sock, addr, srv, cls, initial=pending, initial_reqs=nreqs
            )
        finally:
            try:
                sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            sock.close()

    def complete(ctx, status, nbytes, t_parse, t_resolve, t_send, ok):
        if load_tracker is not None:
            load_tracker.exit()
        sp, cmd = ctx
        stages = {"parse": t_parse, "resolve": t_resolve, "send": t_send}
        if sp is not None:
            sp.add_stages(stages)  # adopts the dict; blackbox shares it
            if not ok and not sp.error:
                sp.error = "connection lost mid-response"
            close_span(sp, status)
        dur = sp.duration if sp is not None else t_parse + t_resolve + t_send
        if trace_label:
            hist_observe(dur, trace_label, cmd)
            counter_labels(trace_label, cmd, str(status)).inc()
            if sp is not None:
                put_exemplar(dur, sp.trace_id, trace_label, cmd)
        bb_record(
            cmd,
            sp.trace_id if sp is not None else "",
            sp.plane if sp is not None else "serve",
            status,
            dur,
            nbytes,
            "",  # the C loop doesn't surface the peer address here
            _blackbox.FLAG_SHED if status == 503
            else _blackbox.FLAG_DEADLINE if status == 504 else 0,
            stages,
        )

    return resolve, handoff, complete
