"""Shared etcd v3 grpc-gateway REST client (/v3/kv/*).

One client for everything that speaks to etcd — the EtcdSequencer and
the etcd filer store — so endpoint parsing, failover, transport, and
error classification live in exactly one place. Plain-http endpoints
ride the pooled keep-alive raw-socket transport (client/operation.py:
the filer store puts this on the metadata hot path, and a TCP
handshake per metadata op is exactly the cost that transport was built
to remove); https endpoints fall back to urllib."""

from __future__ import annotations

import http.client
import json
import threading
import urllib.error
import urllib.request


class EtcdHttpError(RuntimeError):
    """The endpoint answered with a non-200 — reachable but
    misconfigured (gateway disabled, wrong service, auth). Distinct
    from OSError so 'cannot reach' guidance never fires for it."""

    def __init__(self, status: int, body: bytes):
        super().__init__(f"etcd gateway http {status}: {body[:200]!r}")
        self.status = status


class EtcdKv:
    """POST /v3/kv/<op> against the first endpoint that answers; a
    working endpoint rotates to the front so steady state dials it
    directly. HTTP errors (the endpoint answered) are not
    failover-able and raise EtcdHttpError; connection-level failures
    try the next endpoint."""

    def __init__(self, urls: str, timeout: float = 10.0):
        endpoints = []
        for u in urls.split(","):
            u = u.strip().rstrip("/")
            if not u:
                continue
            if not u.startswith("http"):
                u = "http://" + u
            endpoints.append(u)
        if not endpoints:
            raise ValueError("etcd client needs at least one endpoint")
        self._endpoints = endpoints
        self._lock = threading.Lock()  # guards the rotation
        self.timeout = timeout

    def _post(self, endpoint: str, op: str, body: bytes) -> tuple[int, bytes]:
        if endpoint.startswith("http://"):
            from seaweedfs_tpu.client.operation import http_call

            status, _, resp = http_call(
                "POST",
                endpoint[len("http://") :] + f"/v3/kv/{op}",
                body=body,
                headers={"Content-Type": "application/json"},
                timeout=self.timeout,
            )
            return status, resp
        req = urllib.request.Request(
            f"{endpoint}/v3/kv/{op}",
            data=body,
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def call(self, op: str, payload: dict) -> dict:
        with self._lock:
            endpoints = list(self._endpoints)
        body = json.dumps(payload).encode()
        last: OSError | None = None
        for endpoint in endpoints:
            try:
                status, resp = self._post(endpoint, op, body)
            except (OSError, http.client.HTTPException) as e:
                # the pooled transport surfaces some transport faults
                # as HTTPException (e.g. IncompleteRead) — same
                # failover treatment as a socket error
                last = e if isinstance(e, OSError) else OSError(str(e))
                continue
            if status != 200:
                raise EtcdHttpError(status, resp)
            if endpoint != endpoints[0]:
                with self._lock:
                    if endpoint in self._endpoints:
                        self._endpoints.remove(endpoint)
                        self._endpoints.insert(0, endpoint)
            return json.loads(resp)
        raise last if last is not None else OSError("no endpoints")
