"""Shared etcd v3 grpc-gateway REST client (/v3/kv/*).

One client for everything that speaks to etcd — the EtcdSequencer and
the etcd filer store — so endpoint parsing, failover, and error
classification live in exactly one place."""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request


class EtcdKv:
    """POST /v3/kv/<op> against the first endpoint that answers; a
    working endpoint rotates to the front so steady state dials it
    directly. HTTP errors (the endpoint answered) are not
    failover-able and propagate; connection-level failures try the
    next endpoint."""

    def __init__(self, urls: str, timeout: float = 10.0):
        endpoints = []
        for u in urls.split(","):
            u = u.strip().rstrip("/")
            if not u:
                continue
            if not u.startswith("http"):
                u = "http://" + u
            endpoints.append(u)
        if not endpoints:
            raise ValueError("etcd client needs at least one endpoint")
        self._endpoints = endpoints
        self._lock = threading.Lock()  # guards the rotation
        self.timeout = timeout

    def call(self, op: str, payload: dict) -> dict:
        with self._lock:
            endpoints = list(self._endpoints)
        last: OSError | None = None
        for endpoint in endpoints:
            req = urllib.request.Request(
                f"{endpoint}/v3/kv/{op}",
                data=json.dumps(payload).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    if endpoint != endpoints[0]:
                        with self._lock:
                            if endpoint in self._endpoints:
                                self._endpoints.remove(endpoint)
                                self._endpoints.insert(0, endpoint)
                    return json.loads(r.read())
            except urllib.error.HTTPError:
                raise  # reachable: protocol errors are not failover-able
            except OSError as e:
                last = e
        raise last if last is not None else OSError("no endpoints")
