"""multipart/form-data parsing for blob uploads.

Role match: the reference's needle.CreateNeedleFromRequest
(weed/storage/needle/needle.go:85 ParseUpload) accepts both raw bodies
and `curl -F file=@x` multipart forms, taking the first file part's
bytes, filename, and content type.

From-scratch bytes parser: the stdlib email machinery this replaced
costs >1 ms per request on the data plane (policy objects, universal
newlines, MIME header registries — measured dominating the volume
write profile under multipart load); boundary splitting plus a
split-on-colon header loop does the same job in ~10 us. Go's
mime/multipart reader, which the reference leans on, is the same kind
of hand-rolled boundary scanner.
"""

from __future__ import annotations

import re

from dataclasses import dataclass


@dataclass
class UploadPart:
    data: bytes
    filename: str = ""
    mime: str = ""
    is_gzipped: bool = False  # part arrived Content-Encoding: gzip


class MalformedUpload(ValueError):
    """Multipart content type with no parsable file part — the
    reference's ParseUpload errors here rather than storing 0 bytes."""


_BOUNDARY_RE = re.compile(
    r'boundary\s*=\s*(?:"([^"]+)"|([^;,\s]+))', re.IGNORECASE
)
_FILENAME_RE = re.compile(r'filename\s*=\s*(?:"((?:\\.|[^"\\])*)"|([^;\s]+))', re.IGNORECASE)


def _part_headers(raw: bytes) -> dict[str, str]:
    headers: dict[str, str] = {}
    for line in raw.split(b"\r\n"):
        key, sep, value = line.partition(b":")
        if sep:
            headers[key.strip().lower().decode("latin-1")] = (
                value.strip().decode("latin-1")
            )
    return headers


def _decode_transfer(payload: bytes, encoding: str) -> bytes:
    """Content-Transfer-Encoding on a form part (rare; curl never sends
    one, but the previous email-based parser honored it)."""
    enc = encoding.lower()
    if enc in ("", "binary", "7bit", "8bit"):
        return payload
    if enc == "base64":
        import base64
        import binascii

        try:
            return base64.b64decode(payload, validate=False)
        except binascii.Error:
            return payload
    if enc == "quoted-printable":
        import quopri

        return quopri.decodestring(payload)
    return payload


def _find_delim(data: bytes, delim: bytes, start: int) -> tuple[int, int, bool]:
    """Next *valid* delimiter line at/after `start`: returns
    (line_idx, after_boundary_idx, is_closing), or (-1, -1, False).

    A delimiter is CRLF + "--boundary" followed only by transport
    padding (SP/HT) and CRLF; the closing form carries "--" first.
    Occurrences of the boundary bytes mid-line are data, not framing —
    the same scan Go's mime/multipart does (isBoundaryDelimiterLine /
    isFinalBoundary)."""
    pos = start
    while True:
        idx = data.find(delim, pos)
        if idx == -1:
            return -1, -1, False
        after = idx + len(delim)
        closing = data[after : after + 2] == b"--"
        rest_from = after + 2 if closing else after
        eol = data.find(b"\r\n", rest_from)
        tail = data[rest_from:] if eol == -1 else data[rest_from:eol]
        if tail.strip(b" \t") == b"":
            return idx, after, closing
        pos = idx + 1


def parse_upload(body: bytes, content_type: str) -> UploadPart:
    """The first file part of a multipart body, or the raw body itself
    when the request is not multipart/form-data (ParseUpload role)."""
    if not content_type.lower().startswith("multipart/form-data"):
        return UploadPart(data=body, mime=content_type)
    m = _BOUNDARY_RE.search(content_type)
    if m is None:
        raise MalformedUpload("multipart/form-data without a boundary")
    boundary = b"--" + (m.group(1) or m.group(2)).encode("latin-1")

    # RFC 2046 framing: preamble, then boundary-delimited parts, the
    # final boundary carrying a trailing "--". A virtual leading CRLF
    # makes the first boundary parse like every other delimiter line.
    first: UploadPart | None = None
    data = b"\r\n" + body
    delim = b"\r\n" + boundary
    _, pos, closing = _find_delim(data, delim, 0)
    while pos != -1 and not closing:
        eol = data.find(b"\r\n", pos)
        if eol == -1:
            break
        nidx, npos, closing = _find_delim(data, delim, eol)
        part_raw = data[eol + 2 : nidx if nidx != -1 else len(data)]
        pos = npos
        head, sep, payload = part_raw.partition(b"\r\n\r\n")
        if not sep:
            # headerless part: the blank line IS the first thing
            if part_raw.startswith(b"\r\n"):
                head, payload = b"", part_raw[2:]
            else:
                continue
        headers = _part_headers(head)
        payload = _decode_transfer(
            payload, headers.get("content-transfer-encoding", "")
        )
        disp = headers.get("content-disposition", "")
        fm = _FILENAME_RE.search(disp)
        filename = ""
        if fm:
            filename = (fm.group(1) or fm.group(2) or "").replace('\\"', '"')
        ctype = headers.get("content-type", "")
        candidate = UploadPart(
            data=payload,
            filename=filename,
            mime=ctype,
            is_gzipped=headers.get("content-encoding", "").lower() == "gzip",
        )
        if filename:
            # the reference takes the first part that carries a file
            return candidate
        if first is None:
            first = candidate
    if first is None:
        raise MalformedUpload(
            "multipart/form-data body contained no parsable part"
        )
    return first
