"""multipart/form-data parsing for blob uploads.

Role match: the reference's needle.CreateNeedleFromRequest
(weed/storage/needle/needle.go:85 ParseUpload) accepts both raw bodies
and `curl -F file=@x` multipart forms, taking the first file part's
bytes, filename, and content type. Stdlib `email` does the MIME
parsing (cgi.FieldStorage left the stdlib in 3.13)."""

from __future__ import annotations

import email.parser
import email.policy
from dataclasses import dataclass


@dataclass
class UploadPart:
    data: bytes
    filename: str = ""
    mime: str = ""


class MalformedUpload(ValueError):
    """Multipart content type with no parsable file part — the
    reference's ParseUpload errors here rather than storing 0 bytes."""


def parse_upload(body: bytes, content_type: str) -> UploadPart:
    """The first file part of a multipart body, or the raw body itself
    when the request is not multipart/form-data (ParseUpload role)."""
    if not content_type.lower().startswith("multipart/form-data"):
        return UploadPart(data=body, mime=content_type)
    parser = email.parser.BytesParser(policy=email.policy.HTTP)
    msg = parser.parsebytes(
        b"Content-Type: " + content_type.encode("latin-1") + b"\r\n\r\n" + body
    )
    first: UploadPart | None = None
    for part in msg.iter_parts():
        payload = part.get_payload(decode=True)
        if payload is None:
            continue
        filename = part.get_filename() or ""
        # only an EXPLICIT part Content-Type counts (the email parser
        # defaults to text/plain, which must not be stamped on binary)
        ctype = part.get_content_type() if part.get("Content-Type") else ""
        candidate = UploadPart(data=payload, filename=filename, mime=ctype)
        if filename:
            # the reference takes the first part that carries a file
            return candidate
        if first is None:
            first = candidate
    if first is None:
        raise MalformedUpload(
            "multipart/form-data body contained no parsable part"
        )
    return first
