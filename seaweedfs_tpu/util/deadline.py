"""End-to-end request deadlines (docs/CHAOS.md).

One per-request clock that every hop shares. A request's total budget
is minted once — at the client that cares, or at the first gateway a
budget-less request enters — and rides the `X-Weed-Deadline` hop
header as REMAINING milliseconds, re-stamped at each hop (remaining
budget, not an absolute timestamp: cluster nodes share no clock, and
network transit only ever shrinks the budget, which errs safe).

Consumers:
  * `client/operation.http_call` derives every socket operation's
    timeout from the remaining budget, so a server trickling one byte
    per 29 s can no longer outlive the caller's intent (the per-op
    `timeout=` used to reset on every recv);
  * `pb/rpc.Stub` does the same for gRPC attempts and forwards the
    budget as invocation metadata;
  * the mini request loop (util/httpd.serve_connection) parses the
    header at every daemon, 504-fast-rejects already-expired requests
    BEFORE dispatch (no disk touched, no downstream fan-out), and
    installs the deadline as the ambient one so internal hops the
    handler makes inherit it automatically;
  * the hedge driver and the unified RetryPolicy (client/retry.py)
    check the same clock before spending work a caller will never see.

`WEED_DEADLINE=0` kills the plane wholesale (no stamping, no
derivation, no 504 fast-reject). `WEED_DEADLINE_DEFAULT_S` makes every
gateway ENTRY mint a budget for requests that arrive without one
(0/unset = only explicit deadlines propagate).
"""

from __future__ import annotations

import os
import threading
import time

# hop header: remaining milliseconds at stamp time (float text).
# Stamped by client/operation.http_call + pb/rpc.Stub, parsed by
# util/httpd.serve_connection and pb/rpc.servicer_handler.
DEADLINE_HEADER = "x-weed-deadline"

# a budget can never exceed this (header values are untrusted input;
# an absurd value would otherwise pin a connection's socket timeout
# into next week)
MAX_BUDGET_S = 24 * 3600.0

# floor for derived socket timeouts: 0 would mean non-blocking, and a
# sub-millisecond recv window only ever measures scheduler noise
MIN_OP_TIMEOUT_S = 0.001


class DeadlineExceeded(TimeoutError):
    """The request's whole-request budget ran out (client side).

    A TimeoutError subclass on purpose: every existing transport
    handler that treats a socket timeout as 'this attempt failed,
    do not blindly replay' applies verbatim to an exhausted budget."""


def enabled() -> bool:
    """Plane kill switch, read per call like the QoS switches so a
    test or an operator restart can flip it without import-order
    games."""
    return os.environ.get("WEED_DEADLINE", "1") != "0"


def default_budget_s() -> float:
    """Gateway-entry default budget (seconds); 0 = mint nothing."""
    try:
        return float(os.environ.get("WEED_DEADLINE_DEFAULT_S", "0") or 0)
    except ValueError:
        return 0.0


class Deadline:
    """An absolute point on the LOCAL monotonic clock."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + min(seconds, MAX_BUDGET_S))

    def remaining(self) -> float:
        return self.at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.at - time.monotonic() <= 0

    def cap(self, timeout: float | None) -> float:
        """Per-attempt/socket-op timeout derived from the remaining
        budget: min(timeout, remaining), floored so it stays a valid
        blocking timeout. Raises DeadlineExceeded when nothing
        remains — callers must not start work the budget can't pay
        for."""
        rem = self.at - time.monotonic()
        if rem <= 0:
            raise DeadlineExceeded(
                f"deadline exceeded ({rem * 1000.0:.0f} ms over budget)"
            )
        if timeout is None or timeout <= 0:
            return max(rem, MIN_OP_TIMEOUT_S)
        return max(min(timeout, rem), MIN_OP_TIMEOUT_S)

    def header_value(self) -> str:
        """Remaining budget as the on-wire millisecond text (may be
        negative: an expired deadline still propagates so the receiver
        can account the rejection)."""
        return "%.1f" % ((self.at - time.monotonic()) * 1000.0)

    def __repr__(self) -> str:  # debugging/test output only
        return f"Deadline(remaining={self.remaining() * 1000.0:.1f}ms)"


def from_header(value: str) -> Deadline | None:
    """Parse an `X-Weed-Deadline` header value (remaining ms).

    Garbage → None (an unparseable budget must not 504 a request that
    never asked for one); negative values parse to an already-expired
    Deadline — that is the fast-reject contract."""
    try:
        ms = float(value)
    except (TypeError, ValueError):
        return None
    return Deadline(time.monotonic() + min(ms / 1000.0, MAX_BUDGET_S))


def from_grpc_context(context) -> Deadline | None:
    """Deadline carried as gRPC invocation metadata, if any."""
    try:
        md = context.invocation_metadata()
    except Exception:  # noqa: BLE001 - a test double without metadata
        return None
    if md:
        for k, v in md:
            if k == DEADLINE_HEADER:
                return from_header(v)
    return None


# ---------------------------------------------------------------------------
# ambient (per-thread) deadline — the serving funnel installs the
# request's deadline here so every internal hop the handler makes
# (http_call, gRPC Stub, hedged reads) inherits it without threading a
# parameter through dozens of call sites. Mirrors trace's thread-cell
# pattern: one attribute read on the hot path.

_tls = threading.local()


def current() -> Deadline | None:
    return getattr(_tls, "deadline", None)


def set_current(dl: Deadline | None) -> None:
    _tls.deadline = dl


class scope:
    """`with scope(dl):` — install `dl` as the ambient deadline for the
    block, restoring the previous one on exit (internal hops nest:
    a narrower explicit deadline inside a request must not clobber the
    request's own on the way out)."""

    __slots__ = ("_dl", "_prev")

    def __init__(self, dl: Deadline | None):
        self._dl = dl

    def __enter__(self):
        self._prev = getattr(_tls, "deadline", None)
        _tls.deadline = self._dl
        return self._dl

    def __exit__(self, *exc):
        _tls.deadline = self._prev
        return False


def effective(explicit: "Deadline | None" = None) -> Deadline | None:
    """The deadline governing an outbound hop: an explicit one wins,
    else the ambient request deadline, else None. Returns None
    wholesale when the plane is disabled."""
    if not enabled():
        return None
    return explicit if explicit is not None else current()


def stamp(headers: dict, dl: Deadline | None = None) -> None:
    """Write the hop header from `dl` (default: the effective
    deadline); no-op when there is none."""
    dl = effective(dl)
    if dl is not None:
        headers[DEADLINE_HEADER] = dl.header_value()
