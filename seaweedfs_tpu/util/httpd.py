"""Shared HTTP server base for all daemons.

http.server.ThreadingHTTPServer defaults to a TCP accept backlog of 5
(socketserver.TCPServer.request_queue_size). Under a concurrency-16
load-generator burst (`weed benchmark -c 16`, the reference's headline
workload, command/benchmark.go:53) the backlog overflows, the kernel
drops SYNs, and clients stall in 1 s / 3 s retransmission steps — the
benchmark's p99 showed exactly those ~1 s / ~2 s spikes. The reference
never hits this because Go's net/http listens with the system's
somaxconn; a deep backlog restores that behavior.
"""

from __future__ import annotations

import json as _json
import socket
import time as _time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer  # FastRequestMixin drives these through serve_connection
from urllib.parse import unquote_plus

from seaweedfs_tpu import trace as _trace
from seaweedfs_tpu.stats.metrics import (
    DEADLINE_REJECTED,
    HTTP_REQUEST_COUNTER,
    HTTP_REQUEST_HISTOGRAM,
)
from seaweedfs_tpu.trace import blackbox as _blackbox
from seaweedfs_tpu.util import deadline as _deadline


# pre-encoded header block for fast_reply's bytes-headers contract —
# the data-plane's universal reply Content-Type
JSON_HDR = b"Content-Type: application/json\r\n"


def fast_query(qs: str) -> dict:
    """parse_qs-equivalent for the data plane's flat query strings:
    first value wins, blank values dropped, percent/plus decoding only
    when present (the stdlib pays regex + list machinery per call)."""
    q = {}
    if not qs:
        return q
    for part in qs.split("&"):
        k, _, v = part.partition("=")
        if not v:
            continue
        if "%" in k or "+" in k:
            k = unquote_plus(k)
        if "%" in v or "+" in v:
            v = unquote_plus(v)
        if k not in q:
            q[k] = v
    return q


class FastHeaders(dict):
    """Minimal case-insensitive header map (keys stored lowercased).

    Supports the `.get(name)` / `in` / `[name]` access the data-plane
    handlers use; deliberately NOT an email.message.Message (no MIME
    machinery — that parser is where the stdlib handler stack burns
    ~40% of a small-request's CPU)."""

    def get(self, key, default=None):
        # exact-hit first: hot call sites already pass lowercase names,
        # and str.lower() allocates on every miss-free access
        v = dict.get(self, key)
        if v is not None:
            return v
        return dict.get(self, key.lower(), default)

    def __getitem__(self, key):
        try:
            return dict.__getitem__(self, key)
        except KeyError:
            return dict.__getitem__(self, key.lower())

    def __contains__(self, key):
        return dict.__contains__(self, key) or dict.__contains__(
            self, key.lower()
        )


def encode_headers(headers: dict) -> bytearray:
    """Encode a header dict as the b"Name: value\\r\\n"... block, with
    request-derived CR/LF stripped so a hostile value can never split a
    response. The ONE header formatter: fast_reply uses it, and the
    zero-copy GET resolvers (server.fast_resolver, docs/SERVING.md)
    build their pre-formatted response prefixes through it — which is
    what makes C-path and Python-path responses byte-identical by
    construction, not by parallel maintenance."""
    buf = bytearray()
    for k, v in headers.items():
        line = f"{k}: {v}"
        if "\r" in line or "\n" in line:
            line = line.replace("\r", "").replace("\n", "")
        buf += line.encode("latin-1", "replace") + b"\r\n"
    return buf


def reply_prefix(status: int, headers: dict | None = None) -> bytes:
    """Status line + headers for a response the EVENT LOOP will finish:
    the C serving core appends the same `Connection: close` /
    `Content-Length` tail fast_reply writes, so a resolver that builds
    its prefix here yields responses byte-identical to the threaded
    path serving the same request."""
    buf = bytearray(b"HTTP/1.1 %d %s\r\n" % (status, _REASON.get(status, b"OK")))
    if headers:
        buf += encode_headers(headers)
    return bytes(buf)


def etag_matches(header_value, etag: str) -> bool:
    """RFC 9110 §13.1.2 If-None-Match evaluation: `*` matches any
    current representation, otherwise the value is a comma-separated
    list of entity-tags compared WEAKLY (a `W/` prefix on either side
    is ignored). The scanner is quote-aware — the etagc grammar allows
    commas inside a quoted tag, so a naive split would mis-tokenize.
    Malformed members (unterminated quote, bare token) never match.

    The C serving core (native/serve.c weed_etag_match) implements
    this exact scanner over the same bytes; keep the two in lockstep —
    the C-vs-Python identity matrix in tests/ diffs them."""
    if not header_value:
        return False
    v = header_value.strip()
    if v == "*":
        return True
    target = etag[2:] if etag.startswith("W/") else etag
    i, n = 0, len(v)
    while i < n:
        while i < n and v[i] in " \t,":
            i += 1
        if i >= n:
            break
        if v.startswith("W/", i):
            i += 2
        if i < n and v[i] == '"':
            j = v.find('"', i + 1)
            if j < 0:
                return False
            if v[i : j + 1] == target:
                return True
            i = j + 1
        else:
            j = v.find(",", i)
            if j < 0:
                return False
            i = j + 1
    return False


class FastRequestMixin:
    """Marks a handler as data-plane: WeedHTTPServer drives it through
    the mini request loop (serve_connection) instead of the stdlib
    socketserver/handler-per-request machinery, and fast_reply
    writes whole responses (status+headers+body) in ONE buffer/syscall
    — under `weed benchmark` the stdlib's email.feedparser header
    parsing plus send_header-per-line writing cost more than the
    needle append being measured. Head parsing (one-buffer scan,
    FastHeaders, keep-alive/Expect/431 semantics) lives in
    serve_connection — ONE parser, not two that drift."""

    def fast_reply(self, status: int, body: bytes = b"", headers=None) -> None:
        """status + headers + Content-Length + body in ONE write.

        `headers` may be a dict or pre-encoded header bytes
        (b"Name: value\\r\\n"...) — hot handlers pass module-level
        constants so nothing is formatted per request."""
        self._trace_status = status
        buf = bytearray(b"HTTP/1.1 %d %s\r\n" % (status, _REASON.get(status, b"OK")))
        if headers:
            if isinstance(headers, (bytes, bytearray)):
                buf += headers
            else:
                buf += encode_headers(headers)
        if self.close_connection:
            buf += b"Connection: close\r\n"
        buf += b"Content-Length: %d\r\n\r\n" % len(body)
        if body and self.command != "HEAD":
            if len(body) >= 65536:
                # big bodies skip the header+body concat copy: one
                # gathering sendmsg (same bytes on the wire) — the
                # threaded twin of the C loop's writev first flush
                wv = getattr(self.wfile, "writev", None)
                if wv is not None:
                    wv((bytes(buf), body))
                    self._note_sent(len(buf) + len(body))
                    return
            buf += body
        self.wfile.write(buf)
        self._note_sent(len(buf))

    def _note_sent(self, n: int) -> None:
        # wire-byte accounting for the flight recorder: the C fast path
        # reports bytes actually sent, so the threaded arm's wide-event
        # matches (only when the handler didn't already stamp a size —
        # the write path records the uploaded needle size instead)
        sp = getattr(self, "_trace_span", None)
        if sp is not None and not sp.nbytes:
            sp.nbytes = n

    # the stdlib slow paths (filer/master streaming replies) pass
    # through here — recording the code keeps span status and the
    # request-counter status label accurate on every reply shape
    def send_response(self, code, message=None):
        self._trace_status = code
        super().send_response(code, message)


_REASON = {
    200: b"OK",
    201: b"Created",
    202: b"Accepted",
    204: b"No Content",
    206: b"Partial Content",
    207: b"Multi-Status",
    301: b"Moved Permanently",
    302: b"Found",
    304: b"Not Modified",
    400: b"Bad Request",
    401: b"Unauthorized",
    403: b"Forbidden",
    404: b"Not Found",
    405: b"Method Not Allowed",
    409: b"Conflict",
    411: b"Length Required",
    413: b"Payload Too Large",
    416: b"Range Not Satisfiable",
    429: b"Too Many Requests",
    431: b"Request Header Fields Too Large",
    500: b"Internal Server Error",
    501: b"Not Implemented",
    502: b"Bad Gateway",
    503: b"Service Unavailable",
    504: b"Gateway Timeout",
}


class FastHandler(FastRequestMixin, BaseHTTPRequestHandler):
    """The one handler base every serving path derives from: marked
    with FastRequestMixin so WeedHTTPServer drives it through the mini
    request loop (serve_connection), with the quiet log and HTTP/1.1
    keep-alive every daemon wants. Subclasses just define do_*."""

    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # the data plane logs via wlog
        pass


class _BufReader:
    """Minimal buffered reader over a socket for the mini request loop:
    one recv fills a buffer; the request head is scanned out of it in
    one pass, bodies and chunk lines drain it before hitting the
    socket again. Tracks total consumed bytes so the connection loop
    can realign (or bail) when a handler leaves body bytes unread.

    `deadline` (client-side pooled transport only): when set, every
    refill re-arms the socket timeout to min(op_timeout, remaining
    budget) and an exhausted budget raises DeadlineExceeded — this is
    what turns the per-socket-op timeout into a true whole-request
    bound (a server trickling one byte per timeout window used to
    reset the clock on every recv)."""

    __slots__ = ("_sock", "_buf", "_pos", "consumed", "deadline", "op_timeout")

    def __init__(self, sock, initial: bytes = b""):
        # `initial`: bytes already read off the socket by whoever owned
        # the connection before (the C epoll loop hands a connection
        # off WITH the unconsumed tail of its read buffer)
        self._sock = sock
        self._buf = initial
        self._pos = 0
        self.consumed = 0
        self.deadline = None
        self.op_timeout = None

    def _fill(self) -> bool:
        dl = self.deadline
        if dl is not None:
            # raises DeadlineExceeded once the whole-request budget is
            # spent; otherwise shrinks this recv's window to what's left
            self._sock.settimeout(dl.cap(self.op_timeout))
        chunk = self._sock.recv(65536)
        if not chunk:
            return False
        if self._pos:
            self._buf = self._buf[self._pos :] + chunk
            self._pos = 0
        else:
            self._buf += chunk
        return True

    def read_head(self, limit: int = 131072) -> bytes | None:
        """Bytes up to and including the blank line; None on clean EOF
        before any byte; raises ValueError past `limit` (431)."""
        while True:
            idx = self._buf.find(b"\r\n\r\n", self._pos)
            if idx >= 0:
                head = self._buf[self._pos : idx + 4]
                # the limit applies to COMPLETE heads too: when the
                # whole oversized head coalesces into the buffer before
                # the first parse attempt (one big recv, or a C-loop
                # handoff's initial bytes), find() succeeds and the
                # incomplete-head check below never runs — the request
                # would serve as 200 instead of 431 (timing-dependent:
                # caught by the oversized-head test flaking under load)
                if len(head) > limit:
                    raise ValueError("request head too large")
                self._pos = idx + 4
                self.consumed += len(head)
                return head
            if len(self._buf) - self._pos > limit:
                raise ValueError("request head too large")
            if not self._fill():
                return None if len(self._buf) == self._pos else b""

    def read(self, n: int | None = None) -> bytes:
        if n is None:  # EOF-delimited (HTTP/1.0-style bodies)
            while self._fill():
                pass
            out = self._buf[self._pos :]
            self._pos = len(self._buf)
            self.consumed += len(out)
            return out
        avail = len(self._buf) - self._pos
        while avail < n:
            if not self._fill():
                break
            avail = len(self._buf) - self._pos
        out = self._buf[self._pos : self._pos + n]
        self._pos += len(out)
        self.consumed += len(out)
        return out

    def readline(self, limit: int = 65537) -> bytes:
        while True:
            idx = self._buf.find(b"\n", self._pos)
            if idx >= 0 and idx - self._pos < limit:
                out = self._buf[self._pos : idx + 1]
                self._pos = idx + 1
                self.consumed += len(out)
                return out
            if idx < 0 and len(self._buf) - self._pos >= limit:
                out = self._buf[self._pos : self._pos + limit]
                self._pos += limit
                self.consumed += limit
                return out
            if not self._fill():
                out = self._buf[self._pos :]
                self._pos = len(self._buf)
                self.consumed += len(out)
                return out


class _SockWriter:
    """wfile facade: sendall semantics (a raw SocketIO.write may short-
    write large bodies), no buffering to flush.

    With `-serveIdleMs` arming a socket timeout, a plain sendall would
    turn the IDLE timeout into a total-transfer deadline (CPython
    computes ONE deadline for the whole call) and truncate big
    downloads to slow-but-draining clients — worse, TCP only reports
    *writable* once the send queue falls below half full, so even
    per-chunk sendalls time out while the client is sipping a multi-MB
    kernel buffer. send() itself has no such threshold: it accepts
    bytes whenever ANY space exists. So on a timeout we retry the
    send once — moved bytes mean a live client (keep going with a
    fresh window); a zero-progress retry after a full idle window of
    waiting is a true stall and raises. Mirrors the C loop's
    idle-reaper drain probe (serve.c weed_conn_flush_step)."""

    __slots__ = ("_sock",)

    _CHUNK = 1 << 18

    def __init__(self, sock):
        self._sock = sock

    def write(self, data) -> int:
        n = len(data)
        view = memoryview(data)
        pos = 0
        stalled = False
        while pos < n:
            try:
                sent = self._sock.send(view[pos : pos + self._CHUNK])
            except TimeoutError:
                # the client freed no space for a whole idle window;
                # one more zero-progress window confirms the stall
                if stalled:
                    raise
                stalled = True
                continue
            if sent > 0:
                pos += sent
                stalled = False
        return n

    def writev(self, bufs) -> int:
        """Gathering write: header + body land in ONE sendmsg syscall
        (the threaded path's twin of the C loop's writev reply).
        Whatever the kernel didn't take drains through the chunked
        write() loop above, preserving its stall semantics."""
        total = 0
        for b in bufs:
            total += len(b)
        try:
            sent = self._sock.sendmsg(bufs)
        except TimeoutError:
            sent = 0
        if sent >= total:
            return total
        for b in bufs:
            blen = len(b)
            if sent >= blen:
                sent -= blen
                continue
            self.write(memoryview(b)[sent:] if sent else b)
            sent = 0
        return total

    def flush(self) -> None:
        pass


def _deadline_scoped(method, dl):
    """Dispatch wrapper installing `dl` as the ambient deadline for
    exactly this request's handler, so internal hops (http_call, gRPC
    stubs, hedged reads) inherit the remaining budget for free."""

    def run(h, _m=method, _dl=dl):
        _deadline.set_current(_dl)
        try:
            return _m(h)
        finally:
            _deadline.set_current(None)

    return run


def _expired_reject(h) -> None:
    """Stand-in handler for a request whose X-Weed-Deadline arrived
    already expired: 504 without touching disk or fanning out. Dispatch
    runs it like any handler, so the span (annotated, no work stages)
    and the 504-labelled request counter are the rejection's audit
    trail."""
    sp = getattr(h, "_trace_span", None)
    if sp is not None:
        sp.annotate("deadline", "expired-at-entry")
    DEADLINE_REJECTED.labels(
        getattr(h.server, "trace_name", "") or "server"
    ).inc()
    # an expired request's body may never arrive in full (the client
    # has given up); never trust this connection for another request
    h.close_connection = True
    h.fast_reply(
        504, b'{"error": "x-weed-deadline expired before dispatch"}', JSON_HDR
    )


_DISPATCH_CACHE: dict[type, dict] = {}


def _dispatch_table(handler_cls: type) -> dict:
    table = _DISPATCH_CACHE.get(handler_cls)
    if table is None:
        table = {
            name[3:]: getattr(handler_cls, name)
            for name in dir(handler_cls)
            if name.startswith("do_")
        }
        _DISPATCH_CACHE[handler_cls] = table
    return table


def serve_connection(
    sock, addr, server, handler_cls, initial: bytes = b"", initial_reqs: int = 0
) -> None:
    """The mini per-connection request loop: replaces the
    socketserver → handle → handle_one_request → parse_request stack
    on every serving path. One handler object per connection (no
    per-request construction), the whole request head read and parsed
    out of one buffer (no per-header readline), dict dispatch instead
    of getattr-per-request. The handler classes are unchanged — this
    drives the same do_GET/do_POST/... methods with the same surface
    (path/command/headers/rfile/wfile/client_address/close_connection,
    fast_reply, and the inherited stdlib send_response/send_header/
    end_headers/send_error for the slow paths).

    `initial` seeds the read buffer with bytes a previous owner of the
    connection already consumed off the wire — the C epoll loop
    (docs/SERVING.md) hands non-fast-path connections off here with
    the current request head onward."""
    h = handler_cls.__new__(handler_cls)  # skip the stdlib per-request __init__
    h.server = server
    h.client_address = addr
    h.connection = sock
    reader = _BufReader(sock, initial)
    h.rfile = reader
    h.wfile = _SockWriter(sock)
    table = _dispatch_table(handler_cls)
    proto11 = handler_cls.protocol_version >= "HTTP/1.1"
    # keep-alive housekeeping knobs (`-serveIdleMs` / `-serveMaxReqs`),
    # honored identically by this loop and the C epoll loop: a socket
    # timeout bounds idle keep-alive connections (the except arm below
    # already treats TimeoutError as end-of-connection), and max_reqs
    # closes after N responses (Connection: close on the Nth)
    idle_ms = getattr(server, "serve_idle_ms", 0)
    if idle_ms and idle_ms > 0:
        try:
            sock.settimeout(idle_ms / 1000.0)
        except OSError:
            return
    max_reqs = getattr(server, "serve_max_reqs", 0) or 0
    nreqs = initial_reqs  # responses a prior owner (the C loop) served
    # tracing/metrics identity is per-server, not per-request: resolve
    # it once per connection, and hoist every module/attribute the
    # traced dispatch touches into locals — the per-request cost of
    # tracing is dominated by cold cache lines (distinct shared
    # objects touched), so the loop below reads only its own warm
    # frame (docs/TRACING.md)
    trace_label = getattr(server, "trace_name", "")
    trace_node = getattr(server, "trace_node", "")
    gateway_metrics = getattr(server, "gateway_metrics", False)
    debug_gate = getattr(server, "debug_gate", None)
    # QoS plane (docs/QOS.md): this dispatch funnel is the ONE place
    # every daemon's requests pass through (including C-epoll-loop
    # handoffs), so the in-flight load signal and per-client admission
    # control live here. Both default to None — the common path pays
    # one is-None check per request.
    admission = getattr(server, "admission", None)
    load_tracker = getattr(server, "load_tracker", None)
    # deadline plane (docs/CHAOS.md): this same funnel parses the
    # X-Weed-Deadline hop header on every daemon, fast-rejects expired
    # requests with 504 BEFORE dispatch, and installs the budget as
    # the ambient deadline so every internal hop the handler makes
    # inherits it. deadline_default_s set on the server wins; None
    # falls back to the WEED_DEADLINE_DEFAULT_S gateway-entry default.
    ddl_enabled = _deadline.enabled()
    ddl_default = getattr(server, "deadline_default_s", None)
    if ddl_default is None:
        ddl_default = _deadline.default_budget_s()
    ddl_hdr_key = _deadline.DEADLINE_HEADER
    if admission is not None or load_tracker is not None:
        def qos_dispatch(method, h, _adm=admission, _lt=load_tracker):
            if _lt is not None:
                _lt.enter()
            try:
                if _adm is not None:
                    return _adm.gate(method, h)
                return method(h)
            finally:
                if _lt is not None:
                    _lt.exit()
    else:
        qos_dispatch = None
    trace_enabled = _trace.enabled
    span_open, span_close, sample_hit = _trace.connection_tracer(trace_node)
    trace_hdr_key = _trace.TRACE_HEADER
    clock = _time.perf_counter
    hist_observe = HTTP_REQUEST_HISTOGRAM.observe
    put_exemplar = HTTP_REQUEST_HISTOGRAM.put_exemplar
    counter_labels = HTTP_REQUEST_COUNTER.labels
    # weedscope flight recorder (trace/blackbox.py): one wide-event per
    # completed request on BOTH dispatch arms; the closure holds every
    # object the record path touches (WEED_SCOPE=0 → one global check)
    bb_record = _blackbox.recorder(trace_label, trace_node)
    bb_flags = _blackbox.request_flags
    peer = addr[0] if isinstance(addr, tuple) else str(addr)
    span_names: dict[str, str] = {}  # method -> span name, per-conn
    try:
        while True:
            # error replies (fast_reply) read command/close_connection;
            # arm them before any read/parse step can bail (and clear a
            # previous keep-alive request's values)
            h.command = None
            h.close_connection = True
            h._trace_status = 0
            try:
                head = reader.read_head()
            except ValueError:
                h.fast_reply(431)
                return
            if not head:
                return
            lines = head[:-4].decode("iso-8859-1").split("\r\n")
            requestline = lines[0]
            words = requestline.split()
            h.requestline = requestline
            if len(words) == 3:
                command, path, version = words
                if not version.startswith("HTTP/"):
                    _bad_request(h, f"Bad request version ({version!r})")
                    return
            elif len(words) == 2 and words[0] == "GET":
                command, path = words
                version = "HTTP/0.9"
            else:
                _bad_request(h, f"Bad request syntax ({requestline!r})")
                return
            h.command = command
            h.path = path
            h.request_version = version
            close = version <= "HTTP/1.0"

            headers = FastHeaders()
            for line in lines[1:]:
                key, sep, value = line.partition(":")
                if sep:
                    headers[key.strip().lower()] = value.strip()
            h.headers = headers

            conn = headers.get("connection", "").lower()
            if conn == "close":
                close = True
            elif conn == "keep-alive":
                close = False
            h.close_connection = close
            nreqs += 1
            if max_reqs and nreqs >= max_reqs:
                # the Nth response carries Connection: close; set it
                # BEFORE dispatch so fast_reply writes the header
                h.close_connection = True

            method = table.get(command)
            if method is None:
                h.close_connection = True
                h.fast_reply(405)
                return

            # body accounting: a handler that returns without draining
            # its request body would desync the next request on this
            # connection — skip small remainders, close otherwise
            try:
                length = int(headers.get("content-length", 0) or 0)
            except ValueError:
                _bad_request(h, "Bad Content-Length")
                return
            chunked = "chunked" in headers.get("transfer-encoding", "").lower()
            body_end = reader.consumed + length

            # deadline plane: an already-expired budget is rejected
            # HERE — before the 100-continue invite, before admission
            # spends a token, before the handler touches disk. The
            # reject rides the normal dispatch seam so the span and
            # status-labelled request counter record the 504 — but it
            # BYPASSES the admission gate below (an expired request
            # must never drain a client's token bucket, and a dry
            # bucket's 503 + Retry-After would invite the client to
            # retry work it already abandoned).
            h._deadline = None
            if ddl_enabled:
                dhv = headers.get(ddl_hdr_key)
                dl = _deadline.from_header(dhv) if dhv is not None else None
                if dl is None and ddl_default > 0:
                    dl = _deadline.Deadline.after(ddl_default)
                if dl is not None:
                    h._deadline = dl
                    if dl.expired:
                        method = _expired_reject
                    else:
                        method = _deadline_scoped(method, dl)

            # 100 Continue goes out only AFTER the request validates:
            # a bad Content-Length (400 above), an unknown method
            # (405), or an oversized head (431, in read_head) must
            # reject the request outright — an interim 100 first would
            # invite the client to stream a body this connection is
            # about to slam the door on (and on a reused keep-alive
            # connection would desync the error reply that follows)
            if (
                proto11
                and version >= "HTTP/1.1"
                and headers.get("expect", "").lower() == "100-continue"
            ):
                sock.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")

            # tracing plane (docs/TRACING.md): the mini loop is the ONE
            # place every serving daemon's dispatch funnels through, so
            # span minting/inheritance, the /debug/* operator surface,
            # and the per-request metrics live here — volume, master,
            # filer, workers, S3, and WebDAV all get them at once.
            bare = path.partition("?")[0]
            if (
                command == "GET"
                and (
                    bare in (
                        "/debug/traces",
                        "/debug/requests",
                        "/debug/profile",
                        "/debug/blackbox",
                    )
                    or bare.startswith("/capsule/")
                    or (bare == "/metrics" and gateway_metrics)
                )
                # an auth-fronted gateway vetoes the interception
                # (debug_gate False → the request falls through to the
                # handler's own authenticated routing)
                and (debug_gate is None or debug_gate(h))
            ):
                _serve_debug(h, bare)
            elif trace_enabled() and (
                (hdr := headers.get(trace_hdr_key)) is not None
                or sample_hit()
            ):
                t0 = clock()
                name = span_names.get(command)
                if name is None:
                    name = span_names.setdefault(
                        command, f"{trace_label or 'http'}.{command.lower()}"
                    )
                sp = span_open(name, hdr, length, t0)
                h._trace_span = sp if sp else None
                try:
                    if qos_dispatch is None or method is _expired_reject:
                        method(h)
                    else:
                        qos_dispatch(method, h)
                finally:
                    if sp:  # falsy when the tracer flipped off mid-open
                        span_close(sp, h._trace_status)
                # a real span's duration IS the dispatch latency —
                # reuse it instead of a second clock pair
                dur = sp.duration if sp else clock() - t0
                if trace_label:
                    hist_observe(dur, trace_label, command)
                    counter_labels(
                        trace_label, command, str(h._trace_status)
                    ).inc()
                    if sp:
                        # bucket exemplar: this trace id is the one an
                        # operator can paste into /debug/traces
                        put_exemplar(dur, sp.trace_id, trace_label, command)
                bb_record(
                    command,
                    sp.trace_id if sp else "",
                    sp.plane if sp else "serve",
                    h._trace_status,
                    dur,
                    sp.nbytes if sp else 0,
                    peer,
                    bb_flags(headers, h._trace_status),
                    sp.stages if sp else None,
                )
            else:
                h._trace_span = None
                t0 = clock()
                if qos_dispatch is None or method is _expired_reject:
                    method(h)
                else:
                    qos_dispatch(method, h)
                dur = clock() - t0
                if trace_label:
                    hist_observe(dur, trace_label, command)
                    counter_labels(
                        trace_label, command, str(h._trace_status)
                    ).inc()
                bb_record(
                    command,
                    "",
                    "serve",
                    h._trace_status,
                    dur,
                    0,
                    peer,
                    bb_flags(headers, h._trace_status),
                    None,
                )

            # health plane (docs/HEALTH.md): 5xx responses feed the
            # heartbeat request_errors counter the master's per-node
            # error EWMA scores — a reachable-but-failing node goes
            # suspect without anyone staring at logs. 503 (admission /
            # lame-duck shed) and 504 (expired client deadline) are
            # CLIENT-attributable by design and excluded: one client
            # over its token bucket or stamping stale budgets must not
            # be able to drive a healthy node suspect cluster-wide.
            if (
                load_tracker is not None
                and h._trace_status >= 500
                and h._trace_status not in (503, 504)
            ):
                load_tracker.note_error()

            if chunked:
                # can't know from here whether the terminal chunk was
                # consumed; never reuse the connection
                return
            if reader.consumed < body_end:
                if body_end - reader.consumed <= 1 << 20:
                    reader.read(body_end - reader.consumed)
                else:
                    return
            if h.close_connection:
                return
    except (ConnectionError, BrokenPipeError, TimeoutError, OSError):
        pass


def _serve_debug(h, bare: str) -> None:
    """The tracing plane's operator endpoints, served uniformly on
    every daemon by the mini loop itself (no per-server routing to
    drift): `/debug/traces` (recent + slowest-N completed spans,
    ?n= caps the recent list), `/debug/requests` (in-flight dump),
    `/debug/blackbox` (the weedscope flight recorder's tail + sampled-OK
    rings), the `/capsule/*` incident-capsule surface, and — on servers
    that opt in via `server.gateway_metrics` (the S3 and WebDAV
    gateways, whose handlers have no routing slot for it) — `/metrics`
    Prometheus text exposition."""
    if bare == "/metrics":
        from seaweedfs_tpu.stats.metrics import DEFAULT_REGISTRY

        return h.fast_reply(
            200,
            DEFAULT_REGISTRY.render_text().encode(),
            {"Content-Type": "text/plain; version=0.0.4"},
        )
    if bare == "/debug/profile":
        # continuous sampling profiler (telemetry/profiler.py):
        # ?seconds=S captures the NEXT S seconds (capped; parks only
        # this operator connection's thread), ?fmt=folded emits
        # flamegraph.pl input instead of JSON
        from seaweedfs_tpu.telemetry import profiler

        q = fast_query(h.path.partition("?")[2])
        try:
            seconds = float(q.get("seconds", "1"))
        except ValueError:
            seconds = 1.0
        payload = profiler.capture(max(0.0, min(seconds, 30.0)))
        payload["node"] = getattr(h.server, "trace_node", "") or payload.get(
            "node", ""
        )
        if q.get("fmt") == "folded":
            return h.fast_reply(
                200,
                profiler.render_folded(payload).encode(),
                {"Content-Type": "text/plain; charset=utf-8"},
            )
        return h.fast_reply(200, _json.dumps(payload).encode(), JSON_HDR)
    if bare == "/debug/blackbox":
        q = fast_query(h.path.partition("?")[2])
        try:
            n = int(q.get("n", "256"))
        except ValueError:
            n = 256
        return h.fast_reply(
            200, _json.dumps(_blackbox.snapshot(n)).encode(), JSON_HDR
        )
    if bare.startswith("/capsule/"):
        return _serve_capsule(h, bare)
    if bare == "/debug/requests":
        payload = _trace.inflight_payload()
    else:
        q = fast_query(h.path.partition("?")[2])
        try:
            n = int(q.get("n", "64"))
        except ValueError:
            n = 64
        payload = _trace.debug_payload(n)
    h.fast_reply(200, _json.dumps(payload).encode(), JSON_HDR)


def _serve_capsule(h, bare: str) -> None:
    """Per-node incident-capsule surface (telemetry/capsule.py), served
    by every daemon: `/capsule/capture?reason=R` snapshots the node's
    evidence NOW (the leader's CaptureCoordinator dials this on every
    implicated peer when an alert fires), `/capsule/list` returns the
    valid manifests, `/capsule/get?id=I&file=F` streams one capsule
    file for leader-side `capsule.collect` merging."""
    from seaweedfs_tpu.telemetry import capsule

    q = fast_query(h.path.partition("?")[2])
    if bare == "/capsule/capture":
        trigger = q.get("trigger", "manual")
        if trigger not in ("manual", "alert"):  # bound the label set
            trigger = "manual"
        manifest = capsule.capture(
            q.get("reason", "http"),
            trigger=trigger,
            node=getattr(h.server, "trace_node", ""),
        )
        return h.fast_reply(200, _json.dumps(manifest).encode(), JSON_HDR)
    if bare == "/capsule/list":
        return h.fast_reply(
            200,
            _json.dumps({"Capsules": capsule.list_capsules()}).encode(),
            JSON_HDR,
        )
    if bare == "/capsule/get":
        data = capsule.read_file(q.get("id", ""), q.get("file", ""))
        if data is None:
            return h.fast_reply(
                404, b'{"error": "no such capsule file"}', JSON_HDR
            )
        return h.fast_reply(
            200, data, {"Content-Type": "application/octet-stream"}
        )
    return h.fast_reply(404, b'{"error": "unknown capsule route"}', JSON_HDR)


def _bad_request(h, msg: str) -> None:
    h.close_connection = True
    h.request_version = "HTTP/1.1"
    h.fast_reply(400, msg.encode("latin-1", "replace"))


class WeedHTTPServer(ThreadingHTTPServer):
    # deep accept backlog: under a connection burst (256+ concurrent
    # weedload workers) a shallow backlog drops SYNs into 1s/3s
    # retransmission steps; the epoll loop drains it every listen event
    request_queue_size = 1024

    # keep-alive housekeeping knobs (`-serveIdleMs`/`-serveMaxReqs`),
    # enforced by BOTH serving paths (C epoll loop + threaded mini
    # loop); 0 = disabled
    serve_idle_ms = 0
    serve_max_reqs = 0

    # zero-copy GET fast path (docs/SERVING.md): the owning daemon may
    # install `fast_resolver(path, range, head_only) -> plan | None`
    # before serve_forever; None means every request takes the handoff
    # path into the threaded mini loop
    fast_resolver = None

    # QoS plane (docs/QOS.md): the owning daemon may install a
    # qos.admission.AdmissionController (per-client shed with 503 +
    # Retry-After) and/or a qos.LoadTracker (in-flight count for the
    # heartbeat load signal); None = today's behavior
    admission = None
    load_tracker = None

    # deadline plane (docs/CHAOS.md): budget (seconds) minted at entry
    # for requests arriving WITHOUT an X-Weed-Deadline header; None
    # defers to the WEED_DEADLINE_DEFAULT_S env knob, 0 mints nothing
    deadline_default_s = None

    def get_request(self):
        # TCP_NODELAY: keep-alive responses are written headers-then-
        # body; with Nagle on, the body segment waits for the client's
        # delayed ACK (~40 ms) — the whole data plane flatlines at the
        # delayed-ACK timer instead of wire speed
        sock, addr = super().get_request()
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        return sock, addr

    def serve_forever(self, poll_interval=0.5):
        # event-driven serving core (docs/SERVING.md): when the native
        # epoll loop is built and WEED_NATIVE_SERVE != 0, it owns the
        # accept/read/dispatch edge — fast-path GETs never leave C,
        # everything else hands off into serve_connection threads.
        # The threaded socketserver path below is the byte-identical
        # fallback (and the kill switch's landing spot).
        from seaweedfs_tpu.util import native_serve

        if native_serve.try_serve_forever(self):
            return
        super().serve_forever(poll_interval)

    def shutdown(self):
        from seaweedfs_tpu.util import native_serve

        if native_serve.shutdown(self):
            return
        if native_serve.available() and getattr(self, "native_serve", True):
            # start/stop race (caught by the -workers admission tests'
            # fast teardown): the serve thread WILL choose the native
            # loop — the predicate is deterministic — but may not have
            # armed _serve_native yet. Falling through to
            # socketserver.shutdown() here waits forever on an
            # __is_shut_down event the stdlib loop (which never runs)
            # will never set. Wait for the arming instead; a False
            # marker means native setup failed and the thread fell
            # back to the stdlib loop, which CAN be shut down.
            deadline = _time.monotonic() + 5.0
            while _time.monotonic() < deadline:
                state = getattr(self, "_serve_native", None)
                if state:
                    if native_serve.shutdown(self):
                        return
                if state is False:
                    break  # threaded fallback owns the socket
                _time.sleep(0.001)
        super().shutdown()

    def finish_request(self, request, client_address):
        # every in-repo serving path carries FastRequestMixin and rides
        # the mini request loop (volume, master, workers, filer, s3,
        # webdav); the hasattr gate only guards external/test handlers
        if hasattr(self.RequestHandlerClass, "fast_reply"):
            serve_connection(
                request, client_address, self, self.RequestHandlerClass
            )
        else:
            super().finish_request(request, client_address)


class ReusePortWeedHTTPServer(WeedHTTPServer):
    """SO_REUSEPORT listener for processes sharing one host:port
    (`volume -workers N`, gateway `-serveProcs N`); every binder of the
    port must set the option, so lead and workers use this same class.

    server_bind sets the option explicitly: socketserver only learned
    `allow_reuse_port` in Python 3.11, so relying on the class attr
    silently binds WITHOUT it on 3.10 — the second process then dies
    with EADDRINUSE instead of sharing the accept load."""

    allow_reuse_port = True  # honored natively on 3.11+

    def server_bind(self):
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()
