"""Shared HTTP server base for all daemons.

http.server.ThreadingHTTPServer defaults to a TCP accept backlog of 5
(socketserver.TCPServer.request_queue_size). Under a concurrency-16
load-generator burst (`weed benchmark -c 16`, the reference's headline
workload, command/benchmark.go:53) the backlog overflows, the kernel
drops SYNs, and clients stall in 1 s / 3 s retransmission steps — the
benchmark's p99 showed exactly those ~1 s / ~2 s spikes. The reference
never hits this because Go's net/http listens with the system's
somaxconn; a deep backlog restores that behavior.
"""

from __future__ import annotations

import socket
from http.server import ThreadingHTTPServer


class WeedHTTPServer(ThreadingHTTPServer):
    request_queue_size = 256

    def get_request(self):
        # TCP_NODELAY: keep-alive responses are written headers-then-
        # body; with Nagle on, the body segment waits for the client's
        # delayed ACK (~40 ms) — the whole data plane flatlines at the
        # delayed-ACK timer instead of wire speed
        sock, addr = super().get_request()
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        return sock, addr
