"""Shared HTTP server base for all daemons.

http.server.ThreadingHTTPServer defaults to a TCP accept backlog of 5
(socketserver.TCPServer.request_queue_size). Under a concurrency-16
load-generator burst (`weed benchmark -c 16`, the reference's headline
workload, command/benchmark.go:53) the backlog overflows, the kernel
drops SYNs, and clients stall in 1 s / 3 s retransmission steps — the
benchmark's p99 showed exactly those ~1 s / ~2 s spikes. The reference
never hits this because Go's net/http listens with the system's
somaxconn; a deep backlog restores that behavior.
"""

from __future__ import annotations

import socket
from http.server import ThreadingHTTPServer
from urllib.parse import unquote_plus


# pre-encoded header block for fast_reply's bytes-headers contract —
# the data-plane's universal reply Content-Type
JSON_HDR = b"Content-Type: application/json\r\n"


def fast_query(qs: str) -> dict:
    """parse_qs-equivalent for the data plane's flat query strings:
    first value wins, blank values dropped, percent/plus decoding only
    when present (the stdlib pays regex + list machinery per call)."""
    q = {}
    if not qs:
        return q
    for part in qs.split("&"):
        k, _, v = part.partition("=")
        if not v:
            continue
        if "%" in k or "+" in k:
            k = unquote_plus(k)
        if "%" in v or "+" in v:
            v = unquote_plus(v)
        if k not in q:
            q[k] = v
    return q


class FastHeaders(dict):
    """Minimal case-insensitive header map (keys stored lowercased).

    Supports the `.get(name)` / `in` / `[name]` access the data-plane
    handlers use; deliberately NOT an email.message.Message (no MIME
    machinery — that parser is where BaseHTTPRequestHandler burns ~40%
    of a small-request's CPU)."""

    def get(self, key, default=None):
        # exact-hit first: hot call sites already pass lowercase names,
        # and str.lower() allocates on every miss-free access
        v = dict.get(self, key)
        if v is not None:
            return v
        return dict.get(self, key.lower(), default)

    def __getitem__(self, key):
        try:
            return dict.__getitem__(self, key)
        except KeyError:
            return dict.__getitem__(self, key.lower())

    def __contains__(self, key):
        return dict.__contains__(self, key) or dict.__contains__(
            self, key.lower()
        )


class FastRequestMixin:
    """Drop-in replacement for BaseHTTPRequestHandler.parse_request on
    hot data-plane handlers, plus a one-syscall reply writer.

    The stdlib parses headers through email.feedparser (policy objects,
    universal newlines, MIME semantics) and writes responses one
    send_header() call at a time; under `weed benchmark` both together
    cost more than the actual needle append. This mixin parses headers
    with a split-on-colon loop into FastHeaders and assembles whole
    responses in one bytes buffer. Semantics kept: HTTP/1.0 vs 1.1
    keep-alive defaults, Connection: close/keep-alive, Expect:
    100-continue, 414/431 guards (matching net/http's behavior the
    reference leans on)."""

    def parse_request(self) -> bool:  # noqa: C901 - protocol state machine
        self.command = None
        self.request_version = version = self.default_request_version
        self.close_connection = True
        requestline = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        words = requestline.split()
        if len(words) == 3:
            command, path, version = words
            if not version.startswith("HTTP/"):
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            self.request_version = version
            self.close_connection = version <= "HTTP/1.0"
        elif len(words) == 2:
            command, path = words  # HTTP/0.9 GET
            if command != "GET":
                self.send_error(400, f"Bad HTTP/0.9 request type ({command!r})")
                return False
        else:
            self.send_error(400, f"Bad request syntax ({requestline!r})")
            return False
        self.command, self.path = command, path

        headers = FastHeaders()
        rfile = self.rfile
        total = 0
        while True:
            line = rfile.readline(65537)
            if len(line) > 65536:
                self.send_error(431, "Line too long")
                return False
            total += len(line)
            if total > 131072:
                self.send_error(431, "Too many headers")
                return False
            if line in (b"\r\n", b"\n", b""):
                break
            key, sep, value = line.decode("iso-8859-1").partition(":")
            if sep:
                headers[key.strip().lower()] = value.strip()
        self.headers = headers

        conn = headers.get("connection", "").lower()
        if conn == "close":
            self.close_connection = True
        elif conn == "keep-alive":
            self.close_connection = False
        if (
            headers.get("expect", "").lower() == "100-continue"
            and self.protocol_version >= "HTTP/1.1"
            and self.request_version >= "HTTP/1.1"
        ):
            self.wfile.write(b"HTTP/1.1 100 Continue\r\n\r\n")
        return True

    def fast_reply(self, status: int, body: bytes = b"", headers=None) -> None:
        """status + headers + Content-Length + body in ONE write.

        `headers` may be a dict or pre-encoded header bytes
        (b"Name: value\\r\\n"...) — hot handlers pass module-level
        constants so nothing is formatted per request."""
        buf = bytearray(b"HTTP/1.1 %d %s\r\n" % (status, _REASON.get(status, b"OK")))
        if headers:
            if isinstance(headers, (bytes, bytearray)):
                buf += headers
            else:
                for k, v in headers.items():
                    line = f"{k}: {v}"
                    if "\r" in line or "\n" in line:
                        # request-derived values (URL filenames, stored
                        # pairs) must never split the response
                        line = line.replace("\r", "").replace("\n", "")
                    buf += line.encode("latin-1", "replace") + b"\r\n"
        if self.close_connection:
            buf += b"Connection: close\r\n"
        buf += b"Content-Length: %d\r\n\r\n" % len(body)
        if body and self.command != "HEAD":
            buf += body
        self.wfile.write(buf)


_REASON = {
    200: b"OK",
    201: b"Created",
    202: b"Accepted",
    204: b"No Content",
    206: b"Partial Content",
    301: b"Moved Permanently",
    302: b"Found",
    304: b"Not Modified",
    400: b"Bad Request",
    401: b"Unauthorized",
    404: b"Not Found",
    405: b"Method Not Allowed",
    409: b"Conflict",
    413: b"Payload Too Large",
    416: b"Range Not Satisfiable",
    429: b"Too Many Requests",
    500: b"Internal Server Error",
    503: b"Service Unavailable",
}


class WeedHTTPServer(ThreadingHTTPServer):
    request_queue_size = 256

    def get_request(self):
        # TCP_NODELAY: keep-alive responses are written headers-then-
        # body; with Nagle on, the body segment waits for the client's
        # delayed ACK (~40 ms) — the whole data plane flatlines at the
        # delayed-ACK timer instead of wire speed
        sock, addr = super().get_request()
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        return sock, addr


class ReusePortWeedHTTPServer(WeedHTTPServer):
    """SO_REUSEPORT listener for per-core worker processes sharing one
    host:port (`volume -workers N`); every binder of the port must set
    the option, so lead and workers use this same class."""

    allow_reuse_port = True
