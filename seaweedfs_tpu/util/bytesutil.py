"""Big-endian integer codecs.

The entire SeaweedFS on-disk/wire ABI is big-endian
(reference: weed/util/bytes.go — "// big endian"). These helpers are the
single place that encodes that choice.
"""

from __future__ import annotations


def put_u64(v: int) -> bytes:
    return (v & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big")


def put_u32(v: int) -> bytes:
    return (v & 0xFFFFFFFF).to_bytes(4, "big")


def put_u16(v: int) -> bytes:
    return (v & 0xFFFF).to_bytes(2, "big")


def get_u64(b: bytes, off: int = 0) -> int:
    return int.from_bytes(b[off : off + 8], "big")


def get_u32(b: bytes, off: int = 0) -> int:
    return int.from_bytes(b[off : off + 4], "big")


def get_u16(b: bytes, off: int = 0) -> int:
    return int.from_bytes(b[off : off + 2], "big")


def get_uint(b: bytes) -> int:
    """Variable-length big-endian read (any byte length ≥ 1)."""
    return int.from_bytes(b, "big")
