"""Read-availability harness: hammer a keyset through an HTTP endpoint
while a cluster transition (EC migration, rebalance, vacuum) runs
underneath, recording every latency and every failure.

Used by tests/test_migration.py and bench.py's `migration` config to
exercise BASELINE config 5 — the reference's claim that the ec.encode
pipeline's ordering (shards mounted before the volume is deleted,
volume_grpc_erasure_coding.go:25-36) keeps reads green throughout.
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request


class HammerReader(threading.Thread):
    """Reads every key in a loop through `base_url` until stopped,
    verifying full body equality (covers cookie + CRC: any torn or
    stale byte fails the comparison). Records per-request latency and
    every failure."""

    def __init__(self, base_url: str, keys: dict[str, bytes], label: str):
        super().__init__(daemon=True)
        self.base_url = base_url
        self.keys = keys
        self.label = label
        self.stop_event = threading.Event()
        self.latencies: list[float] = []
        self.failures: list[str] = []
        self.reads = 0

    def run(self):
        items = list(self.keys.items())
        while not self.stop_event.is_set():
            for fid, want in items:
                t0 = time.perf_counter()
                try:
                    with urllib.request.urlopen(
                        f"{self.base_url}/{fid}", timeout=10
                    ) as r:
                        body = r.read()
                        status = r.status
                except urllib.error.HTTPError as e:
                    body, status = b"", e.code
                except Exception as e:  # noqa: BLE001 - count as failure
                    self.failures.append(f"{self.label} {fid}: {e!r}")
                    continue
                finally:
                    self.latencies.append(time.perf_counter() - t0)
                    self.reads += 1
                if status != 200:
                    self.failures.append(f"{self.label} {fid}: HTTP {status}")
                elif body != want:
                    self.failures.append(
                        f"{self.label} {fid}: body mismatch "
                        f"({len(body)} vs {len(want)} bytes)"
                    )


def run_with_readers(readers, transition, settle: float = 0.5) -> None:
    """Start readers, run transition(), let readers keep hammering for
    `settle` seconds of post-transition reads, then stop and join."""
    for r in readers:
        r.start()
    try:
        transition()
        time.sleep(settle)
    finally:
        for r in readers:
            r.stop_event.set()
        for r in readers:
            r.join(timeout=30)


_port_state = {"next": None}
_port_lock = threading.Lock()


def free_port() -> int:
    """A listen port for a test/bench server.

    NOT a bare port-0 probe: that hands back a port inside the
    kernel's ephemeral range (`ip_local_port_range`, 32768+ here), and
    any outbound connection the process — or a sibling daemon — makes
    before the server binds can be assigned that exact port as its
    LOCAL port, turning the later bind into EADDRINUSE. Under a full
    tier-1 run (hundreds of servers, thousands of client dials) that
    race killed whole module fixtures ~1 run in 3.

    Instead: walk a range strictly BELOW the ephemeral floor
    (20000–22699 — chosen so the +10000 gRPC sibling convention stays
    below it too), per-process offset against concurrent suites, and
    verify BOTH the port and its +10000 sibling are bindable before
    handing it out (servers bind both; the old probe never checked
    the sibling)."""
    import os
    import socket

    with _port_lock:
        if _port_state["next"] is None:
            _port_state["next"] = 20000 + (os.getpid() % 27) * 100
        for _ in range(2700):
            p = _port_state["next"]
            _port_state["next"] = p + 1 if p + 1 < 22700 else 20000
            try:
                s1 = socket.socket()
                s1.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s1.bind(("127.0.0.1", p))
                try:
                    s2 = socket.socket()
                    s2.setsockopt(
                        socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
                    )
                    try:
                        s2.bind(("127.0.0.1", p + 10000))
                    finally:
                        s2.close()
                finally:
                    s1.close()
                return p
            except OSError:
                continue
    # range exhausted (never expected): the old ephemeral probe is
    # still better than failing outright
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def write_keyset(master_port: int, collection: str, n: int = 40, payload_fn=None):
    """Write n blobs with replication=001; return (vid, {fid: payload},
    source_url) for the volume that received the most keys.
    payload_fn(i) -> bytes sizes each blob (default ~1 KB)."""
    import json as _json

    if payload_fn is None:
        def payload_fn(i):
            return (f"key {i} of {collection} ".encode() * 97)[: 997 + 13 * i]

    by_vid: dict[int, dict[str, bytes]] = {}
    url_by_vid: dict[int, str] = {}
    for i in range(n):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{master_port}/dir/assign"
            f"?collection={collection}&replication=001",
            timeout=10,
        ) as r:
            assign = _json.loads(r.read())
        payload = payload_fn(i)
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{assign['url']}/{assign['fid']}",
                data=payload,
                method="POST",
            ),
            timeout=10,
        ).close()
        vid = int(assign["fid"].split(",")[0])
        by_vid.setdefault(vid, {})[assign["fid"]] = payload
        url_by_vid[vid] = assign["url"]
    vid = max(by_vid, key=lambda v: len(by_vid[v]))
    return vid, by_vid[vid], url_by_vid[vid]


def start_cluster(
    dirs: list[str],
    volume_size_limit_mb: int = 64,
    heartbeat_interval: float = 0.2,
    ready_timeout: float = 45.0,
    master_kwargs: dict | None = None,
    **vs_kwargs,
):
    """Boot 1 master + one VolumeServer per dir (rack{i%2} layout) and
    wait until every node has registered. Returns (master, servers);
    caller stops them. Shared by tests/test_migration.py's fixture and
    bench.py's migration config so both measure the same cluster shape.
    `master_kwargs` feeds MasterServer (e.g. telemetry_interval for the
    cluster-telemetry tests)."""
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    master = MasterServer(
        port=free_port(),
        volume_size_limit_mb=volume_size_limit_mb,
        **(master_kwargs or {}),
    )
    master.start()
    servers = []
    try:
        for i, d in enumerate(dirs):
            vs = VolumeServer(
                [d],
                port=free_port(),
                master=f"127.0.0.1:{master.port}",
                rack=f"rack{i % 2}",
                heartbeat_interval=heartbeat_interval,
                max_volume_counts=[100],
                **vs_kwargs,
            )
            vs.start()
            servers.append(vs)
        deadline = time.time() + ready_timeout
        while (
            time.time() < deadline
            and len(master.topology.data_nodes()) < len(dirs)
        ):
            time.sleep(0.05)
        if len(master.topology.data_nodes()) < len(dirs):
            raise RuntimeError("cluster not ready: not all nodes registered")
    except BaseException:
        for vs in servers:
            vs.stop()
        master.stop()
        raise
    return master, servers
