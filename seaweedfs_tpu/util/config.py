"""TOML configuration with env-var override, the viper idiom.

Behavioral match of weed/util/config.go:19-50: `load_config("filer")`
searches `./filer.toml`, `~/.seaweedfs_tpu/filer.toml`,
`/etc/seaweedfs_tpu/filer.toml` in order; any key can be overridden by
an environment variable `WEED_SECTION_SUB_KEY` (dots → underscores,
upper-cased, `WEED_` prefix — config.go:45-50). Missing files are fine
unless required=True (config.go:31-39).

Template configs (the reference generates these with `weed scaffold`,
command/scaffold.go:33-45) live in SCAFFOLD_TEMPLATES for the CLI.
"""

from __future__ import annotations

import os
import tomllib


CONFIG_SEARCH_DIRS = (".", "~/.seaweedfs_tpu", "/etc/seaweedfs_tpu")
ENV_PREFIX = "WEED_"


class Configuration:
    """Dotted-key view over a parsed TOML tree with env override."""

    def __init__(self, tree: dict, env: dict | None = None):
        self._tree = tree
        self._env = os.environ if env is None else env

    def _env_key(self, key: str) -> str:
        return ENV_PREFIX + key.replace(".", "_").upper()

    def get(self, key: str, default=None):
        env_val = self._env.get(self._env_key(key))
        if env_val is not None:
            return env_val
        node = self._tree
        for part in key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self.get(key, default)
        if isinstance(val, str):
            return val.strip().lower() in ("1", "true", "yes", "on")
        return bool(val)

    def get_int(self, key: str, default: int = 0) -> int:
        val = self.get(key, default)
        return int(val)

    def get_string(self, key: str, default: str = "") -> str:
        val = self.get(key, default)
        return str(val)

    def sub(self, prefix: str) -> dict:
        """The raw subtree under a dotted prefix ({} if absent)."""
        node = self._tree
        for part in prefix.split("."):
            if not isinstance(node, dict) or part not in node:
                return {}
            node = node[part]
        return node if isinstance(node, dict) else {}


def load_config(
    name: str,
    required: bool = False,
    search_dirs: tuple[str, ...] = CONFIG_SEARCH_DIRS,
    env: dict | None = None,
) -> Configuration:
    for d in search_dirs:
        path = os.path.join(os.path.expanduser(d), f"{name}.toml")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f), env=env)
    if required:
        raise FileNotFoundError(
            f"no {name}.toml found in {', '.join(search_dirs)}"
        )
    return Configuration({}, env=env)


SCAFFOLD_TEMPLATES = {
    "security": """\
# security.toml — put in ./, ~/.seaweedfs_tpu/, or /etc/seaweedfs_tpu/
# Any key can be overridden by env var WEED_<SECTION>_<KEY>.

[jwt.signing]
key = ""
expires_after_seconds = 10

[jwt.signing.read]
key = ""
expires_after_seconds = 60

[access]
# ui = false
white_list = []

[grpc]
ca = ""

[grpc.volume]
cert = ""
key = ""

[grpc.master]
cert = ""
key = ""

[grpc.filer]
cert = ""
key = ""

[grpc.client]
cert = ""
key = ""
""",
    "filer": """\
# filer.toml — filer metadata store selection.
# Exactly one store should be enabled.

[memory]
enabled = false

[sqlite]
enabled = true
dbfile = "./filer.db"

[appendlog]
enabled = false
dir = "./filerlog"
""",
    "notification": """\
# notification.toml — filer update-event queue.

[notification.log]
enabled = false

[notification.memory]
enabled = false

[notification.dirqueue]
enabled = false
dir = "./notifications"
""",
    "replication": """\
# replication.toml — weed filer.replicate source and sink.

[source.filer]
enabled = true
grpcAddress = "localhost:18888"
directory = "/buckets"

[sink.filer]
enabled = false
grpcAddress = "localhost:18888"
directory = "/backup"
replication = ""
collection = ""
ttlSec = 0

[sink.local]
enabled = false
directory = "/tmp/backup"
""",
    "master": """\
# master.toml — master maintenance scripts (run by the leader on a cron).

[master.maintenance]
scripts = \"\"\"
  lock
  ec.encode -fullPercent=95 -quietFor=1h
  ec.rebuild -force
  ec.balance -force
  volume.balance -force
  unlock
\"\"\"
sleep_minutes = 17

[master.sequencer]
type = "memory"
""",
}
