"""TOML configuration with env-var override, the viper idiom.

Behavioral match of weed/util/config.go:19-50: `load_config("filer")`
searches `./filer.toml`, `~/.seaweedfs_tpu/filer.toml`,
`/etc/seaweedfs_tpu/filer.toml` in order; any key can be overridden by
an environment variable `WEED_SECTION_SUB_KEY` (dots → underscores,
upper-cased, `WEED_` prefix — config.go:45-50). Missing files are fine
unless required=True (config.go:31-39).

Template configs (the reference generates these with `weed scaffold`,
command/scaffold.go:33-45) live in SCAFFOLD_TEMPLATES for the CLI.
"""

from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:  # Python < 3.11, no tomli: mini parser
        class tomllib:  # type: ignore[no-redef]
            """Fallback reader for the TOML subset this repo's configs
            use ([dotted.sections], string/int/float/bool scalars,
            arrays — including multi-line and quoted elements with
            commas — and # comments). Python 3.11+ ships tomllib and
            never reaches this; on 3.10 images every subcommand that
            loads a *.toml (security, master maintenance, notification,
            replication) would otherwise die at import. Syntax this
            subset does not cover raises ValueError LOUDLY — silently
            misloading a security whitelist would be far worse than
            the crash this class exists to avoid."""

            @staticmethod
            def _scalar(tok: str):
                tok = tok.strip()
                if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
                    return tok[1:-1].encode("raw_unicode_escape").decode(
                        "unicode_escape"
                    )
                if tok.startswith("'") and tok.endswith("'") and len(tok) >= 2:
                    return tok[1:-1]
                if tok in ("true", "false"):
                    return tok == "true"
                try:
                    return int(tok, 0)
                except ValueError:
                    pass
                try:
                    return float(tok)
                except ValueError:
                    pass
                if tok.startswith(("[", "{", '"', "'")):
                    raise ValueError(
                        f"unsupported TOML value {tok!r} (fallback parser; "
                        "install Python 3.11+ or tomli for full TOML)"
                    )
                return tok  # bare token: surface as string

            @staticmethod
            def _strip_comment(line: str) -> str:
                out = []
                quote = None
                for ch in line:
                    if quote:
                        if ch == quote:
                            quote = None
                    elif ch in "\"'":
                        quote = ch
                    elif ch == "#":
                        break
                    out.append(ch)
                return "".join(out).strip()

            @staticmethod
            def _split_elems(inner: str) -> list[str]:
                """Quote-aware top-level comma split of an array body."""
                elems, buf, quote = [], [], None
                for ch in inner:
                    if quote:
                        buf.append(ch)
                        if ch == quote:
                            quote = None
                    elif ch in "\"'":
                        quote = ch
                        buf.append(ch)
                    elif ch == ",":
                        elems.append("".join(buf))
                        buf = []
                    else:
                        buf.append(ch)
                if quote:
                    raise ValueError("unterminated string in TOML array")
                elems.append("".join(buf))
                return [e for e in (e.strip() for e in elems) if e]

            @classmethod
            def load(cls, f) -> dict:
                tree: dict = {}
                node = tree
                lines = f.read().decode("utf-8").splitlines()
                i = 0
                while i < len(lines):
                    line = cls._strip_comment(lines[i])
                    i += 1
                    if not line:
                        continue
                    if line.startswith("[") and line.endswith("]"):
                        node = tree
                        for part in line[1:-1].strip().split("."):
                            node = node.setdefault(part.strip(), {})
                        continue
                    key, sep, val = line.partition("=")
                    if not sep:
                        raise ValueError(
                            f"unsupported TOML line {line!r} (fallback "
                            "parser; install Python 3.11+ or tomli)"
                        )
                    key = key.strip()
                    target = node
                    if key.startswith(('"', "'")):
                        key = key.strip('"').strip("'")
                    else:
                        # bare dotted keys nest, like real TOML
                        # (signing.key = ... under [jwt] must land at
                        # jwt.signing.key, not a literal 'signing.key')
                        parts = [p.strip() for p in key.split(".")]
                        for part in parts[:-1]:
                            target = target.setdefault(part, {})
                        key = parts[-1]
                    val = val.strip()
                    if val.startswith('"""'):
                        # multi-line basic string (master.toml's
                        # maintenance scripts): raw until closing """
                        body = val[3:]
                        while '"""' not in body:
                            if i >= len(lines):
                                raise ValueError(
                                    f"unterminated TOML string for {key!r}"
                                )
                            body += "\n" + lines[i]
                            i += 1
                        target[key] = body[: body.index('"""')].lstrip("\n")
                        continue
                    if val.startswith("["):
                        # multi-line arrays: accumulate until the
                        # closing bracket (quotes respected by the
                        # comment stripper; nesting unsupported → loud)
                        while not val.endswith("]"):
                            if i >= len(lines):
                                raise ValueError(
                                    f"unterminated TOML array for {key!r}"
                                )
                            val += " " + cls._strip_comment(lines[i])
                            i += 1
                        inner = val[1:-1].strip().rstrip(",")
                        target[key] = [
                            cls._scalar(t) for t in cls._split_elems(inner)
                        ]
                    else:
                        target[key] = cls._scalar(val)
                return tree


CONFIG_SEARCH_DIRS = (".", "~/.seaweedfs_tpu", "/etc/seaweedfs_tpu")
ENV_PREFIX = "WEED_"


class Configuration:
    """Dotted-key view over a parsed TOML tree with env override."""

    def __init__(self, tree: dict, env: dict | None = None):
        self._tree = tree
        self._env = os.environ if env is None else env

    def _env_key(self, key: str) -> str:
        return ENV_PREFIX + key.replace(".", "_").upper()

    def get(self, key: str, default=None):
        env_val = self._env.get(self._env_key(key))
        if env_val is not None:
            return env_val
        node = self._tree
        for part in key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self.get(key, default)
        if isinstance(val, str):
            return val.strip().lower() in ("1", "true", "yes", "on")
        return bool(val)

    def get_int(self, key: str, default: int = 0) -> int:
        val = self.get(key, default)
        return int(val)

    def get_string(self, key: str, default: str = "") -> str:
        val = self.get(key, default)
        return str(val)

    def sub(self, prefix: str) -> dict:
        """The raw subtree under a dotted prefix ({} if absent)."""
        node = self._tree
        for part in prefix.split("."):
            if not isinstance(node, dict) or part not in node:
                return {}
            node = node[part]
        return node if isinstance(node, dict) else {}


def load_config(
    name: str,
    required: bool = False,
    search_dirs: tuple[str, ...] = CONFIG_SEARCH_DIRS,
    env: dict | None = None,
) -> Configuration:
    for d in search_dirs:
        path = os.path.join(os.path.expanduser(d), f"{name}.toml")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f), env=env)
    if required:
        raise FileNotFoundError(
            f"no {name}.toml found in {', '.join(search_dirs)}"
        )
    return Configuration({}, env=env)


SCAFFOLD_TEMPLATES = {
    "security": """\
# security.toml — put in ./, ~/.seaweedfs_tpu/, or /etc/seaweedfs_tpu/
# Any key can be overridden by env var WEED_<SECTION>_<KEY>.

[jwt.signing]
key = ""
expires_after_seconds = 10

[jwt.signing.read]
key = ""
expires_after_seconds = 60

[access]
# ui = false
white_list = []

[grpc]
ca = ""

[grpc.volume]
cert = ""
key = ""

[grpc.master]
cert = ""
key = ""

[grpc.filer]
cert = ""
key = ""

[grpc.client]
cert = ""
key = ""
""",
    "filer": """\
# filer.toml — filer metadata store selection.
# Exactly one store should be enabled.

[memory]
enabled = false

[sqlite]
enabled = true
dbfile = "./filer.db"

[appendlog]
enabled = false
dir = "./filerlog"
""",
    "notification": """\
# notification.toml — filer update-event queue.

[notification.log]
enabled = false

[notification.memory]
enabled = false

[notification.dirqueue]
enabled = false
dir = "./notifications"
""",
    "replication": """\
# replication.toml — weed filer.replicate source and sink.

[source.filer]
enabled = true
grpcAddress = "localhost:18888"
directory = "/buckets"

[sink.filer]
enabled = false
grpcAddress = "localhost:18888"
directory = "/backup"
replication = ""
collection = ""
ttlSec = 0

[sink.local]
enabled = false
directory = "/tmp/backup"
""",
    "master": """\
# master.toml — master maintenance scripts (run by the leader on a cron).

[master.maintenance]
scripts = \"\"\"
  lock
  ec.encode -fullPercent=95 -quietFor=1h
  ec.rebuild -force
  ec.balance -force
  volume.balance -force
  unlock
\"\"\"
sleep_minutes = 17

[master.sequencer]
type = "memory"
""",
}
