"""Server-side transparent compression policy.

Behavioral match of weed/util/compression.go IsGzippable /
IsGzippableFileType: the volume server auto-gzips uploads whose
extension/mime say "compresses well" (text, code, json/xml, svg) and
skips already-compressed families (archives, jpeg/png, video); unknown
types fall back to a mostly-text sniff of the payload.
"""

from __future__ import annotations

_ALWAYS = {
    ".svg", ".bmp", ".pdf", ".txt", ".html", ".htm", ".css", ".js",
    ".json", ".php", ".java", ".go", ".rb", ".c", ".cpp", ".h", ".hpp",
}
_NEVER = {".zip", ".rar", ".gz", ".bz2", ".xz", ".png", ".jpg", ".jpeg"}

_TEXTCHARS = bytes(range(32, 127)) + b"\t\n\r\f\b\x1b"


def _is_mostly_text(data: bytes) -> bool:
    sample = data[:1024]
    if not sample or b"\x00" in sample:
        return False
    # translate-delete counts non-text bytes in C: this runs on the
    # volume write hot path for every extension the type rules do not
    # decide (a Python per-byte loop here costs ~40 us/write)
    non_text = len(sample.translate(None, _TEXTCHARS))
    return non_text / len(sample) < 0.15


def is_gzippable_file_type(ext: str, mtype: str) -> tuple[bool, bool]:
    """(should_be_zipped, i_am_sure) — compression.go:54."""
    ext = ext.lower()
    if mtype.startswith("text/"):
        return True, True
    if ext in (".svg", ".bmp"):
        return True, True
    if mtype.startswith("image/"):
        return False, True
    if ext in _NEVER:
        return False, True
    if ext in _ALWAYS:
        return True, True
    if mtype.startswith("application/"):
        if mtype.endswith("xml") or mtype.endswith("json") or mtype.endswith(
            "script"
        ):
            return True, True
    return False, False


def is_gzippable(ext: str, mtype: str, data: bytes) -> bool:
    """compression.go:40 — type rules first, text sniff as tiebreak."""
    should, sure = is_gzippable_file_type(ext, mtype)
    if sure:
        return should
    return _is_mostly_text(data)


def try_gunzip(data: bytes) -> bytes:
    """Decompress if possible, else return the bytes unchanged — the
    serve-stored-bytes fallback for needles whose gzip flag lies.
    gzip.decompress raises EOFError/zlib.error (NOT OSError subclasses)
    on truncated streams, so the net must cover all three."""
    import gzip
    import zlib

    try:
        return gzip.decompress(data)
    except (OSError, EOFError, zlib.error):
        return data
