"""Leveled verbose logging, the glog idiom on top of stdlib logging.

Behavioral match of the reference's vendored glog (weed/glog/glog.go:204
`V(n)` guards, per-module overrides via `-vmodule=pattern=N`
glog.go:1000+, severity files with rotation): messages carry a verbosity
level 0-4; `V(n)` is cheap and returns a no-op logger unless enabled
either globally (`set_verbosity`) or for the calling module
(`set_vmodule`). Severity logging (info/warning/error/fatal) is always
on. Output goes to stderr and optionally to size-rotated files in
`log_dir`, mirroring `weed -logdir`.
"""

from __future__ import annotations

import contextvars
import fnmatch
import inspect
import logging
import logging.handlers
import os
import sys
import threading

# Current request (trace) ID: every V(n)/severity line emitted inside
# a traced request is automatically prefixed `[<trace_id>]`, so
# grepping a log for one request ID yields its full cross-module
# story. Two sources, checked per LOG LINE (never per request — log
# lines are rare, requests are not): the `request_id` contextvar for
# explicit stamping by non-traced code, then a provider callback the
# tracing plane registers to expose its current span's trace id
# (seaweedfs_tpu/trace keeps that in a thread-local; pulling it lazily
# here keeps the request hot path free of per-span contextvar writes).
request_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "weed_request_id", default=""
)

_rid_provider = None


def set_request_id_provider(fn) -> None:
    """Register a zero-arg callable returning the current request
    (trace) ID or "" — consulted when the contextvar is unset."""
    global _rid_provider
    _rid_provider = fn


def _rid_prefix(msg: str) -> str:
    rid = request_id.get()
    if not rid and _rid_provider is not None:
        rid = _rid_provider()
    if not rid:
        return msg
    # rid lands inside a %-format string handed to logging with args;
    # ids are hex-validated at the trust boundary, but escape anyway so
    # an exotic provider value can never corrupt the format
    if "%" in rid:
        rid = rid.replace("%", "%%")
    return f"[{rid}] {msg}"


_lock = threading.Lock()
_verbosity = 0
_vmodule: list[tuple[str, int]] = []  # (module-name glob, level)
_logger = logging.getLogger("seaweedfs_tpu")
_configured = False

MAX_LOG_FILE_BYTES = 1 << 26  # rotate like glog's MaxSize
FATAL_EXIT_CODE = 255


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    with _lock:
        if _configured:
            return
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(levelname).1s%(asctime)s %(module)s:%(lineno)d] %(message)s",
                datefmt="%m%d %H:%M:%S",
            )
        )
        _logger.addHandler(handler)
        _logger.setLevel(logging.INFO)
        _logger.propagate = False
        _configured = True


def set_log_dir(log_dir: str, program: str = "weed") -> None:
    """Also write rotating log files under log_dir (glog file output)."""
    _ensure_configured()
    os.makedirs(log_dir, exist_ok=True)
    handler = logging.handlers.RotatingFileHandler(
        os.path.join(log_dir, f"{program}.log"),
        maxBytes=MAX_LOG_FILE_BYTES,
        backupCount=5,
    )
    handler.setFormatter(
        logging.Formatter(
            "%(levelname).1s%(asctime)s %(module)s:%(lineno)d] %(message)s",
            datefmt="%m%d %H:%M:%S",
        )
    )
    with _lock:
        _logger.addHandler(handler)


def set_verbosity(level: int) -> None:
    """Global -v level; V(n) logs iff n <= level (or a vmodule match)."""
    global _verbosity
    _verbosity = int(level)


def set_vmodule(spec: str) -> None:
    """-vmodule="volume*=2,master_server=3" per-module verbosity."""
    global _vmodule
    pats = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, lvl = part.partition("=")
        pats.append((name, int(lvl or 0)))
    with _lock:
        _vmodule = pats


def _caller_module(depth: int = 2) -> str:
    frame = inspect.stack()[depth]
    mod = os.path.basename(frame.filename)
    return mod[:-3] if mod.endswith(".py") else mod


class _Verbose:
    """Result of V(n): .info/.infof log only when the guard passed."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def __bool__(self) -> bool:
        return self.enabled

    def info(self, msg: str, *args) -> None:
        if self.enabled:
            _ensure_configured()
            _logger.info(_rid_prefix(msg), *args, stacklevel=2)

    infof = info


def V(level: int) -> _Verbose:  # noqa: N802 - glog's exported name
    if level <= _verbosity:
        return _Verbose(True)
    if _vmodule:
        mod = _caller_module()
        for pat, lvl in _vmodule:
            if fnmatch.fnmatch(mod, pat):
                return _Verbose(level <= lvl)
    return _Verbose(False)


def info(msg: str, *args) -> None:
    _ensure_configured()
    _logger.info(_rid_prefix(msg), *args, stacklevel=2)


def warning(msg: str, *args) -> None:
    _ensure_configured()
    _logger.warning(_rid_prefix(msg), *args, stacklevel=2)


def error(msg: str, *args) -> None:
    _ensure_configured()
    _logger.error(_rid_prefix(msg), *args, stacklevel=2)


def fatal(msg: str, *args) -> None:
    """Log at FATAL severity and exit (glog.Fatalf)."""
    _ensure_configured()
    _logger.critical(_rid_prefix(msg), *args, stacklevel=2)
    sys.exit(FATAL_EXIT_CODE)
