"""Single-range `Range: bytes=` parsing shared by every HTTP surface
(volume, filer, S3 gateway) — one place for suffix/open-ended/416
semantics (RFC 7233; Go http.ServeContent role in the reference)."""

from __future__ import annotations


class RangeNotSatisfiable(ValueError):
    pass


def parse_range(header: str, total: int) -> tuple[int, int] | None:
    """(start, end) inclusive for the first range in `header`, or None
    when the header is absent/not a bytes range (serve the full body).
    Raises RangeNotSatisfiable for malformed or out-of-bounds ranges
    (respond 416 with `Content-Range: bytes */total`)."""
    if not header.startswith("bytes="):
        return None
    spec = header[6:].split(",")[0].strip()
    start_s, _, end_s = spec.partition("-")
    try:
        if start_s == "":
            nbytes = int(end_s)
            start, end = max(0, total - nbytes), total - 1
        else:
            start = int(start_s)
            end = int(end_s) if end_s else total - 1
    except ValueError:
        raise RangeNotSatisfiable(spec) from None
    if start >= total or start > end:
        raise RangeNotSatisfiable(spec)
    return start, min(end, total - 1)
