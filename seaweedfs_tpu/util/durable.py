"""Durable-publish helpers: the atomic write→fsync→rename→dirsync idiom.

POSIX gives exactly one crash-atomic primitive — rename(2) — and it is
only as durable as the fsyncs around it: the renamed file's BYTES must
be fsynced before the rename (or a crash can publish an empty/partial
file under the final name: the classic rename-visible-before-data
bug), and the parent DIRECTORY must be fsynced after it (or the rename
itself may not survive the crash). The crash-consistency plane
(docs/ANALYSIS.md v3) statically enforces this ordering tree-wide
(`crash-rename-*` rules in analysis/crashlint.py); these helpers are
the recognized way to satisfy it.

Every recovery-critical state file in the repo publishes through
`publish()` (scrub_state.json, .vif, raft state, LSM manifests,
notification queue cursors, the sequence reservation file). The vacuum
commit in storage/volume.py needs a two-file swap and carries its own
marker protocol on top of `fsync_path`/`fsync_dir`.
"""

from __future__ import annotations

import os


def fsync_path(path: str) -> None:
    """fsync a file's bytes by path (open read-only + fsync: syncing an
    inode needs any fd, not the writing one)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """fsync a directory so entries created/renamed/removed in it
    survive a crash. Best-effort on filesystems that reject directory
    fsync (some overlay/virtio mounts): the rename is then only as
    durable as the host makes it, which is still strictly better than
    not asking."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def publish(tmp: str, dst: str) -> None:
    """Atomically publish `tmp` (fully written, possibly unflushed at
    the OS level) as `dst`: fsync the bytes, rename, fsync the parent
    directory. After a crash, `dst` is either the complete old file or
    the complete new one — never empty, torn, or missing."""
    fsync_path(tmp)
    os.replace(tmp, dst)
    fsync_dir(os.path.dirname(dst))
